"""Evidence for the fullc_gather -> model-axis-sharding mapping.

The reference's ``fullc_gather`` PS mode all-gathers the (in, out)
activations of a big FC layer and computes the full weight gradient on
every worker — trading gradient bandwidth for activation bandwidth
(``async_updater-inl.hpp:67-93,190-221``).  This framework maps the config
key to sharding the FC weight on the mesh's "model" axis and letting GSPMD
choose the collectives.  This script *verifies* what GSPMD actually emits
for the AlexNet fc6 shape under ``mesh = data:4,model:2 fullc_gather=1``:
it dumps the optimized HLO of the train step (8 virtual CPU devices) and
counts the collectives touching the fc6 weight path.

Usage: python experiments/fullc_gather_hlo.py
Writes /tmp/fullc_gather_step.hlo and prints a collective summary.
"""
import os
import re
import sys

sys.path.insert(0, "/root/repo")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main():
    batch = 64
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    t = _make_trainer(
        ALEXNET_NET, batch, "cpu:0-7",
        extra=[("mesh", "data:4,model:2"), ("fullc_gather", "1"),
               ("eval_train", "0"), ("silent", "1")])
    fn = t._build_train_step()
    datas = jnp.zeros((batch, 3, 227, 227), jnp.float32)
    labels = jnp.zeros((batch, 1), jnp.float32)
    lowered = fn.lower(t.params, t.opt_state, t.buffers, datas, labels,
                       (), jnp.int32(0), t._rng_base)
    compiled = lowered.compile()
    txt = compiled.as_text()
    out = "/tmp/fullc_gather_step.hlo"
    with open(out, "w") as f:
        f.write(txt)

    # collective census
    kinds = ["all-gather", "all-reduce", "reduce-scatter", "all-to-all",
             "collective-permute"]
    print(f"wrote {out} ({len(txt.splitlines())} lines)")
    for k in kinds:
        n = len(re.findall(rf"\b{k}\b", txt))
        print(f"  {k:20s} {n}")
    # fc6-adjacent evidence: find all-gathers whose operand/result shapes
    # match the fc6 activation (9216) or weight (9216x4096) dims
    fc_lines = [ln.strip() for ln in txt.splitlines()
                if ("all-gather" in ln or "all-reduce" in ln)
                and ("9216" in ln or "4096" in ln)]
    print(f"fc6-shaped collective instructions: {len(fc_lines)}")
    for ln in fc_lines[:8]:
        print("   ", ln[:160])


if __name__ == "__main__":
    main()
