"""Throughput numbers for the wider model zoo (VGG-16, ResNet).

python experiments/model_bench.py vgg16|resnet20|resnet56
Prints step ms + imgs/sec + analytic MFU on the TPU.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main(which):
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import resnet, vgg
    from bench import conv_flops_per_image, PEAK_FLOPS
    if which == "vgg16":
        conf = vgg(depth=16) + "metric = error\neta = 0.01\nmomentum = 0.9\n"
        batch, shape = 128, (3, 224, 224)
    elif which.startswith("resnet"):
        depth = int(which[6:])
        conf = resnet(num_class=10, depth=depth) + \
            "metric = error\neta = 0.1\nmomentum = 0.9\n"
        batch, shape = 1024, (3, 32, 32)
    else:
        raise SystemExit(f"unknown model {which}")
    nclass = 1000 if which == "vgg16" else 10
    t = _make_trainer(conf, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("silent", "1")])
    rnd = np.random.RandomState(0)
    k, trials = 10, 2
    datas = jnp.asarray(rnd.rand(k, batch, *shape).astype(np.float32)
                        ).astype(jnp.bfloat16)
    labels = jnp.asarray(
        rnd.randint(0, nclass, (k, batch, 1)).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))
    t0 = time.perf_counter()
    for _ in range(trials):
        losses = t.update_many(datas, labels)
    np.asarray(losses)
    dt = time.perf_counter() - t0
    step_ms = dt / (k * trials) * 1e3
    ips = batch * k * trials / dt
    flops = conv_flops_per_image(t.net)
    dev = jax.devices()[0].device_kind
    peak = next((v for kk, v in PEAK_FLOPS.items() if kk in dev), 197e12)
    mfu = 3.0 * flops * ips / peak
    print(f"{which} b{batch}: step={step_ms:.2f}ms imgs/sec={ips:.0f} "
          f"fwd={flops/1e9:.2f}GF/img MFU={mfu*100:.1f}%")


if __name__ == "__main__":
    main(sys.argv[1])
