"""GoogLeNet synthetic-STREAM convergence at b128 (VERDICT r5 #8).

The round-4 record only showed fixed-set memorization; the stream runs
(fresh samples every step) sat at chance for 600 steps at eta=0.002.
This sweep finds hyperparameters under which the stream loss actually
declines (<6.0 by step ~600 from ln(1000)=6.9078) and appends the
winning curve to CONVERGENCE.jsonl.

Data: per-class oriented gratings + noise (see gen() comment),
REGENERATED per dispatch group from a folded key — every batch is new,
so declining loss is generalization to the class distribution, not
memorization.

What made it converge (in order of discovery): sgd at every LR, adam at
1e-3, and LR/momentum warmup all sat at EXACT chance on the
block-prototype stream with a data-independent loss curve; activation
probing showed the trunk attenuating 3x per stage under xavier (logits
below bf16 noise by inception 5).  Two escapes were then found and both
are recorded in CONVERGENCE.jsonl: (a) adam at 3e-4 converges even
under xavier on the block stream (0.32 @ 600 — adaptive step sizes
compensate the tiny gradients; 1e-3 does not), and (b) kaiming init
makes plain SGD converge — after fixing rand_init_weight's kaiming,
which used fan_OUT instead of fan_in (layers/base.py), exactly
under-scaling the deep relu stacks kaiming exists for.

Usage: python experiments/gl_stream.py [eta ...]   (default sweep)
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def stream_curve(eta, steps=600, batch=128, nclass=1000,
                 shape=(3, 224, 224), group=8, extra=(), init="xavier"):
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import googlenet
    t = _make_trainer(
        googlenet(init=init) + "metric = error\n"
        f"eta = {eta}\nmomentum = 0.9\n",
        batch, "tpu", extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("silent", "1"), *extra])
    kp = jax.random.PRNGKey(7)

    # class signal: per-class oriented grating (frequency/phase/channel
    # amplitudes).  The 8x8 block-prototype family used for memorization
    # is conv-HOSTILE as a stream task: the class signal is a global
    # template with locally identical statistics everywhere, so a linear
    # probe solves it in <100 steps (measured: loss 0.0) while AlexNet
    # AND GoogLeNet sit at exact chance for 600 steps under every
    # optimizer/init/LR tried.  Gratings are locally detectable by the
    # oriented-edge features conv stems learn first.
    kf1, kf2, kph, kam = jax.random.split(kp, 4)
    fy = jax.random.uniform(kf1, (nclass,), minval=0.05, maxval=1.5)
    fx = jax.random.uniform(kf2, (nclass,), minval=0.05, maxval=1.5)
    ph = jax.random.uniform(kph, (nclass,), maxval=2 * np.pi)
    amp = jax.random.uniform(kam, (nclass, shape[0]), minval=-1.0,
                             maxval=1.0)
    yy = jnp.arange(shape[1], dtype=jnp.float32)[:, None]
    xx = jnp.arange(shape[2], dtype=jnp.float32)[None, :]

    @jax.jit
    def gen(kg):
        kl, kn = jax.random.split(kg)
        labels = jax.random.randint(kl, (group, batch), 0, nclass)
        wave = jnp.sin(fy[labels][..., None, None] * yy
                       + fx[labels][..., None, None] * xx
                       + ph[labels][..., None, None])
        pat = amp[labels][..., :, None, None] * wave[:, :, None, :, :]
        noise = jax.random.uniform(kn, (group, batch) + shape) * 0.25
        return ((pat + noise).astype(jnp.bfloat16),
                labels[..., None].astype(jnp.float32))

    t.start_round(1)
    curve = []
    for it in range(steps // group):
        datas, labs = gen(jax.random.fold_in(kp, 1000 + it))
        losses = np.asarray(t.update_many(datas, labs))
        curve.extend(float(x) for x in losses)
        if not np.isfinite(curve[-1]):
            break
    return curve


def main():
    # spec: "eta" (sgd), "adam,eta", "k<eta>" (kaiming sgd), "ak<eta>"
    # (kaiming adam), or "eta+warm" (factor-schedule LR warmup x2/75
    # steps + momentum ramp 0.5->0.9).  Defaults = the recorded winners.
    specs = sys.argv[1:] or ["k0.01", "ak0.001"]
    best = None
    for spec in specs:
        extra = []
        init = "xavier"
        name = spec
        if spec.startswith("adam,"):
            eta = float(spec.split(",")[1])
            extra = [("updater", "adam")]
        elif spec.startswith("ak"):  # kaiming + adam
            eta = float(spec[2:])
            init = "kaiming"
            extra = [("updater", "adam")]
        elif spec.startswith("k"):  # kaiming init + sgd
            eta = float(spec[1:])
            init = "kaiming"
        elif spec.endswith("+warm"):
            eta = float(spec[:-5])
            extra = [("eta", str(eta / 16)), ("lr:schedule", "factor"),
                     ("lr:factor", "2"), ("lr:step", "75"),
                     ("momentum_schedule", "1"),
                     ("base_momentum", "0.5"),
                     ("final_momentum", "0.9"),
                     ("saturation_epoch", "300")]
        else:
            eta = float(spec)
        t0 = time.perf_counter()
        c = stream_curve(eta, extra=extra, init=init)
        marks = {s: round(c[s - 1], 4)
                 for s in (1, 100, 200, 300, 400, 500, 600) if s <= len(c)}
        print(f"{name}: {marks} ({time.perf_counter() - t0:.0f}s)",
              flush=True)
        if np.isfinite(c[-1]) and (best is None or c[-1] < best[1][-1]):
            best = (name, c)
    if best is None:
        print("every spec diverged; nothing to record", flush=True)
        return
    spec, c = best
    if c[-1] < 6.0:
        from experiments.convergence import record
        marks = sorted(set([1, 100, 200, 300, 400, 500, 600]))
        record("imagenet-googlenet",
               f"synthetic 1000-class STREAM (per-class oriented "
               f"gratings + noise, fresh samples every step), b128, "
               f"{spec} (k = kaiming init), TPU v5e, bf16",
               "loss (main + 0.3*aux heads) by step (generalization)",
               {s: round(c[s - 1], 4) for s in marks if s <= len(c)})
    else:
        print(f"no spec reached <6.0 (best {spec}: {c[-1]:.4f}); not "
              "recording", flush=True)


if __name__ == "__main__":
    main()
