"""Real-io overlap evidence (VERDICT r5 #6): jpeg dataset -> imgbin
iterator chain -> CLI train on TPU with a profiler trace, then measure
from the trace (a) the device time of each step under the REAL input
pipeline vs the synthetic-input bench number and (b) the inter-step
device gaps, separating io-bound waiting from any serialization the
framework itself would add.

On this box one CPU core sustains ~0.5-1k imgs/sec of jpeg decode
(BASELINE.md round-3 io table), far below the chip's ~26k imgs/sec — so
the device is EXPECTED to idle between steps; the claim under test is
that (1) per-step device time equals the synthetic bench's (the input
path adds no on-device work or layout fixups) and (2) the gap equals the
io shortfall (decode overlaps device execution via threadbuffer), which
anchors the cores-needed-to-feed extrapolation.

Usage: python experiments/io_overlap.py [n_images] [batch]
"""
import glob
import os
import subprocess
import sys
import tempfile

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def write_conf(work, lst, binpath, batch):
    from __graft_entry__ import ALEXNET_NET
    conf = f"""data = train
iter = imgbin
  image_list = {lst}
  image_bin = {binpath}
  rand_crop = 1
  rand_mirror = 1
  decode_thread_num = 8
iter = threadbuffer
iter = end
{ALEXNET_NET}
batch_size = {batch}
dtype = bfloat16
input_s2d = 1
dev = tpu
eta = 0.01
momentum = 0.9
eval_train = 0
silent = 0
"""
    p = os.path.join(work, "io_overlap.conf")
    with open(p, "w") as f:
        f.write(conf)
    return p


def parse_trace(tracedir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = glob.glob(os.path.join(tracedir, "**", "*.xplane.pb"),
                      recursive=True)
    xs = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name != "XLA Modules":
                continue
            evs = sorted((ev.offset_ps, ev.duration_ps,
                          plane.event_metadata[ev.metadata_id].name)
                         for ev in line.events)
            # the train step modules (jit_run / jit_step); ignore tiny
            # convert/slice modules
            steps = [(o, d) for o, d, n in evs if d > 1e9]
            if not steps:
                continue
            durs = [d / 1e9 for _, d in steps]
            gaps = [(steps[i + 1][0] - (steps[i][0] + steps[i][1])) / 1e9
                    for i in range(len(steps) - 1)]
            print(f"steps traced: {len(steps)}")
            print(f"device ms/step: median {np.median(durs):.2f} "
                  f"[{min(durs):.2f}..{max(durs):.2f}]")
            if gaps:
                print(f"inter-step gap ms: median {np.median(gaps):.2f} "
                      f"[{min(gaps):.2f}..{max(gaps):.2f}]")
            return np.median(durs), (np.median(gaps) if gaps else 0.0)
    raise RuntimeError("no step modules found in trace")


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    work = tempfile.mkdtemp(prefix="io_overlap")
    from experiments.io_bench import make_dataset
    print("generating jpeg dataset...", flush=True)
    lst, img_dir, binpath = make_dataset(work, n=n)
    conf = write_conf(work, lst, binpath, batch)
    tracedir = os.path.join(work, "prof")

    # host-side iterator-only rate (decode+augment+batch on this box),
    # via io_bench's warmed measurement loop so the number is comparable
    # to the round-3 io table
    from experiments.io_bench import bench_iter, python_iter
    io_rate = bench_iter(python_iter(lst, binpath, 8), n_epochs=2)
    print(f"iterator-only: {io_rate:.0f} imgs/sec host-side", flush=True)

    env = dict(os.environ, PYTHONPATH=ROOT + ":"
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", conf, "task=train",
         "num_round=2", "max_round=2", f"prof={tracedir}",
         "print_step=4"],
        env=env, cwd=work, capture_output=True, text=True, timeout=3600)
    sys.stderr.write(r.stderr[-2000:])
    assert r.returncode == 0, r.stdout[-2000:]
    dev_ms, gap_ms = parse_trace(tracedir)
    io_ms = batch / io_rate * 1e3
    print(f"io ms/batch (host) {io_ms:.1f} | device ms/step {dev_ms:.1f} "
          f"| gap ms {gap_ms:.1f}")
    print(f"overlap check: gap ≈ io - device would be "
          f"{max(0.0, io_ms - dev_ms):.1f} ms if decode overlaps device "
          f"execution; gap ≈ io ({io_ms:.1f}) would mean serialization")
    chip_rate = batch / (dev_ms / 1e3)
    print(f"cores to feed {chip_rate:.0f} imgs/sec at this per-core rate: "
          f"{chip_rate / io_rate:.1f}")


if __name__ == "__main__":
    main()
