"""Conv1 wgrad/dgrad strategies, timed in-device-loop (see mb_util)."""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/experiments")
from mb_util import bench_op, bench_empty  # noqa: E402
from cxxnet_tpu.ops.nn import conv2d, conv2d_s2d  # noqa: E402

B = 1024


def main():
    rnd = np.random.RandomState(0)
    x = jnp.asarray(rnd.rand(B, 3, 227, 227), jnp.bfloat16)
    w = jnp.asarray(rnd.rand(96, 3, 11, 11), jnp.bfloat16)
    dy = jnp.asarray(rnd.rand(B, 96, 55, 55), jnp.bfloat16)

    print(f"harness floor:        {bench_empty():7.2f} ms")
    print(f"fwd conv:             {bench_op(lambda x, w: conv2d(x, w, stride=4), x, w):7.2f} ms")

    def wg(conv):
        def f(x, w, dy):
            _, vjp = jax.vjp(lambda w: conv(x, w), w)
            return vjp(dy)[0]
        return f

    def dg(conv):
        def f(x, w, dy):
            _, vjp = jax.vjp(lambda x: conv(x, w), x)
            return vjp(dy)[0]
        return f

    c_def = lambda x, w: conv2d(x, w, stride=4)  # noqa: E731
    c_s2d = lambda x, w: conv2d_s2d(x, w, stride=4)  # noqa: E731
    print(f"wgrad default:        {bench_op(wg(c_def), x, w, dy):7.2f} ms")
    print(f"wgrad s2d:            {bench_op(wg(c_s2d), x, w, dy):7.2f} ms")
    print(f"dgrad default:        {bench_op(dg(c_def), x, w, dy):7.2f} ms")
    print(f"dgrad s2d:            {bench_op(dg(c_s2d), x, w, dy):7.2f} ms")

    flops = 2.0 * B * 96 * 55 * 55 * 3 * 11 * 11
    print(f"one pass = {flops/1e9:.1f} GFLOP = {flops/197e12*1e3:.2f} ms @peak")


if __name__ == "__main__":
    main()
