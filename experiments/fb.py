"""Fast full-step bench for iterating on trainer/op changes.

python experiments/fb.py [batch]  -> prints AlexNet step ms + imgs/sec + MFU.
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    model = sys.argv[2] if len(sys.argv) > 2 else "alexnet"
    for a in sys.argv[3:]:
        assert "=" in a, f"extra args must be key=value, got {a!r}"
    kvs = [tuple(a.split("=", 1)) for a in sys.argv[3:]]
    scan_len, trials = 10, 2
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    from bench import conv_flops_per_image, PEAK_FLOPS
    if model == "googlenet":
        from cxxnet_tpu.models import googlenet
        conf = googlenet() + "metric = error\neta = 0.01\nmomentum = 0.9\n" \
            "silent = 1\n"
        shape = (3, 224, 224)
    else:
        conf, shape = ALEXNET_NET, (3, 227, 227)
    t = _make_trainer(conf, batch, "tpu",
                      extra=[("dtype", "bfloat16"),
                             ("eval_train", "0")] + kvs)
    if t._s2d_args is not None:
        # input_s2d: generate data in the pipeline's delivery shape
        from cxxnet_tpu.ops.nn import s2d_staged_shape
        s, kh, kw, oh, ow, _, _ = t._s2d_args
        shape = s2d_staged_shape(shape[0], s, kh, kw, oh, ow)
    # generate on DEVICE: the tunneled host link (and one-core host rand)
    # must not gate a chip-compute measurement
    kd, kl = jax.random.split(jax.random.PRNGKey(0))
    datas = jax.jit(lambda k: jax.random.uniform(
        k, (scan_len, batch) + shape, jnp.float32
    ).astype(jnp.bfloat16))(kd)
    labels = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1), 0, 1000).astype(jnp.float32))(kl)
    t.start_round(1)
    c0 = time.perf_counter()
    np.asarray(t.update_many(datas, labels))
    print(f"compile+warm: {time.perf_counter()-c0:.1f}s", file=sys.stderr)
    t0 = time.perf_counter()
    for _ in range(trials):
        losses = t.update_many(datas, labels)
    np.asarray(losses)
    dt = time.perf_counter() - t0
    steps = trials * scan_len
    step_ms = dt / steps * 1e3
    ips = batch * steps / dt
    flops_fwd = conv_flops_per_image(t.net)
    dev = jax.devices()[0].device_kind
    peak = next((v for k, v in PEAK_FLOPS.items() if k in dev), 197e12)
    mfu = 3.0 * flops_fwd * ips / peak
    print(f"b{batch} step={step_ms:.2f}ms imgs/sec={ips:.0f} "
          f"MFU={mfu*100:.1f}% loss[-1]={float(np.asarray(losses)[-1]):.3f}")


if __name__ == "__main__":
    main()
