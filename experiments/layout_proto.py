"""Prototype: AlexNet conv-stack step time, NCHW vs CHWN activation layout.

Round-2 found every Pallas kernel pays a relayout toll at the pallas_call
boundary because XLA keeps conv activations batch-minor while a logical
NCHW array enters Pallas W-minor.  Hypothesis: make the *logical* layout
CHWN (batch in lanes) for the whole conv stack so Pallas blocks see
(…, W, N) = (sublane, lane) with spatial/channel windows on freely-sliced
major dims.  This script measures whether pure-XLA conv/pool/LRN work is
layout-neutral before any framework integration.

Usage: python experiments/layout_proto.py [batch]
"""
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from experiments.mb_util import bench_op


# ---- layout-parametric ops -------------------------------------------------
# dims: NCHW or CHWN specs for lax.conv_general_dilated


def conv(x, w, stride, pad, groups, layout, first=False):
    lhs = "NCHW" if first else layout
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=(lhs, "OIHW", layout),
        feature_group_count=groups)


def bias_add(x, b, layout):
    shape = {"NCHW": (1, -1, 1, 1), "CHWN": (-1, 1, 1, 1),
             "NHWC": (1, 1, 1, -1)}[layout]
    return x + b.astype(x.dtype).reshape(shape)


def max_pool(x, k, s, layout):
    if layout == "NCHW":
        dims, strides = (1, 1, k, k), (1, 1, s, s)
    elif layout == "CHWN":
        dims, strides = (1, k, k, 1), (1, s, s, 1)
    else:  # NHWC
        dims, strides = (1, k, k, 1), (1, s, s, 1)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                             padding="VALID")


def lrn(x, nsize, alpha, beta, knorm, layout):
    ch_axis = {"NCHW": 1, "CHWN": 0, "NHWC": 3}[layout]
    lo = nsize // 2
    hi = nsize - 1 - lo
    c = x.shape[ch_axis]
    padw = [(0, 0)] * 4
    padw[ch_axis] = (lo, hi)
    sq = jnp.square(x)
    xp = jnp.pad(sq, padw)
    out = lax.slice_in_dim(xp, 0, c, axis=ch_axis)
    for i in range(1, nsize):
        out = out + lax.slice_in_dim(xp, i, i + c, axis=ch_axis)
    norm = out * (alpha / nsize) + knorm
    return x * lax.rsqrt(norm * lax.sqrt(norm))


def alexnet_convstack(params, x, layout):
    """conv1..pool5 exactly as the repo AlexNet config (227 input)."""
    h = conv(x, params["w1"], 4, 0, 1, layout, first=True)
    h = jax.nn.relu(bias_add(h, params["b1"], layout))
    h = max_pool(h, 3, 2, layout)
    h = lrn(h, 5, 0.001, 0.75, 1.0, layout)
    h = conv(h, params["w2"], 1, 2, 2, layout)
    h = jax.nn.relu(bias_add(h, params["b2"], layout))
    h = max_pool(h, 3, 2, layout)
    h = lrn(h, 5, 0.001, 0.75, 1.0, layout)
    h = conv(h, params["w3"], 1, 1, 1, layout)
    h = jax.nn.relu(bias_add(h, params["b3"], layout))
    h = conv(h, params["w4"], 1, 1, 2, layout)
    h = jax.nn.relu(bias_add(h, params["b4"], layout))
    h = conv(h, params["w5"], 1, 1, 2, layout)
    h = jax.nn.relu(bias_add(h, params["b5"], layout))
    h = max_pool(h, 3, 2, layout)
    if layout == "NCHW":
        flat = h.reshape(h.shape[0], -1)
    elif layout == "CHWN":  # (C, H, W, N) -> (N, CHW)
        flat = h.transpose(3, 0, 1, 2).reshape(h.shape[3], -1)
    else:  # NHWC: match NCHW flatten order for weight-shape parity
        flat = h.transpose(0, 3, 1, 2).reshape(h.shape[0], -1)
    return flat


def full_net(params, x, y, layout):
    flat = alexnet_convstack(params, x, layout)
    h = jax.nn.relu(flat @ params["w6"] + params["b6"])
    h = jax.nn.relu(h @ params["w7"] + params["b7"])
    logits = (h @ params["w8"] + params["b8"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def make_params(key, dtype):
    ks = jax.random.split(key, 16)
    p = {}

    def w(i, shape, scale=0.01):
        return (scale * jax.random.normal(ks[i], shape)).astype(dtype)

    p["w1"] = w(0, (96, 3, 11, 11))
    p["b1"] = jnp.zeros((96,), dtype)
    p["w2"] = w(1, (256, 48, 5, 5))
    p["b2"] = jnp.ones((256,), dtype)
    p["w3"] = w(2, (384, 256, 3, 3))
    p["b3"] = jnp.zeros((384,), dtype)
    p["w4"] = w(3, (384, 192, 3, 3))
    p["b4"] = jnp.ones((384,), dtype)
    p["w5"] = w(4, (256, 192, 3, 3))
    p["b5"] = jnp.ones((256,), dtype)
    p["w6"] = w(5, (9216, 4096))
    p["b6"] = jnp.ones((4096,), dtype)
    p["w7"] = w(6, (4096, 4096))
    p["b7"] = jnp.ones((4096,), dtype)
    p["w8"] = w(7, (4096, 1000))
    p["b8"] = jnp.zeros((1000,), dtype)
    return p


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    dtype = jnp.bfloat16
    key = jax.random.PRNGKey(0)
    params = make_params(key, dtype)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, 227, 227),
                          jnp.float32).astype(dtype)
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)

    def step(layout):
        def f(params, x):
            loss, grads = jax.value_and_grad(
                lambda p: full_net(p, x, y, layout))(params)
            # sgd-ish update so grads are consumed (matches real step shape)
            new = jax.tree.map(lambda w, g: w - 0.01 * g.astype(w.dtype),
                               params, grads)
            return loss, new
        return f

    layouts = sys.argv[2].split(",") if len(sys.argv) > 2 \
        else ["NCHW", "CHWN"]
    for layout in layouts:
        t = bench_op(step(layout), params, x, k1=2, k2=8, n=3)
        print(f"{layout}: {t:.2f} ms/step  ({batch / t * 1e3:.0f} imgs/s)")

    # forward-only comparison too (isolates conv fwd + pool + lrn)
    for layout in layouts:
        f = lambda p, xx: jnp.sum(  # noqa: E731
            alexnet_convstack(p, xx, layout).astype(jnp.float32))
        t = bench_op(f, params, x, k1=2, k2=8, n=3)
        print(f"{layout} fwd-only: {t:.2f} ms")

    # transpose probe: what does materializing a conv1-sized activation in
    # another layout cost inside a step? (bounds the pallas boundary toll)
    h1 = jax.random.normal(jax.random.PRNGKey(3), (batch, 96, 55, 55),
                           jnp.float32).astype(jnp.bfloat16)
    for perm, name in (((1, 2, 3, 0), "NCHW->CHWN"),
                       ((0, 2, 3, 1), "NCHW->NHWC")):
        f = lambda a: jnp.transpose(a, perm) * 2.0  # noqa: E731
        t = bench_op(f, h1, k1=4, k2=24)
        print(f"transpose {name} (96,55,55,b{batch}): {t:.3f} ms")
    f = lambda a: a * 2.0  # noqa: E731
    t = bench_op(f, h1, k1=4, k2=24)
    print(f"copy same-layout baseline:            {t:.3f} ms")


if __name__ == "__main__":
    main()
