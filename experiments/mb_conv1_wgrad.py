"""Microbench: AlexNet conv1 weight-grad strategies on TPU.

conv1: x (b,3,227,227) bf16, w (96,3,11,11), stride 4, pad 0 -> y (b,96,55,55).
The XLA default wgrad for a strided conv dilates dy (rate 4), wasting ~15/16
of MXU cycles on zeros.  Candidate: space-to-depth formulation (stride-1
inner conv -> dense wgrad).
"""
import time
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from cxxnet_tpu.ops.nn import conv2d, conv2d_s2d  # noqa: E402

B = 1024


def _sync(r):
    # D2H of one small leaf: block_until_ready is unreliable over the axon
    # tunnel; np.asarray forces a real round-trip
    leaf = jax.tree.leaves(r)[-1]
    np.asarray(jnp.ravel(leaf)[:1])


def timeit(f, *args, n=20):
    _sync(f(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    _sync(r)
    return (time.perf_counter() - t0) / n * 1e3


def main():
    rnd = np.random.RandomState(0)
    x = jnp.asarray(rnd.rand(B, 3, 227, 227), jnp.bfloat16)
    w = jnp.asarray(rnd.rand(96, 3, 11, 11), jnp.bfloat16)
    dy = jnp.asarray(rnd.rand(B, 96, 55, 55), jnp.bfloat16)

    # forward
    fwd = jax.jit(lambda x, w: conv2d(x, w, stride=4))
    print(f"fwd conv:            {timeit(fwd, x, w):7.2f} ms")
    fwd_s2d = jax.jit(lambda x, w: conv2d_s2d(x, w, stride=4))
    print(f"fwd s2d:             {timeit(fwd_s2d, x, w):7.2f} ms")

    # wgrad via vjp of each formulation
    def wg(conv):
        def f(x, w, dy):
            _, vjp = jax.vjp(lambda w: conv(x, w), w)
            return vjp(dy)[0]
        return jax.jit(f)

    print(f"wgrad default:       {timeit(wg(lambda x, w: conv2d(x, w, stride=4)), x, w, dy):7.2f} ms")
    print(f"wgrad s2d:           {timeit(wg(lambda x, w: conv2d_s2d(x, w, stride=4)), x, w, dy):7.2f} ms")

    # dgrad (input grad) both ways
    def dg(conv):
        def f(x, w, dy):
            _, vjp = jax.vjp(lambda x: conv(x, w), x)
            return vjp(dy)[0]
        return jax.jit(f)

    print(f"dgrad default:       {timeit(dg(lambda x, w: conv2d(x, w, stride=4)), x, w, dy):7.2f} ms")
    print(f"dgrad s2d:           {timeit(dg(lambda x, w: conv2d_s2d(x, w, stride=4)), x, w, dy):7.2f} ms")

    # full fwd+both grads fused (closer to what the step compiles)
    def full(conv):
        def f(x, w, dy):
            y, vjp = jax.vjp(lambda x, w: conv(x, w), x, w)
            dx, dw = vjp(dy)
            return y, dx, dw
        return jax.jit(f)

    print(f"fwd+bwd default:     {timeit(full(lambda x, w: conv2d(x, w, stride=4)), x, w, dy):7.2f} ms")
    print(f"fwd+bwd s2d:         {timeit(full(lambda x, w: conv2d_s2d(x, w, stride=4)), x, w, dy):7.2f} ms")
    # mixed: fwd+dgrad default, wgrad s2d
    def mixed(x, w, dy):
        y, vjp_x = jax.vjp(lambda x: conv2d(x, w, stride=4), x)
        dx = vjp_x(dy)[0]
        _, vjp_w = jax.vjp(lambda w: conv2d_s2d(x, w, stride=4), w)
        dw = vjp_w(dy)[0]
        return y, dx, dw
    print(f"fwd+bwd mixed(s2d wg):{timeit(jax.jit(mixed), x, w, dy):6.2f} ms")

    # analytic: 2*flops
    flops = 2.0 * B * 96 * 55 * 55 * 3 * 11 * 11
    print(f"one conv pass = {flops/1e9:.1f} GFLOP -> at 197 TFLOP/s = "
          f"{flops/197e12*1e3:.2f} ms")


if __name__ == "__main__":
    main()
