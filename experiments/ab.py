"""Same-session interleaved A/B bench (VERDICT r3 weak 1: chip-session
variance is ±1.5-2 ms, so only interleaved same-session comparisons at
matched thermal/scheduling state are meaningful).

Builds one trainer per config variant IN ONE PROCESS, shares the
device-resident synthetic data, then interleaves measurement repeats
round-robin.  Reports per-variant median ± spread and the median delta
vs the first (baseline) variant.

Usage:
  python experiments/ab.py [batch] [scan_len] [reps] VARIANT [VARIANT...]
  VARIANT := name[:key=val[,key=val...]]
e.g.
  python experiments/ab.py 1024 6 5 base s2d:input_s2d=1

CAUTION: engine options (pool_bwd, pool_relu_reorder, ...) are process-
global — a variant that sets one changes the default every LATER variant
builds with.  Set such options EXPLICITLY on every variant
(`a:...=0 b:...=1`), never by omission.
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    args = [a for a in sys.argv[1:]]
    model = "alexnet"
    if args and args[0].startswith("model="):
        model = args.pop(0).split("=", 1)[1]
    nums = []
    while args and args[0].replace(".", "").isdigit():
        nums.append(int(args[0]))
        args.pop(0)
    batch = nums[0] if len(nums) > 0 else 1024
    scan_len = nums[1] if len(nums) > 1 else 6
    reps = nums[2] if len(nums) > 2 else 5
    assert args, "need at least one variant"
    variants = []
    for a in args:
        name, _, kvs = a.partition(":")
        extra = [tuple(kv.split("=", 1)) for kv in kvs.split(",") if kv]
        variants.append((name, extra))

    from __graft_entry__ import ALEXNET_NET, _make_trainer
    from bench import (conv_flops_per_image, PEAK_FLOPS,
                       _trace_device_ms)

    if model == "alexnet":
        net_conf, shape = ALEXNET_NET, (3, 227, 227)
    else:
        from cxxnet_tpu.models import zoo
        net_conf = getattr(zoo, model)() + \
            "metric = error\neta = 0.01\nmomentum = 0.9\nsilent = 1\n"
        shape_line = [ln for ln in net_conf.splitlines()
                      if ln.strip().startswith("input_shape")][0]
        shape = tuple(int(x) for x in
                      shape_line.split("=", 1)[1].strip().split(","))

    kd, kl = jax.random.split(jax.random.PRNGKey(0))
    datas = jax.jit(lambda k: jax.random.uniform(
        k, (scan_len, batch, *shape), jnp.float32
    ).astype(jnp.bfloat16))(kd)
    labels = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1), 0, 1000).astype(jnp.float32))(kl)

    trainers, var_datas = {}, {}
    for name, extra in variants:
        t = _make_trainer(net_conf, batch, "tpu",
                          extra=[("dtype", "bfloat16"),
                                 ("eval_train", "0")] + list(extra))
        t.start_round(1)
        d = datas
        if t._s2d_args is not None:
            # the input-pipeline contract under input_s2d: batches arrive
            # s2d-shaped (host iterators emit them; synth data is
            # generated in that shape) — the device-side transform is a
            # measured-slow fallback, not the product path
            from cxxnet_tpu.ops.nn import s2d_staged_shape
            s, kh, kw, oh, ow, _, _ = t._s2d_args
            shp = (scan_len, batch) + s2d_staged_shape(3, s, kh, kw, oh, ow)
            d = jax.jit(lambda k: jax.random.uniform(
                k, shp, jnp.float32).astype(jnp.bfloat16))(kd)
        var_datas[name] = d
        c0 = time.perf_counter()
        try:
            np.asarray(t.update_many(d, labels))  # compile+warm
        except Exception as e:
            print(f"{name}: FAILED {str(e).splitlines()[0][:120]}",
                  file=sys.stderr, flush=True)
            del t
            var_datas.pop(name, None)  # free the staged batch's HBM
            continue
        print(f"{name}: compile+warm {time.perf_counter()-c0:.1f}s",
              file=sys.stderr, flush=True)
        trainers[name] = t

    times = {name: [] for name, _ in variants}
    dev_times = {name: [] for name, _ in variants}
    for r in range(reps):
        for name, _ in variants:
            if name not in trainers:
                continue
            t = trainers[name]
            t0 = time.perf_counter()
            losses = t.update_many(var_datas[name], labels)
            np.asarray(losses)
            times[name].append((time.perf_counter() - t0) / scan_len * 1e3)
    # device-time pass: wall over the tunnel carries +-10 ms dispatch
    # jitter, so the decisive number is the on-chip module time from a
    # trace (2 traced dispatches per variant, interleaved)
    for r in range(2):
        for name, _ in variants:
            if name not in trainers:
                continue
            t = trainers[name]
            tdir = f"/tmp/ab_prof/{name}_{r}"
            import os
            os.system(f"rm -rf {tdir}")
            jax.profiler.start_trace(tdir)
            np.asarray(t.update_many(var_datas[name], labels))
            jax.profiler.stop_trace()
            dev_times[name].append(_trace_device_ms(tdir) / scan_len)

    assert trainers, "all variants failed to compile"
    flops_fwd = conv_flops_per_image(next(iter(trainers.values())).net)
    dev = jax.devices()[0].device_kind
    peak = next((v for k, v in PEAK_FLOPS.items() if k in dev), 197e12)
    base_med = base_dev = None
    for name, _ in variants:
        if name not in trainers:
            continue
        ts = sorted(times[name])
        med = ts[len(ts) // 2]
        dts = sorted(dev_times[name])
        dev_ms = dts[0]
        mfu = 3.0 * flops_fwd * batch / (dev_ms / 1e3) / peak
        delta = "" if base_med is None else (
            f"  wallΔ {med - base_med:+.2f}  devΔ {dev_ms - base_dev:+.2f}")
        if base_med is None:
            base_med, base_dev = med, dev_ms
        print(f"{name:12s} wall median {med:6.2f} [{ts[0]:.2f}..{ts[-1]:.2f}]"
              f"  device {dev_ms:6.2f} ms/step ({dts[-1]:.2f})  "
              f"MFU(dev) {mfu*100:.1f}%{delta}",
              flush=True)


if __name__ == "__main__":
    main()
