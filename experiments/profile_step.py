"""Trace the AlexNet train step and print the per-op time breakdown.

Usage: python experiments/profile_step.py [batch] [config]
Writes the trace under /tmp/cxprof and parses the device plane of the
XSpace proto directly (tensorboard_plugin_profile is available but its
tool pipeline is heavier than needed).
"""
import glob
import os
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def run_traced(tracedir, batch=1024, scan_len=6, model="alexnet",
               extra=()):
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    if model == "alexnet":
        conf, shape = ALEXNET_NET, (3, 227, 227)
    else:
        from cxxnet_tpu.models import googlenet
        conf = googlenet() + "metric = error\neta = 0.01\nmomentum = 0.9\n" \
            "silent = 1\n"
        shape = (3, 224, 224)
    t = _make_trainer(conf, batch, "tpu",
                      extra=[("dtype", "bfloat16"),
                             ("eval_train", "0")] + list(extra))
    if t._s2d_args is not None:
        from cxxnet_tpu.ops.nn import s2d_staged_shape
        s, kh, kw, oh, ow, _, _ = t._s2d_args
        shape = s2d_staged_shape(shape[0], s, kh, kw, oh, ow)
    # generate on DEVICE (the tunneled host link + single host core must
    # not gate the profiled region)
    kd, kl = jax.random.split(jax.random.PRNGKey(0))
    datas = jax.jit(lambda k: jax.random.uniform(
        k, (scan_len, batch, *shape), jnp.float32).astype(jnp.bfloat16))(kd)
    labels = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1), 0, 1000).astype(jnp.float32))(kl)
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # compile+warm
    import time
    t0 = time.perf_counter()
    np.asarray(t.update_many(datas, labels))
    wall = (time.perf_counter() - t0) / scan_len * 1e3
    from bench import conv_flops_per_image, PEAK_FLOPS
    flops = conv_flops_per_image(t.net)
    dev = jax.devices()[0].device_kind
    peak = next((v for k, v in PEAK_FLOPS.items() if k in dev), 197e12)
    mfu = 3.0 * flops * (batch / (wall / 1e3)) / peak
    print(f"{model} b{batch}: wall {wall:.1f} ms/step, "
          f"{batch / (wall / 1e3):.0f} imgs/sec, fwd {flops/1e9:.2f} "
          f"GF/img, analytic MFU {mfu*100:.1f}%")
    jax.profiler.start_trace(tracedir)
    np.asarray(t.update_many(datas, labels))
    jax.profiler.stop_trace()
    return scan_len


def parse(tracedir, nsteps):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = glob.glob(os.path.join(tracedir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane under {tracedir}"
    xs = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        print(f"=== plane: {plane.name}")
        ev_names = plane.event_metadata
        tot = defaultdict(float)
        cnt = defaultdict(int)
        for line in plane.lines:
            if "XLA Ops" not in line.name and "Steps" not in line.name \
                    and "XLA Modules" not in line.name:
                continue
            for ev in line.events:
                name = ev_names[ev.metadata_id].name
                dur = ev.duration_ps / 1e9  # ms
                if "XLA Modules" in line.name:
                    print(f"  module {name}: {dur:.2f} ms total "
                          f"({dur/nsteps:.2f}/step)")
                elif "XLA Ops" in line.name:
                    tot[name] += dur
                    cnt[name] += 1
        if tot:
            print(f"  --- top ops (over {nsteps} steps, ms/step):")
            items = sorted(tot.items(), key=lambda kv: -kv[1])
            s = sum(tot.values())
            acc = 0.0
            for name, d in items[:40]:
                acc += d
                print(f"  {d/nsteps:8.3f}  {cnt[name]//nsteps:3d}x  "
                      f"{name[:100]}")
            print(f"  total device time: {s/nsteps:.2f} ms/step")


if __name__ == "__main__":
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    model = sys.argv[2] if len(sys.argv) > 2 else "alexnet"
    extra = [tuple(a.split("=", 1)) for a in sys.argv[3:]]
    tracedir = f"/tmp/cxprof_{model}_b{batch}"
    os.system(f"rm -rf {tracedir}")
    n = run_traced(tracedir, batch, model=model, extra=extra)
    parse(tracedir, n)
