"""Trace the AlexNet train step and print the per-op time breakdown.

Usage: python experiments/profile_step.py [batch] [config]
Writes the trace under /tmp/cxprof and parses the device plane of the
XSpace proto directly (tensorboard_plugin_profile is available but its
tool pipeline is heavier than needed).
"""
import glob
import os
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def run_traced(tracedir, batch=1024, scan_len=6):
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    t = _make_trainer(ALEXNET_NET, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0")])
    rnd = np.random.RandomState(0)
    datas = jnp.asarray(
        rnd.rand(scan_len, batch, 3, 227, 227).astype(np.float32)
    ).astype(jnp.bfloat16)
    labels = jnp.asarray(
        rnd.randint(0, 1000, (scan_len, batch, 1)).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # compile+warm
    jax.profiler.start_trace(tracedir)
    np.asarray(t.update_many(datas, labels))
    jax.profiler.stop_trace()
    return scan_len


def parse(tracedir, nsteps):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = glob.glob(os.path.join(tracedir, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane under {tracedir}"
    xs = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        print(f"=== plane: {plane.name}")
        ev_names = plane.event_metadata
        tot = defaultdict(float)
        cnt = defaultdict(int)
        for line in plane.lines:
            if "XLA Ops" not in line.name and "Steps" not in line.name \
                    and "XLA Modules" not in line.name:
                continue
            for ev in line.events:
                name = ev_names[ev.metadata_id].name
                dur = ev.duration_ps / 1e9  # ms
                if "XLA Modules" in line.name:
                    print(f"  module {name}: {dur:.2f} ms total "
                          f"({dur/nsteps:.2f}/step)")
                elif "XLA Ops" in line.name:
                    tot[name] += dur
                    cnt[name] += 1
        if tot:
            print(f"  --- top ops (over {nsteps} steps, ms/step):")
            items = sorted(tot.items(), key=lambda kv: -kv[1])
            s = sum(tot.values())
            acc = 0.0
            for name, d in items[:40]:
                acc += d
                print(f"  {d/nsteps:8.3f}  {cnt[name]//nsteps:3d}x  "
                      f"{name[:100]}")
            print(f"  total device time: {s/nsteps:.2f} ms/step")


if __name__ == "__main__":
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    tracedir = f"/tmp/cxprof_b{batch}"
    os.system(f"rm -rf {tracedir}")
    n = run_traced(tracedir, batch)
    parse(tracedir, n)
