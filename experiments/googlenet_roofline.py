"""Analytic MXU-tiling roofline for zoo models (GoogLeNet MFU argument).

For every conv/fullc in the graph, the MXU processes a matmul with
M = batch*oh*ow, K = cin/g*kh*kw, N = cout/g; the systolic array pads K
and N to 128 and M to 8, so the *achievable* FLOPs of a small conv are
model_flops * (K*N) / (K_pad * N_pad).  Summing padded-time over the
graph and adding the elementwise/pool HBM traffic at peak bandwidth
yields the best step time ANY schedule could reach — the honest ceiling
to compare measured MFU against.

Usage: python experiments/googlenet_roofline.py [googlenet|alexnet|resnetN] [batch]
"""
import sys

sys.path.insert(0, "/root/repo")

import numpy as np

PEAK_MACS = 197e12 / 2          # bf16 MACs/s on v5e
HBM_BW = 820e9                  # bytes/s


def pad(v, m):
    return -(-v // m) * m


def analyze(which="googlenet", batch=256):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from cxxnet_tpu.nnet.net import Network
    from cxxnet_tpu.nnet.netconfig import NetConfig
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.layers.conv import ConvolutionLayer, _PoolingBase
    from cxxnet_tpu.layers.fullc import FullConnectLayer
    from cxxnet_tpu.models import googlenet, alexnet, resnet

    if which == "googlenet":
        conf = googlenet()
    elif which.startswith("resnet"):
        conf = resnet(num_class=10, depth=int(which[6:]))
    else:
        conf = alexnet()
    cfg = NetConfig()
    cfg.configure(parse_config_string(conf))
    net = Network(cfg, batch)

    t_mxu = 0.0       # seconds, fwd only
    t_hbm = 0.0
    flops_model = 0.0
    rows = []
    for conn in net.connections:
        l = conn.layer
        out = net.node_shapes[conn.nindex_out[0]]
        inp = net.node_shapes[conn.nindex_in[0]]
        bytes_out = 2.0 * np.prod(out)
        if isinstance(l, ConvolutionLayer):
            n, co, oh, ow = out
            ci = inp[1]
            g = l.param.num_group
            kh, kw = l.param.kernel_height, l.param.kernel_width
            M, K, N = n * oh * ow, (ci // g) * kh * kw, co // g
            macs = g * M * K * N
            macs_pad = g * pad(M, 8) * pad(K, 128) * pad(N, 128)
            t = macs_pad / PEAK_MACS
            t_mxu += t
            flops_model += 2 * macs
            rows.append((conn.param_key, macs / macs_pad, t * 1e3))
        elif isinstance(l, FullConnectLayer):
            n = inp[0]
            K = int(np.prod(inp[1:]))
            N = l.param.num_hidden
            macs = n * K * N
            macs_pad = pad(n, 8) * pad(K, 128) * pad(N, 128)
            t_mxu += macs_pad / PEAK_MACS
            flops_model += 2 * macs
        else:
            # elementwise/pool/concat: one read + one write of the output
            t_hbm += (2.0 * np.prod(inp) if isinstance(l, _PoolingBase)
                      else bytes_out) / HBM_BW + bytes_out / HBM_BW
    # train step ~ 3x fwd MXU (fwd + dgrad + wgrad) and ~2.5x fwd HBM
    t_step = 3.0 * t_mxu + 2.5 * t_hbm
    mfu_ceiling = 3.0 * flops_model / (t_step * 2 * PEAK_MACS)
    print(f"{which} b{batch}: fwd model {flops_model/1e9/batch:.2f} GF/img")
    print(f"  MXU-padded fwd time {t_mxu*1e3:.2f} ms, elementwise/pool "
          f"HBM {t_hbm*1e3:.2f} ms")
    print(f"  ideal train step {t_step*1e3:.2f} ms -> MFU ceiling "
          f"{mfu_ceiling*100:.1f}% (tiling losses only, zero overhead)")
    worst = sorted(rows, key=lambda r: r[1])[:8]
    print("  worst-tiled convs (efficiency, padded fwd ms):")
    for name, eff, ms in worst:
        print(f"    {name:24s} {eff*100:5.1f}%  {ms:6.3f}")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "googlenet"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    analyze(which, batch)
