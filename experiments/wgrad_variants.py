"""Race three Pallas formulations of the conv1 wgrad on real geometry.

All take (H, W, C, N)-layout rows and accumulate dW (96, 432):
  A. loop55: one (96,nb)x(432,nb) lane-contraction dot per column
     (the shipped conv_wgrad_hwcn_pallas inner loop — measured slow)
  B. batchT: rank-3 batched dots over T-column chunks
  C. bigK: in-kernel transpose rows to (C, W, nb), lane-merge to
     (C, W*nb), one K=7040 dot per row

Usage: python experiments/wgrad_variants.py
"""
import functools
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from experiments.mb_util import bench_op

N_, CO, CB, OH, OW, KB = 1024, 96, 48, 55, 55, 3
WB = OH - 1 + KB
NB = 128
TAPS = KB * KB * CB  # 432


def specs():
    kw = {"memory_space": pltpu.VMEM}
    dy_spec = pl.BlockSpec((1, OW, CO, NB), lambda bn, r: (r, 0, 0, bn),
                           **kw)
    x_specs = [pl.BlockSpec((1, WB, CB, NB),
                            lambda bn, r, i=i: (jnp.minimum(r + i, WB - 1),
                                                0, 0, bn), **kw)
               for i in range(KB)]
    dw_spec = pl.BlockSpec((CO, TAPS), lambda bn, r: (0, 0), **kw)
    return dy_spec, x_specs, dw_spec


def call(kern, dy_t, xs_t):
    dy_spec, x_specs, dw_spec = specs()
    return pl.pallas_call(
        kern,
        grid=(N_ // NB, OH),
        in_specs=[dy_spec] + x_specs,
        out_specs=dw_spec,
        out_shape=jax.ShapeDtypeStruct((CO, TAPS), jnp.float32),
        scratch_shapes=[pltpu.VMEM((CO, TAPS), jnp.float32)],
    )(dy_t, xs_t, xs_t, xs_t)


def k_loop55(dy_ref, x0, x1, x2, dw_ref, acc):
    bn, r = pl.program_id(0), pl.program_id(1)

    @pl.when((bn == 0) & (r == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    dy_row = dy_ref[0]
    xs = [x0[0], x1[0], x2[0]]
    a = acc[...]
    for t in range(OW):
        cols = jnp.concatenate(
            [xs[dh][t + dw] for dh in range(KB) for dw in range(KB)],
            axis=0)
        a = a + lax.dot_general(dy_row[t], cols, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
    acc[...] = a

    @pl.when((bn == pl.num_programs(0) - 1) & (r == pl.num_programs(1) - 1))
    def _():
        dw_ref[...] = acc[...]


def k_batchT(dy_ref, x0, x1, x2, dw_ref, acc, *, T=11):
    bn, r = pl.program_id(0), pl.program_id(1)

    @pl.when((bn == 0) & (r == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    dy_row = dy_ref[0]                       # (OW, CO, NB)
    xs = [x0[0], x1[0], x2[0]]               # (WB, CB, NB)
    a = acc[...]
    for t0 in range(0, OW, T):
        dyc = dy_row[t0:t0 + T]              # (T, CO, NB)
        cols = jnp.concatenate(
            [xs[dh][t0 + dw:t0 + dw + T]
             for dh in range(KB) for dw in range(KB)], axis=1)
        # (T, 432, NB); batched contract over lanes
        part = lax.dot_general(dyc, cols, (((2,), (2,)), ((0,), (0,))),
                               preferred_element_type=jnp.float32)
        a = a + jnp.sum(part, axis=0)
    acc[...] = a

    @pl.when((bn == pl.num_programs(0) - 1) & (r == pl.num_programs(1) - 1))
    def _():
        dw_ref[...] = acc[...]


def k_bigK(dy_ref, x0, x1, x2, dw_ref, acc):
    bn, r = pl.program_id(0), pl.program_id(1)

    @pl.when((bn == 0) & (r == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    dy_row = dy_ref[0]                       # (OW, CO, NB)
    dy2 = jnp.transpose(dy_row, (1, 0, 2)).reshape(CO, OW * NB)
    xs = [x0[0], x1[0], x2[0]]
    xt = [jnp.transpose(v, (1, 0, 2)) for v in xs]   # (CB, WB, NB)
    cols = jnp.concatenate(
        [xt[dh][:, dw:dw + OW].reshape(CB, OW * NB)
         for dh in range(KB) for dw in range(KB)], axis=0)
    acc[...] += lax.dot_general(dy2, cols, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)

    @pl.when((bn == pl.num_programs(0) - 1) & (r == pl.num_programs(1) - 1))
    def _():
        dw_ref[...] = acc[...]


def main():
    key = jax.random.PRNGKey(0)
    dy_t = jax.random.normal(key, (OH, OW, CO, N_), jnp.float32
                             ).astype(jnp.bfloat16)
    xs_t = jax.random.normal(jax.random.PRNGKey(1), (WB, WB, CB, N_),
                             jnp.float32).astype(jnp.bfloat16)

    ref = None
    for name, kern in (("loop55", k_loop55),
                       ("batchT11", functools.partial(k_batchT, T=11)),
                       ("bigK", k_bigK)):
        try:
            f = jax.jit(lambda a, b, kern=kern: call(kern, a, b))
            r = f(dy_t, xs_t)
            r.block_until_ready()
            if ref is None:
                ref = np.asarray(r)
            else:
                err = np.abs(np.asarray(r) - ref).max() / (
                    np.abs(ref).max() + 1e-9)
                assert err < 2e-2, (name, err)
            t = bench_op(lambda a, b, kern=kern: call(kern, a, b),
                         dy_t, xs_t, k1=2, k2=10)
            print(f"{name:10s} {t:7.3f} ms")
        except Exception as e:
            print(f"{name:10s} FAIL {str(e).splitlines()[0][:110]}")



# bigK2: operands logically pre-transposed OUTSIDE the kernel to
# (OH, CO, OW, N) / (HB, CB, WB, N) — XLA can satisfy these as layout
# choices on the producer fusions — then ONE K=OW*NB dot per (row, block).
def k_bigK2(dy_ref, x0, x1, x2, dw_ref, acc):
    bn, r = pl.program_id(0), pl.program_id(1)

    @pl.when((bn == 0) & (r == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    dy2 = dy_ref[0].reshape(CO, OW * NB)          # lane-merge
    xs = [x0[0], x1[0], x2[0]]                    # (CB, WB, NB)
    cols = jnp.concatenate(
        [xs[dh][:, dw:dw + OW].reshape(CB, OW * NB)
         for dh in range(KB) for dw in range(KB)], axis=0)
    acc[...] += lax.dot_general(dy2, cols, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)

    @pl.when((bn == pl.num_programs(0) - 1) & (r == pl.num_programs(1) - 1))
    def _():
        dw_ref[...] = acc[...]


def call2(kern, dy_t2, xs_t2):
    kw = {"memory_space": pltpu.VMEM}
    dy_spec = pl.BlockSpec((1, CO, OW, NB), lambda bn, r: (r, 0, 0, bn),
                           **kw)
    x_specs = [pl.BlockSpec((1, CB, WB, NB),
                            lambda bn, r, i=i: (jnp.minimum(r + i, WB - 1),
                                                0, 0, bn), **kw)
               for i in range(KB)]
    dw_spec = pl.BlockSpec((CO, TAPS), lambda bn, r: (0, 0), **kw)
    return pl.pallas_call(
        kern,
        grid=(N_ // NB, OH),
        in_specs=[dy_spec] + x_specs,
        out_specs=dw_spec,
        out_shape=jax.ShapeDtypeStruct((CO, TAPS), jnp.float32),
        scratch_shapes=[pltpu.VMEM((CO, TAPS), jnp.float32)],
    )(dy_t2, xs_t2, xs_t2, xs_t2)


def main2():
    key = jax.random.PRNGKey(0)
    dy_t = jax.random.normal(key, (OH, OW, CO, N_), jnp.float32
                             ).astype(jnp.bfloat16)
    xs_t = jax.random.normal(jax.random.PRNGKey(1), (WB, WB, CB, N_),
                             jnp.float32).astype(jnp.bfloat16)

    def run2(a, b):
        # the logical transposes live INSIDE the benched fn so their cost
        # (or absorption) is measured
        return call2(k_bigK2, jnp.transpose(a, (0, 2, 1, 3)),
                     jnp.transpose(b, (0, 2, 1, 3)))

    r2 = jax.jit(run2)(dy_t, xs_t)
    r1 = jax.jit(lambda a, b: call(k_loop55, a, b))(dy_t, xs_t)
    err = np.abs(np.asarray(r2) - np.asarray(r1)).max() / (
        np.abs(np.asarray(r1)).max() + 1e-9)
    print("bigK2 rel err vs loop55:", err)
    t = bench_op(run2, dy_t, xs_t, k1=2, k2=10)
    print(f"bigK2 (incl transposes) {t:7.3f} ms")


if __name__ == "__main__":
    main()
    main2()


# rowT: T output rows per program — xs row re-reads amortized
# ((T+2)/T vs 3x) and 40 programs instead of 440.
def k_rowT(dy_ref, xm_ref, xh1_ref, xh2_ref, dw_ref, acc, *, T):
    bn, rb = pl.program_id(0), pl.program_id(1)

    @pl.when((bn == 0) & (rb == 0))
    def _():
        acc[...] = jnp.zeros_like(acc)

    a = acc[...]
    xm = xm_ref[...]          # (T, WB, CB, NB) rows rb*T .. rb*T+T-1
    h1 = xh1_ref[0]           # row rb*T+T
    h2 = xh2_ref[0]           # row rb*T+T+1
    for tr in range(T):
        dy_row = dy_ref[tr]
        rows = [xm[tr + i] if tr + i < T else (h1 if tr + i == T else h2)
                for i in range(KB)]
        for t in range(OW):
            cols = jnp.concatenate(
                [rows[dh][t + dw] for dh in range(KB) for dw in range(KB)],
                axis=0)
            a = a + lax.dot_general(dy_row[t], cols,
                                    (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    acc[...] = a

    @pl.when((bn == pl.num_programs(0) - 1) & (rb == pl.num_programs(1) - 1))
    def _():
        dw_ref[...] = acc[...]


def main3(T=11):
    kw = {"memory_space": pltpu.VMEM}
    key = jax.random.PRNGKey(0)
    dy_t = jax.random.normal(key, (OH, OW, CO, N_), jnp.float32
                             ).astype(jnp.bfloat16)
    xs_t = jax.random.normal(jax.random.PRNGKey(1), (WB, WB, CB, N_),
                             jnp.float32).astype(jnp.bfloat16)
    dy_spec = pl.BlockSpec((T, OW, CO, NB), lambda bn, rb: (rb, 0, 0, bn),
                           **kw)
    xm_spec = pl.BlockSpec((T, WB, CB, NB), lambda bn, rb: (rb, 0, 0, bn),
                           **kw)
    h_specs = [pl.BlockSpec(
        (1, WB, CB, NB),
        lambda bn, rb, i=i: (jnp.minimum(rb * T + T + i, WB - 1), 0, 0, bn),
        **kw) for i in range(2)]
    dw_spec = pl.BlockSpec((CO, TAPS), lambda bn, rb: (0, 0), **kw)

    def run(a, b):
        return pl.pallas_call(
            functools.partial(k_rowT, T=T),
            grid=(N_ // NB, OH // T),
            in_specs=[dy_spec, xm_spec] + h_specs,
            out_specs=dw_spec,
            out_shape=jax.ShapeDtypeStruct((CO, TAPS), jnp.float32),
            scratch_shapes=[pltpu.VMEM((CO, TAPS), jnp.float32)],
        )(a, b, b, b)

    r = jax.jit(run)(dy_t, xs_t)
    r1 = jax.jit(lambda a, b: call(k_loop55, a, b))(dy_t, xs_t)
    err = np.abs(np.asarray(r) - np.asarray(r1)).max() / (
        np.abs(np.asarray(r1)).max() + 1e-9)
    print("rowT rel err:", err)
    t = bench_op(run, dy_t, xs_t, k1=2, k2=10)
    print(f"rowT{T:02d} {t:7.3f} ms")
