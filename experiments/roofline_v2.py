"""Per-op fwd/dgrad/wgrad MXU roofline (VERDICT r3 item 2).

The round-3 roofline modeled the train step as ``3 x t_mxu(fwd)`` —
assuming dgrad and wgrad tile exactly like the forward.  They don't:

* **fwd**    — matmul M = n*oh*ow, K = (ci/g)*kh*kw, N = co/g per group;
* **dgrad**  — the transposed conv contracts over the OUTPUT channels.
  The honest ceiling is the stride-phase decomposition (s_h*s_w phase
  convs, each M = n*oh*ow, K = (co/g)*ceil(kh/s)*ceil(kw/s), N = ci/g:
  zero-free, reachable by a phase-split kernel).  XLA's actual lowering
  dilates dy with stride zeros and pays the full K = (co/g)*kh*kw at
  M = n*h*w — reported as ``xla est`` next to the ceiling;
* **wgrad**  — contracts over M = n*oh*ow with output (co/g,
  (ci/g)*kh*kw): M_pad = co/g -> 8, N_pad = (ci/g)*kh*kw -> 128,
  K = n*oh*ow -> 128.  Under ``fast_wgrad = s2d`` the strided small-cin
  convs instead run the dense stride-1 geometry (cin*s_h*s_w channels,
  ceil(k/s) kernel) — both geometries are printed for those convs.

Elementwise/pool/LRN ops are HBM floors (bytes moved at peak bandwidth,
assuming XLA fuses pure elementwise chains into neighbors — relu and
bias ride along with convs for free).  Optimizer traffic: ~20 B/param
(bf16 grad read, f32 master+momentum read/write, bf16 weight write).

Usage: python experiments/roofline_v2.py [alexnet|googlenet|vgg16] [batch]
"""
import sys

sys.path.insert(0, "/root/repo")

import numpy as np

PEAK_MACS = 197e12 / 2          # bf16 MACs/s on v5e
HBM_BW = 820e9                  # bytes/s
BF16 = 2


def pad(v, m):
    return -(-v // m) * m


def _padded(M, K, N):
    """Padded MAC count for one (M,K)x(K,N) matmul: the result tile is
    8 sublanes x 128 lanes, and either output dim may take either slot —
    a 48-channel output is 48/128 efficient in lanes but 48/48 in
    sublanes, so take the better orientation (XLA's layout assignment
    does)."""
    return min(pad(M, 8) * pad(N, 128), pad(N, 8) * pad(M, 128)) \
        * pad(K, 128)


def t_mm(g, M, K, N):
    """Padded-MXU time (s) for g parallel (M,K)x(K,N) matmuls."""
    return g * _padded(M, K, N) / PEAK_MACS


def eff(g, M, K, N):
    return (M * K * N) / _padded(M, K, N)


def analyze(which="alexnet", batch=1024):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from cxxnet_tpu.nnet.net import Network
    from cxxnet_tpu.nnet.netconfig import NetConfig
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.layers.conv import (ConvolutionLayer, LRNLayer,
                                        _PoolingBase)
    from cxxnet_tpu.layers.fullc import FullConnectLayer
    from cxxnet_tpu.models import googlenet, alexnet, vgg

    conf = {"alexnet": alexnet, "googlenet": googlenet,
            "vgg16": lambda: vgg(depth=16)}[which]()
    cfg = NetConfig()
    cfg.configure(parse_config_string(conf))
    net = Network(cfg, batch)

    rows = []           # (name, phase, eff, ceil_ms, note)
    t_total = 0.0       # ceiling step time
    t_hbm = 0.0
    flops_model = 0.0   # fwd-pass model flops (2*macs)
    n_params = 0

    for conn in net.connections:
        l = conn.layer
        out = net.node_shapes[conn.nindex_out[0]]
        inp = net.node_shapes[conn.nindex_in[0]]
        name = (conn.param_key.split("-", 1)[1] if conn.owns_params
                else l.type_names[0])
        if isinstance(l, ConvolutionLayer):
            n, co, oh, ow = out
            ci = inp[1]
            h, w = inp[2], inp[3]
            g = l.param.num_group
            kh, kw = l.param.kernel_height, l.param.kernel_width
            s = l.param.stride
            n_params += (ci // g) * kh * kw * co + co
            macs = g * (n * oh * ow) * ((ci // g) * kh * kw) * (co // g)
            flops_model += 2 * macs

            tf = t_mm(g, n * oh * ow, (ci // g) * kh * kw, co // g)
            rows.append((name, "fwd",
                         eff(g, n * oh * ow, (ci // g) * kh * kw, co // g),
                         tf * 1e3, ""))
            t_total += tf

            first = conn.nindex_in[0] == 0
            if not first:
                # dgrad ceiling: stride-phase decomposition (zero-free)
                kph, kpw = -(-kh // s), -(-kw // s)
                td = s * s * t_mm(g, n * oh * ow, (co // g) * kph * kpw,
                                  ci // g)
                # XLA's dilated-dy estimate for comparison
                td_xla = t_mm(g, n * h * w, (co // g) * kh * kw, ci // g)
                rows.append((name, "dgrad",
                             eff(g, n * oh * ow,
                                 (co // g) * kph * kpw, ci // g),
                             td * 1e3,
                             f"xla est {td_xla*1e3:.2f}"))
                t_total += td
            # wgrad: contraction over n*oh*ow
            tw = t_mm(g, co // g, n * oh * ow, (ci // g) * kh * kw)
            note = ""
            if s > 1 and ci <= 4 and g == 1:
                # fast_wgrad = s2d geometry (what actually runs)
                tw2 = t_mm(1, co, n * oh * ow, ci * s * s * kph_kpw(kh, s)
                           * kph_kpw(kw, s))
                note = f"s2d geom {tw2*1e3:.2f}"
            rows.append((name, "wgrad",
                         eff(g, co // g, n * oh * ow, (ci // g) * kh * kw),
                         tw * 1e3, note))
            t_total += tw
        elif isinstance(l, FullConnectLayer):
            n = inp[0]
            K = int(np.prod(inp[1:]))
            N = l.param.num_hidden
            n_params += K * N + N
            flops_model += 2 * n * K * N
            tf = t_mm(1, n, K, N)
            td = t_mm(1, n, N, K)
            tw = t_mm(1, K, n, N)
            rows.append((name, "fwd", eff(1, n, K, N), tf * 1e3, ""))
            rows.append((name, "dgrad", eff(1, n, N, K), td * 1e3, ""))
            rows.append((name, "wgrad", eff(1, K, n, N), tw * 1e3, ""))
            t_total += tf + td + tw
        elif isinstance(l, _PoolingBase):
            bx, by = BF16 * np.prod(inp), BF16 * np.prod(out)
            tf = (bx + by) / HBM_BW
            tb = (2 * bx + 2 * by) / HBM_BW  # read x,y,dy write dx
            rows.append((name, "fwd", 0.0, tf * 1e3, "hbm"))
            rows.append((name, "bwd", 0.0, tb * 1e3, "hbm"))
            t_hbm += tf + tb
        elif isinstance(l, LRNLayer):
            bx = BF16 * np.prod(inp)
            tf = 2 * bx / HBM_BW
            tb = 4 * bx / HBM_BW
            rows.append((name, "fwd", 0.0, tf * 1e3, "hbm"))
            rows.append((name, "bwd", 0.0, tb * 1e3, "hbm"))
            t_hbm += tf + tb
        # relu/bias/dropout/flatten: assumed fused into neighbors (free)

    t_opt = 20.0 * n_params / HBM_BW
    t_step = t_total + t_hbm + t_opt
    mfu = 3.0 * flops_model / (t_step * 2 * PEAK_MACS)
    print(f"{which} b{batch}: {n_params/1e6:.1f}M params, "
          f"{flops_model/1e9/batch:.2f} GF/img fwd")
    print(f"{'op':14s} {'phase':6s} {'MXUeff':>7s} {'ceil ms':>8s}  note")
    for name, phase, e, ms, note in rows:
        print(f"{name:14s} {phase:6s} {e*100:6.1f}% {ms:8.3f}  {note}")
    print(f"  matmul ceiling {t_total*1e3:.2f} ms, hbm {t_hbm*1e3:.2f} ms, "
          f"optimizer {t_opt*1e3:.2f} ms")
    print(f"  step ceiling {t_step*1e3:.2f} ms -> MFU ceiling "
          f"{mfu*100:.1f}% (3x-fwd model-flops convention)")


def analyze_transformer(d=2048, L=12, s=4096, b=4, heads=16, vocab=8192,
                        causal=True):
    """Per-phase MXU/HBM ceiling for the transformer LM flagship
    (VERDICT r5 item 4).  Matmul phases tile (M,K,N)-padded like the conv
    model; flash attention is modeled from its actual kernel matmuls
    (fwd QK^T + PV; bwd recomputes scores from the saved logsumexp, so
    hardware MACs are ~3.5x fwd — the r3 kernel profile's convention);
    layernorm/residual/embedding are HBM floors; optimizer traffic is
    adam's ~24 B/param."""
    dh = d // heads
    T = b * s
    rows = []
    t_mxu = t_hbm = 0.0
    flops_model = 0.0

    def mm(name, phase, g, M, K, N):
        nonlocal t_mxu
        t = t_mm(g, M, K, N)
        rows.append((name, phase, eff(g, M, K, N), t * 1e3, ""))
        t_mxu += t
        return t

    def hbm(name, phase, nbytes):
        nonlocal t_hbm
        t = nbytes / HBM_BW
        rows.append((name, phase, 0.0, t * 1e3, "hbm"))
        t_hbm += t

    # per-layer projections (x L)
    for nm, K, N in (("qkv", d, 3 * d), ("out_proj", d, d),
                     ("ffn1", d, 4 * d), ("ffn2", 4 * d, d)):
        mm(f"{nm} xL", "fwd", L, T, K, N)
        mm(f"{nm} xL", "dgrad", L, T, N, K)
        mm(f"{nm} xL", "wgrad", L, K, T, N)
        flops_model += 2 * L * T * K * N
    # flash attention: causal halves the score/PV work; bwd = dq + dkdv
    # kernels, each recomputing scores (r3 profile: ~3.5x fwd MACs total)
    causal_f = 0.5 if causal else 1.0
    attn_macs = 2 * b * heads * s * s * dh * causal_f  # QK^T + PV
    flops_model += 2 * attn_macs
    t_attn_f = attn_macs / PEAK_MACS / 0.55   # 55% = measured kernel eff
    t_attn_b = 3.5 * attn_macs / PEAK_MACS / 0.55 - t_attn_f
    rows.append(("flash xL", "fwd", 0.55, L * t_attn_f * 1e3,
                 "kernel eff 55%"))
    rows.append(("flash xL", "bwd", 0.55, L * t_attn_b * 1e3,
                 "recompute incl"))
    t_mxu += L * (t_attn_f + t_attn_b)
    # logits
    mm("logits", "fwd", 1, T, d, vocab)
    mm("logits", "dgrad", 1, T, vocab, d)
    mm("logits", "wgrad", 1, d, T, vocab)
    flops_model += 2 * T * d * vocab
    # softmax-xent over vocab: read logits f32-ish twice + write dlogits
    hbm("xent", "fwd+bwd", 3 * BF16 * T * vocab)
    # layernorms (2/L + final): fwd read+write, bwd read x,dy write dx
    hbm("layernorm", "fwd+bwd", (2 * L + 1) * 5 * BF16 * T * d)
    # residual adds: 2/L, fwd read2+write1, bwd free (identity)
    hbm("residual", "fwd+bwd", 2 * L * 3 * BF16 * T * d)
    # embedding gather + scatter-add bwd
    hbm("embed", "fwd+bwd", 4 * BF16 * T * d)

    n_params = L * (4 * d * d + 2 * d * 4 * d) + vocab * d + s * d
    t_opt = 24.0 * n_params / HBM_BW
    t_step = t_mxu + t_hbm + t_opt
    mfu = 3.0 * flops_model / (t_step * 2 * PEAK_MACS)
    tok_s = T / t_step
    print(f"transformer d{d} L{L} s{s} b{b} h{heads} v{vocab}: "
          f"{n_params/1e6:.1f}M params")
    print(f"{'op':12s} {'phase':8s} {'MXUeff':>7s} {'ceil ms':>8s}  note")
    for name, phase, e, ms, note in rows:
        print(f"{name:12s} {phase:8s} {e*100:6.1f}% {ms:8.3f}  {note}")
    print(f"  matmul ceiling {t_mxu*1e3:.2f} ms, hbm {t_hbm*1e3:.2f} ms, "
          f"optimizer {t_opt*1e3:.2f} ms")
    print(f"  step ceiling {t_step*1e3:.2f} ms -> {tok_s/1e3:.1f}k tok/s, "
          f"MFU ceiling {mfu*100:.1f}% (3x-fwd model-flops convention)")


def kph_kpw(k, s):
    return -(-k // s)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "alexnet"
    if which == "transformer":
        kv = dict(kv.split("=") for kv in sys.argv[2:])
        analyze_transformer(**{k: int(v) for k, v in kv.items()})
    else:
        batch = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
        analyze(which, batch)
