"""Record convergence artifacts for the parity configs (BASELINE.md).

Usage:
  python experiments/convergence.py mnist      # MLP + LeNet, CPU, synthetic
  python experiments/convergence.py imagenet   # AlexNet loss curve, TPU
  python experiments/convergence.py googlenet  # GoogLeNet loss curve, TPU
  python experiments/convergence.py dist       # 2-process DP, CPU

Each subcommand appends one JSON line to CONVERGENCE.jsonl at the repo
root: {"config", "setting", "metric", "values", "date"}.
"""
import json
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "CONVERGENCE.jsonl")


def record(config, setting, metric, values):
    line = {"config": config, "setting": setting, "metric": metric,
            "values": values,
            "date": time.strftime("%Y-%m-%d")}
    with open(OUT, "a") as f:
        f.write(json.dumps(line) + "\n")
    print("recorded:", json.dumps(line))


def _parse_metric_lines(stderr_text, name):
    """[round]\t...name:value  ->  {round: value}"""
    out = {}
    for line in stderr_text.splitlines():
        m = re.match(r"^\[(\d+)\]", line)
        if not m:
            continue
        v = re.search(re.escape(name) + r":([0-9.eE+-]+)", line)
        if v:
            out[int(m.group(1))] = float(v.group(1))
    return out


def run_mnist():
    """MNIST MLP + LeNet on the synthetic generator (no network egress in
    this environment; reference reports ~98% on real MNIST,
    example/MNIST/README.md:108)."""
    work = tempfile.mkdtemp()
    subprocess.run([sys.executable,
                    os.path.join(ROOT, "tools", "make_synth_mnist.py"),
                    "--out", os.path.join(work, "data"),
                    "--train", "6000", "--test", "1000"],
                   check=True, cwd=work)
    for conf, tag in (("MNIST.conf", "mnist-mlp"),
                      ("LeNet.conf", "mnist-lenet")):
        env = dict(os.environ)
        env["PYTHONPATH"] = ROOT + ":" + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu",
             os.path.join(ROOT, "example", "MNIST", conf),
             "num_round=6", "max_round=6", "dev=cpu",
             f"model_dir={work}/m_{tag}", "save_model=0"],
            cwd=work, env=env, capture_output=True, text=True, timeout=3600)
        assert p.returncode == 0, p.stderr[-2000:]
        errs = _parse_metric_lines(p.stderr, "test-error")
        record(tag, "synthetic MNIST 6k/1k, 6 rounds, CPU",
               "test-error by round", errs)


def _loss_curve(net_conf, batch, steps, nclass, shape, extra=()):
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    t = _make_trainer(net_conf, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("silent", "1"), *extra])
    rnd = np.random.RandomState(0)
    # learnable synthetic data: per-class low-res spatial prototype
    # (8x8 per channel, nearest-upsampled), centered, + noise.  The fixed
    # k-step set is staged on device ONCE and re-dispatched (memorization
    # curve) — the tunneled host->device link (~40 MB/s) cannot stream
    # fresh ImageNet-sized batches, and a repeating-set loss curve
    # demonstrates the optimizer path at full model scale just as well.
    k = 10  # scan length per dispatch
    protos = rnd.rand(nclass, shape[0], 8, 8).astype(np.float32)
    ry, rx = -(-shape[1] // 8), -(-shape[2] // 8)
    labels = rnd.randint(0, nclass, (k, batch))
    pat = protos[labels].repeat(ry, axis=3).repeat(rx, axis=4)[
        :, :, :, :shape[1], :shape[2]]
    data = ((pat - 0.5) * 2
            + rnd.rand(k, batch, *shape).astype(np.float32) * 0.25)
    datas = jnp.asarray(data, jnp.bfloat16)
    labs = jnp.asarray(labels[..., None], jnp.float32)
    curves = []
    for it in range(steps // k):
        losses = np.asarray(t.update_many(datas, labs))
        curves.extend(float(x) for x in losses)
    return curves


# The reference's eta=0.01 is tuned for real-ImageNet statistics; the
# synthetic constant-block prototypes carry far more energy per conv
# window and diverge at that rate (measured: loss spikes to ~11 in the
# first rounds, then collapses into a dead-relu state pinned at
# ln(nclass)).  The curves are recorded at the stable 0.002.


def run_imagenet():
    from __graft_entry__ import ALEXNET_NET
    curve = _loss_curve(
        ALEXNET_NET.replace("eta = 0.01", "eta = 0.004"),
        batch=256, steps=1600, nclass=1000, shape=(3, 227, 227))
    record("imagenet-alexnet",
           "synthetic 1000-class (8x8 spatial prototypes + noise), fixed "
           "2560-sample set, b256, eta 0.004, TPU v5e, bf16",
           "softmax loss at steps [1, 400, 800, 1200, 1600]",
           {s: round(curve[s - 1], 4)
            for s in (1, 400, 800, 1200, 1600)})
    # a clear, sustained descent below ln(1000)=6.9078 — NOT the dead-relu
    # plateau pinned there (the init-inflated curve[0] alone would pass a
    # relative check); best observed 6.8034, so gate just above it
    assert curve[-1] < 6.81 and curve[-1] == min(
        curve[s] for s in (0, 399, 799, 1199, 1599)), \
        (curve[0], curve[-1])


def run_googlenet():
    from cxxnet_tpu.models import googlenet
    curve = _loss_curve(
        googlenet() + "metric = error\nrandom_type = xavier\n"
        "eta = 0.002\nmomentum = 0.9\n",
        batch=128, steps=600, nclass=1000, shape=(3, 224, 224))
    record("imagenet-googlenet",
           "synthetic 1000-class (8x8 spatial prototypes + noise), fixed "
           "1280-sample set, b128, eta 0.002, TPU v5e, bf16",
           "loss (main + 0.3*aux heads) at steps [1, 200, 400, 600]",
           {s: round(curve[s - 1], 4) for s in (1, 200, 400, 600)})
    assert curve[-1] < curve[1], (curve[0], curve[-1])


def run_dist():
    p = subprocess.run(
        [sys.executable, "-m", "pytest",
         os.path.join(ROOT, "tests", "test_distributed.py"), "-x", "-q",
         "-s"],
        capture_output=True, text=True, cwd=ROOT, timeout=1800)
    assert p.returncode == 0, p.stdout[-2000:]
    record("mnist-dp-2proc",
           "two-process CPU data parallel (tests/test_distributed.py): "
           "bit-identical replica checkpoints + identical metric lines, "
           "incl. kill-and-continue resume",
           "suite", "passed")


if __name__ == "__main__":
    {"mnist": run_mnist, "imagenet": run_imagenet,
     "googlenet": run_googlenet, "dist": run_dist}[sys.argv[1]]()
