"""Record convergence artifacts for the parity configs (BASELINE.md).

Usage:
  python experiments/convergence.py mnist      # MLP + LeNet, CPU, synthetic
  python experiments/convergence.py imagenet   # AlexNet loss curve, TPU
  python experiments/convergence.py googlenet  # GoogLeNet loss curve, TPU
  python experiments/convergence.py dist       # 2-process DP, CPU

Each subcommand appends one JSON line to CONVERGENCE.jsonl at the repo
root: {"config", "setting", "metric", "values", "date"}.
"""
import json
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "CONVERGENCE.jsonl")


def record(config, setting, metric, values):
    line = {"config": config, "setting": setting, "metric": metric,
            "values": values,
            "date": time.strftime("%Y-%m-%d")}
    with open(OUT, "a") as f:
        f.write(json.dumps(line) + "\n")
    print("recorded:", json.dumps(line))


def _parse_metric_lines(stderr_text, name):
    """[round]\t...name:value  ->  {round: value}"""
    out = {}
    for line in stderr_text.splitlines():
        m = re.match(r"^\[(\d+)\]", line)
        if not m:
            continue
        v = re.search(re.escape(name) + r":([0-9.eE+-]+)", line)
        if v:
            out[int(m.group(1))] = float(v.group(1))
    return out


def run_mnist():
    """MNIST MLP + LeNet on the synthetic generator (no network egress in
    this environment; reference reports ~98% on real MNIST,
    example/MNIST/README.md:108)."""
    work = tempfile.mkdtemp()
    subprocess.run([sys.executable,
                    os.path.join(ROOT, "tools", "make_synth_mnist.py"),
                    "--out", os.path.join(work, "data"),
                    "--train", "6000", "--test", "1000"],
                   check=True, cwd=work)
    for conf, tag in (("MNIST.conf", "mnist-mlp"),
                      ("LeNet.conf", "mnist-lenet")):
        env = dict(os.environ)
        env["PYTHONPATH"] = ROOT + ":" + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu",
             os.path.join(ROOT, "example", "MNIST", conf),
             "num_round=6", "max_round=6", "dev=cpu",
             f"model_dir={work}/m_{tag}", "save_model=0"],
            cwd=work, env=env, capture_output=True, text=True, timeout=3600)
        assert p.returncode == 0, p.stderr[-2000:]
        errs = _parse_metric_lines(p.stderr, "test-error")
        record(tag, "synthetic MNIST 6k/1k, 6 rounds, CPU",
               "test-error by round", errs)


def _loss_curve(net_conf, batch, steps, nclass, shape, extra=(),
                nsamp=512, stop_below=None):
    import jax
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    t = _make_trainer(net_conf, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("silent", "1"), *extra])
    # learnable synthetic data: per-class low-res spatial prototype
    # (8x8 per channel, nearest-upsampled), centered, + noise - generated
    # ON DEVICE (the tunneled host->device link cannot stream real
    # ImageNet; memorizing a fixed small set exercises the full
    # model/optimizer path, the reference's observable-convergence bar
    # scaled to this environment).
    assert nsamp % batch == 0
    k = nsamp // batch
    kd, kl = jax.random.split(jax.random.PRNGKey(0))

    @jax.jit
    def gen(kd, kl):
        labels = jax.random.randint(kl, (k, batch), 0, nclass)
        protos = jax.random.uniform(kd, (nclass, shape[0], 8, 8))
        ry, rx = -(-shape[1] // 8), -(-shape[2] // 8)
        pat = jnp.repeat(jnp.repeat(protos[labels], ry, axis=3), rx,
                         axis=4)[:, :, :, :shape[1], :shape[2]]
        noise = jax.random.uniform(
            jax.random.fold_in(kd, 1), (k, batch) + shape) * 0.25
        return (((pat - 0.5) * 2 + noise).astype(jnp.bfloat16),
                labels[..., None].astype(jnp.float32))

    datas, labs = gen(kd, kl)
    curves = []
    for it in range(steps // k):
        losses = np.asarray(t.update_many(datas, labs))
        curves.extend(float(x) for x in losses)
        if stop_below is not None and curves[-1] < stop_below:
            break
    return curves


def run_imagenet():
    # round-3 recipe (experiments/memorize.py): the flagship config at its
    # OWN eta (0.01) memorizes a fixed 512-sample set from ln(1000)=6.9078
    # to < 0.3 within ~500 steps - the end-to-end correctness evidence
    # round 2 lacked (its 2560-sample/eta-0.004 curves sat near chance).
    from __graft_entry__ import ALEXNET_NET
    curve = _loss_curve(ALEXNET_NET, batch=128, steps=3000, nclass=1000,
                        shape=(3, 227, 227), stop_below=0.25)
    marks = sorted(set([1, 100, 200, 300, 400, len(curve)]))
    record("imagenet-alexnet",
           "synthetic 1000-class (8x8 spatial prototypes + noise), fixed "
           "512-sample set, b128, eta 0.01 (flagship config), TPU v5e, "
           "bf16 + f32 masters",
           "softmax loss by step (memorization)",
           {s: round(curve[s - 1], 4) for s in marks if s <= len(curve)})
    assert curve[-1] < 0.5, ("AlexNet failed to memorize", curve[-1])


def run_googlenet():
    from cxxnet_tpu.models import googlenet
    curve = _loss_curve(
        googlenet() + "metric = error\nrandom_type = xavier\n"
        "eta = 0.01\nmomentum = 0.9\n",
        batch=128, steps=3000, nclass=1000, shape=(3, 224, 224),
        stop_below=0.4)
    marks = sorted(set([1, 200, 400, 800, 1200, len(curve)]))
    record("imagenet-googlenet",
           "synthetic 1000-class (8x8 spatial prototypes + noise), fixed "
           "512-sample set, b128, eta 0.01, TPU v5e, bf16",
           "loss (main + 0.3*aux heads) by step (memorization)",
           {s: round(curve[s - 1], 4) for s in marks if s <= len(curve)})
    # the three heads bound the floor near 1.6x the main head; require a
    # decisive collapse from chance (~9.2 with aux heads)
    assert curve[-1] < 1.5, ("GoogLeNet failed to memorize", curve[-1])


def run_dist():
    p = subprocess.run(
        [sys.executable, "-m", "pytest",
         os.path.join(ROOT, "tests", "test_distributed.py"), "-x", "-q",
         "-s"],
        capture_output=True, text=True, cwd=ROOT, timeout=1800)
    assert p.returncode == 0, p.stdout[-2000:]
    record("mnist-dp-2proc",
           "two-process CPU data parallel (tests/test_distributed.py): "
           "bit-identical replica checkpoints + identical metric lines, "
           "incl. kill-and-continue resume",
           "suite", "passed")


if __name__ == "__main__":
    {"mnist": run_mnist, "imagenet": run_imagenet,
     "googlenet": run_googlenet, "dist": run_dist}[sys.argv[1]]()
