"""Dense vs sorted MoE dispatch: step time + peak memory at scale.

VERDICT r3 #9 acceptance: a measured win at t >= 8k, e >= 16.
Usage: python experiments/moe_bench.py [tokens] [experts] [dim] [hidden]
"""
import sys

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from cxxnet_tpu.layers.base import ForwardContext
from cxxnet_tpu.layers.registry import create_layer
from experiments.mb_util import bench_op


def make(dispatch, e, h, cf=1.25):
    l = create_layer("moe")
    l.set_param("num_expert", str(e))
    l.set_param("nhidden", str(h))
    l.set_param("capacity_factor", str(cf))
    l.set_param("moe_dispatch", dispatch)
    l.set_param("init_sigma", "0.05")
    return l


def main():
    t = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    e = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    d = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    h = int(sys.argv[4]) if len(sys.argv) > 4 else 2048
    b, s = 8, t // 8
    x = jax.random.normal(jax.random.PRNGKey(0), (b, 1, s, d),
                          jnp.float32).astype(jnp.bfloat16)

    for dispatch in ("dense", "sorted"):
        layer = make(dispatch, e, h)
        layer.infer_shapes([(b, 1, s, d)])
        params = layer.init_params(jax.random.PRNGKey(1), [(b, 1, s, d)],
                                   jnp.bfloat16)

        def step(p, xx):
            def loss(p):
                ctx = ForwardContext(train=True, loss_scale=1.0 / b)
                (out,), _ = layer.forward(p, {}, [xx], ctx)
                return (out.astype(jnp.float32) ** 2).sum() + ctx.losses[0]
            l, g = jax.value_and_grad(loss)(p)
            return l, g

        compiled = jax.jit(step).lower(params, x).compile()
        mem = compiled.memory_analysis()
        peak = getattr(mem, "temp_size_in_bytes", 0)
        ms = bench_op(step, params, x, k1=2, k2=10)
        print(f"{dispatch:6s} t={t} e={e} cap={layer._capacity(t)}: "
              f"{ms:7.2f} ms/step  temp {peak/1e6:7.1f} MB")


if __name__ == "__main__":
    main()
