"""Flash-attention kernel autotune on TPU (VERDICT r5 #4).

The d2048 flagship profile shows the flash kernels at ~20% of peak-MAC
efficiency (fwd 7.1 ms/layer vs 1.4 ms ideal at dh=64): the kernel is
DMA-bound (k/v blocks re-fetched per q-block) and VPU-bound (softmax work
scales with h*s^2, so 32 small heads double it vs 16 MXU-wide ones).

Sweeps (bq, bk) block sizes and grid dimension_semantics for both head
geometries of d2048 (h32/dh64 and h16/dh128), printing measured ms and
efficiency vs the causal-MAC ideal.  Winners become the defaults in
ops/pallas_kernels.py (_fa_blocks).

Usage: python experiments/fa_tune.py [s_len] [batch]
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cxxnet_tpu.ops import pallas_kernels as pk  # noqa: E402

PEAK_MACS = 197e12 / 2


def ideal_ms(b, h, s, d, causal=True, bwd=False):
    macs = 2 * b * h * s * s * d * (0.5 if causal else 1.0)
    if bwd:
        macs *= 2.5  # dq (2 mm) + dkdv (3 mm) vs fwd's 2, causal-halved
    return macs / PEAK_MACS * 1e3


ITERS = 10


def measure(fn, *args):
    """Device time per iteration from a profiler trace: the tunnel's
    ~100 ms dispatch round trip swamps wall timings of ms-scale kernels,
    so fn runs ITERS sequential iterations in ONE dispatch and the
    on-chip XLA-module time is read from the trace."""
    import shutil
    import tempfile
    from bench import _trace_device_ms
    np.asarray(fn(*args))  # compile + warm
    tdir = tempfile.mkdtemp(prefix="fa_tune_prof")
    try:
        jax.profiler.start_trace(tdir)
        try:
            np.asarray(fn(*args))
        finally:
            jax.profiler.stop_trace()
        return _trace_device_ms(tdir) / ITERS
    finally:
        shutil.rmtree(tdir, ignore_errors=True)


def vmem_est(bq, bk, d):
    """Rough VMEM bytes for the fwd kernel's resident set."""
    scores = bq * bk * 4 * 2          # s (f32) + p
    blocks = (bq * d + 2 * bk * d) * 2
    acc = bq * d * 4
    return scores + blocks + acc


def main():
    s_len = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    b = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    assert pk._on_tpu(), "run on TPU"

    geoms = [(32, 64), (16, 128)]
    blockset = [(512, 1024), (1024, 512), (1024, 1024), (512, 512),
                (2048, 512), (256, 2048), (1024, 2048), (2048, 1024)]
    # dimension_semantics (parallel,parallel,arbitrary) was swept here and
    # measured identical times to unannotated on v5e; the annotation was
    # dropped from the kernels (a PARALLEL q-block dim would corrupt the
    # fwd kernel's shared lse block under a megacore split)

    base_blocks = pk._fa_blocks
    for h, d in geoms:
        key = jax.random.PRNGKey(0)
        kq, kk, kv, kg = jax.random.split(key, 4)
        q = jax.random.normal(kq, (b, h, s_len, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, h, s_len, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, h, s_len, d), jnp.bfloat16)
        g = jax.random.normal(kg, (b, h, s_len, d), jnp.bfloat16)
        i_f = ideal_ms(b, h, s_len, d)
        i_b = ideal_ms(b, h, s_len, d, bwd=True)

        # ITERS sequential kernel invocations per dispatch (output feeds
        # the next q, so XLA cannot CSE or parallelize them)
        def fwd(q, k, v):
            def body(_, qc):
                return pk.flash_attention(qc, k, v, True)
            return jax.lax.fori_loop(0, ITERS, body, q).sum() \
                .astype(jnp.float32)
        fwd = jax.jit(fwd)

        def train(q, k, v, g):
            def body(_, qc):
                out, vjp = jax.vjp(
                    lambda q, k, v: pk.flash_attention(q, k, v, True),
                    qc, k, v)
                dq, dk, dv = vjp(g)
                # consume ALL cotangents: an unused dk/dv would let XLA
                # dead-code-eliminate the dkv kernel entirely
                return (dq + out * 0.5 + dk * 0.25
                        + dv * 0.125).astype(qc.dtype)
            return jax.lax.fori_loop(0, ITERS, body, q).sum() \
                .astype(jnp.float32)
        trainf = jax.jit(train)

        for bq, bk in blockset:
            if bq > s_len or bk > s_len:
                continue
            if vmem_est(bq, bk, d) > 14 * 2 ** 20:
                print(f"h{h} d{d} bq{bq} bk{bk}: skip (vmem est "
                      f"{vmem_est(bq, bk, d) / 2**20:.1f} MB)")
                continue
            if True:
                pk._fa_blocks = lambda s, d=64, _bq=bq, _bk=bk: (_bq, _bk)
                try:
                    jax.clear_caches()
                    t_f = measure(fwd, q, k, v)
                    t_t = measure(trainf, q, k, v, g) - t_f
                    print(f"h{h} d{d} bq{bq:5d} bk{bk:5d}: "
                          f"fwd {t_f:7.2f} ms (eff {i_f / t_f * 100:4.1f}%)"
                          f"  bwd {t_t:7.2f} ms (eff {i_b / t_t * 100:4.1f}%)",
                          flush=True)
                except Exception as e:
                    print(f"h{h} d{d} bq{bq} bk{bk}: FAILED "
                          f"{str(e).splitlines()[0][:90]}", flush=True)
        pk._fa_blocks = base_blocks


if __name__ == "__main__":
    main()
