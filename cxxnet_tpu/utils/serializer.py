"""Checkpoint serialization: single-file model format.

Reference format (survey §5.4): ``net_type`` int + NetConfig::SaveNet
(structure) + epoch counter + concatenated per-layer weight blobs
(``nnet_impl-inl.hpp:82-87``), with ``reserved[]`` padding for forward
compatibility.  Our format keeps the same *content* in a self-describing
container: one ``.model`` file = numpy ``.npz`` holding a JSON header
(format version, net structure dict, epoch, dtype) plus every tensor under a
flattened ``group/key`` name.  Forward compatibility comes from the JSON
header rather than reserved struct bytes.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, Tuple

import numpy as np

FORMAT_VERSION = 1


def _flatten(tree: Dict, prefix: str = "",
             dtypes: Dict[str, str] = None) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/", dtypes))
        else:
            a = np.asarray(v)
            if a.dtype.kind not in "fiub":
                # numpy's npz container cannot round-trip ml_dtypes
                # extension types (bfloat16 reloads as void "|V2"): store
                # as float32 (exact — bf16 is a truncated f32) and record
                # the original dtype so load restores it
                name = a.dtype.name
                a = a.astype(np.float32)
                if dtypes is not None:
                    dtypes[key] = name
            out[key] = a
    return out


def _unflatten(flat: Dict[str, np.ndarray],
               dtypes: Dict[str, str] = None) -> Dict:
    tree: Dict = {}
    for key, v in flat.items():
        if dtypes and key in dtypes:
            import jax.numpy as jnp
            v = jnp.asarray(v, dtype=dtypes[key])
        parts = key.split("/")
        d = tree
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return tree


def save_model(path: str, *, net_structure: dict, epoch: int,
               params: Dict, buffers: Dict, opt_state: Dict = None,
               extra_meta: Dict = None) -> None:
    dtypes: Dict[str, str] = {}
    arrays: Dict[str, np.ndarray] = {}
    arrays.update(_flatten({"params": params}, dtypes=dtypes))
    arrays.update(_flatten({"buffers": buffers}, dtypes=dtypes))
    if opt_state is not None:
        arrays.update(_flatten({"opt": opt_state}, dtypes=dtypes))
    header = {
        "format_version": FORMAT_VERSION,
        "net": net_structure,
        "epoch": int(epoch),
        "has_opt_state": opt_state is not None,
        "dtypes": dtypes,
        "extra": extra_meta or {},
    }
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **arrays)


def load_model(path: str) -> Tuple[dict, Dict, Dict, Dict]:
    """Return (header, params, buffers, opt_state_or_None)."""
    with np.load(path, allow_pickle=False) as z:
        header = json.loads(bytes(z["__header__"]).decode("utf-8"))
        flat = {k: z[k] for k in z.files if k != "__header__"}
    tree = _unflatten(flat, header.get("dtypes"))
    params = tree.get("params", {})
    buffers = tree.get("buffers", {})
    opt = tree.get("opt") if header.get("has_opt_state") else None
    return header, params, buffers, opt
