"""Checkpoint serialization: single-file model format.

Reference format (survey §5.4): ``net_type`` int + NetConfig::SaveNet
(structure) + epoch counter + concatenated per-layer weight blobs
(``nnet_impl-inl.hpp:82-87``), with ``reserved[]`` padding for forward
compatibility.  Our format keeps the same *content* in a self-describing
container: one ``.model`` file = numpy ``.npz`` holding a JSON header
(format version, net structure dict, epoch, dtype) plus every tensor under a
flattened ``group/key`` name.  Forward compatibility comes from the JSON
header rather than reserved struct bytes.
"""

from __future__ import annotations

import io
import json
import os
from typing import Any, Dict, Tuple

import numpy as np

FORMAT_VERSION = 1


def _flatten(tree: Dict, prefix: str = "",
             dtypes: Dict[str, str] = None) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/", dtypes))
        else:
            a = np.asarray(v)
            if a.dtype.kind not in "fiub":
                # numpy's npz container cannot round-trip ml_dtypes
                # extension types (bfloat16 reloads as void "|V2"): store
                # as float32 (exact — bf16 is a truncated f32) and record
                # the original dtype so load restores it
                name = a.dtype.name
                a = a.astype(np.float32)
                if dtypes is not None:
                    dtypes[key] = name
            out[key] = a
    return out


def _unflatten(flat: Dict[str, np.ndarray],
               dtypes: Dict[str, str] = None) -> Dict:
    tree: Dict = {}
    for key, v in flat.items():
        if dtypes and key in dtypes:
            import jax.numpy as jnp
            v = jnp.asarray(v, dtype=dtypes[key])
        parts = key.split("/")
        d = tree
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return tree


def save_model(path: str, *, net_structure: dict, epoch: int,
               params: Dict, buffers: Dict, opt_state: Dict = None,
               extra_meta: Dict = None) -> None:
    dtypes: Dict[str, str] = {}
    arrays: Dict[str, np.ndarray] = {}
    arrays.update(_flatten({"params": params}, dtypes=dtypes))
    arrays.update(_flatten({"buffers": buffers}, dtypes=dtypes))
    if opt_state is not None:
        arrays.update(_flatten({"opt": opt_state}, dtypes=dtypes))
    header = {
        "format_version": FORMAT_VERSION,
        "net": net_structure,
        "epoch": int(epoch),
        "has_opt_state": opt_state is not None,
        "dtypes": dtypes,
        "extra": extra_meta or {},
    }
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode("utf-8"), dtype=np.uint8)
    # atomic single-file save: a kill mid-write can never leave a
    # half-written newest snapshot for continue=1 to load — the file is
    # either the old complete one or the new complete one
    atomic_write(path, lambda f: np.savez(f, **arrays))


def atomic_write(path: str, write_fn) -> None:
    """Write via ``<path>.tmp`` + fsync + ``os.replace`` — observers see
    either the old complete file or the new complete one, never a
    half-write.  The tmp file is removed when ``write_fn`` raises.
    Shared by the legacy single-file save and the ckpt snapshot shards
    (one copy of the durability protocol).  The containing directory is
    fsynced after the replace: without it the rename itself is not
    durable against power loss, and a checkpoint whose manifest rename
    evaporates on remount while retention already pruned its
    predecessor would leave no loadable snapshot at all."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        except OSError:  # platform without directory fds
            return
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def flatten_tree(tree: Dict, dtypes: Dict[str, str]) -> Dict[str, np.ndarray]:
    """Public flatten for the ckpt shard writer: nested tree ->
    ``{"a/b/c": np.ndarray}`` with ml_dtypes extension types widened to
    exact float32 and recorded in ``dtypes`` (same contract as
    save_model's arrays)."""
    return _flatten(tree, dtypes=dtypes)


def unflatten_tree(flat: Dict[str, np.ndarray],
                   dtypes: Dict[str, str] = None) -> Dict:
    """Inverse of :func:`flatten_tree` (restores recorded dtypes)."""
    return _unflatten(flat, dtypes)


def load_model(path: str) -> Tuple[dict, Dict, Dict, Dict]:
    """Return (header, params, buffers, opt_state_or_None)."""
    with np.load(path, allow_pickle=False) as z:
        header = json.loads(bytes(z["__header__"]).decode("utf-8"))
        flat = {k: z[k] for k in z.files if k != "__header__"}
    tree = _unflatten(flat, header.get("dtypes"))
    params = tree.get("params", {})
    buffers = tree.get("buffers", {})
    opt = tree.get("opt") if header.get("has_opt_state") else None
    return header, params, buffers, opt
