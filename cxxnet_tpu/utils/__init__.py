from .config import (ConfigError, parse_config_file, parse_config_string,
                     parse_keyval_args)

__all__ = ["ConfigError", "parse_config_file", "parse_config_string",
           "parse_keyval_args"]
