"""Config tokenizer: ordered (name, value) pairs from ``key = value`` text.

Capability parity with the reference's ConfigReaderBase
(``src/utils/config.h:20-189``): whitespace-separated tokens around ``=``,
``#`` line comments, double-quoted single-line strings with backslash
escapes, single-quoted multi-line strings.  Config order matters — the same
key may appear many times (e.g. repeated ``layer[..]`` lines, per-section
``iter`` keys), so the output is a list, not a dict.
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

ConfigPairs = List[Tuple[str, str]]


class ConfigError(ValueError):
    pass


def _tokenize(text: str) -> Iterator[str]:
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch == "#":
            while i < n and text[i] not in "\r\n":
                i += 1
        elif ch in " \t\r\n":
            i += 1
        elif ch == '"':
            i += 1
            buf = []
            while True:
                if i >= n:
                    raise ConfigError("unterminated string in config")
                c = text[i]
                if c == "\\":
                    i += 1
                    if i >= n:
                        raise ConfigError("unterminated escape in config")
                    buf.append(text[i])
                    i += 1
                elif c == '"':
                    i += 1
                    break
                elif c in "\r\n":
                    raise ConfigError("unterminated string in config")
                else:
                    buf.append(c)
                    i += 1
            yield '"' + "".join(buf)  # marker prefix: quoted token
        elif ch == "'":
            i += 1
            buf = []
            while True:
                if i >= n:
                    raise ConfigError("unterminated string in config")
                c = text[i]
                if c == "\\":
                    i += 1
                    buf.append(text[i])
                    i += 1
                elif c == "'":
                    i += 1
                    break
                else:
                    buf.append(c)
                    i += 1
            yield '"' + "".join(buf)
        elif ch == "=":
            i += 1
            yield "="
        else:
            j = i
            while j < n and text[j] not in " \t\r\n=#'\"":
                j += 1
            yield text[i:j]
            i = j


def _unmark(tok: str) -> str:
    return tok[1:] if tok.startswith('"') else tok


def parse_config_string(text: str) -> ConfigPairs:
    """Parse config text into an ordered list of (name, value) pairs."""
    toks = list(_tokenize(text))
    pairs: ConfigPairs = []
    i = 0
    while i < len(toks):
        name = toks[i]
        if name == "=":
            raise ConfigError("config line starts with '='")
        if i + 2 >= len(toks) or toks[i + 1] != "=":
            raise ConfigError(f"expected 'name = value' near {_unmark(name)!r}")
        val = toks[i + 2]
        if val == "=":
            raise ConfigError(f"missing value for {_unmark(name)!r}")
        pairs.append((_unmark(name), _unmark(val)))
        i += 3
    return pairs


def parse_config_file(path: str) -> ConfigPairs:
    with open(path, "r") as f:
        return parse_config_string(f.read())


def parse_keyval_args(args: List[str]) -> ConfigPairs:
    """Parse CLI ``key=value`` overrides (reference: cxxnet_main.cpp:67-72)."""
    pairs: ConfigPairs = []
    for a in args:
        if "=" not in a:
            raise ConfigError(f"CLI override must be key=value, got {a!r}")
        k, v = a.split("=", 1)
        pairs.append((k.strip(), v.strip()))
    return pairs
