"""Evaluation metrics: rmse / error / logloss / rec@n + MetricSet.

Reference: ``src/utils/metric.h:20-236``.  Metrics run on the host over
numpy copies of eval-requested node outputs, excluding ``num_batch_padd``
padding instances (reference nnet_impl-inl.hpp:237-240).  Output format
parity: ``\\tname-metric:value`` fragments.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class Metric:
    name = ""

    def __init__(self):
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def clear(self):
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def add_eval(self, pred: np.ndarray, label: np.ndarray) -> None:
        """pred (n, k) scores, label (n, label_width)."""
        vals = self._calc(pred.astype(np.float64), label.astype(np.float64))
        self.sum_metric += float(vals.sum())
        self.cnt_inst += pred.shape[0]

    def get(self) -> float:
        return self.sum_metric / max(self.cnt_inst, 1)

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class MetricRMSE(Metric):
    name = "rmse"

    def _calc(self, pred, label):
        assert pred.shape[1] == label.shape[1], \
            "rmse: prediction and label sizes must match"
        return np.square(pred - label).sum(axis=1)


class MetricError(Metric):
    """argmax error for multi-class scores; threshold-at-0 for single column
    (metric.h MetricError)."""

    name = "error"

    def _calc(self, pred, label):
        if pred.shape[1] != 1:
            maxidx = pred.argmax(axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(np.int64)
        return (maxidx != label[:, 0].astype(np.int64)).astype(np.float64)


class MetricLogloss(Metric):
    name = "logloss"

    def _calc(self, pred, label):
        eps = 1e-15
        if pred.shape[1] != 1:
            tgt = label[:, 0].astype(np.int64)
            p = np.clip(pred[np.arange(len(tgt)), tgt], eps, 1 - eps)
            return -np.log(p)
        p = np.clip(pred[:, 0], eps, 1 - eps)
        y = label[:, 0]
        res = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        assert not np.isnan(res).any(), "NaN detected!"
        return res


class MetricRecall(Metric):
    """rec@n with random tie-break shuffle (metric.h MetricRecall)."""

    def __init__(self, name: str):
        super().__init__()
        assert name.startswith("rec@"), "must specify n for rec@n"
        self.name = name
        self.topn = int(name[4:])
        self._rng = np.random.RandomState(0)

    def _calc(self, pred, label):
        n, k = pred.shape
        assert k >= self.topn, \
            f"rec@{self.topn} meaningless for score list of length {k}"
        # Vectorized: one random secondary key per score reproduces the
        # reference's shuffle-then-stable-sort tie-break (equal scores are
        # ordered uniformly at random), without the per-row Python loop.
        tiebreak = self._rng.random_sample((n, k))
        top = np.lexsort((tiebreak, -pred), axis=1)[:, :self.topn]
        lab = label.astype(np.int64)
        hits = (top[:, :, None] == lab[:, None, :]).any(axis=2).sum(axis=1)
        return hits / label.shape[1]


def create_metric(name: str) -> Metric:
    if name == "rmse":
        return MetricRMSE()
    if name == "error":
        return MetricError()
    if name == "logloss":
        return MetricLogloss()
    if name.startswith("rec@"):
        return MetricRecall(name)
    raise ValueError(f"unknown metric {name!r}")


class MetricSet:
    """Set of (metric, label-field) bindings (metric.h MetricSet)."""

    def __init__(self):
        self.evals: List[Metric] = []
        self.label_fields: List[str] = []

    def add_metric(self, name: str, label_field: str) -> None:
        for m, f in zip(self.evals, self.label_fields):
            if m.name == name and f == label_field:
                return
        self.evals.append(create_metric(name))
        self.label_fields.append(label_field)

    def clear(self):
        for m in self.evals:
            m.clear()

    def add_eval(self, predscores: List[np.ndarray],
                 labels: Dict[str, np.ndarray]) -> None:
        """predscores[i] pairs with self.evals[i]."""
        for m, f, p in zip(self.evals, self.label_fields, predscores):
            m.add_eval(p, labels[f])

    def print_line(self, evname: str) -> str:
        return "".join(f"\t{evname}-{m.name}:{m.get():f}" for m in self.evals)

    def values(self, evname: str) -> Dict[str, float]:
        """Structured twin of :meth:`print_line` for the JSONL sink:
        ``{"<evname>-<metric>": value}`` with the same key spelling as
        the printed fragments."""
        return {f"{evname}-{m.name}": m.get() for m in self.evals}
