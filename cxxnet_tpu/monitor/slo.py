"""SLO burn-rate alerting over the ``serve_window`` record stream.

The serve sentinels (monitor/sentinel.py) detect CHANGE — an EWMA
deviation fires on any sustained shift, good baseline or bad.  An SLO
is the opposite contract: an absolute target (``serve_slo_p99_ms``, a
latency threshold, plus ``serve_slo_avail``, the fraction of requests
that must meet it) and an error BUDGET (``1 - avail``) spent by every
request over the threshold.  Burn rate is budget spend velocity:
``burn = error_rate / budget`` — burn 1.0 spends exactly the budget
over the SLO period, burn 14.4 exhausts a 30-day budget in 2 days.

Multi-window evaluation (the standard fast/slow pair): the FAST window
(``serve_slo_fast_sec``, high threshold ``serve_slo_fast_burn``)
catches an acute outage in seconds; the SLOW window
(``serve_slo_slow_sec``, lower ``serve_slo_slow_burn``) catches a
simmering regression the fast window keeps forgetting.  Both windows
are rings of ``serve_window`` records (the sentinel reporter's
cadence, ``serve_sentinel_window`` seconds each — graftlint enforces
the window seconds divide evenly into records).

The verdict is judged through the ONE comparison engine
(:func:`monitor.diff.compare`, direction + floor semantics) — the same
code path that judges an offline A/B, so the serve admission gate
(ROADMAP item 4: canary promotion on hot-swap) and the live alert can
never disagree about what "over budget" means.  A firing tier emits
one ``slo`` JSONL record on the rising edge (doc/monitor.md) and holds
``firing`` until the burn drops back under threshold; the latest
verdict dict is kept for ``/statusz`` (atomic whole-object swap — the
admin scrape path reads it without locks).
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Any, Callable, Dict, Optional

from .diff import LOWER_BETTER, compare


@dataclasses.dataclass
class SloSpec:
    """Declared serving SLO (serve/__init__.py keys -> here)."""

    p99_ms: float = 0.0        # latency threshold; 0 disables the SLO
    avail: float = 0.999       # fraction of requests under threshold
    fast_sec: float = 60.0     # acute window
    slow_sec: float = 600.0    # simmering window
    fast_burn: float = 14.4    # firing threshold, fast tier
    slow_burn: float = 6.0     # firing threshold, slow tier

    def __post_init__(self):
        if self.p99_ms > 0.0 and not (0.0 < self.avail < 1.0):
            raise ValueError(
                f"serve_slo_avail = {self.avail}: must be in (0, 1) — "
                "1.0 leaves a zero error budget, which no burn rate "
                "can be computed against")
        if self.fast_sec <= 0 or self.slow_sec <= 0:
            raise ValueError("SLO burn windows must be > 0 seconds")

    @property
    def active(self) -> bool:
        return self.p99_ms > 0.0

    @property
    def budget(self) -> float:
        return 1.0 - self.avail


class SloTracker:
    """Feed :meth:`observe` one ``serve_window`` record per reporter
    tick; it maintains both burn windows, emits ``slo`` records on
    rising edges, and keeps the latest verdict for ``/statusz``.

    The record must carry ``requests`` and ``viol`` (requests whose
    latency exceeded ``p99_ms`` — the batcher counts them per window
    when armed with ``slo_ms``); ``window_sec`` sizes the rings on
    first observation.
    """

    def __init__(self, spec: SloSpec, window_sec: float, *,
                 metrics=None, model: str = "default",
                 on_burn: Optional[Callable[[dict], Any]] = None):
        self.spec = spec
        self.metrics = metrics
        self.model = model
        self.on_burn = on_burn
        win = max(float(window_sec), 1e-9)
        self._tiers: Dict[str, dict] = {}
        for tier, sec, thresh in (
                ("fast", spec.fast_sec, spec.fast_burn),
                ("slow", spec.slow_sec, spec.slow_burn)):
            n = max(1, int(math.ceil(sec / win - 1e-9)))
            self._tiers[tier] = {
                "sec": sec, "threshold": thresh, "firing": False,
                "ring": deque(maxlen=n), "burn": 0.0}
        # latest verdict, swapped whole so /statusz reads it lock-free
        self.verdict: Dict[str, Any] = self._verdict()

    # ------------------------------------------------------------ observe
    def observe(self, rec: Dict[str, Any]) -> Optional[dict]:
        """One reporter window.  Returns the ``slo`` record dict when a
        tier crosses onto firing this tick (the flight-capture trigger),
        else None."""
        if not self.spec.active:
            return None
        requests = int(rec.get("requests", 0))
        viol = int(rec.get("viol", 0))
        fired: Optional[dict] = None
        for tier, st in self._tiers.items():
            st["ring"].append((requests, viol))
            total = sum(r for r, _ in st["ring"])
            bad = sum(v for _, v in st["ring"])
            error_rate = bad / total if total else 0.0
            burn = error_rate / self.spec.budget
            st["burn"] = burn
            # the ONE comparison engine judges the threshold crossing:
            # candidate burn vs the declared ceiling, LOWER_BETTER,
            # zero tolerance (any excursion past the ceiling regresses)
            judge = compare(f"slo_{tier}_burn", a=st["threshold"],
                            b=burn, rel=0.0, direction=LOWER_BETTER)
            now_firing = bool(judge["regressed"])
            if now_firing and not st["firing"]:
                out = {"model": self.model, "tier": tier,
                       "burn": round(burn, 4),
                       "threshold": st["threshold"],
                       "budget": self.spec.budget,
                       "error_rate": round(error_rate, 6),
                       "requests": total, "viol": bad,
                       "window_sec": st["sec"],
                       "rel_delta": judge["rel_delta"]}
                if self.metrics is not None:
                    self.metrics.counter_inc("slo_burns")
                    self.metrics.emit("slo", **out)
                if fired is None:
                    fired = out
            st["firing"] = now_firing
        self.verdict = self._verdict()
        if fired is not None and self.on_burn is not None:
            self.on_burn(fired)
        return fired

    # ------------------------------------------------------------ verdict
    def _verdict(self) -> Dict[str, Any]:
        tiers = {tier: {"burn": round(st["burn"], 4),
                        "threshold": st["threshold"],
                        "window_sec": st["sec"],
                        "firing": st["firing"]}
                 for tier, st in self._tiers.items()}
        return {"active": self.spec.active,
                "p99_ms_target": self.spec.p99_ms,
                "avail_target": self.spec.avail,
                "budget": self.spec.budget,
                "ok": not any(t["firing"] for t in tiers.values()),
                **tiers}
