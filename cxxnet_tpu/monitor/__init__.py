"""Telemetry subsystem: structured metrics, logging, in-graph monitors,
and profiler-trace parsing.

The reference cxxnet had two observability surfaces: the updater-level
monitor (per-layer ||w||/||dw|| printed during training, updater.h
SetMonitor) and the examples/sec line whose health mirrored the
ThreadBuffer's.  This package is their TPU-era rework:

* :mod:`.log` — stdlib logging behind the exact line formats the CLI
  always printed (``silent`` maps to log levels);
* :mod:`.metrics` — :class:`MetricsRegistry` (counters / gauges /
  histograms) with a JSONL sink (``metrics_sink = jsonl:<path>``);
* :mod:`.ingraph` — per-layer weight/grad/update norms computed as cheap
  scalars INSIDE the traced step (zero overhead when ``monitor = 0``:
  the step jaxpr is unchanged, asserted in tests);
* :mod:`.trace` — pure-python profiler-trace (xplane.pb) parser shared
  by bench.py, tools/trace_summary.py, and the profiling window
  (one-shot, step-addressed, or recurring via ``prof_every``);
* :mod:`.attribution` — per-layer device-time attribution: joins the
  trace's per-op times against the ``jax.named_scope`` layer stamps
  (the ``layer_profile`` record, read by tools/obsv.py);
* :mod:`.sentinel` — rolling-EWMA regression sentinels over step time /
  comm share / HBM high-water (``anomaly`` records) plus the
  flight-recorder ring dumped on anomalies and TrainingDiverged.

See doc/monitor.md for the config surface and JSONL record schema.
"""

from __future__ import annotations


class TrainingDiverged(RuntimeError):
    """Raised by the NaN/inf loss guard under ``monitor_nan = fatal``."""


from . import log  # noqa: E402
from .metrics import MetricsRegistry  # noqa: E402

__all__ = ["MetricsRegistry", "TrainingDiverged", "log"]
