"""Prometheus text exposition for the live MetricsRegistry.

The admin plane's ``/metrics`` surface (serve/admin.py, doc/serve.md
"Operating a serve host") renders a :meth:`MetricsRegistry.snapshot`
in the Prometheus text format (version 0.0.4) so any off-the-shelf
scraper reads the same counters/gauges/histograms the JSONL records
carry.  Stdlib only, and deliberately tiny: ONE name-mangling rule,
ONE label-escaping rule, and a :func:`parse` that reads its own output
back (the tools/lint.sh self-validation gate and the golden test both
go through it, so the renderer cannot drift from the grammar).

Mapping rules (doc/monitor.md "Exported metric names"):

* counters   -> ``<prefix>_<name>_total`` (``# TYPE ... counter``)
* gauges     -> ``<prefix>_<name>`` (``# TYPE ... gauge``)
* histograms (reservoir summaries) -> a Prometheus ``summary``:
  ``{quantile="0.5|0.95|0.99"}`` samples from the reservoir ranks plus
  the exact ``_sum``/``_count`` pair (count/total are exact even after
  the reservoir saturates — only the quantiles are estimates).
* exact integer histograms (the batcher's ``batch_hist``, the
  scheduler's ``occupancy_hist``) -> a real ``histogram`` with
  cumulative ``le`` buckets ending in ``+Inf``; these arrive through
  the ``hists=`` argument because the registry keeps them as plain
  ``{value: count}`` dicts, not reservoirs.

Name mangling: every char outside ``[a-zA-Z0-9_:]`` becomes ``_``
(and a leading digit gets a ``_`` prefix) — one rule, applied to the
metric name only.  Label VALUES are never mangled; they are escaped:
backslash, double-quote, and newline get a backslash (the full label
escaping the format defines).
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

#: label-value escaping, in the order the format defines (backslash
#: first, or escaping a quote would double-escape its backslash)
_ESCAPES = (("\\", "\\\\"), ("\n", "\\n"), ('"', '\\"'))

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: the reservoir quantiles a Histogram.summary carries, in label form
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def mangle(name: str) -> str:
    """THE name-mangling rule: invalid chars -> ``_``, leading digit
    gets a ``_`` prefix.  Idempotent."""
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out or "_"


def escape_label(value: str) -> str:
    """THE label-value escaping rule (backslash, newline, quote)."""
    for raw, esc in _ESCAPES:
        value = value.replace(raw, esc)
    return value


def _fmt_value(v: float) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(pairs: Dict[str, str]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{mangle(k)}="{escape_label(str(v))}"'
                     for k, v in sorted(pairs.items()))
    return "{" + inner + "}"


def _sample(name: str, labels: Dict[str, str], value: float,
            out: List[str]) -> None:
    out.append(f"{name}{_labels(labels)} {_fmt_value(value)}")


def render(snapshot: Dict[str, Any], *, prefix: str = "cxxnet",
           labels: Optional[Dict[str, str]] = None,
           hists: Optional[Dict[str, Dict[int, int]]] = None) -> str:
    """A :meth:`MetricsRegistry.snapshot` (plus optional exact-count
    ``hists``) as Prometheus exposition text.  Pure function of its
    inputs — the scrape path takes no locks; the caller hands it
    already-copied dicts."""
    base = dict(labels or {})
    out: List[str] = []
    for name, v in sorted(snapshot.get("counters", {}).items()):
        m = f"{prefix}_{mangle(name)}_total"
        out.append(f"# TYPE {m} counter")
        _sample(m, base, v, out)
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        m = f"{prefix}_{mangle(name)}"
        out.append(f"# TYPE {m} gauge")
        _sample(m, base, v, out)
    for name, s in sorted(snapshot.get("histograms", {}).items()):
        m = f"{prefix}_{mangle(name)}"
        out.append(f"# TYPE {m} summary")
        for qlabel, key in _QUANTILES:
            if key in s:
                _sample(m, dict(base, quantile=qlabel), s[key], out)
        _sample(m + "_sum", base, s.get("sum", 0.0), out)
        _sample(m + "_count", base, s.get("count", 0), out)
    for name, counts in sorted((hists or {}).items()):
        m = f"{prefix}_{mangle(name)}"
        out.append(f"# TYPE {m} histogram")
        cum = 0
        total = 0.0
        for edge in sorted(int(k) for k in counts):
            cum += int(counts[edge])
            total += edge * int(counts[edge])
            _sample(m + "_bucket", dict(base, le=str(edge)), cum, out)
        _sample(m + "_bucket", dict(base, le="+Inf"), cum, out)
        _sample(m + "_sum", base, total, out)
        _sample(m + "_count", base, cum, out)
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------- parse

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$")
_LABEL_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"\s*(,|$)')


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                             "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(tok: str) -> float:
    if tok == "+Inf":
        return math.inf
    if tok == "-Inf":
        return -math.inf
    return float(tok)  # "NaN" parses; garbage raises ValueError


def parse(text: str) -> Dict[str, Dict[str, Any]]:
    """Read exposition text back into ``{family: {"type": t, "samples":
    [(name, labels, value), ...]}}``, validating the grammar as it goes
    (malformed lines raise ValueError).  The renderer's own output must
    round-trip — asserted by the tools/lint.sh promtext gate and the
    golden test."""
    fams: Dict[str, Dict[str, Any]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                if parts[3] not in ("counter", "gauge", "summary",
                                    "histogram", "untyped"):
                    raise ValueError(
                        f"promtext line {lineno}: unknown type "
                        f"{parts[3]!r}")
                fams[parts[2]] = {"type": parts[3], "samples": []}
            continue  # HELP / comments pass through unparsed
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"promtext line {lineno}: malformed "
                             f"sample {line!r}")
        name = m.group("name")
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_RE.match(raw, pos)
                if lm is None:
                    raise ValueError(
                        f"promtext line {lineno}: malformed labels "
                        f"{raw!r}")
                labels[lm.group("k")] = _unescape(lm.group("v"))
                pos = lm.end()
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            raise ValueError(f"promtext line {lineno}: bad value "
                             f"{m.group('value')!r}") from None
        # attach to the declaring family: summaries/histograms own
        # their _sum/_count/_bucket children
        fam = None
        for cand in (name, name.rsplit("_", 1)[0]):
            if cand in fams:
                fam = fams[cand]
                break
        if fam is None:
            fam = fams.setdefault(name, {"type": "untyped",
                                         "samples": []})
        if fam["type"] == "counter" and not math.isnan(value) \
                and value < 0:
            raise ValueError(
                f"promtext line {lineno}: counter {name} < 0")
        fam["samples"].append((name, labels, value))
    return fams


def counter_values(fams: Dict[str, Dict[str, Any]]) -> Dict[str, float]:
    """Flatten parsed counter samples to ``{name: value}`` (label-less
    view) — the monotonicity check in the golden test reads this."""
    out: Dict[str, float] = {}
    for fname, fam in fams.items():
        if fam["type"] != "counter":
            continue
        for name, _labels_, value in fam["samples"]:
            out[name] = value
    return out


def live_tables(fams: Dict[str, Dict[str, Any]],
                prefix: str = "cxxnet") -> Dict[str, Any]:
    """Summarize a parsed ``/metrics`` scrape for ``tools/obsv.py
    --live``: counters + gauges flattened, summaries back to
    p50/p95/p99 dicts keyed by the unprefixed registry name."""
    plen = len(prefix) + 1
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "summaries": {}}
    for fname, fam in fams.items():
        short = fname[plen:] if fname.startswith(prefix + "_") else fname
        if fam["type"] == "counter":
            for _n, _l, v in fam["samples"]:
                out["counters"][short[:-6] if short.endswith("_total")
                                else short] = v
        elif fam["type"] == "gauge":
            for _n, _l, v in fam["samples"]:
                out["gauges"][short] = v
        elif fam["type"] == "summary":
            s: Dict[str, float] = {}
            for name, labels, v in fam["samples"]:
                if name.endswith("_sum"):
                    s["sum"] = v
                elif name.endswith("_count"):
                    s["count"] = v
                elif labels.get("quantile") == "0.5":
                    s["p50"] = v
                elif labels.get("quantile") == "0.95":
                    s["p95"] = v
                elif labels.get("quantile") == "0.99":
                    s["p99"] = v
            out["summaries"][short] = s
    return out
