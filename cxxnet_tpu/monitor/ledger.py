"""Goodput ledger: where a whole run's wall clock went (doc/monitor.md).

The observatory can decompose one run three ways — per-layer device
time (attribution.py), host spans (spans.py), HBM (memory.py) — but
none of them answers the operator's first question: *what fraction of
this run's wall was useful work?*  :func:`build_ledger` folds the
records a training run already emits (``compile`` / ``step`` /
``round`` / ``ckpt`` / ``rollback``) into one end-of-run ``ledger``
record attributing the measured wall into categories:

========================  ====================================================
``compile``               first-dispatch jit trace + XLA compile wall
``dispatch``              host wall spent dispatching train steps — the
                          useful-work category goodput is computed from
``pipe_bubble``           pipeline fill/drain idle inside the dispatched
                          step: ``dispatch × pipe_bubble_frac`` carved out
                          of the useful-work category.  Producers stamp
                          ``pipe_bubble_frac`` (analytic ``(S-1)/(M+S-1)``
                          from the trainer) on step/round records of
                          pipelined runs; absent field → 0 carve
``input_wait``            blocked on the host iterator / staging queue
``h2d_staging``           critical-path device staging (stack + cast +
                          transfer).  With ``prefetch_device > 0`` the
                          transfer ran on the producer thread and OVERLAPPED
                          compute, so only the part that fits the residual
                          wall is booked here; the rest is reported as
                          ``h2d_overlapped_sec`` (informational, not a
                          category — it cost no wall)
``eval``                  round-boundary evaluation passes
``ckpt_blocked``          what the train loop paid for snapshots (host pull
                          + bounded-queue backpressure; the off-thread write
                          wall is in the ``ckpt`` records, not here)
``rollback_lost``         work later discarded by a divergence rollback: the
                          full wall (train + eval) of every completed round
                          past the restored snapshot, plus the dying round's
                          partial step accounting
``other``                 the residual — init, iterator construction, metric
                          math, logging, the untimed tail of the dying round
========================  ====================================================

The categories tile the wall by construction (``other`` is the
residual), so ``sum(categories) == wall_sec`` up to rounding — asserted
within 5% on the CPU MNIST e2e (tests/test_ledger.py).  ``goodput_pct``
is ``dispatch / wall``.

Two producers share this one fold: the task ``finally`` in main.py
re-reads its own sink file and emits the record even when the run died
in ``TrainingDiverged``; ``tools/obsv.py`` recomputes it post-hoc for
any historical JSONL that lacks one (``source = "posthoc"``, wall from
the record timestamp span).  The cross-run comparator
(monitor/diff.py, ``tools/obsv.py --diff``) compares the shares.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from . import log as mlog

#: ledger categories, in render order; they tile ``wall_sec``
CATEGORIES = ("compile", "dispatch", "pipe_bubble", "input_wait",
              "h2d_staging", "eval", "ckpt_blocked", "rollback_lost",
              "other")


def parse_record_line(line: str):
    """One JSONL line -> a record dict, or None (blank / not a record).
    Raises ValueError on an unparseable line — callers decide the skip
    policy (load_records counts + warns once; the obsv Follower keeps a
    torn tail buffered instead).  The ONE per-line parse every tolerant
    reader shares."""
    line = line.strip()
    if not line:
        return None
    r = json.loads(line)
    return r if isinstance(r, dict) and "kind" in r else None


def load_records(path: str, who: str = "ledger",
                 offset: int = 0) -> List[dict]:
    """Tolerant JSONL reader: every well-formed ``{"kind": ...}`` object
    in the file, in order.  A run killed mid-``sink.write`` leaves a
    torn final line — that (or any other unparseable line) is SKIPPED
    with one warning per read instead of raising ``JSONDecodeError``
    and making the run's own report unreadable.  ``offset`` skips bytes
    already accounted elsewhere (the sink opens append-mode, so a
    reused path carries earlier sessions; the task ledger anchors at
    the file size it saw at run start)."""
    recs: List[dict] = []
    skipped = 0
    with open(path) as f:
        if offset:
            f.seek(offset)
        for line in f:
            try:
                r = parse_record_line(line)
            except ValueError:
                skipped += 1
                continue
            if r is not None:
                recs.append(r)
    if skipped:
        # one warning per read, never per line — a torn tail is one fact
        mlog.warn(f"{who}: {path}: skipped {skipped} unparseable JSONL "
                  "line(s) (the torn tail a killed run leaves mid-write)")
    return recs


def by_kind(recs: List[dict]) -> Dict[str, List[dict]]:
    """Group a record stream by ``kind`` (insertion-ordered) — shared
    by the diff engine and the obsv report so the two readers can
    never diverge on grouping."""
    out: Dict[str, List[dict]] = {}
    for r in recs:
        out.setdefault(r.get("kind", ""), []).append(r)
    return out


def last_session(recs: List[dict]) -> List[dict]:
    """The LAST session's records in a (possibly multi-session,
    append-mode) stream.  Sessions end with their ``ledger`` record, so
    the last session is everything after the previous ledger: when the
    stream ends with a ledger, the segment between the second-to-last
    ledger and the end (that completed run); otherwise the trailing
    unledgered records (the live / killed run).  Streams without any
    ledger pass through whole.  Read-side consumers (the run report,
    the cross-run diff) slice here so their throughput/layer/latency
    numbers describe the same session the ledger does.

    Known limit: a predecessor KILLED before its own ledger landed
    leaves no boundary a reader can find, so its records blend into
    the next session's read-side metrics (the producer's emitted
    ledger stays correct — it anchors at the byte offset it saw at
    run start).  Prefer a fresh ``metrics_sink`` path per run when a
    diff must be exact after crashes (doc/monitor.md)."""
    idx = [i for i, r in enumerate(recs) if r.get("kind") == "ledger"]
    if not idx:
        return recs
    if idx[-1] == len(recs) - 1:
        start = idx[-2] + 1 if len(idx) > 1 else 0
    else:
        start = idx[-1] + 1
    return recs[start:]


def _f(rec: dict, key: str) -> float:
    v = rec.get(key)
    return float(v) if v is not None else 0.0


def build_ledger(recs: List[dict],
                 wall_sec: Optional[float] = None,
                 source: str = "run") -> Optional[dict]:
    """Fold a record stream into the ledger dict (the ``ledger`` record
    body).  ``wall_sec`` is the measured task wall when the producer
    knows it (the task ``finally``); None derives it from the stream's
    timestamp span (the post-hoc path).  Returns None when the stream
    carries nothing to account (no records at all).

    The sink opens append-mode, so a reused ``metrics_sink`` path holds
    EARLIER sessions too; each session ends with its own ledger record,
    so the fold covers only what the last ledger in the stream did not
    — everything after it.  (A mid-stream ``run`` record is NOT a
    session boundary: rollback restores rebuild the net and emit one
    per attempt, and slicing there would discard the lost work the
    ledger exists to account.)  The one stream a ledger cannot bound —
    a predecessor killed before its own ledger landed — is handled by
    the producer's byte-offset anchor (``load_records(offset=...)``)."""
    for i in range(len(recs) - 1, -1, -1):
        if recs[i].get("kind") == "ledger":
            recs = recs[i + 1:]
            break
    compile_sec = dispatch = bubble = input_wait = eval_sec = 0.0
    h2d_raw = ckpt_blocked = lost = 0.0
    kept: List[dict] = []       # completed rounds still standing
    rounds_lost = 0
    # step records carry per-print-window marks; a round record, emitted
    # at round end, carries the SAME round's full sums — so pending step
    # marks are superseded (discarded) when their round record lands,
    # and only the dying round's partial accounting survives the stream
    pend = {"dispatch": 0.0, "bubble": 0.0, "input_wait": 0.0, "h2d": 0.0}
    # compile happens INSIDE its round's wall (the first dispatch), so
    # a rolled-back round's lost wall must shed the compile portion the
    # `compile` category already booked — the compile record's round is
    # 0-based, the round record's 1-based (same loop iteration)
    compile_by_round: Dict[int, float] = {}
    n_anom = n_nan = n_rb = 0
    first_ts: Optional[float] = None
    last_ts: Optional[float] = None
    for r in recs:
        ts = r.get("ts")
        if isinstance(ts, (int, float)):
            first_ts = ts if first_ts is None else first_ts
            last_ts = ts
        k = r.get("kind")
        if k == "compile":
            compile_sec += _f(r, "compile_sec")
            if r.get("round") is not None:
                compile_by_round[int(r["round"])] = _f(r, "compile_sec")
        elif k == "step":
            # pipelined steps spend a known fill/drain fraction of their
            # dispatch wall idle (pipe_bubble_frac, stamped by main.py):
            # carve it out of the useful-work category
            d = _f(r, "dispatch_sec")
            bub = d * _f(r, "pipe_bubble_frac")
            pend["dispatch"] += d - bub
            pend["bubble"] += bub
            pend["input_wait"] += _f(r, "iter_wait_sec")
            pend["h2d"] += _f(r, "h2d_sec")
        elif k == "round":
            kept.append(r)
            pend = {"dispatch": 0.0, "bubble": 0.0,
                    "input_wait": 0.0, "h2d": 0.0}
        elif k == "ckpt":
            ckpt_blocked += _f(r, "blocked_sec")
        elif k == "rollback":
            n_rb += 1
            restored = r.get("restored_round")
            if restored is not None:
                # completed rounds past the restored snapshot will be
                # retrained — their whole wall is lost work, and so is
                # the dying round's partial step accounting
                dead = [q for q in kept if (q.get("round") or 0) > restored]
                kept = [q for q in kept
                        if (q.get("round") or 0) <= restored]
                rounds_lost += len(dead)
                for q in dead:
                    # shed the compile wall nested in this round — it
                    # is already the `compile` category, and counting
                    # it again in rollback_lost would break the tiling
                    nested = compile_by_round.get(
                        int(q.get("round") or 0) - 1, 0.0)
                    lost += max(_f(q, "wall_sec") - nested, 0.0) \
                        + _f(q, "eval_sec")
            lost += pend["dispatch"] + pend["bubble"] \
                + pend["input_wait"] + pend["h2d"]
            pend = {"dispatch": 0.0, "bubble": 0.0,
                    "input_wait": 0.0, "h2d": 0.0}
        elif k == "anomaly":
            n_anom += 1
        elif k == "nan":
            n_nan += 1
    for r in kept:
        d = _f(r, "dispatch_sec")
        bub = d * _f(r, "pipe_bubble_frac")
        dispatch += d - bub
        bubble += bub
        input_wait += _f(r, "iter_wait_sec")
        eval_sec += _f(r, "eval_sec")
        h2d_raw += _f(r, "h2d_sec")
    # a run that died mid-round (TrainingDiverged with no rollback left)
    # leaves its last round as step marks only — book them where the
    # time actually went instead of letting the whole round read "other"
    dispatch += pend["dispatch"]
    bubble += pend["bubble"]
    input_wait += pend["input_wait"]
    h2d_raw += pend["h2d"]
    if wall_sec is None:
        if first_ts is None:
            return None
        wall_sec = max(last_ts - first_ts, 0.0)
    wall_sec = float(wall_sec)
    base = (compile_sec + dispatch + bubble + input_wait + eval_sec
            + ckpt_blocked + lost)
    residual = wall_sec - base
    # h2d that ran on the prefetch producer thread overlapped compute
    # and cost no wall: only the part that fits the residual is a
    # category (the prefetch_device = 0 case, where staging IS
    # critical-path time between dispatches)
    h2d_staging = min(h2d_raw, max(residual, 0.0))
    other = max(wall_sec - base - h2d_staging, 0.0)
    cats = {"compile": compile_sec, "dispatch": dispatch,
            "pipe_bubble": bubble, "input_wait": input_wait,
            "h2d_staging": h2d_staging, "eval": eval_sec,
            "ckpt_blocked": ckpt_blocked, "rollback_lost": lost,
            "other": other}
    cats = {k: round(v, 4) for k, v in cats.items()}
    denom = wall_sec or 1.0
    return {
        "wall_sec": round(wall_sec, 4),
        "categories": cats,
        "shares": {k: round(v / denom, 4) for k, v in cats.items()},
        "goodput_pct": round(dispatch / denom * 100.0, 2),
        "h2d_overlapped_sec": round(max(h2d_raw - h2d_staging, 0.0), 4),
        "rounds": len(kept),
        "rounds_lost": rounds_lost,
        "rollbacks": n_rb,
        "anomalies": n_anom,
        "nonfinite_steps": n_nan,
        "source": source,
    }


def format_ledger(led: dict) -> str:
    """One human line (the task-end log message and the obsv header)."""
    cats = led.get("categories") or {}
    parts = [f"{k} {cats.get(k, 0.0):.3g}s" for k in CATEGORIES
             if cats.get(k)]
    tail = ""
    if led.get("rounds_lost"):
        tail = f"; {led['rounds_lost']} round(s) lost to rollback"
    return (f"goodput {led.get('goodput_pct', 0.0):.1f}% of "
            f"{led.get('wall_sec', 0.0):.3g}s wall "
            f"({', '.join(parts)}){tail}")
