"""Per-layer device-time attribution: trace op times -> layer scopes.

The net builder stamps every connection's forward with
``jax.named_scope(conn_scope_name(i, conn))`` (nnet/net.py), so each
HLO instruction's ``op_name`` metadata — and, through XLA's fusion
metadata, each post-fusion op the profiler times — carries the layer it
came from, through forward AND the jax.grad transpose.  This module
joins the two ends back together without importing jax (it runs in
tools/obsv.py and CI):

* :func:`hlo_op_scopes` parses the COMPILED (optimized) HLO text of the
  train step (``NetTrainer.step_hlo_text``) into ``instruction name ->
  layer scope``.  This is the join that works everywhere: trace op
  events are named after HLO instructions on both the TPU runtime
  ("XLA Ops" lines) and the CPU thunk runtime, but only the TPU trace
  embeds the framework op path in the trace itself.
* :func:`scope_of_path` matches a framework op path (an event
  metadata ``display_name`` like ``"jit(step)/03-conv/conv_general"``,
  or an HLO ``op_name``) against the known scope strings; the LAST
  (innermost) match wins, and transform wrappers
  (``transpose(jvp(03-conv))``) match by substring — scope strings are
  pairwise non-substring by construction (layers/base.conn_scope_name).
* :func:`layer_table` walks already-parsed planes and buckets per-op
  device time by layer, with collectives split into their own bucket
  (shared classifier with trace.comm_summary_in — the substring-trap
  rule applies here too), joined against the analytic per-layer
  flops/bytes model (analysis/costmodel.py) for achieved-vs-roofline
  MFU.  The result is the ``layer_profile`` JSONL record's payload
  (doc/monitor.md).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence

from .trace import XPlane, collective_kind, total_ms_in

#: pseudo-rows for time the scope join can't (or shouldn't) name
COMM_ROW = "(collectives)"
OTHER_ROW = "(unattributed)"

# one optimized-HLO instruction line: indented "[ROOT] %name = ..."
# (module headers, computation signatures, and braces don't match)
_INSTR = re.compile(r"^\s*(?:ROOT\s+)?%?([A-Za-z0-9_.\-]+)\s*=\s")
_OP_NAME = re.compile(r'op_name="([^"]*)"')


def _scope_re(scopes: Sequence[str]) -> Optional[re.Pattern]:
    if not scopes:
        return None
    # longest-first so an alternation at the same position can't stop
    # at a shorter alternative
    parts = sorted(scopes, key=len, reverse=True)
    return re.compile("|".join(re.escape(s) for s in parts))


def scope_of_path(path: str, scope_re: Optional[re.Pattern]
                  ) -> Optional[str]:
    """Innermost known scope in a framework op path, or None."""
    if not path or scope_re is None:
        return None
    last = None
    for m in scope_re.finditer(path):
        last = m.group(0)
    return last


def hlo_op_scopes(hlo_text: str, scopes: Sequence[str]
                  ) -> Dict[str, Optional[str]]:
    """Optimized-HLO text -> {instruction name: layer scope or None}.

    Every instruction line is recorded (scope None when its op_name
    carries no known scope, or it has no metadata at all): membership in
    this map is how :func:`layer_table` recognizes "this trace event is
    an op of the profiled program" on runtimes whose traces carry no
    framework paths.  Fused-computation bodies are included — harmless,
    since their instructions never appear as trace events, and useful
    when a runtime names thunks after body roots."""
    sre = _scope_re(scopes)
    out: Dict[str, Optional[str]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR.match(line)
        if m is None:
            continue
        nm = _OP_NAME.search(line)
        out[m.group(1)] = scope_of_path(nm.group(1) if nm else "", sre)
    return out


def scopes_from_planes(planes: List[XPlane]) -> List[str]:
    """Recover scope strings from a trace alone by the naming
    convention (``NN-name`` path segments) — the fallback join for
    ``tools/obsv.py --trace`` runs that have no trainer to ask."""
    # '(' / ')' are delimiters too: transform wrappers render scopes as
    # "transpose(jvp(00-conv))" and a layer whose forward fused under a
    # neighbor may only appear in such backward paths.  \d{2,}: the
    # zero-padded index grows past two digits on 100+-connection nets,
    # and a lookahead keeps adjacent segments visible to finditer.
    seg = re.compile(r"(?:^|[/()])(\d{2,}-[A-Za-z0-9_.\-]+)(?=[/()]|$)")
    found = set()
    for plane in planes:
        for path in plane.event_display.values():
            for m in seg.finditer(path):
                found.add(m.group(1))
    return sorted(found)


def layer_table(planes: List[XPlane], scopes: Sequence[str],
                op_scopes: Optional[Dict[str, Optional[str]]] = None,
                steps: int = 1,
                costs: Optional[Dict[str, Dict[str, float]]] = None,
                peak_flops: Optional[float] = None,
                peak_bw: Optional[float] = None) -> Dict[str, object]:
    """Bucket per-op device time by layer scope.

    An event counts iff it is recognizably an XLA op of the profiled
    program: its framework path (event-metadata ``display_name``)
    carries a known scope, its name appears in ``op_scopes`` (the
    compiled-HLO join), or it is a collective by base opcode.  Runtime
    bookkeeping events (thread-pool regions, python lines, module-level
    spans) match none of those and are skipped, so the table's total is
    op time, not wall clock.

    Returns the ``layer_profile`` record payload: per-step
    ``device_total_ms`` (XLA-Modules total when the trace has one, else
    the counted-op sum), ``attributed_ms``, ``coverage``
    (attributed/total), and ``rows`` sorted by device time — each row
    ``{layer, device_ms, count, share, comm_ms}`` plus, when the
    analytic cost model and chip peaks are known, ``flops``, ``bytes``,
    ``mfu_pct`` (achieved flops vs peak), ``roofline_ms`` (the
    max(compute, bandwidth) analytic floor), and ``roofline_x``
    (measured / floor — the "distance" column ROADMAP item 4 reads).
    """
    sre = _scope_re(scopes)
    op_scopes = op_scopes or {}
    steps = max(int(steps), 1)
    buckets: Dict[str, List[float]] = {}  # scope -> [ms, count, comm_ms]
    ops_ms = 0.0
    for plane in planes:
        for line in plane.lines:
            if line.name == "python":
                continue
            for ev in line.events:
                name = plane.event_names.get(ev.metadata_id, "")
                scope = scope_of_path(
                    plane.event_display.get(ev.metadata_id, ""), sre)
                known = name in op_scopes
                if scope is None and known:
                    scope = op_scopes[name]
                comm = collective_kind(name) is not None
                if scope is None and not known and not comm and (
                        op_scopes or not plane.event_display.get(
                            ev.metadata_id)):
                    # not an op of the profiled program.  With an
                    # op_scopes map, membership is the oracle; without
                    # one (degraded trainer paths, obsv --trace) any
                    # event carrying a framework path still counts, in
                    # (unattributed) — scope-less program ops must not
                    # vanish and read as coverage ~1.0
                    continue
                ms = ev.duration_ps / 1e9
                ops_ms += ms
                row = scope if scope is not None else (
                    COMM_ROW if comm else OTHER_ROW)
                cur = buckets.setdefault(row, [0.0, 0, 0.0])
                cur[0] += ms
                cur[1] += 1
                if comm:
                    cur[2] += ms
    device_ms = total_ms_in(planes) or ops_ms
    costs = costs or {}
    rows = []
    for scope, (ms, n, comm_ms) in sorted(buckets.items(),
                                          key=lambda kv: -kv[1][0]):
        row = {"layer": scope, "device_ms": round(ms / steps, 4),
               "count": n,
               "share": round(ms / ops_ms, 4) if ops_ms else 0.0,
               "comm_ms": round(comm_ms / steps, 4)}
        c = costs.get(scope)
        if c:
            row["flops"] = c["flops"]
            row["bytes"] = c["bytes"]
            sec = ms / steps / 1e3
            if sec > 0 and peak_flops:
                row["mfu_pct"] = round(
                    c["flops"] / sec / peak_flops * 100.0, 2)
            if peak_flops and peak_bw:
                floor_ms = max(c["flops"] / peak_flops,
                               c["bytes"] / peak_bw) * 1e3
                row["roofline_ms"] = round(floor_ms, 4)
                if floor_ms > 0:
                    row["roofline_x"] = round(ms / steps / floor_ms, 2)
        rows.append(row)
    attributed = sum(ms for s, (ms, _, _) in buckets.items()
                     if s not in (COMM_ROW, OTHER_ROW))
    return {
        "steps": steps,
        "device_total_ms": round(device_ms / steps, 4),
        "ops_total_ms": round(ops_ms / steps, 4),
        "attributed_ms": round(attributed / steps, 4),
        "coverage": round(attributed / ops_ms, 4) if ops_ms else 0.0,
        "rows": rows,
    }
