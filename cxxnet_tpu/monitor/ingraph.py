"""In-graph norm monitor: the reference's updater monitor, resurrected.

The reference printed per-layer ``||w||``/``||dw||`` from inside the
updater when monitoring was on (updater.h SetMonitor).  Here the norms
are computed INSIDE the jitted train step — three f32 scalars per
parameter leaf (weight norm, grad norm, update norm), stacked so the
step returns one tiny ``(3,)`` array per leaf alongside the loss.  The
reduction is one extra pass over the parameters, trivial next to
fwd+bwd, and rides the existing per-step D2H.

``monitor = 0`` traces none of this: the step builder only calls
:func:`group_stats` when monitoring is on, so the lowered HLO is
byte-identical to an unmonitored build (asserted in
tests/test_monitor.py).

The update norm uses the ACTUAL parameter delta (new - old), so the
update/weight ratio reflects momentum/adam/LR-schedule effects, not the
raw gradient — on a non-apply microstep of ``update_period > 1`` it is
exactly 0.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ..nnet.net import iter_param_leaves


def _norm(x) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    return jnp.sqrt(jnp.sum(x32 * x32))


def group_stats(params, grads, new_params) -> Dict[str, jnp.ndarray]:
    """Per-leaf ``[||w||, ||dw||, ||w_new - w||]`` stacks, keyed
    ``"<param_key>/<tag>"`` (nested pairtest tags join with ``:``)."""
    flat_w = dict(iter_param_leaves(params))
    flat_g = dict(iter_param_leaves(grads))
    flat_n = dict(iter_param_leaves(new_params))
    return {name: jnp.stack([_norm(w), _norm(flat_g[name]),
                             _norm(flat_n[name] - w)])
            for name, w in flat_w.items()}


def unpack_stats(host_stats) -> Dict[str, Dict[str, float]]:
    """Host-side view of one step's monitor output: per-leaf
    ``{w_norm, g_norm, u_norm, u_ratio}`` floats."""
    out = {}
    for name, v in host_stats.items():
        w, g, u = (float(v[0]), float(v[1]), float(v[2]))
        out[name] = {"w_norm": w, "g_norm": g, "u_norm": u,
                     "u_ratio": u / (w + 1e-12)}
    return out
