"""Host-side span tracing: the request-path half of the observatory.

PR 7's observatory attributes **device** time per layer; the host-side
request path that serving traffic rides — MicroBatcher queue → coalesce
→ pad → dispatch → device → respond, plus the async checkpoint writer
and the device prefetcher — emitted only one end-to-end number per
request (``serve_latency_sec``), so a p99 regression was undebuggable:
queue wait, batch-formation wait, and device time were
indistinguishable.  :class:`SpanTracer` is the per-request equivalent
of the reference's per-round updater monitor: named, timestamped spans
on a shared monotonic clock, emitted as ``span`` JSONL records through
the existing :class:`~cxxnet_tpu.monitor.metrics.MetricsRegistry` sink.

Design constraints (the serving hot path is the customer):

* **Zero overhead when off.**  ``trace_sample = 0`` (the default) keeps
  the tracer disabled: :meth:`SpanTracer.new_trace` returns ``None``
  after one int compare, :meth:`SpanTracer.span` returns a shared
  no-op context manager, and :meth:`SpanTracer.emit` returns before
  building anything — zero allocations, zero records (asserted by
  tests/test_spans.py, and the monitor=0 HLO-equality contract is
  untouched: spans are host-side only, never traced into the step).
* **Sampling.**  ``trace_sample = N`` traces every Nth request
  (``N = 1`` traces all).  The sampling decision is made ONCE per
  request at :meth:`new_trace`; every downstream span either carries
  that request's ``trace_id`` or is skipped, so a sampled request's
  span chain is always complete and an unsampled one costs nothing.
* **Thread-safe ids.**  ``trace_id``s come from one counter under one
  lock — concurrent submitters get disjoint ids (tests assert it).
* **Cross-thread spans.**  A span's wall is defined by two
  ``time.perf_counter()`` stamps, not by which thread emits it: the
  queue-wait span begins on the client thread and ends on the
  dispatcher's, so the batcher emits it from the dispatcher with the
  client's recorded stamps (and the client's thread name via ``tid=``,
  so the Perfetto export puts it on the right track).
* **Batch linking.**  A coalesced dispatch serves many requests; its
  span carries ``riders`` — every sampled rider's trace_id — and
  :meth:`link` makes that list available (thread-local) to spans
  emitted inside the dispatch (the engine's pad/device/unpad), so
  ``tools/spans2trace.py`` can draw flow arrows from each request to
  the batch that served it.

Record schema (doc/monitor.md): ``{"kind": "span", "span": <stage>,
"us": <start, µs since the tracer epoch>, "dur_us": <int>, "tid":
<thread name>, "trace_id": <int, per-request spans>, "riders": [ids,
batch-level spans], ...stage attrs}``.

The read side: ``tools/obsv.py`` renders the per-stage p50/p95/p99
decomposition (via :func:`stage_decomposition`, shared with
``bench.py --serve``), ``tools/spans2trace.py`` exports Chrome
trace-event JSON loadable in Perfetto next to the device-trace
windows, and serve-side sentinels watch the windowed stats
(monitor/sentinel.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

#: request-path stage names in path order (doc/monitor.md "Reading a
#: p99 breakdown").  ``pad``/``device``/``unpad`` nest INSIDE
#: ``dispatch`` — shares are fractions of total request wall, so the
#: four top-level stages (queue_wait/coalesce/dispatch/respond) sum to
#: ~1.0 and the dispatch sub-stages re-decompose the dispatch share.
REQUEST_STAGES = ("queue_wait", "coalesce", "dispatch", "pad", "device",
                  "unpad", "respond")


class _NullSpan:
    """Shared no-op context manager: the disabled-tracer fast path
    allocates nothing (one module-level instance serves every call)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context-manager form: stamps entry/exit and emits on exit."""

    __slots__ = ("tracer", "name", "trace_id", "attrs", "t0")

    def __init__(self, tracer: "SpanTracer", name: str,
                 trace_id: Optional[int], attrs: dict):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.tracer.emit(self.name, self.t0, time.perf_counter(),
                         trace_id=self.trace_id, **self.attrs)
        return False


class _Link:
    """Context manager installing a thread-local rider list: spans
    emitted inside (the engine's pad/device/unpad, which don't know
    which requests ride the batch) inherit it automatically."""

    __slots__ = ("tracer", "riders", "prev")

    def __init__(self, tracer: "SpanTracer", riders: Sequence[int]):
        self.tracer = tracer
        self.riders = list(riders)
        self.prev = None

    def __enter__(self):
        tls = self.tracer._tls
        self.prev = getattr(tls, "riders", None)
        tls.riders = self.riders
        return self

    def __exit__(self, *exc):
        self.tracer._tls.riders = self.prev
        return False


class SpanTracer:
    """Low-overhead host-side span tracer over a MetricsRegistry sink.

    One per registry (``MetricsRegistry.tracer``); disabled until
    ``trace_sample = N`` arms it AND the registry has an active sink
    (no sink, no records — same contract as every other record kind).
    """

    def __init__(self, metrics, sample: int = 0):
        self.metrics = metrics
        # racelint: atomic(int swap: the flight capture's reporter thread re-arms it; every reader re-reads per call)
        self.sample = int(sample)
        self._epoch = time.perf_counter()
        self._lock = threading.Lock()
        # last allocated trace_id
        self._next_id = 0  # racelint: guarded-by(self._lock)
        # requests offered to the sampler
        self._n_seen = 0   # racelint: guarded-by(self._lock)
        self._tls = threading.local()

    # ------------------------------------------------------------- state
    @property
    def enabled(self) -> bool:
        """True only when sampling is armed AND records can land."""
        return self.sample > 0 and self.metrics.sink is not None

    # racelint: thread(reporter)
    def configure(self, sample: int) -> None:
        """(Re)arm: ``trace_sample = N`` traces every Nth request,
        ``0`` disables.  The tracer object is stable so components that
        grabbed ``metrics.tracer`` early see the change.  Called from
        the reporter thread when a flight capture boosts sampling."""
        self.sample = int(sample)

    @property
    def watermark(self) -> int:
        """The last allocated trace_id (GIL-atomic int read, no lock):
        two watermark reads bracket an id RANGE, which is how the
        flight capture (serve/admin.py) names the spans it boosted —
        ``serve_flight`` records carry ``trace_first``/``trace_last``
        from exactly this."""
        # racelint: ok(race_unguarded) — GIL-atomic int read; the flight heuristic tolerates a watermark one id stale
        return self._next_id

    @staticmethod
    def now() -> float:
        return time.perf_counter()

    # -------------------------------------------------------------- ids
    def new_trace(self) -> Optional[int]:
        """The per-request sampling decision: every ``sample``-th
        request gets a fresh, process-unique trace_id; the rest get
        ``None`` (and no downstream span touches them).  Thread-safe;
        near-free when disabled."""
        if self.sample <= 0 or self.metrics.sink is None:
            return None
        with self._lock:
            n = self._n_seen
            self._n_seen += 1
            if n % self.sample:
                return None
            self._next_id += 1
            return self._next_id

    def sampled(self, n: int) -> bool:
        """Stateless sampling helper for non-request series (prefetch
        items, ...): does the caller's ``n``-th event fall on this
        tracer's sampling grid?"""
        return self.sample > 0 and n % self.sample == 0

    # ------------------------------------------------------------- emit
    def emit(self, name: str, t0: float, t1: float, *,
             trace_id: Optional[int] = None,
             riders: Optional[Sequence[int]] = None,
             tid: Optional[str] = None, **attrs) -> None:
        """One ``span`` record from two monotonic stamps.  ``tid``
        overrides the thread-name track for cross-thread spans (a
        queue-wait span belongs on the CLIENT's track even though the
        dispatcher emits it)."""
        if self.sample <= 0 or self.metrics.sink is None:
            return
        rec = {"span": name,
               "us": int((t0 - self._epoch) * 1e6),
               "dur_us": max(int((t1 - t0) * 1e6), 0),
               "tid": tid if tid is not None
               else threading.current_thread().name}
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if riders is None:
            riders = getattr(self._tls, "riders", None)
        if riders:
            rec["riders"] = list(riders)
        rec.update(attrs)
        self.metrics.emit("span", **rec)

    def span(self, name: str, trace_id: Optional[int] = None, **attrs):
        """Context-manager span; returns the shared no-op when the
        tracer is disabled (zero allocation on the off path)."""
        if self.sample <= 0 or self.metrics.sink is None:
            return _NULL_SPAN
        return _Span(self, name, trace_id, attrs)

    # explicit begin/end for call sites where a context manager does
    # not fit (spans crossing function boundaries or threads)
    def begin(self, name: str, trace_id: Optional[int] = None, **attrs):
        """Returns an opaque token for :meth:`end`, or ``None`` when
        disabled (``end(None)`` is a no-op, so callers need no guard)."""
        if self.sample <= 0 or self.metrics.sink is None:
            return None
        return (name, time.perf_counter(), trace_id, attrs)

    def end(self, token) -> None:
        if token is None:
            return
        name, t0, trace_id, attrs = token
        self.emit(name, t0, time.perf_counter(), trace_id=trace_id,
                  **attrs)

    def link(self, riders: Sequence[int]):
        """Install ``riders`` thread-locally for spans emitted inside
        (see :class:`_Link`); no-op when disabled or empty."""
        if not riders or self.sample <= 0 or self.metrics.sink is None:
            return _NULL_SPAN
        return _Link(self, riders)

    def linked(self) -> Optional[List[int]]:
        """The rider list installed on THIS thread (``None`` outside a
        :meth:`link` block).  Dispatch sub-spans gate on it so an
        unsampled batch emits nothing — the sampling contract extends
        through the engine, not just the batcher."""
        return getattr(self._tls, "riders", None)


class NullTracer:
    """Tracer-shaped no-op for call sites without a registry (the
    ``tracer or spans.NULL`` idiom keeps their span code unguarded)."""

    sample = 0
    enabled = False
    watermark = 0

    def new_trace(self):
        return None

    def sampled(self, n: int) -> bool:
        return False

    def emit(self, *a, **k):
        return None

    def span(self, *a, **k):
        return _NULL_SPAN

    def begin(self, *a, **k):
        return None

    def end(self, token):
        return None

    def link(self, riders):
        return _NULL_SPAN

    def linked(self):
        return None


NULL = NullTracer()


# --------------------------------------------------------------- analysis

def span_records(records: Sequence[dict]) -> List[dict]:
    """Filter a record stream down to well-formed span records."""
    return [r for r in records
            if r.get("kind") == "span" and "span" in r and "dur_us" in r]


def stage_decomposition(records: Sequence[dict]) -> dict:
    """Per-stage request-path latency decomposition from span records
    (the table behind ``tools/obsv.py``'s serving section and
    ``bench.py --serve``'s per-point report).

    Per-request spans (carrying ``trace_id``) count once; batch-level
    spans (carrying ``riders``) count once PER RIDER — every rider
    experienced that dispatch's duration.  ``share`` is the stage's
    fraction of total request wall (the summed ``request`` spans, or
    the top-level stage total when none landed), so queue_wait +
    coalesce + dispatch + respond ≈ 1.0 and pad/device/unpad
    re-decompose the dispatch share.
    """
    per_stage: Dict[str, List[float]] = {}
    request_ms = 0.0
    n_requests = 0
    for r in span_records(records):
        name = r["span"]
        ms = r["dur_us"] / 1e3
        if name == "request":
            request_ms += ms
            n_requests += 1
            continue
        if name not in REQUEST_STAGES:
            continue
        weight = 1 if r.get("trace_id") is not None \
            else len(r.get("riders") or ())
        if weight <= 0:
            continue
        per_stage.setdefault(name, []).extend([ms] * weight)
    if not per_stage:
        return {"requests": n_requests, "stages": []}
    if request_ms <= 0.0:
        request_ms = sum(sum(v) for k, v in per_stage.items()
                         if k in ("queue_wait", "coalesce", "dispatch",
                                  "respond"))
    from .metrics import nearest_rank
    stages = []
    for name in REQUEST_STAGES:
        vals = per_stage.get(name)
        if not vals:
            continue
        vals.sort()

        def pct(q):
            return round(nearest_rank(vals, q), 3)

        total = sum(vals)
        stages.append({
            "stage": name, "count": len(vals),
            "p50_ms": pct(50), "p95_ms": pct(95), "p99_ms": pct(99),
            "total_ms": round(total, 3),
            "share": round(total / request_ms, 4) if request_ms else None,
        })
    return {"requests": n_requests, "stages": stages,
            "request_ms_total": round(request_ms, 3)}
