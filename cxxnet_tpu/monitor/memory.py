"""Memory observatory: per-layer HBM attribution from the compiled step.

The time side of the observatory (monitor/attribution.py) joins trace op
*durations* back to layers; this module does the same for *bytes*.  The
optimized-HLO text the trainer already caches (``NetTrainer.
step_hlo_text``) is a scheduled program whose every instruction carries
its output type (shape + dtype -> bytes) and, through ``op_name``
metadata, the layer scope that produced it — so a classic
def/last-use liveness walk over the ENTRY computation reconstructs the
buffer-assignment picture XLA never exports as structured data:

* :func:`parse_shape_bytes` — ``"f32[32,128]{1,0}"`` (or a tuple type)
  to bytes;
* :func:`hlo_entry_buffers` — ENTRY instructions to
  :class:`BufferInfo` rows (bytes, operands, layer scope, class);
* :func:`live_timeline` — program-order live-byte curve, its peak, and
  the per-layer breakdown of the live set AT the peak.  Donated-alias
  outputs (``input_output_alias`` in the module header: the new
  params/opt the step writes back over its arguments) are classed
  ``alias``, not ``temp``, so parameter bytes are never double-counted
  against the executable's temp allocation — the ``rows sum ~= temps``
  acceptance only holds with that exclusion;
* :func:`mem_table` — the ``mem_profile`` JSONL record payload
  (doc/monitor.md): executable totals, the peak-live timeline, and
  per-layer ``act_bytes`` rows ready to join the trainer-side
  param/opt accounting and the analytic model (analysis/memmodel.py).

Like attribution.py this module never imports jax — it runs in
tools/obsv.py and in CI over checked-in HLO fixtures.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .attribution import OTHER_ROW, _scope_re, scope_of_path

#: HLO element type -> bytes per element (token/opaque left out: size 0)
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
}

_ARRAY_TYPE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OP_NAME = re.compile(r'op_name="([^"]*)"')
#: one alias entry in the module header's input_output_alias map:
#: ``{<output tuple index>}: (<parameter>, {}, may-alias)``
_ALIAS = re.compile(r"\{(\d+)\}:\s*\((\d+),")
_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?([A-Za-z0-9_.\-]+)\s*=\s*(.*)$")


def parse_shape_bytes(type_str: str) -> int:
    """Bytes of one HLO type string: array (``f32[16,144]{3,2,1,0}``),
    scalar (``f32[]``), or tuple (sum of components).  Layout braces and
    ``/*index=N*/`` comments are ignored; unknown element types count
    zero (token, opaque) — sizes must never be invented."""
    total = 0
    for dtype, dims in _ARRAY_TYPE.findall(type_str):
        per = _DTYPE_BYTES.get(dtype)
        if per is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * per
    return total


@dataclasses.dataclass
class BufferInfo:
    """One ENTRY instruction's output buffer."""

    name: str
    index: int            # program order (scheduled HLO)
    bytes: int
    operands: Tuple[str, ...]
    scope: Optional[str]  # layer scope from op_name metadata, or None
    klass: str            # "param" | "temp" | "alias" | "output"
    is_root: bool = False


def _split_type(rest: str) -> Tuple[str, str]:
    """Split ``"<type> <opcode>(operands...), attrs"`` at the type
    boundary (tuple types carry nested parens and commas)."""
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rest[: i + 1], rest[i + 1:]
        return rest, ""
    head, _, tail = rest.partition(" ")
    return head, tail


def _entry_lines(hlo_text: str) -> List[str]:
    out: List[str] = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            out.append(line)
    return out


def output_aliases(hlo_text: str) -> Dict[int, int]:
    """``input_output_alias`` map from the module header: output tuple
    index -> parameter number (the donated buffers the step writes its
    new params/opt back into)."""
    head = hlo_text.split("\n", 1)[0]
    start = head.find("input_output_alias={")
    if start < 0:
        return {}
    depth = 0
    body = ""
    for i in range(start + len("input_output_alias="), len(head)):
        ch = head[i]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                body = head[start:i + 1]
                break
    return {int(o): int(p) for o, p in _ALIAS.findall(body)}


def entry_param_count(hlo_text: str) -> int:
    """Number of ENTRY-computation parameters — the SPMD donation audit
    (analysis/spmdlint.py) sanity-checks this against the flattened
    operand trees before attributing alias-map param numbers to leaves
    (nested computations carry their own parameters, so a global regex
    would overcount)."""
    n = 0
    for line in _entry_lines(hlo_text):
        if " parameter(" in line:
            n += 1
    return n


def hlo_entry_buffers(hlo_text: str, scopes: Sequence[str]
                      ) -> List[BufferInfo]:
    """Parse the ENTRY computation into buffer rows (program order).

    Classes: ``param`` (entry arguments — the executable's
    args_bytes), ``alias`` (ROOT tuple components that
    ``input_output_alias`` maps back onto donated arguments),
    ``output`` (fresh ROOT components: loss, eval outputs), ``temp``
    (everything else — what the executable's temp allocation holds).
    The ROOT tuple instruction itself is bookkeeping (pointers, not
    storage) and is excluded."""
    sre = _scope_re(scopes)
    lines = _entry_lines(hlo_text)
    bufs: List[BufferInfo] = []
    root_name = None
    root_operands: Tuple[str, ...] = ()
    root_is_tuple = False
    for line in lines:
        m = _INSTR.match(line)
        if m is None:
            continue
        name, rest = m.group(1), m.group(2)
        type_str, tail = _split_type(rest)
        opcode = tail.strip().split("(", 1)[0].strip()
        # strip metadata before scanning operands: op_name paths can
        # carry anything, including %-like text
        meta = _OP_NAME.search(line)
        body = line.split(", metadata=")[0]
        operands = tuple(re.findall(r"%([A-Za-z0-9_.\-]+)",
                                    body.split("= ", 1)[-1]))
        is_root = line.lstrip().startswith("ROOT")
        scope = scope_of_path(meta.group(1) if meta else "", sre)
        klass = "param" if opcode == "parameter" else "temp"
        bi = BufferInfo(name=name, index=len(bufs),
                        bytes=parse_shape_bytes(type_str),
                        operands=operands, scope=scope, klass=klass,
                        is_root=is_root)
        if is_root:
            root_name = name
            root_operands = operands
            root_is_tuple = opcode == "tuple"
        bufs.append(bi)
    # classify the ROOT: a `tuple` ROOT is a pointer shell whose
    # operands are the real output buffers, some of them mapped back
    # onto donated params by input_output_alias; any other ROOT (a
    # single-array result, or a tuple-typed op that materializes its
    # own outputs) is itself the output
    aliases = output_aliases(hlo_text)
    by_name = {b.name: b for b in bufs}
    if root_name is not None:
        root = by_name[root_name]
        if root_is_tuple:
            root.bytes = 0
            root.klass = "output"
            for k, oname in enumerate(root_operands):
                b = by_name.get(oname)
                if b is None or b.klass == "param":
                    continue
                b.klass = "alias" if k in aliases else "output"
        else:
            root.klass = "alias" if 0 in aliases else "output"
    return bufs


def live_timeline(bufs: List[BufferInfo], samples: int = 32
                  ) -> Dict[str, object]:
    """Def/last-use liveness over the scheduled program: the ``temp``
    live-byte curve, its peak, and the per-layer breakdown of the live
    set at the peak program point.

    Only ``temp``-class buffers enter the curve — parameters sit in the
    argument allocation for the whole program and aliased/fresh outputs
    in the argument/output allocations, so counting them would
    double-book against the executable's reported ``temp`` bytes.  Two
    buffer-assignment behaviors are modeled so the curve tracks the
    real allocation instead of over-reading it (validated ~0.3% off
    the executable's temp total on the CPU MNIST e2e): an operand
    making its LAST use at an instruction is freed before that
    instruction's own output is allocated (XLA's in-place reuse), and a
    temp nothing ever reads never enters the curve (it would be DCE'd).
    Returns ``peak_bytes``, ``peak_index``, ``peak_frac`` (fraction of
    the program at the peak point), ``timeline`` (``samples`` evenly
    spaced live-byte readings), and ``at_peak`` (scope -> live bytes,
    unjoined buffers under ``(unattributed)``)."""
    n = len(bufs)
    if n == 0:
        return {"peak_bytes": 0, "peak_index": 0, "peak_frac": 0.0,
                "timeline": [], "at_peak": {}}
    last_use: Dict[str, int] = {}
    for b in bufs:
        for o in b.operands:
            last_use[o] = b.index
    live = 0
    curve: List[int] = []
    peak, peak_i = 0, 0
    # keyed by (unique) buffer name: membership, removal, and the
    # peak-set copy stay O(1)/O(live) — a flagship step's ENTRY runs
    # tens of thousands of instructions, so a list-scanning walk would
    # go quadratic inside the train loop's window-close path
    live_set: Dict[str, BufferInfo] = {}
    at_peak: List[BufferInfo] = []
    for i, b in enumerate(bufs):
        for o in dict.fromkeys(b.operands):
            ob = live_set.get(o)
            if ob is not None and last_use.get(o) == i:
                live -= ob.bytes
                del live_set[o]
        if b.klass == "temp" and last_use.get(b.name, b.index) > b.index:
            live += b.bytes
            live_set[b.name] = b
        if live > peak:
            peak, peak_i = live, i
            at_peak = list(live_set.values())
        curve.append(live)
    step = max(n / max(samples, 1), 1.0)
    timeline = [curve[min(int(k * step), n - 1)]
                for k in range(min(samples, n))]
    breakdown: Dict[str, int] = {}
    for b in at_peak:
        key = b.scope if b.scope is not None else OTHER_ROW
        breakdown[key] = breakdown.get(key, 0) + b.bytes
    return {"peak_bytes": peak, "peak_index": peak_i,
            "peak_frac": round(peak_i / n, 4), "timeline": timeline,
            "at_peak": breakdown}


def mem_table(hlo_text: str, scopes: Sequence[str],
              exec_stats: Optional[Dict[str, int]] = None,
              param_rows: Optional[Dict[str, Dict[str, int]]] = None,
              model_rows: Optional[Dict[str, Dict[str, float]]] = None
              ) -> Dict[str, object]:
    """The ``mem_profile`` record payload (doc/monitor.md).

    ``exec_stats`` is the compiled executable's measured truth
    (``NetTrainer.step_memory_stats``: args/out/temp/alias/code bytes);
    ``param_rows`` maps scope -> ``{param_bytes, opt_bytes}`` (the
    trainer's per-device leaf accounting, ZeRO/model shards already
    divided out); ``model_rows`` maps scope -> the analytic model's
    per-layer bytes (analysis/memmodel.py) and adds ``model_bytes`` /
    ``model_x`` columns the same way layer_profile carries roofline
    columns.  Rows are sorted by total bytes; ``coverage`` is the
    scope-attributed share of peak-live temp bytes."""
    bufs = hlo_entry_buffers(hlo_text, scopes)
    tl = live_timeline(bufs)
    at_peak: Dict[str, int] = dict(tl["at_peak"])
    param_rows = param_rows or {}
    model_rows = model_rows or {}
    all_scopes = sorted(set(at_peak) | set(param_rows))
    peak = int(tl["peak_bytes"])
    rows = []
    for scope in all_scopes:
        act = int(at_peak.get(scope, 0))
        pr = param_rows.get(scope, {})
        row = {"layer": scope,
               "param_bytes": int(pr.get("param_bytes", 0)),
               "opt_bytes": int(pr.get("opt_bytes", 0)),
               "act_bytes": act}
        row["total_bytes"] = (row["param_bytes"] + row["opt_bytes"]
                              + act)
        mr = model_rows.get(scope)
        if mr:
            mb = int(sum(mr.values()))
            row["model_bytes"] = mb
            if mb > 0:
                row["model_x"] = round(row["total_bytes"] / mb, 2)
        rows.append(row)
    rows.sort(key=lambda r: -r["total_bytes"])
    total = sum(r["total_bytes"] for r in rows) or 1
    for r in rows:
        r["share"] = round(r["total_bytes"] / total, 4)
    attributed = sum(v for k, v in at_peak.items() if k != OTHER_ROW)
    out: Dict[str, object] = {
        "peak_live_bytes": peak,
        "peak_frac": tl["peak_frac"],
        "timeline": [int(v) for v in tl["timeline"]],
        "coverage": round(attributed / peak, 4) if peak else 0.0,
        "rows": rows,
    }
    if exec_stats:
        out["exec"] = {k: int(v) for k, v in exec_stats.items()}
    return out
