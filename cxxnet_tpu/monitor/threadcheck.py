"""threadcheck: test-only lock-witness sanitizer + interleaving harness.

The static half of the concurrency discipline lives in
``analysis/racelint.py``: every cross-thread-mutated attribute carries a
declared policy, and ``guarded-by`` accesses are verified *lexically*.
This module is the dynamic half — it turns those same declarations into
runtime assertions, so a guarded attribute touched without its lock
fails the touching test with a stack trace instead of corrupting state
silently.

Witness
-------
:func:`checked` builds a subclass of a production class whose
``guarded-by``-declared attributes (parsed by racelint's own
:func:`~cxxnet_tpu.analysis.racelint.collect_policies`, so lint and
witness can never disagree about the attr→lock map) are replaced with
data descriptors.  After :func:`arm` is called on an instance, every
read or write of a guarded attribute asserts that one of its declaring
locks is held by the current thread, raising :class:`LockWitnessError`
otherwise.  Plain ``threading.Lock`` attributes are wrapped in
:class:`WitnessLock` at arm time for exact ownership tracking;
``Condition``/``RLock`` objects are queried through their ``_is_owned``.

``__slots__`` classes work: the subclass delegates storage to the
parent's slot member descriptors, and the subclass's fresh ``__dict__``
holds the witness bookkeeping.

Interleaving harness
--------------------
:func:`hook` is a no-op marker that race fixtures place between the
read and the write of a critical section; a test installs a callback
with :func:`set_hook` (usually a barrier wait) to force the exact
interleaving that loses an update — deterministically, not
stochastically.  :func:`stress` is the post-fix side: N threads hammer
a callable under a tiny ``sys.setswitchinterval`` so the fixed code can
demonstrate it no longer loses updates.

Test-only by design: nothing in the serving/checkpoint/io planes
imports this module; tests opt in per class.
"""

from __future__ import annotations

import inspect
import sys
import threading
from typing import Callable, Dict, Iterable, Optional, Tuple


class LockWitnessError(AssertionError):
    """A guarded-by-declared attribute was touched without its lock."""


class WitnessLock:
    """Owner-tracking wrapper over a ``threading.Lock``.

    Mutual exclusion is delegated to the wrapped lock (so other holders
    of the same inner lock object — e.g. a ``Condition`` built over it —
    still exclude correctly); ownership is recorded here so
    :func:`held_by_me` answers for the *current thread*, which a plain
    ``Lock.locked()`` cannot."""

    def __init__(self, inner: Optional[threading.Lock] = None):
        self._inner = inner if inner is not None else threading.Lock()
        self._owner: Optional[int] = None
        self.acquisitions = 0    # telemetry for tests

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self.acquisitions += 1
        return got

    def release(self) -> None:
        self._owner = None
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


def _held(lock) -> bool:
    """Best-effort: does the CURRENT thread hold ``lock``?"""
    if isinstance(lock, WitnessLock):
        return lock.held_by_me()
    is_owned = getattr(lock, "_is_owned", None)  # RLock / Condition
    if is_owned is not None:
        try:
            return bool(is_owned())
        except Exception:  # noqa: BLE001 — witness must not crash code
            return False
    locked = getattr(lock, "locked", None)
    return bool(locked()) if locked is not None else False


class _WitnessAttr:
    """Data descriptor over one guarded attribute: storage delegates to
    the parent slot member (``__slots__`` classes) or the instance dict;
    every touch after :func:`arm` asserts a declaring lock is held."""

    def __init__(self, base: type, name: str, locks: Tuple[str, ...]):
        self._member = base.__dict__.get(name)   # slot member descriptor
        self._name = name
        self._locks = locks
        # value-storage key, distinct from the ``_threadcheck_armed``
        # flag namespace (a guarded attr named ``armed`` must not
        # collide with the witness's own arming bit)
        self._key = f"_threadcheck_value_{name}"

    def _check(self, obj, op: str) -> None:
        if not obj.__dict__.get("_threadcheck_armed", False):
            return   # construction / un-armed instance: no witness
        for lname in self._locks:
            lock = getattr(obj, lname, None)
            if lock is not None and _held(lock):
                return
        raise LockWitnessError(
            f"{type(obj).__name__}.{self._name}: {op} on thread "
            f"{threading.current_thread().name!r} without holding "
            f"{' or '.join('self.' + n for n in self._locks)} "
            f"(declared guarded-by; see racelint)")

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj, "read")
        if self._member is not None:
            return self._member.__get__(obj, objtype)
        try:
            return obj.__dict__[self._key]
        except KeyError:
            raise AttributeError(self._name) from None

    def __set__(self, obj, value) -> None:
        self._check(obj, "write")
        if self._member is not None:
            self._member.__set__(obj, value)
        else:
            obj.__dict__[self._key] = value


def guarded_attrs(cls: type) -> Dict[str, Tuple[str, ...]]:
    """{attr: (lock attr names, ...)} for one class, parsed from its
    source file's ``# racelint: guarded-by(...)`` annotations."""
    from ..analysis import racelint
    src = inspect.getsourcefile(cls)
    if src is None:
        return {}
    polmap = racelint.collect_policies(src).get(cls.__name__, {})
    out: Dict[str, Tuple[str, ...]] = {}
    for attr, pol in polmap.items():
        if pol.kind == "guarded-by":
            out[attr] = tuple(a[5:] for a in pol.args
                              if a.startswith("self."))
    return out


def checked(cls: type) -> type:
    """Subclass of ``cls`` with witness descriptors over every
    guarded-by-declared attribute.  Instances behave identically until
    :func:`arm` is called on them."""
    guarded = guarded_attrs(cls)
    ns: Dict[str, object] = {
        "_threadcheck_guarded": guarded,
        # subclass deliberately has no __slots__: its __dict__ carries
        # the witness bookkeeping even over a __slots__ parent
    }
    for attr, locks in guarded.items():
        ns[attr] = _WitnessAttr(cls, attr, locks)
    return type(f"Checked{cls.__name__}", (cls,), ns)


def arm(obj) -> None:
    """Start witnessing ``obj`` (an instance of a :func:`checked`
    subclass): wrap its plain-Lock lock attributes in
    :class:`WitnessLock` for exact ownership, then enable the
    assertions."""
    guarded = getattr(type(obj), "_threadcheck_guarded", None)
    if guarded is None:
        raise TypeError(
            f"{type(obj).__name__} is not a checked() subclass")
    for locks in guarded.values():
        for lname in locks:
            lock = getattr(obj, lname, None)
            if lock is None or isinstance(lock, WitnessLock):
                continue
            # only wrap bare Locks; Condition/RLock already track owners
            if type(lock) is type(threading.Lock()):
                setattr(obj, lname, WitnessLock(lock))
    obj.__dict__["_threadcheck_armed"] = True


def disarm(obj) -> None:
    obj.__dict__["_threadcheck_armed"] = False


# --------------------------------------------------------------------------
# interleaving harness

_hooks: Dict[str, Callable[[], None]] = {}
_hook_lock = threading.Lock()


def hook(name: str) -> None:
    """Interleaving marker: a no-op unless a test installed a callback
    under ``name``.  Race fixtures call this between the read and the
    write of their critical section so tests can force the losing
    schedule with a barrier instead of praying to the scheduler."""
    cb = _hooks.get(name)
    if cb is not None:
        cb()


def set_hook(name: str, cb: Callable[[], None]) -> None:
    with _hook_lock:
        _hooks[name] = cb


def clear_hooks() -> None:
    with _hook_lock:
        _hooks.clear()


def stress(fn: Callable[[int], None], *, threads: int = 4,
           iters: int = 200, switch_interval: float = 1e-5) -> None:
    """Post-fix side of the harness: ``threads`` workers call
    ``fn(worker_index)`` ``iters`` times each under an aggressive
    bytecode switch interval, re-raising the first worker exception.
    A start barrier lines the workers up so contention is real."""
    start = threading.Barrier(threads)
    errors: list = []

    def run(idx: int) -> None:
        try:
            start.wait()
            for _ in range(iters):
                fn(idx)
        except BaseException as e:  # noqa: BLE001 — reported to caller
            errors.append(e)

    old = sys.getswitchinterval()
    sys.setswitchinterval(switch_interval)
    try:
        ts = [threading.Thread(target=run, args=(i,), daemon=True,
                               name=f"cxxnet-threadcheck-stress-{i}")
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)
    if errors:
        raise errors[0]


def run_interleaved(first: Callable[[], None],
                    second: Callable[[], None],
                    hook_name: str) -> None:
    """Deterministic two-thread lost-update schedule:

    thread A runs ``first`` and parks at ``hook_name`` (installed here)
    mid-critical-section; thread B then runs ``second`` to completion;
    A resumes.  With an unguarded read-modify-write, A's resumed write
    clobbers B's — the canonical race, forced every time."""
    a_at_hook = threading.Event()
    b_done = threading.Event()
    in_a = threading.local()
    a_errors: list = []

    def gate() -> None:
        # only thread A parks; B passes straight through the hook
        if getattr(in_a, "yes", False):
            a_at_hook.set()
            b_done.wait(timeout=10.0)

    set_hook(hook_name, gate)
    try:
        def run_a() -> None:
            try:
                in_a.yes = True
                first()
            except BaseException as e:  # noqa: BLE001 — reraised below
                a_errors.append(e)
                a_at_hook.set()  # unblock the caller's wait

        ta = threading.Thread(target=run_a, daemon=True,
                              name="cxxnet-threadcheck-a")
        ta.start()
        assert a_at_hook.wait(timeout=10.0), \
            f"fixture never reached hook {hook_name!r}"
        if not a_errors:
            second()
        b_done.set()
        ta.join(timeout=10.0)
        assert not ta.is_alive(), "interleaved thread A did not finish"
        if a_errors:
            raise a_errors[0]
    finally:
        clear_hooks()
