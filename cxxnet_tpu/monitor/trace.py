"""Profiler-trace (xplane.pb) parsing + the generalized profiling window.

One implementation shared by bench.py (device step time), the telemetry
round records, and tools/trace_summary.py — the round-6 BASELINE work
hand-rolled this parse twice; third time it's a library.

The parser is a minimal protobuf wire-format decoder for the XSpace
proto (tensorflow/tsl/profiler/protobuf/xplane.proto), reading only the
fields the tools need: plane/line names, event metadata names, and event
durations.  No tensorflow import — the bench container has TF, the test
container might not, and a 600 MB dependency for four varint fields is
the wrong trade.  Field numbers verified against the installed proto:
XSpace.planes=1; XPlane.name=2/lines=3/event_metadata=4 (map: key=1,
value=2); XLine.name=2/events=4; XEvent.metadata_id=1/offset_ps=2/
duration_ps=3; XEventMetadata.id=1/name=2/display_name=3.

Collective classification: cross-chip reduction ops (all-reduce /
reduce-scatter / all-gather / all-to-all / collective-permute, plus
their async ``-start``/``-done`` halves) get a dedicated comm bucket
instead of lumping with fusions — the comm column in
tools/trace_summary.py, the bench ``--dp-scaling`` comm/compute split,
and the ``comm_sec``/``overlap_frac`` gauges all read through it.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple

# --------------------------------------------------------------- wire format

_WIRE_VARINT, _WIRE_I64, _WIRE_LEN, _WIRE_I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift, val = 0, 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow (corrupt trace?)")


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    LEN fields yield the raw bytes; varints yield ints; fixed-width
    fields yield raw bytes (unused here but skipped correctly)."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            val, i = _read_varint(buf, i)
        elif wire == _WIRE_LEN:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == _WIRE_I64:
            val = buf[i:i + 8]
            i += 8
        elif wire == _WIRE_I32:
            val = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# ----------------------------------------------------------------- xplane

class XEvent:
    __slots__ = ("metadata_id", "duration_ps", "offset_ps")

    def __init__(self, metadata_id: int, duration_ps: int,
                 offset_ps: int = 0):
        self.metadata_id = metadata_id
        self.duration_ps = duration_ps
        self.offset_ps = offset_ps


class XLine:
    __slots__ = ("name", "events")

    def __init__(self, name: str, events: List[XEvent]):
        self.name = name
        self.events = events


class XPlane:
    __slots__ = ("name", "lines", "event_names", "event_display")

    def __init__(self, name: str, lines: List[XLine],
                 event_names: Dict[int, str],
                 event_display: Optional[Dict[int, str]] = None):
        self.name = name
        self.lines = lines
        self.event_names = event_names
        # XEventMetadata.display_name (field 3): TPU op events carry the
        # framework op path here ("jit(step)/03-conv/conv_general_..."),
        # which is where layer attribution reads named scopes from when
        # the trace itself has them (monitor/attribution.py)
        self.event_display = event_display if event_display is not None \
            else {}


def _parse_event(buf: bytes) -> XEvent:
    mid = dur = off = 0
    for field, _, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            off = val
        elif field == 3:
            dur = val
    return XEvent(mid, dur, off)


def _parse_line(buf: bytes) -> XLine:
    name, events = "", []
    for field, _, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 4:
            events.append(_parse_event(val))
    return XLine(name, events)


def _parse_event_metadata_entry(buf: bytes) -> Tuple[int, str, str]:
    """map<int64, XEventMetadata> entry -> (id, name, display_name)."""
    key, name, display = 0, "", ""
    for field, _, val in _fields(buf):
        if field == 1:
            key = val
        elif field == 2:
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 3:
                    display = v2.decode("utf-8", "replace")
    return key, name, display


def _parse_plane(buf: bytes) -> XPlane:
    name, lines, event_names, event_display = "", [], {}, {}
    for field, _, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            lines.append(_parse_line(val))
        elif field == 4:
            k, v, d = _parse_event_metadata_entry(val)
            event_names[k] = v
            if d:
                event_display[k] = d
    return XPlane(name, lines, event_names, event_display)


def parse_xspace(path: str) -> List[XPlane]:
    """Parse one ``*.xplane.pb`` file into a list of planes."""
    with open(path, "rb") as f:
        buf = f.read()
    return [_parse_plane(val) for field, wire, val in _fields(buf)
            if field == 1 and wire == _WIRE_LEN]


def find_xplane(path: str) -> str:
    """``path`` is either an ``.xplane.pb`` file or a profiler log dir
    (the newest xplane under it wins — jax writes one per session)."""
    if os.path.isfile(path):
        return path
    paths = glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {path!r}")
    return max(paths, key=os.path.getmtime)


# --------------------------------------------------------------- summaries

def _matching_events(planes: List[XPlane], plane_filter: str,
                     line_filter: str) -> Iterator[Tuple[XPlane, XEvent]]:
    for plane in planes:
        if plane_filter not in plane.name:
            continue
        for line in plane.lines:
            if line_filter not in line.name:
                continue
            for ev in line.events:
                yield plane, ev


def total_ms_in(planes: List[XPlane], plane_filter: str = "TPU",
                line_filter: str = "XLA Modules") -> float:
    return sum(ev.duration_ps / 1e9
               for _, ev in _matching_events(planes, plane_filter,
                                             line_filter))


def op_totals_in(planes: List[XPlane], plane_filter: str = "TPU",
                 line_filter: str = "XLA Ops"
                 ) -> Dict[str, Tuple[float, int]]:
    out: Dict[str, List[float]] = {}
    for plane, ev in _matching_events(planes, plane_filter, line_filter):
        name = plane.event_names.get(ev.metadata_id, f"#{ev.metadata_id}")
        cur = out.setdefault(name, [0.0, 0])
        cur[0] += ev.duration_ps / 1e9
        cur[1] += 1
    return {k: (v[0], v[1]) for k, v in out.items()}


def device_total_ms(path: str, plane_filter: str = "TPU",
                    line_filter: str = "XLA Modules") -> float:
    """Total on-chip XLA-module time (ms) across matching device planes
    — the bench.py "device step" numerator."""
    return total_ms_in(parse_xspace(find_xplane(path)),
                       plane_filter, line_filter)


def op_totals(path: str, plane_filter: str = "TPU",
              line_filter: str = "XLA Ops") -> Dict[str, Tuple[float, int]]:
    """Aggregate per-op device time: op name -> (total_ms, count)."""
    return op_totals_in(parse_xspace(find_xplane(path)),
                        plane_filter, line_filter)


def top_ops(path: str, k: int = 10, plane_filter: str = "TPU",
            line_filter: str = "XLA Ops"
            ) -> List[Tuple[str, float, int]]:
    """Top-k ops by total device time: [(name, total_ms, count), ...]."""
    totals = op_totals(path, plane_filter, line_filter)
    ranked = sorted(((name, ms, n) for name, (ms, n) in totals.items()),
                    key=lambda t: -t[1])
    return ranked[:k]


# ------------------------------------------------------------- collectives

#: cross-chip collective op families (XLA HLO opcode spellings)
COLLECTIVE_KINDS = frozenset((
    "all-reduce", "reduce-scatter", "all-gather", "all-to-all",
    "collective-permute", "collective-broadcast",
))


def collective_kind(op_name: str) -> Optional[Tuple[str, str]]:
    """``(kind, phase)`` for collective ops, ``None`` for everything
    else.  ``phase`` is ``"start"``/``"done"`` for the async halves,
    ``"sync"`` otherwise.

    Classifies on the BASE opcode (the text before the first ``.``),
    never by substring over the full name: the round-5 trace parser
    matched "copy-done" against whole event strings and counted every
    fusion CONSUMING an async copy as a copy (BASELINE.md round 5); the
    same bug here would book a fusion named ``loop-all-reduce-fusion.3``
    as communication.
    """
    base = op_name.lstrip("%").split(".", 1)[0]
    for suffix, phase in (("-start", "start"), ("-done", "done")):
        if base.endswith(suffix):
            kind = base[: -len(suffix)]
            return (kind, phase) if kind in COLLECTIVE_KINDS else None
    return (base, "sync") if base in COLLECTIVE_KINDS else None


def comm_summary_in(planes: List[XPlane], plane_filter: str = "TPU",
                    line_filter: str = "XLA Ops") -> Dict[str, object]:
    """Trace-attributed collective time.

    Async ``-start``/``-done`` halves are PAIRED (FIFO per kind within a
    line — starts and dones interleave in program order) and counted
    once: the pair's wall is its in-flight span
    ``done.end - start.offset`` (communication rides behind whatever
    compute executes between the halves), its EXPOSED time is the done
    op's duration (the wait the device actually ate).  Sync collectives
    are fully exposed.  ``overlap_frac = 1 - exposed/comm`` is then the
    fraction of collective wall hidden behind compute.
    """
    comm_ms = exposed_ms = 0.0
    by_kind: Dict[str, List[float]] = {}
    unpaired = 0
    for plane in planes:
        if plane_filter not in plane.name:
            continue
        for line in plane.lines:
            if line_filter not in line.name:
                continue
            open_starts: Dict[str, List[XEvent]] = {}
            events = sorted(line.events, key=lambda e: e.offset_ps)
            for ev in events:
                name = plane.event_names.get(ev.metadata_id, "")
                ck = collective_kind(name)
                if ck is None:
                    continue
                kind, phase = ck
                if phase == "start":
                    open_starts.setdefault(kind, []).append(ev)
                    continue
                if phase == "done" and open_starts.get(kind):
                    start = open_starts[kind].pop(0)
                    flight = (ev.offset_ps + ev.duration_ps
                              - start.offset_ps) / 1e9
                    exposed = ev.duration_ps / 1e9
                else:
                    # sync op, or a done whose start fell outside the
                    # trace window: fully exposed
                    flight = exposed = ev.duration_ps / 1e9
                    if phase == "done":
                        unpaired += 1
                comm_ms += flight
                exposed_ms += exposed
                cur = by_kind.setdefault(kind, [0.0, 0])
                cur[0] += flight
                cur[1] += 1
            for kind, starts in open_starts.items():
                for ev in starts:  # start with no done in the window
                    unpaired += 1
                    dur = ev.duration_ps / 1e9
                    comm_ms += dur
                    exposed_ms += dur
                    cur = by_kind.setdefault(kind, [0.0, 0])
                    cur[0] += dur
                    cur[1] += 1
    frac = 0.0
    if comm_ms > 0:
        frac = min(max(1.0 - exposed_ms / comm_ms, 0.0), 1.0)
    return {"comm_ms": comm_ms, "exposed_ms": exposed_ms,
            "overlap_frac": frac, "unpaired": unpaired,
            "by_kind": {k: (v[0], v[1]) for k, v in by_kind.items()}}


def comm_report(path: str, steps: int = 1, plane_filter: str = "TPU",
                line_filter: str = "XLA Ops") -> Dict[str, object]:
    """Per-step comm/compute attribution of one trace — the
    ``comm_sec`` / ``overlap_frac`` gauge source (doc/monitor.md) and
    the bench ``--dp-scaling`` comm-share numbers."""
    return comm_report_in(parse_xspace(find_xplane(path)), steps,
                          plane_filter, line_filter)


def comm_report_in(planes: List[XPlane], steps: int = 1,
                   plane_filter: str = "TPU",
                   line_filter: str = "XLA Ops") -> Dict[str, object]:
    """:func:`comm_report` over already-parsed planes (the profiling
    window parses once and feeds both this and layer attribution).
    Falls back to an unfiltered plane scan when nothing matches
    ``plane_filter`` (CPU runtime traces name their planes
    differently)."""
    device_ms = total_ms_in(planes, plane_filter)
    comm = comm_summary_in(planes, plane_filter, line_filter)
    if device_ms == 0.0 and comm["comm_ms"] == 0.0 and plane_filter:
        device_ms = total_ms_in(planes, "")
        comm = comm_summary_in(planes, "", line_filter)
    steps = max(int(steps), 1)
    comm_sec = comm["comm_ms"] / 1e3 / steps
    device_sec = device_ms / 1e3 / steps
    return {
        "steps": steps,
        "device_sec": round(device_sec, 6),
        "comm_sec": round(comm_sec, 6),
        "comm_share": round(comm["comm_ms"] / device_ms, 4)
        if device_ms else 0.0,
        "overlap_frac": round(comm["overlap_frac"], 4),
        "comm_by_kind": {k: round(ms / steps, 3)
                         for k, (ms, _) in comm["by_kind"].items()},
    }


# --------------------------------------------------------- profiling window

class ProfileWindow:
    """Generalized profiler window over the train loop.

    Replaces the hard-coded "trace the second round" block: with
    ``prof_start_step >= 0`` the trace starts before global update step N
    (steps count update dispatches across rounds) and runs
    ``prof_num_steps`` steps (0 = to round end).  With the default
    ``prof_start_step = -1`` the legacy behavior holds — the window opens
    at the start of the round past compilation (the second round, or the
    only round) — but ``prof_num_steps`` can now bound it.

    ``every = N`` (``prof_every``, doc/monitor.md) turns the one-shot
    window into a RECURRING one: a fresh window opens at the start of
    every Nth round (first at the legacy prof round, past compilation),
    each writing its trace under ``<trace_dir>/rNNNN`` so per-window
    reports never read a stale xplane.  Each closed window leaves its
    location/length in ``last_window_dir`` / ``last_window_steps`` for
    the report emitters.  All hooks are no-ops when ``trace_dir`` is
    empty, and — for one-shot windows — once the window closed.
    """

    def __init__(self, trace_dir: str, start_step: int = -1,
                 num_steps: int = 0, every: int = 0):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self.every = every
        self.active = False
        self.done = False
        self._steps_traced = 0
        self.last_window_dir = ""
        self.last_window_steps = 0

    @property
    def steps_traced(self) -> int:
        return self._steps_traced

    def _start(self, where: str) -> None:
        import jax
        jax.profiler.start_trace(where)
        self.active = True
        self.last_window_dir = where
        self._steps_traced = 0

    def maybe_start_round(self, rounds_done: int, prof_round: int) -> None:
        """Round-boundary hook for whole-round windows (legacy one-shot
        and the recurring ``prof_every`` cadence)."""
        if not self.trace_dir or self.start_step >= 0 or self.active:
            return
        if self.every > 0:
            if rounds_done >= prof_round \
                    and (rounds_done - prof_round) % self.every == 0:
                self._start(os.path.join(self.trace_dir,
                                         f"r{rounds_done:04d}"))
        elif not self.done and rounds_done == prof_round:
            self._start(self.trace_dir)

    def maybe_start_step(self, global_step: int) -> None:
        """Pre-dispatch hook: opens a step-addressed window."""
        if (self.trace_dir and self.start_step >= 0 and not self.done
                and not self.active and global_step >= self.start_step):
            self._start(self.trace_dir)

    def after_step(self) -> bool:
        """Post-dispatch hook; returns True when this step closed the
        window (the caller emits the trace report)."""
        if not self.active:
            return False
        self._steps_traced += 1
        if self.num_steps and self._steps_traced >= self.num_steps:
            self.stop()
            return True
        return False

    def round_end(self) -> bool:
        """Round-boundary hook; an unbounded window closes here."""
        if self.active and not self.num_steps:
            self.stop()
            return True
        return False

    def stop(self) -> None:
        import jax
        jax.profiler.stop_trace()
        self.active = False
        self.last_window_steps = self._steps_traced
        if not self.every:
            self.done = True
