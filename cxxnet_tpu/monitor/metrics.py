"""MetricsRegistry: counters / gauges / histograms + the JSONL sink.

One registry per trainer (``NetTrainer.metrics``).  Counters are also
how jit-retrace detection works: the step builders bump
``train_step_traces`` / ``eval_step_traces`` from INSIDE the traced
python body, which executes once per trace — a count climbing past the
expected compilations (base step, masked tail step) flags silent
recompiles from ``round_batch = 0`` shape churn.

Sink spec: ``metrics_sink = jsonl:<path>`` appends one JSON object per
record, each stamped with ``ts`` (unix seconds) and ``kind``.  Records
share field names with BENCH_*.json (``device_step_ms``,
``step_ms_median``, ...) so one pandas/gnuplot pipeline reads both; see
doc/monitor.md for the per-kind schema.
"""

from __future__ import annotations

import json
import math
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, TextIO


def nearest_rank(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a SORTED list: ceil(n*q/100)-1,
    clamped.  The one copy shared by Histogram.summary, the serve
    window stats (serve/batcher.py), and the span-table decomposition
    (monitor/spans.py) — their p99s must agree by construction."""
    i = max(math.ceil(len(sorted_vals) * q / 100.0) - 1, 0)
    return sorted_vals[min(i, len(sorted_vals) - 1)]


class Histogram:
    """Streaming summary (count/sum/min/max/last + p50/p95/p99).

    Percentiles come from a bounded reservoir (uniform sample of
    everything observed, ``_RESERVOIR`` values max, deterministic
    replacement) — exact until the reservoir fills, an unbiased estimate
    after, and never more than a few KB of host memory per series.  The
    serving-telemetry consumer (``pred``/``extract`` per-batch latency,
    the ``latency`` JSONL record — ROADMAP item 1) reads tail latency
    through this.

    Thread-safe: ``serve_latency_sec`` is observed from every serve
    client thread at once, so the count/total/reservoir update is one
    critical section — the unlocked read-modify-write it replaced lost
    observations under contention (two clients reading the same
    ``count`` and both writing ``count + 1``)."""

    _RESERVOIR = 2048

    __slots__ = ("count", "total", "min", "max", "last", "_samples",
                 "_rng", "_lock")

    def __init__(self):
        self.count = 0                        # racelint: guarded-by(self._lock)
        self.total = 0.0                      # racelint: guarded-by(self._lock)
        self.min: Optional[float] = None      # racelint: guarded-by(self._lock)
        self.max: Optional[float] = None      # racelint: guarded-by(self._lock)
        self.last: Optional[float] = None     # racelint: guarded-by(self._lock)
        self._samples: List[float] = []       # racelint: guarded-by(self._lock)
        # fixed seed: summaries must not vary run to run on equal input
        self._rng = random.Random(0x5EED)
        self._lock = threading.Lock()

    # racelint: thread(shared)
    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            if len(self._samples) < self._RESERVOIR:
                self._samples.append(v)
            else:  # reservoir replacement: keep a uniform sample
                j = self._rng.randrange(self.count)
                if j < self._RESERVOIR:
                    self._samples[j] = v

    _nearest_rank = staticmethod(nearest_rank)

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 100]; nearest-rank over the reservoir."""
        with self._lock:
            s = sorted(self._samples)
        if not s:
            return None
        return self._nearest_rank(s, q)

    def summary(self) -> Dict[str, float]:
        with self._lock:
            out = {"count": self.count, "sum": self.total}
            if self.count:
                # one sort feeds all three ranks
                s = sorted(self._samples)
                out.update(min=self.min, max=self.max,
                           mean=self.total / self.count, last=self.last,
                           p50=self._nearest_rank(s, 50),
                           p95=self._nearest_rank(s, 95),
                           p99=self._nearest_rank(s, 99))
        return out


class JsonlSink:
    def __init__(self, path: str):
        self.path = path
        # a predecessor killed mid-write leaves a torn, newline-less
        # tail; appending straight after it would glue THIS run's first
        # record onto the torn line and lose both — restore the line
        # boundary before the first write
        torn = False
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                torn = f.read(1) != b"\n"
        except (OSError, ValueError):
            pass  # missing or empty file: nothing to repair
        # append-only stream by design (torn tails are tolerated by
        # every JSONL reader here; atomic_write would buffer the run)
        # disclint: ok(atomic-write)
        self._fo: TextIO = open(path, "a")  # racelint: guarded-by(self._lock)
        if torn:
            self._fo.write("\n")
        # the async checkpoint writer emits its `ckpt` record from the
        # writer thread while the train loop emits step records; a
        # buffered TextIOWrapper is not thread-safe, so serialize writes
        # or two records can interleave mid-line (torn JSONL)
        self._lock = threading.Lock()

    # racelint: thread(shared)
    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, sort_keys=True, default=_jsonable) + "\n"
        with self._lock:
            self._fo.write(line)
            self._fo.flush()  # records must survive a fatal NaN abort

    def close(self) -> None:
        with self._lock:
            self._fo.close()


def _jsonable(v):
    """Last-resort coercion: numpy scalars and device arrays become
    python floats; anything else becomes its repr (a record must never
    kill the training step)."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


def create_sink(spec: str) -> Optional[JsonlSink]:
    """Parse a ``metrics_sink`` value.  Empty/"none"/"0" disable."""
    if not spec or spec in ("none", "0"):
        return None
    if spec.startswith("jsonl:"):
        return JsonlSink(spec[len("jsonl:"):])
    raise ValueError(
        f"metrics_sink = {spec!r}: expected jsonl:<path> (or none)")


class MetricsRegistry:
    """Counters, gauges, histograms, an optional record sink, and the
    host-side span tracer (monitor/spans.py — disabled until
    ``trace_sample`` arms it; components reach it as
    ``metrics.tracer``, the one object every request-path layer
    already shares)."""

    def __init__(self):
        # racelint: atomic(per-key writes, single writer per key by convention; the scrape path reads via copy_racy)
        self.counters: Dict[str, int] = {}
        # racelint: atomic(per-key float store; scrape reads via copy_racy)
        self.gauges: Dict[str, float] = {}
        # racelint: atomic(per-key insert via setdefault; Histogram itself is internally locked)
        self.histograms: Dict[str, Histogram] = {}
        # racelint: atomic(whole-object swap; emit() snapshots one reference per call)
        self.sink: Optional[JsonlSink] = None
        # registry birth stamp: the admin plane's /statusz uptime and
        # the promtext scrape both date from here (serve/admin.py)
        self.created = time.time()
        from .spans import SpanTracer
        self.tracer = SpanTracer(self)

    # ------------------------------------------------------------- config
    def configure_sink(self, spec: str) -> None:
        old, self.sink = self.sink, None
        if old is not None:
            old.close()
        self.sink = create_sink(spec)

    def configure_tracer(self, sample: int) -> None:
        """``trace_sample = N``: span-trace every Nth request (0 off).
        Span records land only while the sink is active — the tracer
        object itself is stable, so early-bound references stay live."""
        self.tracer.configure(sample)

    @property
    def active(self) -> bool:
        return self.sink is not None

    # ----------------------------------------------------------- instruments
    # racelint: thread(shared)
    def counter_inc(self, name: str, n: int = 1) -> int:
        self.counters[name] = self.counters.get(name, 0) + n
        return self.counters[name]

    # racelint: thread(shared)
    def set_gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    # racelint: thread(shared)
    def observe(self, name: str, value: float) -> None:
        h = self.histograms.get(name)
        if h is None:
            # setdefault is one C-level dict op: two threads first-
            # observing the same series converge on ONE Histogram —
            # the get-then-insert it replaced let the loser's instance
            # (and its observation) vanish
            h = self.histograms.setdefault(name, Histogram())
        h.observe(value)

    def snapshot(self) -> Dict[str, Any]:
        return {"counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: h.summary()
                               for k, h in self.histograms.items()}}

    # --------------------------------------------------------------- records
    # racelint: thread(shared)
    def emit(self, kind: str, **fields) -> None:
        """Write one JSONL record (no-op without a sink).  Sink I/O
        failures (disk full, path gone) disable the sink and warn instead
        of propagating — telemetry must never kill a training run."""
        # one snapshot of the reference: a concurrent emit failure (or
        # close()) swaps self.sink to None, and re-reading it after the
        # None-check raised AttributeError into the train loop
        sink = self.sink
        if sink is None:
            return
        rec = {"ts": round(time.time(), 3), "kind": kind}
        rec.update(fields)
        try:
            sink.write(rec)
        except (OSError, ValueError) as e:  # ValueError: closed file
            path = sink.path
            try:
                sink.close()
            except (OSError, ValueError):
                pass
            if self.sink is sink:
                self.sink = None
            from . import log
            log.warn(f"metrics sink {path}: {e}; telemetry disabled "
                     "for the rest of the run")

    def close(self) -> None:
        sink, self.sink = self.sink, None
        if sink is not None:
            sink.close()


def device_memory_gauges(devices) -> Dict[str, float]:
    """HBM gauges from ``device.memory_stats()`` — max over the local
    devices (the high-water device is the OOM risk; the sentinel keeps
    watching it), plus the min and the per-device spread when more than
    one device reports, so a SKEWED shard — one device holding an
    unsharded embedding while its peers idle — is visible instead of
    hiding under the max.  Empty dict when the backend doesn't report
    (CPU) — callers omit the fields rather than write zeros that read
    as "no memory used"."""
    peaks: list = []
    in_uses: list = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        if "peak_bytes_in_use" in stats:
            peaks.append(int(stats["peak_bytes_in_use"]))
        if "bytes_in_use" in stats:
            in_uses.append(int(stats["bytes_in_use"]))
    out: Dict[str, int] = {}
    if peaks:
        out["hbm_peak_bytes"] = max(peaks)
        if len(peaks) > 1:
            out["hbm_peak_bytes_min"] = min(peaks)
            if max(peaks) > 0:
                out["hbm_peak_spread_pct"] = round(
                    (max(peaks) - min(peaks)) / max(peaks) * 100.0, 2)
    if in_uses:
        out["hbm_bytes_in_use"] = max(in_uses)
    return out
