"""Regression sentinels + flight recorder over the telemetry stream.

The JSONL sink records what happened; nothing watched the stream for
"this run just got slower / chattier / fatter" — that was manual
archaeology over round records.  ``sentinel = 1`` (doc/monitor.md) arms
rolling-EWMA watchers over the three trend series every perf PR reads:

* ``examples_per_sec`` (step records) — throughput regressions
  (direction ``drop``: an input stall, a silent retrace, a slow disk);
* ``comm_share`` (trace records, per closed profiling window) —
  communication creep (direction ``rise``);
* ``hbm_peak_bytes`` (round records) — memory high-water creep toward
  an OOM (direction ``rise``).

Serving runs (``serve_sentinel = 1``, doc/serve.md) arm three more
over the ``serve_window`` records the task's reporter thread emits:
``serve_p99_ms`` (rise — tail-latency regression), ``serve_qps``
(drop — throughput collapse), and ``serve_queue_depth`` (rise —
standing-queue growth, the saturation precursor).  These are the
serving-regression signal the hot-swap/rollback machinery (ROADMAP
item 4) consumes.

Each watcher smooths its series with an EWMA and fires an ``anomaly``
record when a new value deviates more than ``sentinel_rel`` (relative)
from the smoothed baseline in its bad direction, after
``sentinel_warmup`` observations.  Anomalous values still fold into the
EWMA afterwards, so a sustained level shift fires a bounded burst while
the baseline converges instead of alarming forever.

The flight recorder keeps the last ``sentinel_ring`` step records in a
ring; an anomaly — or ``TrainingDiverged`` / any mid-round exception in
the train task — dumps the ring to the sink as one ``flight`` record,
so the steps leading INTO the incident survive the abort (the sink
flushes per record; see metrics.JsonlSink).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from .metrics import MetricsRegistry


class Ewma:
    """Exponentially-weighted mean; ``None`` until the first update."""

    __slots__ = ("alpha", "mean")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.mean: Optional[float] = None

    def update(self, value: float) -> Optional[float]:
        """Fold ``value`` in; returns the PRE-update mean (the baseline
        the value should be judged against)."""
        prev = self.mean
        self.mean = value if prev is None else (
            self.alpha * value + (1.0 - self.alpha) * prev)
        return prev


class Sentinel:
    """One watched series: EWMA baseline + relative-deviation trigger."""

    def __init__(self, metric: str, direction: str, rel: float,
                 warmup: int, alpha: float = 0.3):
        assert direction in ("drop", "rise"), direction
        self.metric = metric
        self.direction = direction
        self.rel = rel
        self.warmup = max(int(warmup), 1)
        self.ewma = Ewma(alpha)
        self.seen = 0

    def observe(self, value: float) -> Optional[Dict[str, float]]:
        """Returns the anomaly payload when ``value`` breaks the
        threshold, else None.  Zero/negative baselines never fire (a
        0 -> small hbm gauge is a backend coming online, not creep)."""
        self.seen += 1
        baseline = self.ewma.update(float(value))
        if baseline is None or baseline <= 0 or self.seen <= self.warmup:
            return None
        rel_dev = (value - baseline) / baseline
        bad = rel_dev < -self.rel if self.direction == "drop" \
            else rel_dev > self.rel
        if not bad:
            return None
        return {"metric": self.metric, "value": float(value),
                "ewma": round(baseline, 6),
                "rel_dev": round(rel_dev, 4),
                "direction": self.direction}


class SentinelBank:
    """The task-level bundle: three sentinels + the flight ring.

    The train loop calls :meth:`observe_step` / :meth:`observe_round` /
    :meth:`observe_trace` with the SAME record dicts it emits to the
    sink, and :meth:`flight_dump` from its exception path.  Everything
    degrades to a no-op without an active sink (the lint pass warns at
    check time — sentinel thresholds require ``metrics_sink``)."""

    def __init__(self, metrics: MetricsRegistry, rel: float = 0.2,
                 warmup: int = 3, ring: int = 64, alpha: float = 0.3,
                 on_anomaly=None):
        if rel <= 0:
            # a zero/negative threshold fires on every post-warmup
            # observation — an anomaly-plus-flight storm, never intended
            from . import log
            log.warn(f"sentinel_rel={rel} must be > 0; using 0.2")
            rel = 0.2
        self.metrics = metrics
        # serving runs touch the ring from two threads at once: the
        # reporter appends serve_window records while the main thread's
        # abort path runs flight_dump BEFORE the reporter is joined —
        # list(ring)-during-append raises "deque mutated during
        # iteration" and costs the flight evidence at the worst moment
        self.ring: deque = deque(maxlen=max(int(ring), 1))  # racelint: guarded-by(self._lock)
        self._lock = threading.Lock()
        self.sentinels = {
            "examples_per_sec": Sentinel("examples_per_sec", "drop",
                                         rel, warmup, alpha),
            "comm_share": Sentinel("comm_share", "rise", rel, warmup,
                                   alpha),
            "hbm_peak_bytes": Sentinel("hbm_peak_bytes", "rise", rel,
                                       warmup, alpha),
            # serve-side sentinels (doc/serve.md): fed by the
            # ``serve_window`` records task_serve's reporter thread
            # emits — the serving-regression signal the
            # hot-swap/rollback machinery (ROADMAP item 4) acts on
            "serve_p99_ms": Sentinel("serve_p99_ms", "rise", rel,
                                     warmup, alpha),
            "serve_qps": Sentinel("serve_qps", "drop", rel, warmup,
                                  alpha),
            "serve_queue_depth": Sentinel("serve_queue_depth", "rise",
                                          rel, warmup, alpha),
        }
        self.anomalies: List[Dict] = []  # racelint: guarded-by(self._lock)
        # optional anomaly callback (serve/admin.FlightCapture.trigger
        # rides here): called AFTER the anomaly/flight records land, so
        # a failing hook can never cost the primary evidence
        self.on_anomaly = on_anomaly

    # ---------------------------------------------------- resume state
    def state(self) -> Dict:
        """JSON-able resume state (the checkpoint manifest carries it):
        per-series EWMA mean + observation count, plus the flight ring.
        Without this a resumed run re-warms its baselines from scratch
        and the first post-resume rounds can neither fire nor extend a
        pre-kill trend."""
        with self._lock:
            ring = list(self.ring)
        return {"sentinels": {k: {"mean": s.ewma.mean, "seen": s.seen}
                              for k, s in self.sentinels.items()},
                "ring": ring}

    def set_state(self, st: Dict) -> None:
        for k, sv in (st.get("sentinels") or {}).items():
            s = self.sentinels.get(k)
            if s is None:
                continue
            mean = sv.get("mean")
            s.ewma.mean = None if mean is None else float(mean)
            s.seen = int(sv.get("seen", 0))
        with self._lock:
            for rec in st.get("ring") or []:
                self.ring.append(rec)

    # ------------------------------------------------------------ hooks
    def observe_step(self, rec: Dict) -> None:
        with self._lock:
            self.ring.append(dict(rec, kind="step"))
        if rec.get("examples_per_sec"):
            self._check("examples_per_sec", rec["examples_per_sec"], rec)

    def observe_round(self, rec: Dict) -> None:
        if rec.get("hbm_peak_bytes"):
            self._check("hbm_peak_bytes", rec["hbm_peak_bytes"], rec)

    def observe_trace(self, rec: Dict) -> None:
        if rec.get("comm_share"):
            self._check("comm_share", rec["comm_share"], rec)

    # racelint: thread(reporter)
    def observe_serve(self, rec: Dict) -> None:
        """One ``serve_window`` record: windowed p99 latency (rise),
        achieved QPS (drop), and live queue depth (rise).  Windows
        also enter the flight ring, so a serving anomaly dumps the
        windows leading into it.  A zero queue-depth baseline never
        fires (the Sentinel contract) — depth watching arms only once
        the server actually runs a standing queue."""
        with self._lock:
            self.ring.append(dict(rec, kind="serve_window"))
        if rec.get("p99_ms"):
            self._check("serve_p99_ms", rec["p99_ms"], rec)
        if rec.get("qps"):
            self._check("serve_qps", rec["qps"], rec)
        if rec.get("queue_depth") is not None:
            self._check("serve_queue_depth", rec["queue_depth"], rec)

    def _check(self, name: str, value: float, rec: Dict) -> None:
        hit = self.sentinels[name].observe(value)
        if hit is None:
            return
        for k in ("round", "step", "global_step", "window"):
            if k in rec:
                hit[k] = rec[k]
        with self._lock:
            self.anomalies.append(hit)
        self.metrics.counter_inc("anomalies")
        self.metrics.emit("anomaly", **hit)
        self.flight_dump(f"anomaly: {name} {hit['direction']} "
                         f"{hit['rel_dev']:+.0%} vs ewma")
        if self.on_anomaly is not None:
            try:
                self.on_anomaly(hit)
            except Exception as e:  # noqa: BLE001 — a capture-hook
                # failure must not kill the reporter thread
                from . import log
                log.warn(f"sentinel on_anomaly hook failed: {e}")

    # ------------------------------------------------------ flight ring
    def flight_dump(self, reason: str) -> None:
        """Dump (and clear) the step ring as one ``flight`` record.  An
        empty ring writes nothing — a TrainingDiverged on the very first
        monitored step has no history to preserve.  Snapshot-and-clear
        happens under the ring lock (the reporter may still be
        appending); the sink write runs outside it so slow disk never
        blocks the reporter's next window."""
        with self._lock:
            records = list(self.ring)
            self.ring.clear()
        if not records:
            return
        self.metrics.emit("flight", reason=reason,
                          n_records=len(records),
                          records=records)
