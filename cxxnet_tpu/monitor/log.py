"""Stdlib logging behind the CLI's historical print surface.

Every ``print`` in trainer/main used one of four shapes; each gets a
function here, keeping the exact line format (handlers format records as
bare ``%(message)s``, so output-scraping consumers see byte-identical
lines):

* :func:`info`   — progress chatter, stdout, suppressed by ``silent = 1``
* :func:`notice` — task milestones ("start predicting..."), stdout,
  printed regardless of ``silent`` (parity with the reference driver)
* :func:`result` — evaluation lines (``[r]\\ttrain-error:...``), stderr,
  never suppressed (round results are the product, not chatter)
* :func:`warn`   — warnings/exceedances, stderr, never suppressed

``silent`` maps to levels — :func:`set_silent` moves the stdout logger
between INFO and WARNING; ``notice`` emits at WARNING so it survives.
The mapping is process-global (like the loggers themselves): the last
component to set ``silent`` wins, which matches the CLI where one task
owns the process.

Handlers resolve ``sys.stdout``/``sys.stderr`` at emit time, so output
lands wherever the descriptor points *now* (pytest capsys, pipe
redirection after import, notebook cell capture).
"""

from __future__ import annotations

import logging
import sys

_FMT = logging.Formatter("%(message)s")


class _DynamicStreamHandler(logging.StreamHandler):
    """StreamHandler that looks up the stream by name on every emit."""

    def __init__(self, stream_name: str):
        self._stream_name = stream_name
        super().__init__()

    @property
    def stream(self):
        return getattr(sys, self._stream_name)

    @stream.setter
    def stream(self, value):  # base __init__ assigns; the name wins
        pass


def _build(name: str, stream_name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.propagate = False
    if not logger.handlers:
        h = _DynamicStreamHandler(stream_name)
        h.setFormatter(_FMT)
        logger.addHandler(h)
    logger.setLevel(logging.INFO)
    return logger


_out = _build("cxxnet_tpu.out", "stdout")
_err = _build("cxxnet_tpu.err", "stderr")


def set_silent(flag) -> None:
    """``silent = 1`` suppresses info-level chatter (stdout logger to
    WARNING); results/warnings/notices still print."""
    _out.setLevel(logging.WARNING if int(flag) else logging.INFO)


def is_silent() -> bool:
    return _out.level > logging.INFO


def info(msg: str) -> None:
    _out.info(msg)


def notice(msg: str) -> None:
    _out.warning(msg)


def result(msg: str) -> None:
    _err.info(msg)


def warn(msg: str) -> None:
    _err.warning(msg)
