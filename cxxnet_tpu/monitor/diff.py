"""Cross-run regression diff: the ONE threshold/comparison engine.

A "faster" claim needs a baseline and a verdict, not two tables a human
squints at (ROADMAP item 3); canary/rollback-on-regression (item 4)
needs the same run-vs-run verdict as a primitive.  This module is that
primitive, shared by every consumer so exactly one comparison
implementation exists:

* ``tools/obsv.py --diff A.jsonl B.jsonl`` — align two metrics streams
  (throughput, ledger shares, per-layer ``layer_profile`` rows joined
  by the stable ``conn_scope_name`` contract, ``mem_profile``
  peak-live, comm share/overlap, latency percentiles) and exit nonzero
  on any regression past ``rel`` — a CI gate, not just a report;
* ``bench.py --against BENCH_rNN.json`` — the same engine over a bench
  payload vs a recorded round;
* ``tests/test_bench_guard.py`` — the ±10% ``device_step_ms`` guard
  routes its comparison through :func:`compare`.

Verdict semantics: ``b`` is the candidate, ``a`` the baseline;
``rel_delta = (b - a) / |a|``.  A comparison regresses when the delta
moves past ``rel`` in the metric's bad direction AND the absolute move
clears the metric's significance floor (so a 0.01→0.02 share wiggle on
a 50-second CPU run cannot fail CI); it improves symmetrically.  A
metric missing from either side is not compared — absence is reported,
never judged.  A metric with direction ``None`` rides as CONTEXT: its
delta is shown but never gates.  The ledger needs that distinction:
utilization (``goodput_pct``, the dispatch share) RISES when the
device gets slower, and compile/eval/other shares shift with run shape
— speed verdicts come from throughput and latency, while the judged
ledger rows are the shares whose growth is unambiguous badput
(``input_wait``, ``h2d_staging``, ``ckpt_blocked``, ``rollback_lost``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .ledger import CATEGORIES, build_ledger, by_kind as _by_kind, \
    last_session

#: metric directions: which way is worse
LOWER_BETTER = "lower_better"    # an increase is a regression
HIGHER_BETTER = "higher_better"  # a decrease is a regression


def compare(metric: str, a, b, rel: float = 0.10,
            direction: Optional[str] = LOWER_BETTER,
            abs_floor: float = 0.0) -> dict:
    """One comparison: candidate ``b`` against baseline ``a``.
    ``direction = None`` computes the delta but never judges (a
    context row)."""
    out = {"metric": metric, "a": a, "b": b, "direction": direction,
           "rel_delta": None, "regressed": False, "improved": False}
    if a is None or b is None:
        return out
    a, b = float(a), float(b)
    if a == 0.0:
        # no baseline magnitude, no RELATIVE verdict (a 10% threshold
        # of zero is meaningless) — but a metric with a significance
        # floor is still judged by its absolute move: a clean baseline
        # has rollback_lost/ckpt_blocked shares of exactly 0.0, and
        # those are precisely the badput classes the gate exists for
        out["rel_delta"] = 0.0 if b == 0.0 else None
        if direction is not None and abs_floor > 0.0 \
                and abs(b - a) >= abs_floor:
            grew = b > a
            out["regressed"] = grew == (direction == LOWER_BETTER)
            out["improved"] = not out["regressed"]
        return out
    delta = (b - a) / abs(a)
    out["rel_delta"] = round(delta, 4)
    if direction is None or abs(b - a) < abs_floor:
        return out
    bad = delta > rel if direction == LOWER_BETTER else delta < -rel
    good = delta < -rel if direction == LOWER_BETTER else delta > rel
    out["regressed"] = bool(bad)
    out["improved"] = bool(good)
    return out


# ------------------------------------------------- metric extraction
#: ledger shares whose growth is unambiguous badput — the JUDGED rows.
#: compile/eval/other shift with run shape, and the dispatch share
#: (goodput) rises when the device merely slows down; those ride as
#: context rows (direction None) instead
_JUDGED_SHARES = ("pipe_bubble", "input_wait", "h2d_staging",
                  "ckpt_blocked", "rollback_lost")


def run_metrics(recs: List[dict]
                ) -> Dict[str, Tuple[float, Optional[str], float]]:
    """Extract the comparable scalars of one run:
    ``name -> (value, direction_or_None, abs_floor)``."""
    by = _by_kind(recs)
    out: Dict[str, Tuple[float, str, float]] = {}
    eps = [r["examples_per_sec"] for r in by.get("step", [])
           if r.get("examples_per_sec")]
    if eps:
        # the mean over all print windows is the judged throughput
        # signal; the final window is ONE sample — scheduler wiggle on
        # a short run routinely moves it past any rel threshold, so it
        # rides as context
        out["examples_per_sec_mean"] = (sum(eps) / len(eps),
                                        HIGHER_BETTER, 0.0)
        out["examples_per_sec_last"] = (eps[-1], None, 0.0)
    led = by.get("ledger", [None])[-1] or build_ledger(recs,
                                                       source="posthoc")
    if led:
        # context: utilization is not speed (a slower kernel RAISES it)
        out["goodput_pct"] = (led.get("goodput_pct"), None, 0.0)
        shares = led.get("shares") or {}
        for cat in CATEGORIES:
            if cat not in shares or cat == "dispatch":
                continue  # dispatch share == goodput_pct, one row
            if cat in _JUDGED_SHARES:
                # floor 0.02: a two-points-of-wall move is the smallest
                # share shift worth a verdict on CI-sized runs
                out[f"ledger_share_{cat}"] = (shares[cat],
                                              LOWER_BETTER, 0.02)
            else:
                out[f"ledger_share_{cat}"] = (shares[cat], None, 0.0)
    if by.get("trace"):
        t = by["trace"][-1]
        if t.get("comm_share") is not None:
            out["comm_share"] = (t["comm_share"], LOWER_BETTER, 0.02)
        if t.get("overlap_frac") is not None:
            out["overlap_frac"] = (t["overlap_frac"], HIGHER_BETTER, 0.05)
    if by.get("mem_profile"):
        m = by["mem_profile"][-1]
        if m.get("peak_live_bytes") is not None:
            out["peak_live_bytes"] = (m["peak_live_bytes"],
                                      LOWER_BETTER, 0.0)
        if m.get("hbm_peak_bytes") is not None:
            out["hbm_peak_bytes"] = (m["hbm_peak_bytes"],
                                     LOWER_BETTER, 0.0)
    for r in by.get("latency", []):
        op = r.get("op", "?")
        for q in ("p50", "p95", "p99"):
            if r.get(q) is not None:
                # floor 0.2 ms: below that, CPU-CI timer noise
                out[f"{op}_{q}_ms"] = (r[q], LOWER_BETTER, 0.2)
    if by.get("serve"):
        s = by["serve"][-1]
        if s.get("qps") is not None:
            out["serve_qps"] = (s["qps"], HIGHER_BETTER, 0.0)
    return out


def layer_rows(recs: List[dict]) -> Dict[str, float]:
    """``layer -> device_ms`` from the last ``layer_profile`` record —
    the join key is the ``conn_scope_name`` contract (layers/base.py),
    stable across runs of the same config."""
    by = _by_kind(recs)
    if not by.get("layer_profile"):
        return {}
    rows = by["layer_profile"][-1].get("rows") or []
    return {r["layer"]: r.get("device_ms")
            for r in rows if r.get("layer") is not None}


def diff_runs(recs_a: List[dict], recs_b: List[dict],
              rel: float = 0.10) -> dict:
    """Align two record streams and judge every shared metric.  Each
    stream is sliced to its LAST session first (ledger.last_session):
    an append-mode sink carries earlier sessions, and mixing their step
    records into the mean would judge a run neither side actually
    ran."""
    recs_a, recs_b = last_session(recs_a), last_session(recs_b)
    ma, mb = run_metrics(recs_a), run_metrics(recs_b)
    metrics = []
    for name in ma:
        if name not in mb:
            continue
        va, direction, floor = ma[name]
        vb = mb[name][0]
        metrics.append(compare(name, va, vb, rel=rel,
                               direction=direction, abs_floor=floor))
    la, lb = layer_rows(recs_a), layer_rows(recs_b)
    layers = [compare(name, la[name], lb[name], rel=rel,
                      direction=LOWER_BETTER, abs_floor=0.05)
              for name in la if name in lb]
    all_cmp = metrics + layers
    return {
        "rel": rel,
        "metrics": metrics,
        "layers": layers,
        "layers_only_a": sorted(set(la) - set(lb)),
        "layers_only_b": sorted(set(lb) - set(la)),
        "uncompared": sorted(set(ma) ^ set(mb)),
        "regressions": sum(1 for c in all_cmp if c["regressed"]),
        "improvements": sum(1 for c in all_cmp if c["improved"]),
    }


# --------------------------------------------------------- bench diff
def bench_direction(key: str) -> Optional[str]:
    """Direction heuristic over the BENCH payload field vocabulary
    (doc/monitor.md: shared with the JSONL records).  None = not a
    judged metric (counts, ids, configuration).  The higher-better
    vocabulary is tested FIRST: throughput fields end in ``_sec`` too
    (``imgs_per_sec``), and a suffix-first rule would invert their
    verdict — the exact wrong-way CI gate this module exists to
    prevent."""
    k = key.lower()
    if k in ("trials", "ts", "n", "rc", "devices", "batch", "clients"):
        return None
    if ("per_sec" in k or "per_chip" in k or "qps" in k or "mfu" in k
            or "speedup" in k or "efficiency" in k or "tokens" in k):
        return HIGHER_BETTER
    if "_ms" in k or k.endswith("ms") or "latency" in k \
            or "compile" in k or k.endswith("_sec"):
        return LOWER_BETTER
    return None


def _bench_flat(payload: dict, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in payload.items():
        name = prefix + k
        if isinstance(v, dict):
            out.update(_bench_flat(v, name + "."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = float(v)
    return out


def diff_bench(prior: dict, current: dict, rel: float = 0.10) -> dict:
    """Judge a bench payload against a recorded one.  ``BENCH_rNN.json``
    round files wrap the payload in ``parsed`` — both shapes accepted.
    Direction comes from the field name (the leaf key of a dotted
    path), so ``arms.fused.step_ms`` is judged lower-better.  The
    generic headline fields ``value``/``vs_baseline`` are named by the
    sibling ``metric`` string — ``serve_p95_ms`` and ``opt_ab_step_ms``
    headlines are LOWER-better — so their direction derives from it,
    never from the literal key (an unrecognized metric name leaves them
    uncompared rather than guessed)."""
    prior = prior.get("parsed", prior)
    current = current.get("parsed", current)
    head_dir = bench_direction(str(prior.get("metric", "")))
    fa, fb = _bench_flat(prior), _bench_flat(current)
    metrics = []
    for name in fa:
        if name not in fb:
            continue
        leaf = name.rsplit(".", 1)[-1]
        direction = head_dir if leaf in ("value", "vs_baseline") \
            else bench_direction(leaf)
        if direction is None:
            continue
        metrics.append(compare(name, fa[name], fb[name], rel=rel,
                               direction=direction))
    return {
        "rel": rel,
        "metrics": metrics,
        "uncompared": sorted(set(fa) ^ set(fb)),
        "regressions": sum(1 for c in metrics if c["regressed"]),
        "improvements": sum(1 for c in metrics if c["improved"]),
    }


# ---------------------------------------------------------- rendering
def _fmt_val(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    if abs(v) >= 1e6:
        return f"{v / 1e6:.2f}M"
    return f"{v:.4g}"


def _verdict(c: dict) -> str:
    if c["regressed"]:
        return "REGRESSED"
    if c["improved"]:
        return "improved"
    if c["rel_delta"] is None:
        return "-"
    if c.get("direction") is None:
        return "(ctx)"  # context row: shown, never judged
    return "ok"


def render_diff(d: dict, label_a: str = "A", label_b: str = "B") -> str:
    """Aligned terminal table for a :func:`diff_runs` /
    :func:`diff_bench` result."""
    lines = [f"run diff: {label_b} (candidate) vs {label_a} (baseline), "
             f"rel threshold {d['rel']:.0%}"]
    rows = []
    for c in d.get("metrics", []) + d.get("layers", []):
        delta = ("-" if c["rel_delta"] is None
                 else f"{c['rel_delta']:+.1%}")
        rows.append([c["metric"], _fmt_val(c["a"]), _fmt_val(c["b"]),
                     delta, _verdict(c)])
    if rows:
        headers = ["metric", label_a, label_b, "delta", "verdict"]
        widths = [max(len(h), *(len(r[i]) for r in rows))
                  for i, h in enumerate(headers)]
        fmt = "  ".join(f"{{:>{w}}}" for w in widths)
        lines.append(fmt.format(*headers))
        lines.extend(fmt.format(*r) for r in rows)
    else:
        lines.append("(no shared metrics to compare)")
    for side, only in (("only in " + label_a, d.get("layers_only_a")),
                       ("only in " + label_b, d.get("layers_only_b"))):
        if only:
            lines.append(f"layers {side}: {', '.join(only)}")
    lines.append(
        f"verdict: {d['regressions']} regression(s), "
        f"{d['improvements']} improvement(s)"
        + (" — FAIL" if d["regressions"] else " — ok"))
    return "\n".join(lines)
