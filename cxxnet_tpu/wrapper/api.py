"""numpy-facing Python API: Net / DataIter / train.

Reference: ``wrapper/cxxnet.py`` (Python-2 ctypes wrapper over the C ABI,
``wrapper/cxxnet_wrapper.h``).  Same surface, modern Python: a ``Net`` is
configured by a config string + set_param calls, updates on numpy batches or
a DataIter, and exposes predict/extract/evaluate/get_weight/set_weight.  The
C ABI itself lives in ``native/capi`` (see native/README.md) for C/C++
embedders; Python users get this module directly — no ctypes round trip
through a C shim just to come back into Python.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io.data import DataBatch
from ..io.factory import create_iterator, init_iterator
from ..monitor import log as mlog
from ..nnet.trainer import NetTrainer
from ..utils.config import parse_config_string


class DataIter:
    """Iterator built from a config string (CXNIOCreateFromConfig parity:
    the same ``iter = ...`` sections the CLI uses)."""

    def __init__(self, cfg: str):
        pairs = parse_config_string(cfg)
        self._it = create_iterator(pairs)
        init_iterator(self._it, [])
        self.head = True
        self.tail = False
        self._batch: Optional[DataBatch] = None

    def before_first(self) -> None:
        self._it.before_first()
        self.head = True
        self.tail = False

    def next(self) -> bool:
        self._batch = self._it.next()
        self.head = False
        self.tail = self._batch is None
        return not self.tail

    def check_valid(self) -> None:
        if self.head:
            raise RuntimeError(
                "iterator at head state, call next() to get to a valid state")
        if self.tail:
            raise RuntimeError("iterator reached the end")

    @property
    def value(self) -> DataBatch:
        self.check_valid()
        return self._batch

    def get_data(self) -> np.ndarray:
        self.check_valid()
        return self._batch.data

    def get_label(self) -> np.ndarray:
        self.check_valid()
        return self._batch.label


def _as_batch(data: np.ndarray, label: Optional[np.ndarray]) -> DataBatch:
    if data.ndim != 4:
        raise ValueError(
            "need a 4-d tensor (batch, channel, height, width)")
    if label is None:
        label = np.zeros((data.shape[0], 1), np.float32)
    else:
        label = np.asarray(label, np.float32)
        if label.ndim == 1:
            label = label.reshape(-1, 1)
        if label.ndim != 2 or label.shape[0] != data.shape[0]:
            raise ValueError("label must be (batch,) or (batch, width)")
    return DataBatch(data=np.asarray(data, np.float32), label=label,
                     index=np.arange(data.shape[0], dtype=np.uint32))


class Net:
    """Neural net object (CXNNetCreate parity)."""

    def __init__(self, dev: str = "tpu", cfg: str = ""):
        self._trainer = NetTrainer()
        self._trainer.set_param("dev", dev)
        for k, v in parse_config_string(cfg):
            self._trainer.set_param(k, v)
        self._serve = None

    def set_param(self, name, value) -> None:
        self._trainer.set_param(str(name), str(value))

    def init_model(self) -> None:
        self._trainer.init_model()

    def load_model(self, fname: str) -> None:
        self._trainer.load_model(fname)

    def save_model(self, fname: str) -> None:
        self._trainer.save_model(fname)

    def copy_model_from(self, fname: str) -> None:
        self._trainer.copy_model_from(fname)

    def start_round(self, round_counter: int) -> None:
        self._trainer.start_round(round_counter)

    def update(self, data, label: Optional[np.ndarray] = None) -> None:
        """Update on a DataIter's current batch or a numpy (data, label)."""
        if isinstance(data, DataIter):
            data.check_valid()
            self._trainer.update(data.value)
        elif isinstance(data, np.ndarray):
            if label is None:
                raise ValueError("Net.update: need label to update")
            self._trainer.update(_as_batch(data, label))
        else:
            raise TypeError(f"update does not support {type(data)}")

    def enable_serving(self, cfg: str = "") -> None:
        """Route ``predict`` through the dynamic micro-batching serve
        path (serve/, doc/serve.md): pinned shape buckets compile once
        here, then concurrent ``predict`` calls from ANY thread coalesce
        into batched dispatches and never retrace.  ``cfg`` takes the
        same ``serve_* = value`` pairs the CLI task does
        (``"serve_shapes = 1,8\\nserve_dtype = bf16"``).  The legacy
        single-shot path returns on :meth:`disable_serving` — and stays
        in use for ``DataIter`` inputs either way (their batches carry
        padding metadata the serve path deliberately doesn't)."""
        from ..serve import ServeConfig
        from ..serve.host import ServeModel
        if self._serve is not None:
            raise RuntimeError("serving already enabled")
        sm = ServeModel(
            self._trainer, ServeConfig.from_pairs(parse_config_string(cfg)))
        try:
            sm.warmup()
        except BaseException:
            sm.close()
            raise
        self._serve = sm

    def disable_serving(self) -> None:
        """Shut the batcher down (joins its thread) and restore the
        legacy single-shot predict."""
        if self._serve is not None:
            self._serve.close()
            self._serve = None

    def predict(self, data) -> np.ndarray:
        if isinstance(data, DataIter):
            data.check_valid()
            return self._trainer.predict(data.value)
        if self._serve is not None:
            raw = self._serve.predict(
                _as_batch(np.asarray(data), None).data)
            if raw.shape[1] > 1:
                return raw.argmax(axis=1).astype(np.float32)
            return raw[:, 0]
        return self._trainer.predict(_as_batch(np.asarray(data), None))

    def extract(self, data, node_name: str) -> np.ndarray:
        if isinstance(data, DataIter):
            data.check_valid()
            return self._trainer.extract_feature(data.value, node_name)
        return self._trainer.extract_feature(
            _as_batch(np.asarray(data), None), node_name)

    def evaluate(self, data: "DataIter", name: str) -> str:
        if not isinstance(data, DataIter):
            raise TypeError(
                f"evaluate needs a DataIter, got {type(data).__name__}")
        return self._trainer.evaluate(iter(data._it), name)

    def get_weight(self, layer_name: str, tag: str) -> Optional[np.ndarray]:
        if tag not in ("wmat", "bias"):
            raise ValueError("tag must be bias or wmat")
        try:
            return self._trainer.get_weight(layer_name, tag)
        except KeyError:
            return None

    def set_weight(self, weight: np.ndarray, layer_name: str, tag: str) -> None:
        if tag not in ("wmat", "bias"):
            raise ValueError("tag must be bias or wmat")
        self._trainer.set_weight(np.asarray(weight, np.float32),
                                 layer_name, tag)


class ServingHost:
    """Concurrent multi-model serving from Python (serve/host.py over
    config strings): load N snapshots, route by model name, share the
    process's device pool.  Each model gets its own micro-batcher and
    shape buckets, so ``predict`` is thread-safe per model AND across
    models.

        host = ServingHost()
        host.add_model("mnist", "model_in = m/0010.model\\n"
                                "batch_size = 100\\nserve_shapes = 1,8")
        host.predict("mnist", rows)   # from any thread
        host.close()
    """

    def __init__(self, dev: str = "tpu"):
        from ..serve.host import ModelHost
        self._dev = dev
        self._host = ModelHost()

    def add_model(self, name: str, cfg: str) -> None:
        """Load one snapshot behind its own engine+batcher.  ``cfg`` is
        the usual config-string surface and must carry ``model_in``
        (the snapshot) and ``batch_size``; ``serve_*`` keys configure
        this model's buckets/dtype/batching."""
        from ..serve.host import load_serve_model
        pairs = [("dev", self._dev)] + parse_config_string(cfg)
        self._host.attach(load_serve_model(pairs, name=name, warmup=False))

    @property
    def models(self):
        return self._host.names

    def predict(self, name: str, data: np.ndarray) -> np.ndarray:
        """Raw output rows of model ``name`` for ``(n, c, h, w)`` data."""
        return self._host.predict(name,
                                  _as_batch(np.asarray(data), None).data)

    def retraces(self) -> int:
        """Total traces past warmup across hosted models (0 = healthy)."""
        return self._host.retraces()

    def close(self) -> None:
        self._host.close()


def train(cfg: str, data, num_round: int, param, eval_data=None,
          label: Optional[np.ndarray] = None, dev: str = "tpu") -> Net:
    """One-call train loop (wrapper/cxxnet.py train parity).

    ``data`` is a DataIter, or a numpy array with ``label=``.
    """
    net = Net(dev=dev, cfg=cfg)
    items = param.items() if isinstance(param, dict) else param
    for k, v in items:
        net.set_param(k, v)
    net.init_model()
    for r in range(num_round):
        net.start_round(r)
        if isinstance(data, DataIter):
            data.before_first()
            scounter = 0
            while data.next():
                net.update(data)
                scounter += 1
                if scounter % 100 == 0:
                    mlog.notice(f"[{r}] {scounter} batch passed")
        else:
            net.update(data=data, label=label)
        if eval_data is not None:
            mlog.result(net.evaluate(eval_data, "eval"))
    return net
