from .api import DataIter, Net, train

__all__ = ["DataIter", "Net", "train"]
