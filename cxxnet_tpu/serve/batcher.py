"""Dynamic micro-batching: many client threads -> one device loop.

The inverse of :class:`~cxxnet_tpu.io.device_prefetch.DevicePrefetcher`
(one producer thread feeding one consumer): here MANY producers — client
threads calling :meth:`MicroBatcher.submit` — feed a bounded request
queue, and ONE dispatcher thread drains it, coalescing concurrent
requests into a single predict call of up to ``serve_max_batch`` rows or
until ``serve_max_wait_ms`` passes since the batch opened.  The thread
discipline is the prefetcher's, reused in reverse: a bounded queue for
backpressure, a poison/latch protocol so a dispatcher failure surfaces
in every waiting client instead of hanging them, and ``close()`` joins
the thread (the ThreadBufferIterator hygiene rules).

Coalescing preserves per-row results bit-for-bit at f32: every op in an
eval-mode forward is row-independent (matmul rows, convolution batch
elements, eval batch-norm against running stats, per-row softmax), so a
request served alone in a padded bucket and the same request served
inside a coalesced batch produce identical bytes — asserted by
tests/test_serve.py, and the property that makes dynamic batching safe
to enable by default.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np


class ServeClosed(RuntimeError):
    """Raised to submitters when the batcher is shut down."""


@dataclasses.dataclass
class _Request:
    data: np.ndarray
    event: threading.Event
    t0: float
    result: Optional[np.ndarray] = None
    error: Optional[BaseException] = None
    # span tracing (monitor/spans.py): the sampled request's trace_id
    # (None when unsampled or tracing is off), the submitting thread's
    # name (its Perfetto track), and the stage boundary stamps the
    # dispatcher records as the request moves through it — dequeue
    # (queue_wait ends) and runner completion (respond begins)
    trace_id: Optional[int] = None
    tid: Optional[str] = None
    t_deq: float = 0.0
    t_served: float = 0.0


class MicroBatcher:
    """Bounded request queue + coalescing dispatcher over ``runner``
    (rows ``(n,) + input_shape`` -> output rows, row-aligned).

    ``submit`` is thread-safe and blocking: it enqueues the request
    (with backpressure past ``queue_depth``), waits for the dispatcher
    to serve the coalesced batch, and returns this request's slice of
    the result.  A dispatch never exceeds ``max_batch`` rows — a
    request that would overflow the open batch is held back and opens
    the next one (only a SINGLE request larger than ``max_batch``
    dispatches alone, and the engine splits it across buckets).  A
    runner exception fails THE WHOLE batch plus
    everything queued behind it and latches the batcher dead — clients
    get the exception, never a hang (the DevicePrefetcher
    ProducerError contract, fanned out)."""

    def __init__(self, runner: Callable[[np.ndarray], np.ndarray], *,
                 max_batch: int = 32, max_wait_ms: float = 2.0,
                 queue_depth: int = 64, metrics=None,
                 name: str = "serve"):
        self.runner = runner
        self.max_batch = max(1, int(max_batch))
        self.max_wait_ms = float(max_wait_ms)
        self.metrics = metrics
        self.name = name
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_depth)))
        self._thread: Optional[threading.Thread] = None
        self._failed: Optional[BaseException] = None
        self._closing = False
        # dispatch accounting for the ``serve`` record / bench report.
        # Queue depth is sampled at BOTH ends — submit() (arrival) and
        # the dispatcher (drain) — under _stats_lock: sampling only at
        # dispatch time made bursts that arrived and fully drained
        # between two dispatches invisible to depth_max
        self.n_requests = 0
        self.n_batches = 0
        self.rows_served = 0
        self.batch_hist: Dict[int, int] = {}
        self.depth_sum = 0
        self.depth_samples = 0
        self.depth_max = 0
        self._stats_lock = threading.Lock()
        # windowed stats for the serve-side sentinels (opt-in: the
        # reporter thread in task_serve flips track_window on and
        # drains via window_stats(); off by default so the hot path
        # pays nothing)
        self.track_window = False
        self._win_lock = threading.Lock()
        self._win_lats: list = []
        self._win_requests = 0

    # ------------------------------------------------------------- client
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"cxxnet-serve-batcher-{self.name}")
        self._thread.start()

    def submit(self, x: np.ndarray) -> np.ndarray:
        """One request (``(n,) + input_shape`` rows); returns its output
        rows once the coalesced batch it rode in completes."""
        if self._failed is not None:
            raise self._failed
        if self._closing:
            raise ServeClosed(f"batcher {self.name!r} is shut down")
        assert self._thread is not None, "call start() first"
        tracer = self.metrics.tracer if self.metrics is not None else None
        req = _Request(data=np.asarray(x), event=threading.Event(),
                       t0=time.perf_counter())
        if tracer is not None and tracer.enabled:
            req.trace_id = tracer.new_trace()
            if req.trace_id is not None:
                req.tid = threading.current_thread().name
        # bounded put that re-checks the latch: a client must neither
        # block forever on a dead batcher's full queue nor enqueue
        # behind the shutdown drain (generation_put's discipline)
        while True:
            if self._failed is not None:
                raise self._failed
            if self._closing:
                raise ServeClosed(f"batcher {self.name!r} is shut down")
            try:
                self._q.put(req, timeout=0.05)
                break
            except queue.Full:
                continue
        # arrival-side depth sample (the satellite fix): a burst that
        # arrives and drains between two dispatches is visible only
        # here — the dispatcher's sample runs after it already drained
        # the queue into the open batch
        self._observe_depth(self._q.qsize())
        # the latch can land between the check above and the put: the
        # dispatcher drains and dies, and our request sits in a queue
        # nobody reads.  Poll the thread while waiting — if it is gone,
        # release the queue ourselves (every req gets error + event)
        while not req.event.wait(0.1):
            t = self._thread
            if t is None or not t.is_alive():
                self._drain(self._failed)
        if req.error is not None:
            raise req.error
        latency = time.perf_counter() - req.t0
        # t_served == 0 means the dispatcher skipped the span stamps
        # (tracing toggled off between submit and dispatch): no chain
        if req.trace_id is not None and tracer is not None \
                and req.t_served > 0.0:
            # respond: runner completion -> this client actually awake
            # and returning; request: the whole submit->result wall,
            # stamped from the SAME latency the histogram records so
            # the span chain and serve_latency_sec agree exactly
            tracer.emit("respond", req.t_served, req.t0 + latency,
                        trace_id=req.trace_id, model=self.name)
            tracer.emit("request", req.t0, req.t0 + latency,
                        trace_id=req.trace_id, model=self.name)
        if self.metrics is not None:
            self.metrics.observe("serve_latency_sec", latency)
        if self.track_window:
            with self._win_lock:
                self._win_lats.append(latency)
                self._win_requests += 1
        return req.result

    def _observe_depth(self, depth: int) -> None:
        with self._stats_lock:
            self.depth_sum += depth
            self.depth_samples += 1
            if depth > self.depth_max:
                self.depth_max = depth

    def window_stats(self) -> Dict[str, Any]:
        """Drain the current sentinel window: request count, latency
        percentiles (ms), and the live queue depth.  The serve-side
        sentinel reporter (main.task_serve) calls this once per
        ``serve_sentinel_window`` seconds."""
        with self._win_lock:
            lats, self._win_lats = self._win_lats, []
            n, self._win_requests = self._win_requests, 0
        out: Dict[str, Any] = {"requests": n,
                               "queue_depth": self._q.qsize()}
        if lats:
            from ..monitor.metrics import nearest_rank
            lats.sort()
            out.update(
                p50_ms=round(nearest_rank(lats, 50) * 1e3, 3),
                p95_ms=round(nearest_rank(lats, 95) * 1e3, 3),
                p99_ms=round(nearest_rank(lats, 99) * 1e3, 3))
        return out

    # --------------------------------------------------------- dispatcher
    def _loop(self) -> None:
        carry = None        # a coalesce-overflow request held for the
        while True:         # NEXT batch (dispatches never exceed
            if carry is not None:                        # max_batch)
                first, carry = carry, None
            else:
                first = self._q.get()
                if first is None:
                    return
                first.t_deq = time.perf_counter()
            batch = [first]
            rows = first.data.shape[0]
            stop = False
            deadline = time.perf_counter() + self.max_wait_ms / 1e3
            while rows < self.max_batch:
                rem = deadline - time.perf_counter()
                if rem <= 0:
                    break
                try:
                    r = self._q.get(timeout=rem)
                except queue.Empty:
                    break
                if r is None:       # shutdown sentinel mid-coalesce:
                    stop = True     # serve what we have, then exit
                    break
                r.t_deq = time.perf_counter()
                if rows + r.data.shape[0] > self.max_batch:
                    carry = r       # would overflow: opens the next batch
                    break
                batch.append(r)
                rows += r.data.shape[0]
            depth = self._q.qsize()
            self._observe_depth(depth)
            if self.metrics is not None:
                self.metrics.set_gauge("serve_queue_depth", depth)
            if not self._run(batch, rows):
                if carry is not None:   # latched: the held request must
                    carry.error = self._failed      # fail too, not hang
                    carry.event.set()
                return              # runner failed: latched + drained
            if stop:
                return

    def _run(self, batch, rows: int) -> bool:
        tracer = self.metrics.tracer if self.metrics is not None else None
        riders = [r.trace_id for r in batch if r.trace_id is not None] \
            if tracer is not None and tracer.enabled else []
        try:
            t_disp = time.perf_counter()
            if riders:
                # close out each sampled rider's pre-dispatch stages:
                # queue_wait (submit -> dequeued, on the rider's own
                # track) and coalesce (dequeued -> this dispatch; a
                # carry request's coalesce spans into the next batch)
                for r in batch:
                    if r.trace_id is None:
                        continue
                    tracer.emit("queue_wait", r.t0, r.t_deq,
                                trace_id=r.trace_id, tid=r.tid,
                                model=self.name)
                    tracer.emit("coalesce", r.t_deq, t_disp,
                                trace_id=r.trace_id, tid=r.tid,
                                model=self.name)
            if len(batch) == 1:
                data = batch[0].data
            else:
                data = np.concatenate([r.data for r in batch], axis=0)
            if riders:
                # the engine's pad/device/unpad spans inherit the rider
                # list through the thread-local link
                with tracer.link(riders):
                    out = self.runner(data)
                t_done = time.perf_counter()
                tracer.emit("dispatch", t_disp, t_done, riders=riders,
                            rows=rows, requests=len(batch),
                            model=self.name)
                for r in batch:
                    if r.trace_id is not None:
                        r.t_served = t_done
            else:
                out = self.runner(data)
            self.n_batches += 1
            self.n_requests += len(batch)
            self.rows_served += rows
            self.batch_hist[rows] = self.batch_hist.get(rows, 0) + 1
            if self.metrics is not None:
                self.metrics.observe("serve_batch_rows", rows)
            off = 0
            for r in batch:
                k = r.data.shape[0]
                r.result = out[off:off + k]
                off += k
                r.event.set()
            return True
        except BaseException as e:  # noqa: BLE001 — must reach clients
            self._failed = e
            for r in batch:
                r.error = e
                r.event.set()
            self._drain(e)
            return False

    def _drain(self, err: Optional[BaseException]) -> None:
        """Fail (or, post-shutdown, reject) everything still queued."""
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r is None:
                continue
            r.error = err if err is not None else ServeClosed(
                f"batcher {self.name!r} shut down before this request "
                "was served")
            r.event.set()

    # ------------------------------------------------------------ teardown
    def close(self) -> None:
        """Stop accepting requests, serve everything already queued,
        join the dispatcher, and reject stragglers.  Idempotent."""
        self._closing = True
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join()
        self._thread = None
        # requests that raced the sentinel (or arrived after a failure)
        # must still be released — no client left waiting on an event
        self._drain(self._failed)

    @property
    def mean_batch(self) -> float:
        return self.rows_served / self.n_batches if self.n_batches else 0.0

    @property
    def mean_depth(self) -> float:
        return self.depth_sum / self.depth_samples \
            if self.depth_samples else 0.0

    def stats(self) -> Dict[str, Any]:
        """Dispatch accounting for the ``serve`` JSONL record."""
        return {
            "requests": self.n_requests,
            "batches": self.n_batches,
            "rows": self.rows_served,
            "mean_batch": round(self.mean_batch, 2),
            "batch_hist": {str(k): v
                           for k, v in sorted(self.batch_hist.items())},
            "queue_depth_mean": round(self.mean_depth, 2),
            "queue_depth_max": self.depth_max,
        }
