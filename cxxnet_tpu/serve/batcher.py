"""Dynamic micro-batching: many client threads -> one device loop.

The inverse of :class:`~cxxnet_tpu.io.device_prefetch.DevicePrefetcher`
(one producer thread feeding one consumer): here MANY producers — client
threads calling :meth:`MicroBatcher.submit` — feed a bounded request
queue, and ONE dispatcher thread drains it, coalescing concurrent
requests into a single predict call of up to ``serve_max_batch`` rows or
until ``serve_max_wait_ms`` passes since the batch opened.  The thread
discipline is the prefetcher's, reused in reverse: a bounded queue for
backpressure, a poison/latch protocol so a dispatcher failure surfaces
in every waiting client instead of hanging them, and ``close()`` joins
the thread (the ThreadBufferIterator hygiene rules).

Coalescing preserves per-row results bit-for-bit at f32: every op in an
eval-mode forward is row-independent (matmul rows, convolution batch
elements, eval batch-norm against running stats, per-row softmax), so a
request served alone in a padded bucket and the same request served
inside a coalesced batch produce identical bytes — asserted by
tests/test_serve.py, and the property that makes dynamic batching safe
to enable by default.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np


class ServeClosed(RuntimeError):
    """Raised to submitters when the batcher is shut down."""


@dataclasses.dataclass
class _Request:
    data: np.ndarray
    event: threading.Event
    t0: float
    result: Optional[np.ndarray] = None
    error: Optional[BaseException] = None
    # span tracing (monitor/spans.py): the sampled request's trace_id
    # (None when unsampled or tracing is off), the submitting thread's
    # name (its Perfetto track), and the stage boundary stamps the
    # dispatcher records as the request moves through it — dequeue
    # (queue_wait ends) and runner completion (respond begins)
    trace_id: Optional[int] = None
    tid: Optional[str] = None
    t_deq: float = 0.0
    t_served: float = 0.0


class MicroBatcher:
    """Bounded request queue + coalescing dispatcher over ``runner``
    (rows ``(n,) + input_shape`` -> output rows, row-aligned).

    ``submit`` is thread-safe and blocking: it enqueues the request
    (with backpressure past ``queue_depth``), waits for the dispatcher
    to serve the coalesced batch, and returns this request's slice of
    the result.  A dispatch never exceeds ``max_batch`` rows — a
    request that would overflow the open batch is held back and opens
    the next one (only a SINGLE request larger than ``max_batch``
    dispatches alone, and the engine splits it across buckets).  A
    runner exception fails THE WHOLE batch plus
    everything queued behind it and latches the batcher dead — clients
    get the exception, never a hang (the DevicePrefetcher
    ProducerError contract, fanned out)."""

    def __init__(self, runner: Callable[[np.ndarray], np.ndarray], *,
                 max_batch: int = 32, max_wait_ms: float = 2.0,
                 queue_depth: int = 64, metrics=None,
                 name: str = "serve"):
        self.runner = runner
        self.max_batch = max(1, int(max_batch))
        self.max_wait_ms = float(max_wait_ms)
        self.metrics = metrics
        self.name = name
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_depth)))
        self._thread: Optional[threading.Thread] = None
        # racelint: latch(write-once by the dispatcher; racy reads fan the failure out to submitters)
        self._failed: Optional[BaseException] = None
        self._closing = False
        # dispatch accounting for the ``serve`` record / bench report.
        # Queue depth is sampled at BOTH ends — submit() (arrival) and
        # the dispatcher (drain) — under _stats_lock: sampling only at
        # dispatch time made bursts that arrived and fully drained
        # between two dispatches invisible to depth_max
        self.n_requests = 0    # racelint: atomic(plain-int bump, dispatcher is the only writer; scrape reads tolerate staleness)
        self.n_batches = 0     # racelint: atomic(plain-int bump, dispatcher-only writer)
        self.rows_served = 0   # racelint: atomic(plain-int bump, dispatcher-only writer)
        # racelint: atomic(per-key int bump, dispatcher-only writer; the scrape path copies via copy_racy)
        self.batch_hist: Dict[int, int] = {}
        self.depth_sum = 0      # racelint: guarded-by(self._stats_lock)
        self.depth_samples = 0  # racelint: guarded-by(self._stats_lock)
        self.depth_max = 0      # racelint: guarded-by(self._stats_lock)
        self._stats_lock = threading.Lock()
        # windowed stats for the serve-side sentinels (opt-in: the
        # reporter thread in task_serve flips track_window on and
        # drains via window_stats(); off by default so the hot path
        # pays nothing)
        self.track_window = False
        self._win_lock = threading.Lock()
        self._win_lats: list = []
        self._win_requests = 0
        # SLO arming (monitor/slo.py): when task_serve declares
        # serve_slo_p99_ms, each windowed request over the threshold
        # counts as one budget violation; window_stats() drains the
        # count into the serve_window record's ``viol`` field
        self.slo_ms = 0.0
        self._win_viol = 0

    # ------------------------------------------------------------- client
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"cxxnet-serve-batcher-{self.name}")
        self._thread.start()

    def submit(self, x: np.ndarray) -> np.ndarray:
        """One request (``(n,) + input_shape`` rows); returns its output
        rows once the coalesced batch it rode in completes."""
        if self._failed is not None:
            raise self._failed
        if self._closing:
            raise ServeClosed(f"batcher {self.name!r} is shut down")
        assert self._thread is not None, "call start() first"
        tracer = self.metrics.tracer if self.metrics is not None else None
        req = _Request(data=np.asarray(x), event=threading.Event(),
                       t0=time.perf_counter())
        if tracer is not None and tracer.enabled:
            req.trace_id = tracer.new_trace()
            if req.trace_id is not None:
                req.tid = threading.current_thread().name
        # bounded put that re-checks the latch: a client must neither
        # block forever on a dead batcher's full queue nor enqueue
        # behind the shutdown drain (generation_put's discipline)
        while True:
            if self._failed is not None:
                raise self._failed
            if self._closing:
                raise ServeClosed(f"batcher {self.name!r} is shut down")
            try:
                self._q.put(req, timeout=0.05)
                break
            except queue.Full:
                continue
        # arrival-side depth sample (the satellite fix): a burst that
        # arrives and drains between two dispatches is visible only
        # here — the dispatcher's sample runs after it already drained
        # the queue into the open batch
        self._observe_depth(self._q.qsize())
        # the latch can land between the check above and the put: the
        # dispatcher drains and dies, and our request sits in a queue
        # nobody reads.  Poll the thread while waiting — if it is gone,
        # release the queue ourselves (every req gets error + event)
        while not req.event.wait(0.1):
            t = self._thread
            if t is None or not t.is_alive():
                self._drain(self._failed)
        if req.error is not None:
            raise req.error
        latency = time.perf_counter() - req.t0
        # t_served == 0 means the dispatcher skipped the span stamps
        # (tracing toggled off between submit and dispatch): no chain
        if req.trace_id is not None and tracer is not None \
                and req.t_served > 0.0:
            # respond: runner completion -> this client actually awake
            # and returning; request: the whole submit->result wall,
            # stamped from the SAME latency the histogram records so
            # the span chain and serve_latency_sec agree exactly
            tracer.emit("respond", req.t_served, req.t0 + latency,
                        trace_id=req.trace_id, model=self.name)
            tracer.emit("request", req.t0, req.t0 + latency,
                        trace_id=req.trace_id, model=self.name)
        if self.metrics is not None:
            self.metrics.observe("serve_latency_sec", latency)
        if self.track_window:
            with self._win_lock:
                self._win_lats.append(latency)
                self._win_requests += 1
                if self.slo_ms > 0.0 and latency * 1e3 > self.slo_ms:
                    self._win_viol += 1
        return req.result

    def _observe_depth(self, depth: int) -> None:
        with self._stats_lock:
            self.depth_sum += depth
            self.depth_samples += 1
            if depth > self.depth_max:
                self.depth_max = depth

    def window_stats(self) -> Dict[str, Any]:
        """Drain the current sentinel window: request count, latency
        percentiles (ms), and the live queue depth.  The serve-side
        sentinel reporter (main.task_serve) calls this once per
        ``serve_sentinel_window`` seconds."""
        with self._win_lock:
            lats, self._win_lats = self._win_lats, []
            n, self._win_requests = self._win_requests, 0
            viol, self._win_viol = self._win_viol, 0
        out: Dict[str, Any] = {"requests": n,
                               "queue_depth": self._q.qsize()}
        if self.slo_ms > 0.0:
            out["viol"] = viol
        if lats:
            from ..monitor.metrics import nearest_rank
            lats.sort()
            out.update(
                p50_ms=round(nearest_rank(lats, 50) * 1e3, 3),
                p95_ms=round(nearest_rank(lats, 95) * 1e3, 3),
                p99_ms=round(nearest_rank(lats, 99) * 1e3, 3))
        return out

    # --------------------------------------------------------- dispatcher
    def _loop(self) -> None:
        carry = None        # a coalesce-overflow request held for the
        while True:         # NEXT batch (dispatches never exceed
            if carry is not None:                        # max_batch)
                first, carry = carry, None
            else:
                first = self._q.get()
                if first is None:
                    return
                first.t_deq = time.perf_counter()
            batch = [first]
            rows = first.data.shape[0]
            stop = False
            deadline = time.perf_counter() + self.max_wait_ms / 1e3
            while rows < self.max_batch:
                rem = deadline - time.perf_counter()
                if rem <= 0:
                    break
                try:
                    r = self._q.get(timeout=rem)
                except queue.Empty:
                    break
                if r is None:       # shutdown sentinel mid-coalesce:
                    stop = True     # serve what we have, then exit
                    break
                r.t_deq = time.perf_counter()
                if rows + r.data.shape[0] > self.max_batch:
                    carry = r       # would overflow: opens the next batch
                    break
                batch.append(r)
                rows += r.data.shape[0]
            depth = self._q.qsize()
            self._observe_depth(depth)
            if self.metrics is not None:
                self.metrics.set_gauge("serve_queue_depth", depth)
            if not self._run(batch, rows):
                if carry is not None:   # latched: the held request must
                    carry.error = self._failed      # fail too, not hang
                    carry.event.set()
                return              # runner failed: latched + drained
            if stop:
                return

    def _run(self, batch, rows: int) -> bool:
        tracer = self.metrics.tracer if self.metrics is not None else None
        riders = [r.trace_id for r in batch if r.trace_id is not None] \
            if tracer is not None and tracer.enabled else []
        try:
            t_disp = time.perf_counter()
            if riders:
                # close out each sampled rider's pre-dispatch stages:
                # queue_wait (submit -> dequeued, on the rider's own
                # track) and coalesce (dequeued -> this dispatch; a
                # carry request's coalesce spans into the next batch)
                for r in batch:
                    if r.trace_id is None:
                        continue
                    tracer.emit("queue_wait", r.t0, r.t_deq,
                                trace_id=r.trace_id, tid=r.tid,
                                model=self.name)
                    tracer.emit("coalesce", r.t_deq, t_disp,
                                trace_id=r.trace_id, tid=r.tid,
                                model=self.name)
            if len(batch) == 1:
                data = batch[0].data
            else:
                data = np.concatenate([r.data for r in batch], axis=0)
            if riders:
                # the engine's pad/device/unpad spans inherit the rider
                # list through the thread-local link
                with tracer.link(riders):
                    out = self.runner(data)
                t_done = time.perf_counter()
                tracer.emit("dispatch", t_disp, t_done, riders=riders,
                            rows=rows, requests=len(batch),
                            model=self.name)
                for r in batch:
                    if r.trace_id is not None:
                        r.t_served = t_done
            else:
                out = self.runner(data)
            self.n_batches += 1
            self.n_requests += len(batch)
            self.rows_served += rows
            self.batch_hist[rows] = self.batch_hist.get(rows, 0) + 1
            if self.metrics is not None:
                self.metrics.observe("serve_batch_rows", rows)
            off = 0
            for r in batch:
                k = r.data.shape[0]
                r.result = out[off:off + k]
                off += k
                r.event.set()
            return True
        except BaseException as e:  # noqa: BLE001 — must reach clients
            self._failed = e
            for r in batch:
                r.error = e
                r.event.set()
            self._drain(e)
            return False

    def _drain(self, err: Optional[BaseException]) -> None:
        """Fail (or, post-shutdown, reject) everything still queued."""
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r is None:
                continue
            r.error = err if err is not None else ServeClosed(
                f"batcher {self.name!r} shut down before this request "
                "was served")
            r.event.set()

    # ------------------------------------------------------------ teardown
    def close(self) -> None:
        """Stop accepting requests, serve everything already queued,
        join the dispatcher, and reject stragglers.  Idempotent."""
        self._closing = True
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join()
        self._thread = None
        # requests that raced the sentinel (or arrived after a failure)
        # must still be released — no client left waiting on an event
        self._drain(self._failed)

    @property
    def mean_batch(self) -> float:
        return self.rows_served / self.n_batches if self.n_batches else 0.0

    @property
    def mean_depth(self) -> float:
        # sum and count move together only under the lock: an unlocked
        # pair read can tear across a concurrent _observe_depth and
        # report a mean no sample window ever had
        with self._stats_lock:
            return self.depth_sum / self.depth_samples \
                if self.depth_samples else 0.0

    def stats(self) -> Dict[str, Any]:
        """Dispatch accounting for the ``serve`` JSONL record."""
        with self._stats_lock:
            depth_mean = self.depth_sum / self.depth_samples \
                if self.depth_samples else 0.0
            depth_max = self.depth_max
        return {
            "requests": self.n_requests,
            "batches": self.n_batches,
            "rows": self.rows_served,
            "mean_batch": round(self.mean_batch, 2),
            "batch_hist": {str(k): v
                           for k, v in sorted(self.batch_hist.items())},
            "queue_depth_mean": round(depth_mean, 2),
            "queue_depth_max": depth_max,
        }


@dataclasses.dataclass
class _GenRequest:
    """One generation request riding the step scheduler."""
    prompt: "np.ndarray"
    max_new: int
    event: threading.Event
    t0: float
    rng: Optional[Any] = None
    tokens: Optional[list] = None       # generated ids (the result)
    pos: int = 0                        # next cache write position
    # speculative decoding: columns valid in the DRAFT cache.  Trails
    # ``pos`` by at most 1 (only after a fully accepted block — the
    # draft never consumed its own last proposal); the catch-up tick at
    # the top of each round closes the gap.  Rollback after a rejected
    # tail is just this counter: the length mask hides stale columns,
    # no buffer copy
    dpos: int = 0
    # chunked prefill: next chunk offset while the prompt streams into
    # the cache decode_prefill_chunk columns at a time
    chunk_off: int = 0
    error: Optional[BaseException] = None
    trace_id: Optional[int] = None
    tid: Optional[str] = None


class StepScheduler:
    """Token-level continuous batching over a decode ``runner``
    (:class:`~cxxnet_tpu.serve.decode.DecodeEngine` or a fake with the
    same ``slots`` / ``prefill(slot, tokens)`` / ``step(tokens,
    positions)`` surface).

    The MicroBatcher generalized from request-level to STEP-level
    scheduling: instead of coalescing whole requests into one dispatch,
    the dispatcher thread runs a decode loop where requests join and
    leave the in-flight batch BETWEEN single-token steps — a finished
    sequence's cache slot is freed and immediately refilled from the
    queue, so a short generation never waits on the longest one in its
    batch (no head-of-line blocking).  ``continuous=False`` degrades to
    request-level batching (admit only into an EMPTY batch, run it to
    completion) — the A/B baseline ``bench.py --lm-serve`` measures
    against.

    Speculative decoding (``draft`` + ``spec_k``, doc/serve.md): each
    decode round runs ``spec_k`` cheap single-token steps on the DRAFT
    runner to propose a candidate block, then ONE flagship ``block``
    dispatch verifies all ``spec_k + 1`` positions against the flagship
    cache.  The accepted prefix advances the cache several columns per
    flagship dispatch; a rejected tail rolls both caches back by
    arithmetic on the length counters (the mask hides stale columns —
    no buffer copy).  Greedy speculative output is BITWISE identical to
    plain greedy decode (every verify row is the sequential step's
    logits row); non-greedy sampling uses standard rejection sampling
    off the verified distributions, which preserves the target
    distribution exactly.

    Chunked prefill (``prefill_chunk``): instead of one whole-prompt
    prefill stalling every in-flight request's next token, the prompt
    streams into the cache ``prefill_chunk`` columns per ``block``
    dispatch, ONE chunk tick interleaved between decode rounds —
    bounding head-of-line blocking at one chunk.

    Thread discipline is MicroBatcher's verbatim: bounded queue,
    ``None`` shutdown sentinel, a runner exception latches the
    scheduler dead and fans out to every active AND queued request —
    clients get the exception, never a hang."""

    def __init__(self, runner, *, max_new_tokens: int = 32,
                 eos: int = -1, sample: str = "greedy",
                 temp: float = 1.0, topk: int = 0, seed: int = 0,
                 queue_depth: int = 64, continuous: bool = True,
                 draft=None, spec_k: int = 0, prefill_chunk: int = 0,
                 metrics=None, name: str = "decode"):
        self.runner = runner
        self.max_new_tokens = max(1, int(max_new_tokens))
        self.eos = int(eos)
        self.sample_kind = sample
        self.temp = float(temp)
        self.topk = int(topk)
        self.seed = int(seed)
        self.continuous = bool(continuous)
        self.draft = draft
        self.spec_k = int(spec_k)
        self.prefill_chunk = int(prefill_chunk)
        self._spec = draft is not None and self.spec_k >= 1
        self.metrics = metrics
        self.name = name
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_depth)))
        self._thread: Optional[threading.Thread] = None
        # racelint: latch(write-once by the decode loop; racy reads fan the failure out to submitters)
        self._failed: Optional[BaseException] = None
        self._closing = False
        self._draining = False
        self._active: Dict[int, _GenRequest] = {}
        # slots mid-chunked-prefill (FIFO by admission: _fill_order)
        self._filling: Dict[int, _GenRequest] = {}
        self._fill_order: list = []
        self._free: list = list(range(runner.slots))
        self._req_seq = 0  # racelint: guarded-by(self._stats_lock)
        # accounting for the serve_gen record / --lm-serve sweep.
        # Counters below are decode-loop-single-writer plain-int bumps;
        # the admin scrape path reads them unlocked by design (PR 17)
        self.n_requests = 0        # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_tokens = 0          # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_steps = 0           # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_prefills = 0        # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_prefill_chunks = 0  # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_draft_steps = 0     # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_verify_calls = 0    # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_spec_proposed = 0   # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.n_spec_accepted = 0   # racelint: atomic(plain-int bump, decode-loop-only writer)
        self._draft_wall = 0.0     # racelint: atomic(float bump, decode-loop-only writer)
        self._verify_wall = 0.0    # racelint: atomic(float bump, decode-loop-only writer)
        # racelint: atomic(per-key int bump, decode-loop-only writer; scrape copies via copy_racy)
        self.occ_hist: Dict[int, int] = {}
        # per-step decode+sample wall
        self._tok_lats: list = []  # racelint: guarded-by(self._stats_lock)
        self._stats_lock = threading.Lock()

    # ------------------------------------------------------------- client
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"cxxnet-decode-sched-{self.name}")
        self._thread.start()

    def submit(self, prompt: "np.ndarray",
               max_new_tokens: Optional[int] = None) -> list:
        """One generation request: blocks until the sequence finishes
        (or the scheduler dies) and returns the generated token ids.
        Thread-safe; prompts longer than the cache are rejected here,
        not in the decode loop."""
        if self._failed is not None:
            raise self._failed
        if self._closing:
            raise ServeClosed(f"scheduler {self.name!r} is shut down")
        assert self._thread is not None, "call start() first"
        prompt = np.asarray(prompt).reshape(-1)
        limit = getattr(self.runner, "max_seqlen", None)
        if prompt.shape[0] < 1 or (limit is not None
                                   and prompt.shape[0] > limit):
            raise ValueError(
                f"submit: prompt of {prompt.shape[0]} tokens, cache "
                f"holds 1..{limit}")
        tracer = self.metrics.tracer if self.metrics is not None else None
        with self._stats_lock:
            self._req_seq += 1
            rid = self._req_seq
        rng = np.random.RandomState((self.seed * 1000003 + rid)
                                    % (2 ** 31)) \
            if self.sample_kind != "greedy" else None
        req = _GenRequest(prompt=prompt,
                          max_new=int(max_new_tokens
                                      or self.max_new_tokens),
                          event=threading.Event(),
                          t0=time.perf_counter(), rng=rng)
        if tracer is not None and tracer.enabled:
            req.trace_id = tracer.new_trace()
            if req.trace_id is not None:
                req.tid = threading.current_thread().name
        while True:
            if self._failed is not None:
                raise self._failed
            if self._closing:
                raise ServeClosed(f"scheduler {self.name!r} is shut down")
            try:
                self._q.put(req, timeout=0.05)
                break
            except queue.Full:
                continue
        while not req.event.wait(0.1):
            t = self._thread
            if t is None or not t.is_alive():
                self._gen_drain(self._failed)
        if req.error is not None:
            raise req.error
        latency = time.perf_counter() - req.t0
        if req.trace_id is not None and tracer is not None:
            tracer.emit("request", req.t0, req.t0 + latency,
                        trace_id=req.trace_id, tid=req.tid,
                        model=self.name, tokens=len(req.tokens))
        if self.metrics is not None:
            self.metrics.observe("gen_latency_sec", latency)
        return req.tokens

    # --------------------------------------------------------- dispatcher
    def _loop(self) -> None:
        batch_open = True   # request-level mode: admission window —
        while True:         # open while the batch has not stepped yet
            if not self._active and not self._filling:
                if self._draining:
                    return
                batch_open = True
                r = self._q.get()
                if r is None:
                    self._gen_drain(None)
                    return
                if not self._admit(r):
                    return
            # token-level admission: refill free slots from the queue
            # between steps (continuous), or fill the open batch once
            # and run it to completion (request-level baseline — the
            # head-of-line blocking --lm-serve measures against)
            while self._free and not self._draining \
                    and (self.continuous or batch_open):
                try:
                    r = self._q.get_nowait()
                except queue.Empty:
                    break
                if r is None:
                    self._draining = True
                    break
                if not self._admit(r):
                    return
            # chunked prefill: ONE chunk tick per loop iteration for
            # the oldest joining prompt, interleaved with the decode
            # round below — a long prompt costs every in-flight request
            # at most one chunk of head-of-line latency per token
            if self._filling:
                if not self._chunk_tick():
                    return
            if not self._active:
                continue
            batch_open = False
            if not (self._spec_round() if self._spec
                    else self._step_once()):
                return

    def _sample(self, logits, req: _GenRequest) -> int:
        from .decode import sample_token
        return sample_token(logits, self.sample_kind, self.temp,
                            self.topk, req.rng)

    def _finish(self, slot: int, req: _GenRequest) -> None:
        self._free.append(slot)
        del self._active[slot]
        self.n_requests += 1
        req.event.set()

    def _admit(self, req: _GenRequest) -> bool:
        """Prefill ``req`` into a free slot (or queue it for chunked
        prefill); False latches the scheduler dead (exception already
        fanned out)."""
        tracer = self.metrics.tracer if self.metrics is not None else None
        slot = self._free.pop()
        if self.prefill_chunk > 0:
            # chunked admission: the prompt streams into the cache one
            # _chunk_tick at a time; the request activates (samples its
            # first token) on the last chunk
            req.chunk_off = 0
            self._filling[slot] = req
            self._fill_order.append(slot)
            return True
        try:
            t0 = time.perf_counter()
            logits = self.runner.prefill(slot, req.prompt)
            t1 = time.perf_counter()
            if req.trace_id is not None and tracer is not None:
                tracer.emit("prefill", t0, t1, trace_id=req.trace_id,
                            slot=slot, prompt=int(req.prompt.shape[0]),
                            model=self.name)
            self.n_prefills += 1
            self._activate(slot, req, logits)
            return True
        except BaseException as e:  # noqa: BLE001 — must reach clients
            self._free.append(slot)
            self._fail(e, extra=[req])
            return False

    def _activate(self, slot: int, req: _GenRequest, logits) -> None:
        """The prompt is fully cached: prefill the draft (speculation),
        sample the first token off the last-prompt-position ``logits``
        row, and move ``req`` into the active batch (or finish it).
        Caller owns exception handling — a draft prefill failure latches
        like a flagship one."""
        tracer = self.metrics.tracer if self.metrics is not None else None
        plen = int(req.prompt.shape[0])
        if self._spec:
            t0 = time.perf_counter()
            self.draft.prefill(slot, req.prompt)
            t1 = time.perf_counter()
            self._draft_wall += t1 - t0
            if req.trace_id is not None and tracer is not None:
                tracer.emit("draft", t0, t1, trace_id=req.trace_id,
                            slot=slot, prompt=plen, model=self.name)
        req.dpos = plen
        tok = self._sample(logits, req)
        req.tokens = [tok]
        req.pos = plen
        self.n_tokens += 1
        limit = getattr(self.runner, "max_seqlen", None)
        if tok == self.eos or len(req.tokens) >= req.max_new \
                or (limit is not None and req.pos >= limit):
            self._free.append(slot)
            self.n_requests += 1
            req.event.set()
        else:
            self._active[slot] = req

    def _base_positions(self) -> "np.ndarray":
        """Per-slot next-write FLAGSHIP cache column — the sacrificial
        position an idle slot passes in a batched dispatch: garbage
        scattered there sits past the slot's length mask and is
        overwritten by the dispatch that first computes at it, so it is
        never read (the property every batched multi-slot dispatch
        leans on)."""
        positions = np.zeros((self.runner.slots,), np.int32)
        for slot, req in self._active.items():
            positions[slot] = req.pos
        for slot, req in self._filling.items():
            positions[slot] = req.chunk_off
        return positions

    def _chunk_tick(self) -> bool:
        """One chunked-prefill dispatch: the next ``prefill_chunk``
        prompt columns of the OLDEST joining request (FIFO), every
        other slot sacrificial.  On the last chunk the request
        activates.  False latches the scheduler dead."""
        tracer = self.metrics.tracer if self.metrics is not None else None
        slot = self._fill_order[0]
        req = self._filling[slot]
        C = self.prefill_chunk
        off = req.chunk_off
        plen = int(req.prompt.shape[0])
        tokens = np.zeros((self.runner.slots, C), np.int32)
        positions = self._base_positions()
        chunk = req.prompt[off:off + C]
        tokens[slot, :chunk.shape[0]] = chunk
        positions[slot] = off
        try:
            t0 = time.perf_counter()
            logits = self.runner.block(tokens, positions)
            t1 = time.perf_counter()
            self.n_prefill_chunks += 1
            if req.trace_id is not None and tracer is not None:
                tracer.emit("prefill_chunk", t0, t1,
                            trace_id=req.trace_id, slot=slot,
                            offset=off, model=self.name)
            req.chunk_off = off + C
            if req.chunk_off >= plen:
                self._fill_order.pop(0)
                del self._filling[slot]
                self.n_prefills += 1
                # the last prompt position's logits row — same row the
                # whole-prompt prefill returns, bitwise (block rows are
                # the sequential steps' rows)
                self._activate(slot, req, logits[slot, plen - 1 - off])
            return True
        except BaseException as e:  # noqa: BLE001 — must reach clients
            # req may already be out of _filling (activation threw):
            # make sure it fails either way; _fail covers _filling
            extra = [] if req.event.is_set() else [req]
            self._fail(e, extra=extra)
            return False

    def _step_once(self) -> bool:
        """One single-token decode step over every active slot; False
        latches the scheduler dead."""
        tracer = self.metrics.tracer if self.metrics is not None else None
        riders = [r.trace_id for r in self._active.values()
                  if r.trace_id is not None] \
            if tracer is not None and tracer.enabled else []
        slots = self.runner.slots
        tokens = np.zeros((slots,), np.int32)
        positions = np.zeros((slots,), np.int32)
        for slot, req in self._active.items():
            tokens[slot] = req.tokens[-1]
            positions[slot] = req.pos
        n_active = len(self._active)
        try:
            t0 = time.perf_counter()
            if riders:
                with tracer.link(riders):
                    logits = self.runner.step(tokens, positions)
            else:
                logits = self.runner.step(tokens, positions)
            t1 = time.perf_counter()
            limit = getattr(self.runner, "max_seqlen", None)
            for slot in list(self._active):
                req = self._active[slot]
                tok = self._sample(logits[slot], req)
                req.tokens.append(tok)
                req.pos += 1
                self.n_tokens += 1
                if tok == self.eos or len(req.tokens) >= req.max_new \
                        or (limit is not None and req.pos >= limit):
                    self._finish(slot, req)
            t2 = time.perf_counter()
            if riders:
                tracer.emit("decode", t0, t1, riders=riders,
                            active=n_active, model=self.name)
                tracer.emit("sample", t1, t2, riders=riders,
                            active=n_active, model=self.name)
            self.n_steps += 1
            self.occ_hist[n_active] = self.occ_hist.get(n_active, 0) + 1
            step_wall = t2 - t0
            with self._stats_lock:
                self._tok_lats.append(step_wall)
            if self.metrics is not None:
                self.metrics.observe("token_latency_sec", step_wall)
            return True
        except BaseException as e:  # noqa: BLE001 — must reach clients
            self._fail(e)
            return False

    def _draft_positions(self) -> "np.ndarray":
        """Per-slot next-write DRAFT cache column.  Idle slots are
        sacrificial at 0 — a filling/free slot's draft row is fully
        rewritten by its whole-prompt draft prefill at activation."""
        positions = np.zeros((self.runner.slots,), np.int32)
        for slot, req in self._active.items():
            positions[slot] = req.dpos
        return positions

    def _spec_round(self) -> bool:
        """One speculative decode round over every active slot: (1) a
        draft catch-up tick for slots whose draft cache trails the
        flagship by one column (the fully-accepted-block case — the
        draft never fed its own last proposal), (2) ``spec_k`` draft
        steps proposing a candidate block, (3) ONE flagship ``block``
        dispatch verifying all ``spec_k + 1`` positions against the
        flagship cache, (4) host-side acceptance — greedy takes the
        longest argmax-agreeing prefix, which makes speculative greedy
        output BITWISE identical to plain greedy decode (every verify
        row is the sequential step's logits row); non-greedy does
        standard rejection sampling off the verified distributions.
        Rejected tails roll both caches back by arithmetic on the
        length counters (``pos``/``dpos``) — the length mask hides the
        stale columns, no buffer copy.  False latches the scheduler
        dead."""
        from .decode import draw_from, sample_probs
        tracer = self.metrics.tracer if self.metrics is not None else None
        riders = [r.trace_id for r in self._active.values()
                  if r.trace_id is not None] \
            if tracer is not None and tracer.enabled else []
        slots = self.runner.slots
        k = self.spec_k
        greedy = self.sample_kind == "greedy"
        n_active = len(self._active)
        round_draft_steps = 0
        try:
            t0 = time.perf_counter()
            # --- (1) catch-up: feed the true token at the draft's next
            # column; non-lagging slots ride sacrificially (their own
            # next column — overwritten by the first proposal step)
            if any(req.dpos < req.pos for req in self._active.values()):
                tokens = np.zeros((slots,), np.int32)
                positions = self._draft_positions()
                for slot, req in self._active.items():
                    if req.dpos < req.pos:
                        plen = int(req.prompt.shape[0])
                        tokens[slot] = req.tokens[req.dpos - plen]
                self.draft.step(tokens, positions)
                round_draft_steps += 1
                for req in self._active.values():
                    if req.dpos < req.pos:
                        req.dpos += 1
            # --- (2) spec_k proposal steps: the draft feeds the pending
            # token first, then chains its own proposals
            props = np.zeros((slots, k), np.int32)
            dprobs: Dict = {}           # (slot, j) -> draft prob vector
            feed = np.zeros((slots,), np.int32)
            for slot, req in self._active.items():
                feed[slot] = req.tokens[-1]
            for j in range(k):
                positions = self._draft_positions()
                logits = self.draft.step(feed, positions)
                round_draft_steps += 1
                for slot, req in self._active.items():
                    if greedy:
                        d = int(np.argmax(logits[slot]))
                    else:
                        p = sample_probs(logits[slot], self.sample_kind,
                                         self.temp, self.topk)
                        d = draw_from(p, req.rng)
                        dprobs[(slot, j)] = p
                    props[slot, j] = d
                    feed[slot] = d
                    req.dpos += 1
            t1 = time.perf_counter()
            self._draft_wall += t1 - t0
            # --- (3) verify: pending token + the k proposals, ONE
            # flagship dispatch over all slots
            vtokens = np.zeros((slots, k + 1), np.int32)
            vpos = self._base_positions()
            for slot, req in self._active.items():
                vtokens[slot, 0] = req.tokens[-1]
                vtokens[slot, 1:] = props[slot]
            if riders:
                with tracer.link(riders):
                    logits = self.runner.block(vtokens, vpos)
            else:
                logits = self.runner.block(vtokens, vpos)
            self.n_verify_calls += 1
            t2 = time.perf_counter()
            self._verify_wall += t2 - t1
            # --- (4) acceptance + emission
            limit = getattr(self.runner, "max_seqlen", None)
            for slot in list(self._active):
                req = self._active[slot]
                emitted = []
                if greedy:
                    # longest prefix where the draft agrees with the
                    # verified argmax; the first disagreeing position
                    # emits the VERIFIED token (so even a 0-acceptance
                    # draft leaves the output stream bitwise greedy)
                    for i in range(k + 1):
                        g = int(np.argmax(logits[slot, i]))
                        emitted.append(g)
                        if i < k and props[slot, i] != g:
                            break
                else:
                    for i in range(k):
                        pt = sample_probs(logits[slot, i],
                                          self.sample_kind, self.temp,
                                          self.topk)
                        pd = dprobs[(slot, i)]
                        d = int(props[slot, i])
                        if req.rng.random_sample() * pd[d] < pt[d]:
                            emitted.append(d)
                            continue
                        res = np.maximum(pt - pd, 0.0)
                        tot = res.sum()
                        emitted.append(
                            draw_from(res / tot, req.rng) if tot > 0.0
                            else draw_from(pt, req.rng))
                        break
                    else:
                        pt = sample_probs(logits[slot, k],
                                          self.sample_kind, self.temp,
                                          self.topk)
                        emitted.append(draw_from(pt, req.rng))
                m = len(emitted) - 1        # proposals accepted
                self.n_spec_proposed += k
                self.n_spec_accepted += m
                # draft rollback is counter arithmetic: lag 1 only
                # after a fully accepted block (m == k)
                req.dpos = req.pos + 1 + min(m, k - 1)
                for tok in emitted:
                    req.tokens.append(tok)
                    req.pos += 1
                    self.n_tokens += 1
                    if tok == self.eos \
                            or len(req.tokens) >= req.max_new \
                            or (limit is not None and req.pos >= limit):
                        self._finish(slot, req)
                        break
            t3 = time.perf_counter()
            if riders:
                tracer.emit("draft", t0, t1, riders=riders,
                            active=n_active, model=self.name)
                tracer.emit("verify", t1, t2, riders=riders,
                            active=n_active, model=self.name)
                tracer.emit("sample", t2, t3, riders=riders,
                            active=n_active, model=self.name)
            self.n_steps += 1
            self.n_draft_steps += round_draft_steps
            self.occ_hist[n_active] = self.occ_hist.get(n_active, 0) + 1
            step_wall = t3 - t0
            with self._stats_lock:
                self._tok_lats.append(step_wall)
            if self.metrics is not None:
                self.metrics.observe("token_latency_sec", step_wall)
                self.metrics.counter_inc("spec_draft_steps",
                                         round_draft_steps)
                self.metrics.counter_inc("spec_verify_calls")
                if self.n_spec_proposed:
                    self.metrics.set_gauge(
                        "spec_accept_rate",
                        self.n_spec_accepted / self.n_spec_proposed)
            return True
        except BaseException as e:  # noqa: BLE001 — must reach clients
            self._fail(e)
            return False

    def _fail(self, e: BaseException, extra=()) -> None:
        """Latch dead and fan the exception out to every active,
        chunk-prefilling, AND queued request (the MicroBatcher _run
        contract)."""
        self._failed = e
        for req in (list(self._active.values())
                    + list(self._filling.values()) + list(extra)):
            req.error = e
            req.event.set()
        self._active.clear()
        self._filling.clear()
        self._fill_order.clear()
        self._gen_drain(e)

    def _gen_drain(self, err: Optional[BaseException]) -> None:
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r is None:
                continue
            r.error = err if err is not None else ServeClosed(
                f"scheduler {self.name!r} shut down before this request "
                "was served")
            r.event.set()

    # ------------------------------------------------------------ teardown
    def close(self) -> None:
        """Stop accepting requests, finish everything active/queued,
        join the dispatcher, reject stragglers.  Idempotent."""
        self._closing = True
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join()
        self._thread = None
        self._gen_drain(self._failed)

    # ------------------------------------------------------------- stats
    @property
    def mean_occupancy(self) -> float:
        if not self.occ_hist:
            return 0.0
        total = sum(self.occ_hist.values())
        return sum(k * v for k, v in self.occ_hist.items()) / total

    def stats(self) -> Dict[str, Any]:
        """Decode accounting for the ``serve_gen`` JSONL record: step
        and token counts, batch-occupancy histogram, and per-token
        latency percentiles (ms)."""
        with self._stats_lock:
            lats = sorted(self._tok_lats)
        out: Dict[str, Any] = {
            "requests": self.n_requests,
            "tokens": self.n_tokens,
            "steps": self.n_steps,
            "prefills": self.n_prefills,
            "mean_occupancy": round(self.mean_occupancy, 2),
            "occupancy_hist": {str(k): v for k, v
                               in sorted(self.occ_hist.items())},
            "batching": "continuous" if self.continuous else "request",
        }
        if self._spec:
            out.update(
                spec_k=self.spec_k,
                draft_steps=self.n_draft_steps,
                verify_calls=self.n_verify_calls,
                acceptance_rate=round(
                    self.n_spec_accepted / self.n_spec_proposed, 4)
                if self.n_spec_proposed else 0.0,
                draft_ms=round(self._draft_wall * 1e3, 3),
                verify_ms=round(self._verify_wall * 1e3, 3))
        if self.prefill_chunk > 0:
            out.update(prefill_chunk=self.prefill_chunk,
                       prefill_chunks=self.n_prefill_chunks)
        if lats:
            from ..monitor.metrics import nearest_rank
            out.update(
                tok_p50_ms=round(nearest_rank(lats, 50) * 1e3, 3),
                tok_p95_ms=round(nearest_rank(lats, 95) * 1e3, 3),
                tok_p99_ms=round(nearest_rank(lats, 99) * 1e3, 3))
        return out
