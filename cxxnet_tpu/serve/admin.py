"""Live serving control plane: the in-process admin HTTP endpoint.

``serve_admin_port = N`` starts one stdlib ``http.server`` thread
inside the serve task (owned by :class:`~cxxnet_tpu.serve.host.
ModelHost`, joined by its ``close()``), turning the post-hoc JSONL
observability stack into something a load balancer can health-check
and a scraper can poll while the host is under load:

* ``/metrics``  — the live MetricsRegistry in Prometheus text format
  (monitor/promtext.py), plus exact ``le``-bucket histograms for the
  batcher's batch-size and the scheduler's occupancy distributions.
* ``/healthz``  — 200 while the process serves (liveness).
* ``/readyz``   — 200 only while ``ModelHost.ready`` holds: every
  model warmed, executables pinned, ``retraces == 0``; 503 during
  warmup and from the moment ``close()`` begins (the hot-swap
  admission signal ROADMAP item 4 gates on).
* ``/statusz``  — per-model JSON: QPS / p99 over the last reporter
  window, queue depth, batch/occupancy histograms, ``footprint()``
  bytes, retraces, uptime, the config echo, and the SLO verdict
  (monitor/slo.py).

THE scrape-path rule (asserted by tests/test_admin.py): handlers never
take the dispatcher's locks.  Counters/gauges are GIL-atomic dict
reads, histogram summaries come from ``snapshot()`` copies, the last
window record and the SLO verdict are whole-object swaps, and the one
hazard left — copying a dict the dispatcher is growing — is handled by
:func:`copy_racy` (bounded retry on the "changed size during
iteration" race), not by locking the writer.

:class:`FlightCapture` closes the anomaly loop: when a serve sentinel
or an SLO burn fires, it boosts ``trace_sample`` for the next
``serve_flight_requests`` requests, snapshots batcher/scheduler stats,
and emits one ``serve_flight`` record carrying the recent
``serve_window`` ring and the captured span trace_id range — a p99
spike leaves a diagnosable corpse instead of a bare anomaly line.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from ..monitor import log as mlog
from ..monitor import promtext


def copy_racy(d: Dict, tries: int = 8) -> Dict:
    """Copy a dict another thread may be growing, WITHOUT locking the
    writer: dict iteration is GIL-consistent but raises RuntimeError if
    an insert lands mid-copy — rare, so a bounded retry converges; the
    final attempt falls back to an item-at-a-time copy that tolerates
    concurrent growth."""
    for _ in range(tries):
        try:
            return dict(d)
        except RuntimeError:
            continue
    out = {}
    for k in list(d.keys()):
        try:
            out[k] = d[k]
        except KeyError:
            continue
    return out


class FlightCapture:
    """Anomaly-triggered span boost + one ``serve_flight`` record.

    Armed by :meth:`trigger` (from a sentinel anomaly or an SLO burn —
    idempotent while armed, so a storm of anomalies yields ONE flight);
    :meth:`tick` runs once per reporter window and completes the
    capture after ``requests`` boosted requests (or ``max_ticks``
    windows, so a dead-air host still lands its record)."""

    def __init__(self, metrics, count_fn: Callable[[], int], *,
                 model: str = "default", boost: int = 1,
                 requests: int = 16, max_ticks: int = 10,
                 ring: int = 8,
                 stats_fn: Optional[Callable[[], dict]] = None):
        self.metrics = metrics
        self.count_fn = count_fn          # lock-free served-request count
        self.model = model
        self.boost = max(1, int(boost))
        self.requests = max(1, int(requests))
        self.max_ticks = max(1, int(max_ticks))
        self.stats_fn = stats_fn
        # racelint: atomic(bounded deque, GIL-atomic appends; tick() snapshots under the arm lock and staleness is tolerated)
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._lock = threading.Lock()
        self.armed = False        # racelint: guarded-by(self._lock)
        self._reason = ""         # racelint: guarded-by(self._lock)
        self._prev_sample = 0     # racelint: guarded-by(self._lock)
        self._wm0 = 0             # racelint: guarded-by(self._lock)
        self._n0 = 0              # racelint: guarded-by(self._lock)
        self._ticks = 0           # racelint: guarded-by(self._lock)

    def note_window(self, rec: dict) -> None:
        """Ring of recent ``serve_window`` records — the flight's
        context payload (kept here, NOT in the sentinel bank: its ring
        clears on every ``flight_dump``)."""
        self._ring.append(dict(rec))

    def trigger(self, reason: str) -> bool:
        """Arm the capture; False when already armed (one flight per
        storm)."""
        with self._lock:
            if self.armed:
                return False
            tracer = self.metrics.tracer
            self._prev_sample = tracer.sample
            self._wm0 = tracer.watermark
            self._n0 = self.count_fn()
            self._ticks = 0
            reason = str(reason)
            self._reason = reason
            self.armed = True
            tracer.configure(self.boost)
        # log from the local: reading self._reason after the lock drops
        # can observe a LATER flight's reason (torn-log race)
        mlog.info(f"serve flight armed ({reason}): trace_sample "
                  f"-> {self.boost} for next {self.requests} requests")
        return True

    # racelint: thread(reporter)
    def tick(self) -> Optional[dict]:
        """One reporter window; returns the ``serve_flight`` record
        when the capture completes this tick, else None."""
        with self._lock:
            if not self.armed:
                return None
            self._ticks += 1
            boosted = self.count_fn() - self._n0
            if boosted < self.requests and self._ticks < self.max_ticks:
                return None
            tracer = self.metrics.tracer
            tracer.configure(self._prev_sample)
            wm1 = tracer.watermark
            rec: Dict[str, Any] = {
                "model": self.model, "reason": self._reason,
                "requests_boosted": int(boosted),
                "sample_boost": self.boost,
                "trace_first": self._wm0 + 1 if wm1 > self._wm0 else 0,
                "trace_last": wm1 if wm1 > self._wm0 else 0,
                "n_windows": len(self._ring),
                "windows": list(self._ring),
            }
            if self.stats_fn is not None:
                rec["stats"] = self.stats_fn()
            self.armed = False
        self.metrics.counter_inc("serve_flights")
        self.metrics.emit("serve_flight", **rec)
        # rec carries the reason captured under the lock; self._reason
        # may already belong to the next flight by now
        mlog.info(f"serve flight captured: {rec['requests_boosted']} "
                  f"requests, traces {rec['trace_first']}.."
                  f"{rec['trace_last']} ({rec['reason']})")
        return rec


class AdminServer:
    """The four-surface admin endpoint over one ``ThreadingHTTPServer``
    (daemon per-request threads, one acceptor thread named
    ``cxxnet-serve-admin`` that ``close()`` joins)."""

    def __init__(self, host, metrics, *, port: int,
                 addr: str = "0.0.0.0",
                 config: Optional[Dict[str, Any]] = None):
        self.host = host
        self.metrics = metrics
        self._addr = (addr, int(port))
        self._config = dict(config or {})
        self._t0 = time.time()
        # racelint: atomic(whole-object swap: start()/close() publish; the acceptor loop and port property only read)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # whole-object swaps the scrape path reads without locks
        # racelint: atomic(whole-object dict swap, reporter is the single writer; handlers read the old or the new map, never a torn one)
        self._last_window: Dict[str, dict] = {}
        # racelint: atomic(whole-object dict swap, note_ready is the single writer)
        self._footprints: Dict[str, dict] = {}
        self.slo = None          # SloTracker (task_serve wires it)
        self.flight: Optional[FlightCapture] = None

    # ------------------------------------------------------------ wiring
    def note_window(self, model: str, rec: dict) -> None:
        """Reporter tick -> cached last window (atomic dict assignment;
        /statusz reads it instead of draining window_stats(), which
        belongs to the reporter and takes the batcher's window lock)."""
        self._last_window = dict(self._last_window, **{model: dict(rec)})
        if self.flight is not None:
            self.flight.note_window(rec)

    def note_ready(self) -> None:
        """Cache each model's footprint at ready time — footprint()
        walks executables and device buffers, too heavy for a 10 Hz
        scrape path."""
        try:
            self._footprints = {name: self.host.model(name).footprint()
                                for name in self.host.names}
        except Exception as e:  # noqa: BLE001 — status must not gate ready
            mlog.warn(f"admin: footprint cache failed: {e}")

    # ------------------------------------------------------------- server
    def start(self) -> int:
        """Bind + serve; returns the bound port (``serve_admin_port``
        echoes it, and port 0 in tests binds an ephemeral one)."""
        admin = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr per request
                return

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    admin._route(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-response

        self._httpd = ThreadingHTTPServer(self._addr, _Handler)
        self._httpd.daemon_threads = True

        def _serve():
            try:
                self._httpd.serve_forever(poll_interval=0.1)
            except Exception as e:  # noqa: BLE001 — thread contract:
                # surface, never die silently (disclint thread rule)
                mlog.warn(f"serve admin endpoint died: {e}")

        self._thread = threading.Thread(target=_serve, daemon=True,
                                        name="cxxnet-serve-admin")
        self._thread.start()
        mlog.info(f"serve admin endpoint on "
                  f"http://{self._addr[0]}:{self.port}/  "
                  "(/metrics /healthz /readyz /statusz)")
        return self.port

    @property
    def port(self) -> int:
        assert self._httpd is not None, "call start() first"
        return self._httpd.server_address[1]

    def close(self) -> None:
        """Stop accepting, join the acceptor.  Idempotent."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # ------------------------------------------------------------ routing
    def _route(self, h: BaseHTTPRequestHandler) -> None:
        path = h.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            body = self._metrics_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
            code = 200
        elif path == "/healthz":
            body, ctype, code = b"ok\n", "text/plain", 200
        elif path == "/readyz":
            ready = bool(self.host.ready)
            body = b"ready\n" if ready else b"not ready\n"
            ctype, code = "text/plain", (200 if ready else 503)
        elif path in ("/statusz", "/"):
            body = (json.dumps(self._statusz(), sort_keys=True,
                               default=repr) + "\n").encode()
            ctype, code = "application/json", 200
        else:
            body, ctype, code = b"not found\n", "text/plain", 404
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    # ------------------------------------------------------------ surfaces
    def _exact_hists(self) -> Dict[str, Dict[int, int]]:
        """Batch-size / occupancy distributions as exact ``le``-bucket
        histograms (aggregated across models — one model per task run
        today, and promtext keeps one family per name)."""
        hists: Dict[str, Dict[int, int]] = {}
        for name in self.host.names:
            m = self.host.model(name)
            bat = getattr(m, "batcher", None)
            if bat is not None:
                agg = hists.setdefault("serve_batch_hist", {})
                for k, v in copy_racy(bat.batch_hist).items():
                    agg[int(k)] = agg.get(int(k), 0) + int(v)
            sched = getattr(m, "scheduler", None)
            if sched is not None:
                agg = hists.setdefault("decode_occupancy_hist", {})
                for k, v in copy_racy(sched.occ_hist).items():
                    agg[int(k)] = agg.get(int(k), 0) + int(v)
        return hists

    def _metrics_text(self) -> str:
        snap = {"counters": copy_racy(self.metrics.counters),
                "gauges": copy_racy(self.metrics.gauges),
                "histograms": {k: h.summary() for k, h
                               in copy_racy(
                                   self.metrics.histograms).items()}}
        return promtext.render(snap, hists=self._exact_hists())

    def _model_status(self, name: str) -> Dict[str, Any]:
        m = self.host.model(name)
        out: Dict[str, Any] = {"retraces": int(m.retraces),
                               "dtype": m.cfg.dtype}
        win = self._last_window.get(name)
        if win is not None:
            out["last_window"] = win  # QPS / p99 over the last window
        fp = self._footprints.get(name)
        if fp:
            out["footprint"] = fp
        bat = getattr(m, "batcher", None)
        if bat is not None:
            # plain-int attrs + racy dict copies; NEVER bat._stats_lock
            n_b = bat.n_batches
            out.update(
                kind="predict", requests=bat.n_requests, batches=n_b,
                rows=bat.rows_served,
                mean_batch=round(bat.rows_served / n_b, 2) if n_b
                else 0.0,
                batch_hist={str(k): v for k, v in sorted(
                    copy_racy(bat.batch_hist).items())},
                queue_depth_max=bat.depth_max)
            eng_stats = getattr(m.engine, "stats", None)
            if eng_stats is not None:
                out["engine"] = eng_stats()
        sched = getattr(m, "scheduler", None)
        if sched is not None:
            occ = copy_racy(sched.occ_hist)
            tot = sum(occ.values())
            out.update(
                kind="generate", requests=sched.n_requests,
                tokens=sched.n_tokens, steps=sched.n_steps,
                prefills=sched.n_prefills,
                mean_occupancy=round(sum(k * v for k, v in occ.items())
                                     / tot, 2) if tot else 0.0,
                occupancy_hist={str(k): v
                                for k, v in sorted(occ.items())})
            eng_stats = getattr(m.engine, "stats", None)
            if eng_stats is not None:
                out["engine"] = eng_stats()
        return out

    def _statusz(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "uptime_sec": round(time.time() - self._t0, 3),
            "ready": bool(self.host.ready),
            "models": {name: self._model_status(name)
                       for name in self.host.names},
            "config": self._config,
            "flights": self.metrics.counters.get("serve_flights", 0),
        }
        slo = self.slo
        if slo is not None:
            out["slo"] = slo.verdict  # whole-object swap, no lock
        return out
