"""Model hosting: engine + batcher bundles, routed by model name.

One process serves N models over the SHARED device pool: every
:class:`ServeModel` jits against the same JAX devices (and the same
trainer-level mesh rules), so co-hosted models time-share the chip the
way co-hosted services time-share a CPU — XLA schedules whichever
model's executable is dispatched.  Each model keeps its OWN batcher
thread and its own shape buckets / dtype variant, so a hot model
coalescing at depth never blocks a cold one's latency.

``ModelHost`` is the routing table (:meth:`ModelHost.predict` by model
name); :func:`load_serve_model` builds a ServeModel from config pairs +
a snapshot, the CLI/wrapper-shared path.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from . import ServeConfig
from .batcher import MicroBatcher
from .engine import PredictEngine
from ..monitor import log as mlog


class ServeModel:
    """One served model: a pinned-shape engine fronted by its own
    micro-batcher.  ``predict`` is the thread-safe client surface."""

    def __init__(self, trainer, cfg: Optional[ServeConfig] = None, *,
                 metrics=None, name: str = "default"):
        self.name = name
        self.cfg = cfg or ServeConfig()
        self.trainer = trainer
        self.metrics = metrics if metrics is not None else trainer.metrics
        self.engine = PredictEngine(trainer, shapes=self.cfg.shapes,
                                    dtype=self.cfg.dtype,
                                    metrics=self.metrics)
        max_batch = min(self.cfg.max_batch, max(self.cfg.shapes))
        if self.cfg.max_batch > max(self.cfg.shapes):
            mlog.warn(
                f"serve[{name}]: serve_max_batch = {self.cfg.max_batch} "
                f"exceeds the largest bucket ({max(self.cfg.shapes)}); "
                "coalescing caps at the bucket")
        self.batcher = MicroBatcher(
            self.engine.predict, max_batch=max_batch,
            max_wait_ms=self.cfg.max_wait_ms,
            queue_depth=self.cfg.queue_depth, metrics=self.metrics,
            name=name)

    def warmup(self) -> None:
        """Compile every bucket and start the dispatcher; after this,
        ``predict`` never traces (``engine.retraces`` stays 0)."""
        tracer = self.metrics.tracer if self.metrics is not None else None
        if tracer is not None and tracer.enabled:
            with tracer.span("serve_warmup", model=self.name,
                             buckets=len(self.engine.shapes)):
                self.engine.warmup()
        else:
            self.engine.warmup()
        self.batcher.start()

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Raw final-node rows for ``x``, batched with whatever other
        requests are in flight.  Thread-safe."""
        return self.batcher.submit(np.asarray(x, np.float32))

    @property
    def retraces(self) -> int:
        return self.engine.retraces

    def footprint(self) -> Dict[str, int]:
        """Per-device resident bytes this model costs the host
        (engine weights + warmed executables — doc/memory.md); empty
        before warmup."""
        return self.engine.footprint()

    def close(self) -> None:
        self.batcher.close()


class GenModel:
    """One served LM: a KV-cache decode engine fronted by the
    token-level continuous-batching step scheduler (serve/decode.py,
    doc/serve.md "Incremental decode").  The generation-side sibling of
    :class:`ServeModel` — same warmup / retraces / footprint / close
    surface, ``generate`` instead of ``predict``."""

    def __init__(self, trainer, cfg: Optional[ServeConfig] = None, *,
                 draft_trainer=None, metrics=None,
                 name: str = "default"):
        from .batcher import StepScheduler
        from .decode import DecodeEngine
        self.name = name
        self.cfg = cfg or ServeConfig(gen=1)
        self.trainer = trainer
        self.metrics = metrics if metrics is not None else trainer.metrics
        spec = draft_trainer is not None and self.cfg.spec_k >= 1
        # block executables this model needs warmed: the speculative
        # verify width (spec_k + 1) and the chunked-prefill width
        widths = []
        if spec:
            widths.append(self.cfg.spec_k + 1)
        if self.cfg.prefill_chunk > 0:
            widths.append(self.cfg.prefill_chunk)
        self.engine = DecodeEngine(trainer, slots=self.cfg.slots,
                                   max_seqlen=self.cfg.max_seqlen,
                                   metrics=self.metrics,
                                   kv_dtype=self.cfg.kv_dtype,
                                   block_widths=widths)
        self.draft = None
        if spec:
            # the draft shares slots + cache geometry so slot ids line
            # up across both engines; vocab must agree or proposals are
            # meaningless
            self.draft = DecodeEngine(
                draft_trainer, slots=self.cfg.slots,
                max_seqlen=self.engine.max_seqlen,
                metrics=self.metrics, kv_dtype=self.cfg.kv_dtype)
            if self.draft.vocab != self.engine.vocab:
                raise ValueError(
                    f"serve_draft_model: draft vocab {self.draft.vocab}"
                    f" != flagship vocab {self.engine.vocab}")
            if self.draft.max_seqlen != self.engine.max_seqlen:
                raise ValueError(
                    "serve_draft_model: draft max_seqlen "
                    f"{self.draft.max_seqlen} != flagship "
                    f"{self.engine.max_seqlen} (the draft net must be "
                    "built at the flagship's decode width)")
        self.scheduler = StepScheduler(
            self.engine, max_new_tokens=self.cfg.gen_tokens,
            eos=self.cfg.gen_eos, sample=self.cfg.gen_sample,
            temp=self.cfg.gen_temp, topk=self.cfg.gen_topk,
            seed=self.cfg.gen_seed, queue_depth=self.cfg.queue_depth,
            continuous=self.cfg.gen_batching == "continuous",
            draft=self.draft, spec_k=self.cfg.spec_k,
            prefill_chunk=self.cfg.prefill_chunk,
            metrics=self.metrics, name=name)

    def warmup(self) -> None:
        """Compile the full decode executable set (flagship prefill /
        step / block widths, plus the draft's prefill / step) and start
        the scheduler; after this, generation never traces
        (``retraces`` stays 0)."""
        tracer = self.metrics.tracer if self.metrics is not None else None
        if tracer is not None and tracer.enabled:
            with tracer.span("decode_warmup", model=self.name,
                             slots=self.engine.slots):
                self.engine.warmup()
                if self.draft is not None:
                    self.draft.warmup()
        else:
            self.engine.warmup()
            if self.draft is not None:
                self.draft.warmup()
        self.scheduler.start()

    def generate(self, prompt: np.ndarray,
                 max_new_tokens: Optional[int] = None) -> list:
        """Generated token ids for ``prompt``, decoded alongside
        whatever other sequences are in flight.  Thread-safe."""
        return self.scheduler.submit(prompt, max_new_tokens)

    @property
    def retraces(self) -> int:
        n = self.engine.retraces
        if self.draft is not None:
            n += self.draft.retraces
        return n

    def footprint(self) -> Dict[str, int]:
        fp = self.engine.footprint()
        if self.draft is not None and fp:
            dfp = self.draft.footprint()
            fp = dict(fp)
            fp["draft_bytes"] = dfp.get("total_bytes", 0)
            fp["total_bytes"] = fp.get("total_bytes", 0) \
                + fp["draft_bytes"]
        return fp

    def close(self) -> None:
        self.scheduler.close()


class ModelHost:
    """Concurrent multi-model routing over the shared device pool.

    The host also carries the serving READY lifecycle and owns the
    optional admin endpoint (serve/admin.py): ``ready`` is the
    hot-swap admission signal (ROADMAP item 4) — False until
    :meth:`mark_ready` verifies every hosted model warmed with its
    executables pinned and ``retraces() == 0``, and False again from
    the first line of :meth:`close`, BEFORE any batcher drains, so a
    load balancer polling ``/readyz`` stops routing ahead of the
    teardown."""

    def __init__(self):
        self._models: Dict[str, ServeModel] = {}
        # racelint: atomic(bool swap: mark_ready()/close() write on the driving thread; /healthz handlers only read)
        self._ready = False
        self.admin = None       # AdminServer once start_admin() ran

    # ----------------------------------------------------- ready lifecycle
    @property
    # racelint: thread(handler)
    def ready(self) -> bool:
        return self._ready

    def mark_ready(self) -> bool:
        """Flip ready if (and only if) the admission contract holds:
        at least one model, every engine warmed (executables pinned),
        zero retraces.  Returns the new state; call after warmup (and
        after calibration, which may retrace nothing but takes time a
        health check should see as not-yet-ready)."""
        warmed = bool(self._models) and all(
            getattr(m.engine, "_traces_at_warmup", None) is not None
            for m in self._models.values())
        self._ready = warmed and self.retraces() == 0
        if self._ready and self.admin is not None:
            self.admin.note_ready()     # cache footprints for /statusz
        elif warmed and not self._ready:
            mlog.warn(f"host not ready: {self.retraces()} retraces "
                      "after warmup (executables not pinned)")
        return self._ready

    def start_admin(self, metrics, *, port: int,
                    config=None) -> "object":
        """Start the admin endpoint (serve/admin.AdminServer) on
        ``port`` (0 binds ephemeral); the host owns it — ``close()``
        joins it LAST, so /healthz answers through the drain."""
        from .admin import AdminServer
        if self.admin is not None:
            raise RuntimeError("admin endpoint already started")
        self.admin = AdminServer(self, metrics, port=port,
                                 config=config)
        self.admin.start()
        return self.admin

    def add(self, name: str, trainer, cfg: Optional[ServeConfig] = None,
            *, metrics=None, warmup: bool = True) -> ServeModel:
        if name in self._models:
            raise ValueError(f"model {name!r} already hosted")
        sm = ServeModel(trainer, cfg, metrics=metrics, name=name)
        self._models[name] = sm
        if warmup:
            sm.warmup()
        return sm

    def attach(self, sm: ServeModel, *, warmup: bool = True) -> ServeModel:
        """Host an already-built ServeModel (load_serve_model's output)
        under its own name."""
        if sm.name in self._models:
            raise ValueError(f"model {sm.name!r} already hosted")
        self._models[sm.name] = sm
        if warmup:
            sm.warmup()
        return sm

    def model(self, name: str) -> ServeModel:
        try:
            return self._models[name]
        except KeyError:
            raise KeyError(
                f"no model {name!r} hosted; available: "
                f"{sorted(self._models)}") from None

    def predict(self, name: str, x: np.ndarray) -> np.ndarray:
        return self.model(name).predict(x)

    @property
    def names(self):
        return sorted(self._models)

    def retraces(self) -> int:
        return sum(m.retraces for m in self._models.values())

    def footprint(self) -> Dict[str, object]:
        """Per-model + combined resident bytes over the shared device
        pool — the number to pack against before adding one model too
        many (doc/memory.md; the pool's HBM capacity is
        analysis/costmodel.HBM_BYTES)."""
        per = {name: m.footprint() for name, m in self._models.items()}
        return {"models": per,
                "total_bytes": sum(fp.get("total_bytes", 0)
                                   for fp in per.values())}

    def close(self) -> None:
        self._ready = False     # /readyz flips before any drain begins
        for m in self._models.values():
            m.close()
        self._models.clear()
        if self.admin is not None:
            self.admin.close()
            self.admin = None


def load_serve_model(pairs: Sequence[Tuple[str, str]], *,
                     name: str = "default",
                     warmup: bool = True) -> ServeModel:
    """Build a ServeModel from ordered config pairs: ``model_in`` names
    the snapshot (net structure restored from it), ``batch_size``/
    ``dev``/``dtype``/engine keys configure the trainer, ``serve_*``
    keys the serving front.  The CLI task and the wrapper's
    ``ServingHost`` both load through here."""
    from ..nnet.trainer import NetTrainer
    last = dict(pairs)
    model_in = last.get("model_in", "NULL")
    if model_in == "NULL":
        raise ValueError("serve: model_in (a snapshot) is required")
    t = NetTrainer()
    for k, v in pairs:
        t.set_param(k, v)
    t.load_model(model_in)
    sm = ServeModel(t, ServeConfig.from_pairs(pairs), name=name)
    if warmup:
        sm.warmup()
    return sm


def load_draft_trainer(pairs: Sequence[Tuple[str, str]], path: str):
    """Load the speculative DRAFT net's trainer from its own snapshot
    (``serve_draft_model``), through the same path load_serve_model
    uses: session pairs configure the trainer (batch_size / dev /
    engine keys), the snapshot header restores the draft's OWN net
    structure — so the flagship's ``netconfig`` section never leaks
    into the draft."""
    from ..nnet.trainer import NetTrainer
    t = NetTrainer()
    for k, v in pairs:
        t.set_param(k, v)
    t.load_model(path)
    return t
