"""Incremental-decode engine: KV-cached autoregressive generation.

Generating N tokens through the batch predict path costs N full forward
passes over the whole prefix — O(N²) attention FLOPs and a fresh
dispatch per token (ROADMAP item 1).  This engine closes the gap with a
per-layer KV cache held in pinned, DONATED ``(decode_slots,
max_seqlen)`` device buffers and a fixed, AOT-warmed executable set,
the serve engine's bucket discipline taken to its limit:

* **prefill** — one prompt row at its natural padded length runs the
  normal causal forward; every attention layer captures its fresh
  (k, v) into the cache row for the request's slot.  Prefill logits are
  byte-identical to a plain eval forward (the attention math is the
  stock path — capture is a tee, not a rewrite).
* **step** — ONE position per active slot: each attention layer
  scatters the new (k, v) at ``positions`` and attends over the whole
  cache under the length mask ``arange(max_seqlen) <= position``.
  Masked scores get ``ring.NEG_INF`` exactly like the causal mask,
  softmax to exactly 0.0, and drop out of the p·V reduction — so the
  incremental logits are bitwise equal to the full forward at f32
  (asserted by tests/test_decode.py; bf16 holds the usual SERVE_TOL
  envelope), even though never-written cache slots hold stale garbage.
* **block(W)** — step generalized to ``W`` consecutive positions per
  slot, one compiled executable per declared width
  (``block_widths``): the speculative-verify dispatch (``W = spec_k +
  1``) and the chunked-prefill dispatch (``W = decode_prefill_chunk``)
  both ride it.  Query ``w`` masks at ``arange(max_seqlen) <=
  position + w`` — causal within the block — so every one of the ``W``
  logits rows is bitwise the sequential step's row at that position,
  which is the property that makes speculative greedy decode exactly
  reproduce plain greedy decode (doc/serve.md "Speculative decoding").

Every executable bumps ``decode_step_traces`` at trace time (the
``serve_step_traces`` retrace oracle, same contract):
:attr:`DecodeEngine.retraces` must read 0 after warmup no matter how
requests join and leave.  The cache buffers are donated back to XLA
every step, so steady-state decode allocates nothing.  ``kv_dtype =
"bf16"`` stores the cache in bfloat16 — halving the dominant
serve-memory term — while activations, score accumulation, and logits
stay f32 (cast on write, upcast on read; pairtested inside SERVE_TOL
by tests/test_decode.py).

Sampling (greedy / temperature / top-k) runs host-side off the LM-head
logits — :func:`sample_token` — keeping the executables sampling-free
(one compiled program serves every sampling config).

:meth:`DecodeEngine.footprint` extends ``PredictEngine.footprint()``
with ``kv_cache_bytes`` so the PR 12 memory pre-flight can reject an
oversubscribed ``(decode_slots, decode_max_seqlen)`` at task=check time
(analysis/conflint.py's decode rules do the same analytically).
"""

from __future__ import annotations

import time
import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np

#: ordered sampling kinds (serve_gen_sample enum; doc/serve.md)
SAMPLE_KINDS = ("greedy", "temperature", "topk")


def sample_token(logits: np.ndarray, kind: str = "greedy",
                 temp: float = 1.0, topk: int = 0,
                 rng: Optional[np.random.RandomState] = None) -> int:
    """One token id off a ``(vocab,)`` logits row.

    ``greedy`` is argmax (deterministic — the parity tests' mode);
    ``temperature`` softmax-samples ``logits / temp``; ``topk``
    restricts to the ``topk`` highest logits first.  ``rng`` is the
    caller's per-request RandomState so replays are deterministic.
    """
    if kind == "greedy":
        return int(np.argmax(logits))
    if kind not in SAMPLE_KINDS:
        raise ValueError(
            f"serve_gen_sample = {kind!r}: expected one of "
            f"{'/'.join(SAMPLE_KINDS)}")
    z = np.asarray(logits, np.float64) / max(float(temp), 1e-6)
    if kind == "topk":
        k = max(1, int(topk))
        if k < z.shape[0]:
            keep = np.argpartition(z, -k)[-k:]
            masked = np.full_like(z, -np.inf)
            masked[keep] = z[keep]
            z = masked
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    r = (rng.random_sample() if rng is not None
         else np.random.random_sample())
    return int(min(np.searchsorted(np.cumsum(p), r), z.shape[0] - 1))


def sample_probs(logits: np.ndarray, kind: str = "temperature",
                 temp: float = 1.0, topk: int = 0) -> np.ndarray:
    """The full ``(vocab,)`` f64 probability vector :func:`sample_token`
    draws from under ``kind``/``temp``/``topk`` — the distribution
    speculative rejection sampling needs explicitly (accept proposal
    ``d`` with ``min(1, p_target(d) / p_draft(d))``, resample rejects
    from ``normalize(max(p_target - p_draft, 0))``; doc/serve.md
    "Speculative decoding")."""
    if kind not in SAMPLE_KINDS or kind == "greedy":
        raise ValueError(
            f"sample_probs: kind {kind!r} has no sampling distribution "
            "(greedy is argmax)")
    z = np.asarray(logits, np.float64) / max(float(temp), 1e-6)
    if kind == "topk":
        k = max(1, int(topk))
        if k < z.shape[0]:
            keep = np.argpartition(z, -k)[-k:]
            masked = np.full_like(z, -np.inf)
            masked[keep] = z[keep]
            z = masked
    z = z - z.max()
    p = np.exp(z)
    return p / p.sum()


def draw_from(p: np.ndarray, rng) -> int:
    """Inverse-CDF draw from a probability vector — the same cumsum /
    searchsorted arithmetic :func:`sample_token` uses, so a draw from
    ``sample_probs(logits, ...)`` with the same rng state lands on the
    same token id."""
    r = (rng.random_sample() if rng is not None
         else np.random.random_sample())
    return int(min(np.searchsorted(np.cumsum(p), r), p.shape[0] - 1))


class DecodeEngine:
    """KV-cached incremental decode over a loaded LM :class:`NetTrainer`.

    Build once, :meth:`warmup` once (both executables compile, the
    trace counter snapshots), then :meth:`prefill` / :meth:`step` from
    the scheduler thread.  ``slots`` is the fixed decode batch —
    token-level continuous batching (serve/batcher.StepScheduler) keeps
    the slots full by admitting queued prompts the moment a sequence
    finishes."""

    def __init__(self, trainer, *, slots: int = 4, max_seqlen: int = 0,
                 metrics=None, kv_dtype: str = "",
                 block_widths: Tuple[int, ...] = ()):
        if trainer.net is None:
            raise ValueError("DecodeEngine needs an initialized/loaded "
                             "trainer")
        if trainer.mesh.size > 1:
            raise ValueError(
                "incremental decode runs single-device for now "
                f"(mesh has {trainer.mesh.size} devices); drop the "
                "mesh_shape for task=serve generation")
        self.trainer = trainer
        self.metrics = metrics if metrics is not None else trainer.metrics
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError(f"decode_slots = {slots}: must be >= 1")
        net = trainer.net
        # the LM contract: (b, 1, 1, S) token ids in, attention layers
        # causal, a softmax_seq self-loop as the loss head whose INPUT
        # node carries the raw logits (forward stops before it — the
        # rebind would overwrite them with probabilities)
        in_shape = net.node_shapes[0]
        if in_shape[1] != 1 or in_shape[2] != 1:
            raise ValueError(
                "incremental decode needs a token-id input "
                f"(b,1,1,seq); the netconfig input is {in_shape}")
        self.max_seqlen = int(max_seqlen) or int(in_shape[3])
        if self.max_seqlen != int(in_shape[3]):
            raise ValueError(
                f"decode_max_seqlen = {self.max_seqlen} but the "
                f"netconfig input width is {in_shape[3]}; the prefill "
                "executable runs the net at its declared width, so the "
                "two must match (resize input_shape instead)")
        from ..layers.loss import LossLayerBase
        from ..layers.sequence import AttentionLayer
        self._att: List[Tuple[int, object]] = []
        self._head_end: Optional[int] = None
        self._logits_node: Optional[int] = None
        for i, conn in enumerate(net.connections):
            if isinstance(conn.layer, AttentionLayer):
                if not conn.layer.causal:
                    raise ValueError(
                        f"incremental decode requires causal = 1 on "
                        f"every attention layer (connection {i} is "
                        "bidirectional)")
                self._att.append((i, conn.layer))
            elif isinstance(conn.layer, LossLayerBase) \
                    and self._head_end is None:
                self._head_end = i
                self._logits_node = conn.nindex_in[0]
        if not self._att:
            raise ValueError(
                "incremental decode needs at least one attention layer "
                "(not an LM netconfig?)")
        if self._head_end is None:
            raise ValueError(
                "incremental decode needs a softmax_seq (or other loss) "
                "self-loop marking the LM head")
        if len({id(l) for _, l in self._att}) != len(self._att):
            raise ValueError(
                "incremental decode does not support shared attention "
                "layers (each connection needs its own cache row)")
        # stamp each attention connection's cache key: the layer reads
        # it inside the traced forward to find its cache entry
        for i, layer in self._att:
            layer._decode_key = f"a{i}"
        nhead = self._att[0][1].nhead
        dim = net.node_shapes[net.connections[self._att[0][0]]
                              .nindex_in[0]][3]
        self.nhead, self.head_dim = nhead, dim // nhead
        self.vocab = int(net.node_shapes[self._logits_node][3])
        # KV-cache storage dtype (decode_kv_dtype): "" = the net's
        # compute dtype (the f32 reference), "bf16" halves the dominant
        # serve-memory term (cast on write, f32 accumulation on read)
        if kv_dtype not in ("", "f32", "bf16"):
            raise ValueError(
                f"decode_kv_dtype = {kv_dtype!r}: expected f32 or bf16")
        import jax.numpy as jnp
        self.kv_dtype = kv_dtype or (
            "bf16" if np.dtype(trainer.net.dtype) == np.dtype(jnp.bfloat16)
        else "f32")
        self._kv_jdtype = jnp.bfloat16 if self.kv_dtype == "bf16" \
            else jnp.float32
        self.block_widths = tuple(sorted({int(w) for w in block_widths
                                          if int(w) > 0}))
        for w in self.block_widths:
            if w > self.max_seqlen:
                raise ValueError(
                    f"block width {w} exceeds decode_max_seqlen = "
                    f"{self.max_seqlen}")
        self._caches = self._alloc_caches()
        self._prefill_fn = None
        self._step_fn = None
        self._block_fns: Dict[int, object] = {}
        self._traces_at_warmup: Optional[int] = None
        # per-ENGINE trace count: the "decode_step_traces" metrics
        # counter is shared by every engine on the metrics object (the
        # draft engine warms against the flagship's metrics), so
        # ``retraces`` must not charge one engine for another's warmup
        self._trace_count = 0
        # racelint: atomic(float swap, written once during warmup before handlers can scrape)
        self.warmup_sec = 0.0
        # executable-call accounting for /statusz (serve/admin.py):
        # dispatcher-thread writes, GIL-atomic reads, no lock
        self.prefill_calls = 0   # racelint: atomic(plain-int bump, decode-loop-only writer; scrape reads are GIL-atomic)
        self.step_calls = 0      # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.block_calls = 0     # racelint: atomic(plain-int bump, decode-loop-only writer)
        self.prompt_tokens = 0   # racelint: atomic(plain-int bump, decode-loop-only writer)

    # ------------------------------------------------------------- build
    def _alloc_caches(self):
        import jax.numpy as jnp
        shape = (self.slots, self.nhead, self.max_seqlen, self.head_dim)
        return {layer._decode_key: {
            "k": jnp.zeros(shape, self._kv_jdtype),
            "v": jnp.zeros(shape, self._kv_jdtype)}
            for _, layer in self._att}

    def kv_cache_bytes(self) -> int:
        """Analytic KV bytes: 2 (k+v) per attention layer, sized at the
        cache storage dtype (``kv_dtype``).  Mirrors analysis/conflint's
        decode HBM rule so the lint and the live engine agree on the
        number."""
        itemsize = 2 if self.kv_dtype == "bf16" else 4
        return (2 * len(self._att) * self.slots * self.nhead
                * self.max_seqlen * self.head_dim * itemsize)

    def _run_net(self, params, buffers, ids, decode):
        """Traced: the LM forward up to (not including) the loss head,
        returning raw (b, 1, s, V) logits."""
        from ..layers.base import ForwardContext
        ctx = ForwardContext(train=False, decode=decode)
        nodes, _ = self.trainer.net.forward(
            params, buffers, {0: ids}, ctx, until=self._head_end)
        return nodes[self._logits_node]

    def _build_prefill(self):
        import jax
        import jax.numpy as jnp
        from ..layers.base import DecodeState
        t = self.trainer
        S = self.max_seqlen

        def pfill(params, buffers, caches, ids, slot_ids, lengths):
            self._trace_count += 1
            self.metrics.counter_inc("decode_step_traces")
            dec = DecodeState(mode="prefill", caches={}, max_seqlen=S)
            logits = self._run_net(params, buffers, ids, dec)
            # last-prompt-position logits row per prefilled prompt
            pb = ids.shape[0]
            out = logits[jnp.arange(pb), 0,
                         jnp.clip(lengths - 1, 0, S - 1),
                         :].astype(jnp.float32)
            new_caches = {
                key: {"k": caches[key]["k"].at[slot_ids].set(
                          kv["k"].astype(caches[key]["k"].dtype)),
                      "v": caches[key]["v"].at[slot_ids].set(
                          kv["v"].astype(caches[key]["v"].dtype))}
                for key, kv in dec.caches.items()}
            return out, new_caches

        fn = jax.jit(pfill, donate_argnums=(2,))
        ids0 = np.zeros((1, 1, 1, S), np.float32)
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn.lower(t.params, t.buffers, self._caches, ids0,
                            np.zeros((1,), np.int32),
                            np.ones((1,), np.int32)).compile()

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from ..layers.base import DecodeState
        t = self.trainer
        S = self.max_seqlen

        def dstep(params, buffers, caches, tokens, positions):
            self._trace_count += 1
            self.metrics.counter_inc("decode_step_traces")
            positions = jnp.clip(positions.astype(jnp.int32), 0, S - 1)
            dec = DecodeState(mode="step",
                              caches={k: dict(v)
                                      for k, v in caches.items()},
                              positions=positions, max_seqlen=S)
            ids = tokens.astype(jnp.float32).reshape(self.slots, 1, 1, 1)
            logits = self._run_net(params, buffers, ids, dec)
            return logits[:, 0, 0, :].astype(jnp.float32), dec.caches

        fn = jax.jit(dstep, donate_argnums=(2,))
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn.lower(t.params, t.buffers, self._caches,
                            np.zeros((self.slots,), np.int32),
                            np.zeros((self.slots,), np.int32)).compile()

    def _build_block(self, width: int):
        """The multi-column step: ``width`` consecutive positions per
        slot in one dispatch (DecodeState mode="block") — the
        speculative-verify and chunked-prefill executable.  Returns
        ``(slots, width, vocab)`` f32 logits; row ``w`` of a slot is
        bitwise the single-token step's logits at ``positions[slot] +
        w`` (the layer-side mask contract)."""
        import jax
        import jax.numpy as jnp
        from ..layers.base import DecodeState
        t = self.trainer
        S = self.max_seqlen
        W = int(width)

        def dblock(params, buffers, caches, tokens, positions):
            self._trace_count += 1
            self.metrics.counter_inc("decode_step_traces")
            positions = jnp.clip(positions.astype(jnp.int32), 0, S - 1)
            dec = DecodeState(mode="block",
                              caches={k: dict(v)
                                      for k, v in caches.items()},
                              positions=positions, max_seqlen=S)
            ids = tokens.astype(jnp.float32).reshape(self.slots, 1, 1, W)
            logits = self._run_net(params, buffers, ids, dec)
            return logits[:, 0, :, :].astype(jnp.float32), dec.caches

        fn = jax.jit(dblock, donate_argnums=(2,))
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn.lower(t.params, t.buffers, self._caches,
                            np.zeros((self.slots, W), np.int32),
                            np.zeros((self.slots,), np.int32)).compile()

    def warmup(self) -> None:
        """Compile EVERY executable (prefill, step, one block per
        declared width) and snapshot the trace counter: from here on,
        decoding that traces anything is a bug (:attr:`retraces`,
        asserted through the task=serve CLI)."""
        t0 = time.perf_counter()
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
        if self._step_fn is None:
            self._step_fn = self._build_step()
        for w in self.block_widths:
            if w not in self._block_fns:
                self._block_fns[w] = self._build_block(w)
        self.warmup_sec = time.perf_counter() - t0
        self._traces_at_warmup = self._trace_count

    @property
    def retraces(self) -> int:
        """THIS engine's traces past warmup — 0 in a healthy steady
        state (the shared metrics counter would also charge a co-hosted
        engine's warmup here)."""
        if self._traces_at_warmup is None:
            return 0
        return self._trace_count - self._traces_at_warmup

    def footprint(self) -> Dict[str, int]:
        """Per-device resident bytes (doc/memory.md):
        PredictEngine.footprint()'s schema plus ``kv_cache_bytes`` —
        the decode-specific line the mem pre-flight budgets against.
        Empty before warmup or when the backend doesn't report."""
        if self._prefill_fn is None or self._step_fn is None:
            return {}
        from ..analysis.memmodel import tree_device_bytes
        weight = tree_device_bytes(self.trainer.params) \
            + tree_device_bytes(self.trainer.buffers)
        opt = tree_device_bytes(getattr(self.trainer, "opt_state", {})
                                or {})
        kv = int(tree_device_bytes(self._caches))
        temp = out = code = 0
        for fn in (self._prefill_fn, self._step_fn,
                   *self._block_fns.values()):
            try:
                ma = fn.memory_analysis()
            except Exception:  # noqa: BLE001 — optional backend API
                return {}
            temp += int(ma.temp_size_in_bytes)
            out += int(ma.output_size_in_bytes)
            code += int(ma.generated_code_size_in_bytes)
        fp = {"weight_bytes": weight, "opt_bytes": opt,
              "kv_cache_bytes": kv, "exec_temp_bytes": temp,
              "exec_out_bytes": out, "exec_code_bytes": code,
              "buckets": 2 + len(self._block_fns),
              "total_bytes": weight + opt + kv + temp + out + code}
        if self.kv_dtype == "bf16":
            # bytes the narrower cache saves vs the f32 reference —
            # the decode_kv_dtype headline /statusz surfaces
            fp["kv_saved_bytes"] = kv
        return fp

    # racelint: thread(handler)
    def stats(self) -> Dict[str, object]:
        """Executable-call accounting for /statusz: prefill/step/block
        call counts, prompt-token volume, and the fixed cache
        geometry.  Runs on admin handler threads (scrape-path rule:
        unlocked GIL-atomic reads, never a dispatcher lock)."""
        return {"prefill_calls": self.prefill_calls,
                "step_calls": self.step_calls,
                "block_calls": self.block_calls,
                "prompt_tokens": self.prompt_tokens,
                "slots": self.slots, "max_seqlen": self.max_seqlen,
                "kv_dtype": self.kv_dtype,
                "kv_cache_bytes": self.kv_cache_bytes(),
                "warmup_sec": round(self.warmup_sec, 3)}

    # ------------------------------------------------------------ decode
    def prefill(self, slot: int, tokens: np.ndarray) -> np.ndarray:
        """Fill ``slot``'s cache rows with ``tokens`` (a 1-D prompt, 1..
        max_seqlen ids) and return the f32 ``(vocab,)`` logits at the
        last prompt position — the row the first generated token
        samples from."""
        if self._traces_at_warmup is None:
            self.warmup()
        tokens = np.asarray(tokens).reshape(-1)
        L = tokens.shape[0]
        if not 0 < L <= self.max_seqlen:
            raise ValueError(
                f"prefill: prompt of {L} tokens, but the cache holds "
                f"1..{self.max_seqlen}")
        if not 0 <= slot < self.slots:
            raise ValueError(f"prefill: slot {slot} out of "
                             f"0..{self.slots - 1}")
        self.prefill_calls += 1
        self.prompt_tokens += L
        ids = np.zeros((1, 1, 1, self.max_seqlen), np.float32)
        ids[0, 0, 0, :L] = tokens.astype(np.float32)
        logits, self._caches = self._prefill_fn(
            self.trainer.params, self.trainer.buffers, self._caches,
            ids, np.asarray([slot], np.int32),
            np.asarray([L], np.int32))
        return np.asarray(logits)[0]

    def step(self, tokens: np.ndarray,
             positions: np.ndarray) -> np.ndarray:
        """One decode step for ALL slots: append ``tokens[i]`` at
        ``positions[i]`` in slot i's cache and return the f32
        ``(slots, vocab)`` next-token logits.  Inactive slots are
        harmless — pass position 0 and any token; their row computes
        over one garbage position and the scheduler discards it (a
        free slot's cache is fully overwritten by its next prefill)."""
        if self._traces_at_warmup is None:
            self.warmup()
        self.step_calls += 1
        logits, self._caches = self._step_fn(
            self.trainer.params, self.trainer.buffers, self._caches,
            np.ascontiguousarray(tokens, np.int32),
            np.ascontiguousarray(positions, np.int32))
        return np.asarray(logits)

    def block(self, tokens: np.ndarray,
              positions: np.ndarray) -> np.ndarray:
        """One multi-column dispatch for ALL slots: append
        ``tokens[i, w]`` at ``positions[i] + w`` in slot i's cache and
        return the f32 ``(slots, width, vocab)`` logits — row ``w`` is
        the next-token distribution after position ``positions[i] + w``,
        bitwise the sequential step's.  The width must be one of the
        warmed ``block_widths``; a cold width compiles on demand and
        shows up in :attr:`retraces` (the scheduler never does this).
        Slots not participating pass their own next-write position and
        any tokens: the scattered garbage sits past their length mask
        and is overwritten by the dispatch that first computes there."""
        if self._traces_at_warmup is None:
            self.warmup()
        tokens = np.ascontiguousarray(tokens, np.int32)
        W = int(tokens.shape[1])
        fn = self._block_fns.get(W)
        if fn is None:
            fn = self._block_fns[W] = self._build_block(W)
        self.block_calls += 1
        logits, self._caches = fn(
            self.trainer.params, self.trainer.buffers, self._caches,
            tokens, np.ascontiguousarray(positions, np.int32))
        return np.asarray(logits)

    # ------------------------------------------------------------ oracle
    def full_logits(self, tokens: np.ndarray) -> np.ndarray:
        """The O(N²) reference: a plain (cache-free) eval forward over
        the zero-padded prompt, raw logits for every position —
        ``(max_seqlen, vocab)`` f32.  The parity tests compare
        :meth:`prefill`/:meth:`step` logits against rows of this
        bitwise at f32 (causality keeps the pad positions invisible)."""
        import jax
        tokens = np.asarray(tokens).reshape(-1)
        if tokens.shape[0] > self.max_seqlen:
            raise ValueError("full_logits: prompt exceeds max_seqlen")
        ids = np.zeros((1, 1, 1, self.max_seqlen), np.float32)
        ids[0, 0, 0, :tokens.shape[0]] = tokens.astype(np.float32)
        logits = jax.jit(
            lambda p, b, d: self._run_net(p, b, d, None))(
                self.trainer.params, self.trainer.buffers, ids)
        return np.asarray(logits, np.float32)[0, 0]
