"""Donated-buffer, pinned-shape predict engine.

Serving cannot afford the train path's lazy-jit contract: a request
stream with ragged batch sizes would retrace per shape (the
``round_batch = 0`` churn class the retrace counters exist to catch),
and the first unlucky request would eat a full XLA compile.  The engine
therefore declares its shapes up front (``serve_shapes = 1,8,32``),
AOT-lowers ONE executable per bucket at :meth:`warmup`, and pads every
request up to the nearest bucket.  The compiled executables reject any
other shape outright, so steady-state serving provably never retraces —
the ``serve_step_traces`` counter (bumped at trace time, exactly like
``train_step_traces``) stays at its post-warmup value, asserted by
:attr:`retraces` and tests/test_serve.py.

The request buffer is DONATED to the executable
(``donate_argnums``): the engine stages one device buffer per dispatch
and hands its memory back to XLA for intermediates/outputs, so a
saturated server holds a bounded working set instead of accumulating
per-request input buffers.  (Backends that cannot alias it — e.g. CPU,
where the flattened output is smaller than the input — just drop the
hint; the compile-time warning is filtered.)

``serve_dtype`` selects the predict variant:

* ``f32`` — the reference: shares the trainer's parameter buffers.
* ``bf16`` — parameters cast to bfloat16 once at build; the input casts
  in-step, so the staged request buffer stays f32 for every variant.
  Halves weight HBM + bandwidth; tail-latency win on memory-bound nets.
* ``int8`` — per-output-channel symmetric int8 quantization of the
  ``wmat`` leaves of fullc/conv layers (scale = absmax/127 per channel
  on dim 0, the layout both layers share); the step dequantizes
  (``q * scale``) before the matmul/conv, so this is weight-only
  quantization — 4x less weight memory, f32 activations and f32
  numerics downstream of the dequant.

Each quantized variant is pairtested against the f32 reference within
the declared :data:`SERVE_TOL` envelope (:meth:`PredictEngine.pairtest`,
wired to ``serve_calib`` at task startup and to tests/test_serve.py).
"""

from __future__ import annotations

import time
import warnings
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..monitor import log as mlog

#: declared pairtest envelopes per predict variant:
#: max |variant - f32| / (max |f32| + eps) over one predict call.
#: bf16 carries ~8 mantissa bits (rel step 2^-8 ≈ 4e-3) that compound
#: over the depth of the net; per-channel int8 weights hold ~1/255
#: per-tensor error that the dequantized matmul accumulates similarly.
SERVE_TOL = {"f32": 0.0, "bf16": 2e-2, "int8": 6e-2}


def quantize_per_channel(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 quantization of a weight whose
    dim 0 is the output channel (fullc ``(nhidden, nin)``, conv
    ``(nchannel, cin/g, kh, kw)``).  Returns ``(q, scale)`` with
    ``q * scale ~= w``; a dead channel (all zeros) gets scale 0."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w).reshape(w.shape[0], -1), axis=1)
    scale = absmax / 127.0
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(w / safe.reshape((-1,) + (1,) * (w.ndim - 1))),
                -127, 127).astype(np.int8)
    return q, scale.reshape((-1,) + (1,) * (w.ndim - 1)).astype(np.float32)


class PredictEngine:
    """Pinned-shape predict over a loaded :class:`NetTrainer`.

    Build once, :meth:`warmup` once (all buckets compile, counters
    snapshot), then :meth:`predict` from any thread — though concurrent
    callers should go through :class:`~cxxnet_tpu.serve.batcher.
    MicroBatcher`, which also coalesces them into fuller buckets."""

    def __init__(self, trainer, *, shapes: Sequence[int] = (1, 8, 32),
                 dtype: str = "f32", metrics=None):
        if trainer.net is None:
            raise ValueError(
                "PredictEngine needs an initialized/loaded trainer")
        self.trainer = trainer
        self.shapes = tuple(sorted(set(int(s) for s in shapes)))
        if not self.shapes or any(s <= 0 for s in self.shapes):
            raise ValueError(
                f"serve_shapes must be positive, got {shapes}")
        if dtype not in SERVE_TOL:
            raise ValueError(f"serve_dtype = {dtype!r}: expected one of "
                             f"{'/'.join(SERVE_TOL)}")
        self.dtype = dtype
        self.metrics = metrics if metrics is not None else trainer.metrics
        ndata = trainer.mesh.shape.get("data", 1)
        bad = [s for s in self.shapes if s % ndata]
        if bad:
            raise ValueError(
                f"serve_shapes {bad} not divisible by the mesh data "
                f"axis ({ndata}); every bucket shards over it")
        self._params, self._scales = self._prepare_params()
        self._fns: Dict[int, object] = {}
        self._ref_fns: Dict[int, object] = {}
        self._traces_at_warmup: Optional[int] = None
        self.warmup_sec = 0.0
        # dispatch accounting for /statusz (serve/admin.py): which
        # bucket each dispatch landed in and how many pad rows it cost.
        # Dispatcher-thread writes, GIL-atomic reads — no lock, and the
        # admin scrape path copies racily (copy_racy)
        self.bucket_hist: Dict[int, int] = {}
        self.pad_rows = 0
        self.dispatches = 0

    # ------------------------------------------------------------- params
    def _quant_keys(self) -> set:
        from ..layers.conv import ConvolutionLayer
        from ..layers.fullc import FullConnectLayer
        return {c.param_key for c in self.trainer.net.connections
                if c.owns_params
                and type(c.layer) in (ConvolutionLayer, FullConnectLayer)}

    def _prepare_params(self):
        """The serve-side parameter tree (+ per-channel scales for int8).
        f32 aliases the trainer's buffers outright — no copy, so a
        multi-variant host pays for extra weight memory only where a
        variant actually transforms the weights."""
        import jax
        import jax.numpy as jnp
        t = self.trainer
        if self.dtype == "f32":
            return t.params, {}
        if self.dtype == "bf16":
            cast = jax.tree.map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, t.params)
            return jax.device_put(cast, t.param_shardings), {}
        qkeys = self._quant_keys()
        params, scales = {}, {}
        for pkey, group in t.params.items():
            if pkey in qkeys and isinstance(group.get("wmat"),
                                            jax.Array):
                q, s = quantize_per_channel(np.asarray(group["wmat"]))
                g = dict(group)
                g["wmat"] = jax.device_put(
                    jnp.asarray(q), t.param_shardings[pkey]["wmat"])
                params[pkey] = g
                scales[pkey] = {"wmat": jnp.asarray(s)}
            else:
                params[pkey] = group
        return params, scales

    def _dequant(self, params, scales):
        """Traced: rebuild compute-dtype weights from the stored serve
        variant (int8 ``q * scale``; other variants pass through)."""
        if not scales:
            return params
        out = dict(params)
        for pkey, sg in scales.items():
            g = dict(out[pkey])
            g["wmat"] = g["wmat"].astype(np.float32) * sg["wmat"]
            out[pkey] = g
        return out

    # -------------------------------------------------------------- build
    def _build_fn(self, bucket: int):
        """AOT-lower the pinned predict for one bucket: jit with the
        trainer's shardings, the request buffer donated, traced ONCE
        here (the trace-time ``serve_step_traces`` bump is the retrace
        oracle) and compiled to an executable that rejects any other
        shape."""
        import jax
        import jax.numpy as jnp
        t = self.trainer
        nid = t.net.final_node

        def sstep(params, scales, buffers, data):
            self.metrics.counter_inc("serve_step_traces")
            p = self._dequant(params, scales)
            if self.dtype == "bf16":
                data = data.astype(jnp.bfloat16)
            return t.forward_eval(p, buffers, data, (nid,))[nid]

        fn = jax.jit(
            sstep,
            in_shardings=(t.param_shardings, t.repl, t.buffer_shardings,
                          t.batch_shard),
            out_shardings=t.repl,
            donate_argnums=(3,))
        data = self._stage(np.zeros((bucket,) + self._in_shape, np.float32))
        with warnings.catch_warnings():
            # CPU cannot alias the (smaller) output onto the donated
            # request buffer; the dropped hint is expected, not news
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn.lower(self._params, self._scales, t.buffers,
                            data).compile()

    @property
    def _in_shape(self) -> Tuple[int, ...]:
        return tuple(self.trainer.net.node_shapes[0][1:])

    def _stage(self, arr: np.ndarray):
        """Host rows -> device-resident staged request buffer (sharded
        over the data axis, through the ``input_s2d`` staging transform
        when configured — the same staging predict_raw uses)."""
        import jax
        t = self.trainer
        return t._s2d_transform(
            jax.device_put(np.ascontiguousarray(arr, np.float32),
                           t.batch_shard))

    def warmup(self) -> None:
        """Compile every declared bucket and snapshot the trace counter:
        from here on, serving that traces ANYTHING is a bug the counter
        (and :attr:`retraces`) makes visible."""
        t0 = time.perf_counter()
        for b in self.shapes:
            if b not in self._fns:
                self._fns[b] = self._build_fn(b)
        self.warmup_sec = time.perf_counter() - t0
        self._traces_at_warmup = self.metrics.counters.get(
            "serve_step_traces", 0)

    @property
    def retraces(self) -> int:
        """Traces past warmup — 0 in a healthy steady state."""
        if self._traces_at_warmup is None:
            return 0
        return self.metrics.counters.get("serve_step_traces", 0) \
            - self._traces_at_warmup

    def footprint(self) -> Dict[str, int]:
        """Per-device resident bytes this model costs the host
        (doc/memory.md): everything serving keeps alive — the
        serve-variant weight tree counted ONCE (every bucket executable
        shares it), the trainer's buffers (batch-norm stats ride into
        every dispatch), and, for a cast/quantized variant, the
        trainer's ORIGINAL params too (the trainer stays alive, so both
        copies are resident; an f32 variant aliases them, one copy) —
        plus the live trainer's optimizer state (``opt_bytes``:
        momentum is 1x param bytes, adam 2x, f32 masters more — the
        trainer materializes it at load and serving keeps it resident)
        and each warmed bucket's temp/output/code allocations from
        ``memory_analysis()``.  The number the multi-model host packs
        against instead of packing blind.  Empty dict before warmup or
        when the backend doesn't report."""
        if not self._fns:
            return {}
        # the ONE shard-aware per-device accounting rule, shared with
        # the analytic memory model
        from ..analysis.memmodel import (leaf_device_bytes,
                                         tree_device_bytes)
        weight = tree_device_bytes(self._params) \
            + tree_device_bytes(self._scales) \
            + tree_device_bytes(self.trainer.buffers)
        if self.dtype == "bf16":
            # the whole cast tree is a copy; the trainer's f32 tree
            # stays resident alongside it
            weight += tree_device_bytes(self.trainer.params)
        elif self.dtype == "int8":
            # only the quantized wmat leaves are copies — the rest of
            # the serve tree aliases the trainer's groups
            for pkey in self._quant_keys():
                g = self.trainer.params.get(pkey, {})
                if "wmat" in g:
                    weight += leaf_device_bytes(g["wmat"])
        opt = tree_device_bytes(getattr(self.trainer, "opt_state", {})
                                or {})
        temp = out = code = 0
        for fn in self._fns.values():
            try:
                ma = fn.memory_analysis()
            except Exception:  # noqa: BLE001 — optional backend API
                return {}
            temp += int(ma.temp_size_in_bytes)
            out += int(ma.output_size_in_bytes)
            code += int(ma.generated_code_size_in_bytes)
        return {"weight_bytes": weight, "opt_bytes": opt,
                "exec_temp_bytes": temp,
                "exec_out_bytes": out, "exec_code_bytes": code,
                "buckets": len(self._fns),
                "total_bytes": weight + opt + temp + out + code}

    def stats(self) -> Dict[str, object]:
        """Dispatch-side accounting for /statusz: bucket occupancy and
        padding waste (pad_rows / (pad_rows + rows) is the fraction of
        device rows burned on padding — the signal for re-declaring
        ``serve_shapes``)."""
        hist = dict(self.bucket_hist)
        return {"dispatches": self.dispatches,
                "bucket_hist": {str(k): v
                                for k, v in sorted(hist.items())},
                "pad_rows": self.pad_rows,
                "warmup_sec": round(self.warmup_sec, 3)}

    # ------------------------------------------------------------ predict
    def bucket_for(self, n: int) -> int:
        """Smallest declared bucket holding ``n`` rows."""
        for b in self.shapes:
            if n <= b:
                return b
        return self.shapes[-1]

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Raw final-node rows for ``x`` (``(n,) + input_shape``); any
        ``n``: oversize requests split across max-bucket dispatches, the
        remainder pads up to its nearest bucket."""
        if self._traces_at_warmup is None:
            self.warmup()
        x = np.asarray(x, np.float32)
        if x.shape[1:] != self._in_shape:
            raise ValueError(
                f"predict: rows of shape {x.shape[1:]} but the model "
                f"takes {self._in_shape}")
        t = self.trainer
        n = x.shape[0]
        # span tracing (monitor/spans.py): pad/device/unpad decompose
        # the batcher's dispatch span; rider trace_ids arrive through
        # the tracer's thread-local link, so these rows need no
        # plumbing.  Gated on the link itself, not just the tracer:
        # a dispatch with no sampled rider must emit nothing, or
        # trace_sample=100 would still write 3 records per dispatch
        tracer = self.metrics.tracer
        tracing = tracer is not None and tracer.enabled \
            and tracer.linked() is not None
        outs, i = [], 0
        while i < n:
            take = min(n - i, self.shapes[-1])
            b = self.bucket_for(take)
            self.bucket_hist[b] = self.bucket_hist.get(b, 0) + 1
            self.pad_rows += b - take
            self.dispatches += 1
            t_pad0 = time.perf_counter() if tracing else 0.0
            chunk = x[i:i + take]
            if take < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - take,) + self._in_shape,
                                     np.float32)])
            staged = self._stage(chunk)
            if tracing:
                t_dev0 = time.perf_counter()
                tracer.emit("pad", t_pad0, t_dev0, bucket=b, rows=take)
            out = self._fns[b](self._params, self._scales, t.buffers,
                               staged)
            # np.asarray is the D2H sync: the device span closes only
            # once the result bytes are actually on the host
            host = np.asarray(out)
            if tracing:
                t_unpad0 = time.perf_counter()
                tracer.emit("device", t_dev0, t_unpad0, bucket=b,
                            rows=take)
            outs.append(host[:take])
            if tracing:
                tracer.emit("unpad", t_unpad0, time.perf_counter(),
                            bucket=b, rows=take)
            i += take
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    # ----------------------------------------------------------- pairtest
    def reference_predict(self, x: np.ndarray) -> np.ndarray:
        """f32 single-shot reference (original parameters, plain jit —
        calibration-only, so per-bucket tracing is fine and deliberately
        NOT counted as a serve trace).  Rows pad up to the declared
        buckets exactly like :meth:`predict` — the buckets are the
        shapes validated divisible by the mesh data axis, so a ragged
        calibration batch still stages cleanly on a sharded mesh."""
        import jax
        t = self.trainer
        nid = t.net.final_node
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        outs, i = [], 0
        while i < n:
            take = min(n - i, self.shapes[-1])
            b = self.bucket_for(take)
            chunk = x[i:i + take]
            if take < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - take,) + self._in_shape,
                                     np.float32)])
            if b not in self._ref_fns:
                self._ref_fns[b] = jax.jit(
                    lambda p, bu, d: t.forward_eval(p, bu, d, (nid,))[nid],
                    in_shardings=(t.param_shardings, t.buffer_shardings,
                                  t.batch_shard),
                    out_shardings=t.repl)
            outs.append(np.asarray(
                self._ref_fns[b](t.params, t.buffers,
                                 self._stage(chunk)))[:take])
            i += take
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def pairtest(self, x: np.ndarray) -> float:
        """Max relative error of this variant vs the f32 reference on
        ``x`` — the measured side of the :data:`SERVE_TOL` envelope
        (``serve_calib`` runs this on real request data at startup)."""
        got = self.predict(x)
        ref = self.reference_predict(np.asarray(x, np.float32))
        denom = float(np.max(np.abs(ref))) + 1e-6
        err = float(np.max(np.abs(got - ref))) / denom
        tol = SERVE_TOL[self.dtype]
        if tol and err > tol:
            mlog.warn(f"serve pairtest: {self.dtype} predict deviates "
                      f"{err:.3g} from f32 (envelope {tol:g})")
        return err
