"""Inference serving subsystem: ``task = serve`` (doc/serve.md).

The reference ships batch-mode ``task = pred``/``extract`` plus a ctypes
wrapper — offline inference.  The ROADMAP north star is serving heavy
traffic, and this package is the missing leg: a donated-buffer,
pinned-shape predict engine that never retraces in steady state
(:mod:`.engine`), a dynamic micro-batching front that coalesces
concurrent client requests (:mod:`.batcher`), and concurrent multi-model
hosting with shared devices (:mod:`.host`).

Layering (mirrors the train side):

* :class:`~cxxnet_tpu.serve.engine.PredictEngine` — one pre-lowered
  executable per declared shape bucket (``serve_shapes``), requests
  padded up to the nearest bucket; ``serve_dtype`` selects the f32 /
  bf16 / per-channel-int8 weight variants.
* :class:`~cxxnet_tpu.serve.batcher.MicroBatcher` — bounded request
  queue + dispatcher thread (the DevicePrefetcher producer-thread
  discipline run in reverse: many clients feed one device loop).
* :class:`~cxxnet_tpu.serve.host.ServeModel` /
  :class:`~cxxnet_tpu.serve.host.ModelHost` — engine+batcher bundles,
  routed by model name over the process's shared device pool.

Config keys are declared in :data:`SERVE_KEYS` and harvested into
``main.TASK_KEYS`` so graftlint sees them; :class:`ServeConfig` is the
parsed form every consumer (CLI task, wrapper, bench) shares.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..analysis.schema import K


def parse_shapes(val: str) -> List[int]:
    """Parse a ``serve_shapes`` spec ("1,8,32"); raises ValueError with
    the same message the lint check reports."""
    msg = shapes_check(val)
    if msg is not None:
        raise ValueError(f"serve_shapes = {val!r}: {msg}")
    return [int(p) for p in val.split(",") if p.strip()]


def shapes_check(val: str) -> Optional[str]:
    """Lint-time validator for ``serve_shapes`` (KeySpec.check): the
    buckets must be positive, strictly ascending ints."""
    try:
        parts = [int(p) for p in val.split(",") if p.strip()]
    except ValueError:
        return "expected comma-separated batch-size buckets, e.g. 1,8,32"
    if not parts:
        return "expected at least one batch-size bucket"
    if any(p <= 0 for p in parts):
        return "buckets must be positive"
    if sorted(set(parts)) != parts:
        return "buckets must be strictly ascending (sorted, no duplicates)"
    return None


#: config keys the serving subsystem consumes (ServeConfig.from_pairs);
#: merged into main.TASK_KEYS so the declared-key registry and
#: graftlint's cross-key rules see them (doc/check.md)
SERVE_KEYS = (
    K("serve_shapes", "str", check=shapes_check,
      help="pinned batch-size buckets, ascending (requests pad up to "
           "the nearest; one pre-lowered executable each)"),
    K("serve_max_batch", "int", lo=1,
      help="coalesce at most this many rows per dispatch "
           "(0/unset = the largest bucket)"),
    K("serve_max_wait_ms", "float", lo=0.0,
      help="max time the batcher holds a request open for coalescing"),
    K("serve_dtype", "enum", choices=("f32", "bf16", "int8"),
      help="predict variant: f32 reference, bf16 cast, or per-channel "
           "int8 weights for fullc/conv (doc/serve.md)"),
    K("serve_clients", "int", lo=1,
      help="task=serve: concurrent client threads replaying the pred "
           "iterator as single-row requests"),
    K("serve_calib", "int", lo=0,
      help="pairtest the quantized variant against f32 on this many "
           "request batches at startup (serve_dtype != f32)"),
    K("serve_queue_depth", "int", lo=1,
      help="bounded request-queue depth (backpressure past it)"),
    K("serve_sentinel", "int", lo=0, hi=1,
      help="serve-side EWMA regression sentinels (p99 rise / QPS drop "
           "/ queue-depth rise) over windowed serve_window records; "
           "needs metrics_sink (doc/serve.md)"),
    K("serve_sentinel_window", "float", lo=0.01,
      help="seconds per sentinel observation window (the reporter "
           "thread's cadence)"),
    # -- incremental decode / generation (serve/decode.py, doc/serve.md)
    K("serve_gen", "int", lo=0, hi=1,
      help="task=serve: autoregressive generation through the KV-cache "
           "decode engine instead of batch predict (LM netconfigs)"),
    K("decode_slots", "int", lo=1,
      help="in-flight decode batch: cache rows the step executable "
           "carries (token-level continuous batching keeps them full)"),
    K("decode_max_seqlen", "int", lo=1,
      help="KV-cache length per slot; must equal the netconfig input "
           "width (the prefill executable runs the net at its declared "
           "width).  Unset = the input width"),
    K("serve_gen_tokens", "int", lo=1,
      help="max new tokens generated per request"),
    K("serve_gen_sample", "enum",
      choices=("greedy", "temperature", "topk"),
      help="sampling off the LM head: greedy argmax (deterministic), "
           "temperature softmax, or top-k restricted"),
    K("serve_gen_temp", "float", lo=1e-6,
      help="softmax temperature for temperature/topk sampling"),
    K("serve_gen_topk", "int", lo=1,
      help="top-k cutoff for serve_gen_sample = topk"),
    K("serve_gen_seed", "int", lo=0,
      help="per-request deterministic sampling seed"),
    K("serve_gen_eos", "int", lo=-1,
      help="stop token id (-1 = never; generation runs to "
           "serve_gen_tokens or the cache end)"),
    K("serve_gen_prompt", "int", lo=1,
      help="task=serve: prompt length taken from each pred-iterator "
           "row's leading token ids"),
    K("serve_gen_batching", "enum", choices=("continuous", "request"),
      help="continuous = requests join/leave the decode batch between "
           "steps; request = fill a batch and run it to completion "
           "(the A/B baseline)"),
    # -- speculative decoding + chunked prefill (doc/serve.md)
    K("serve_draft_model", "path",
      help="snapshot of the small DRAFT net for speculative decoding "
           "(loaded through the load_serve_model path; same vocab and "
           "decode_max_seqlen as the flagship)"),
    K("spec_k", "int", lo=0,
      help="draft tokens proposed per speculative round; the flagship "
           "verifies all spec_k+1 positions in ONE block dispatch "
           "(0 = speculation off; requires serve_draft_model)"),
    K("decode_prefill_chunk", "int", lo=0,
      help="chunked prefill: stream the prompt into the KV cache this "
           "many columns per dispatch, interleaved between decode "
           "rounds (0 = whole-prompt prefill)"),
    K("decode_kv_dtype", "enum", choices=("f32", "bf16"),
      help="KV-cache storage dtype: bf16 halves the dominant serve "
           "memory term (cast on write, f32 accumulation on read; "
           "pairtested within SERVE_TOL)"),
    # -- live control plane (serve/admin.py, doc/serve.md "Operating a
    #    serve host")
    K("serve_admin_port", "int", lo=0, hi=65535,
      help="in-process admin HTTP endpoint (/metrics /healthz /readyz "
           "/statusz) on this port; 0 = off (the range check IS the "
           "lint: 1-65535 to enable)"),
    K("serve_slo_p99_ms", "float", lo=0.0,
      help="latency SLO threshold: requests slower than this spend "
           "error budget (monitor/slo.py); 0 = SLO off"),
    K("serve_slo_avail", "float", lo=0.0, hi=1.0,
      help="fraction of requests that must meet serve_slo_p99_ms "
           "(budget = 1 - avail); must be < 1.0 when the SLO is on"),
    K("serve_slo_fast_sec", "float", lo=0.01,
      help="fast burn window seconds (acute outage tier); must be an "
           "integer multiple of serve_sentinel_window"),
    K("serve_slo_slow_sec", "float", lo=0.01,
      help="slow burn window seconds (simmering regression tier); "
           "must be an integer multiple of serve_sentinel_window"),
    K("serve_slo_fast_burn", "float", lo=1e-6,
      help="fast-tier firing threshold (budget-spend velocity; 14.4 "
           "= a 30-day budget gone in 2 days)"),
    K("serve_slo_slow_burn", "float", lo=1e-6,
      help="slow-tier firing threshold"),
    K("serve_flight_requests", "int", lo=1,
      help="anomaly flight capture: boost trace_sample for this many "
           "requests before dumping the serve_flight record"),
    K("serve_flight_boost", "int", lo=1,
      help="trace_sample value while a flight capture is armed (1 = "
           "trace every request)"),
)


@dataclasses.dataclass
class ServeConfig:
    """Parsed serving configuration, shared by ``task = serve``
    (main.py), the wrapper's serving path, and ``bench.py --serve``."""

    shapes: Tuple[int, ...] = (1, 8, 32)
    max_batch: int = 0          # 0 = the largest bucket
    max_wait_ms: float = 2.0
    dtype: str = "f32"
    clients: int = 4
    calib: int = 0
    queue_depth: int = 64
    sentinel: int = 0
    sentinel_window: float = 1.0
    # incremental decode / generation (serve/decode.py)
    gen: int = 0
    slots: int = 4
    max_seqlen: int = 0         # 0 = the netconfig input width
    gen_tokens: int = 32
    gen_sample: str = "greedy"
    gen_temp: float = 1.0
    gen_topk: int = 0
    gen_seed: int = 0
    gen_eos: int = -1
    gen_prompt: int = 8
    gen_batching: str = "continuous"
    # speculative decoding + chunked prefill (serve/batcher.py)
    draft_model: str = ""       # draft-net snapshot; "" = no speculation
    spec_k: int = 0             # proposals per round; 0 = speculation off
    prefill_chunk: int = 0      # 0 = whole-prompt prefill
    kv_dtype: str = ""          # "" = f32 (or bf16 when the net is bf16)
    # live control plane (serve/admin.py) + SLO (monitor/slo.py)
    admin_port: int = 0         # 0 = no admin endpoint
    slo_p99_ms: float = 0.0     # 0 = no SLO
    slo_avail: float = 0.999
    slo_fast_sec: float = 60.0
    slo_slow_sec: float = 600.0
    slo_fast_burn: float = 14.4
    slo_slow_burn: float = 6.0
    flight_requests: int = 16
    flight_boost: int = 1

    def __post_init__(self):
        if self.sentinel_window <= 0:
            raise ValueError(
                f"serve_sentinel_window = {self.sentinel_window}: must "
                "be > 0 (seconds per observation window)")
        self.shapes = tuple(self.shapes)
        if not (self.shapes and all(s > 0 for s in self.shapes)
                and list(self.shapes) == sorted(set(self.shapes))):
            raise ValueError(
                f"serve_shapes must be positive ascending, got "
                f"{self.shapes}")
        if self.dtype not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"serve_dtype = {self.dtype!r}: expected f32, bf16, or "
                "int8")
        if self.max_batch <= 0:
            self.max_batch = max(self.shapes)
        if self.gen_sample not in ("greedy", "temperature", "topk"):
            raise ValueError(
                f"serve_gen_sample = {self.gen_sample!r}: expected "
                "greedy, temperature, or topk")
        if self.gen_batching not in ("continuous", "request"):
            raise ValueError(
                f"serve_gen_batching = {self.gen_batching!r}: expected "
                "continuous or request")
        if self.gen_sample == "topk" and self.gen_topk < 1:
            raise ValueError(
                "serve_gen_sample = topk requires serve_gen_topk >= 1")
        if self.spec_k < 0:
            raise ValueError(f"spec_k = {self.spec_k}: must be >= 0")
        if self.prefill_chunk < 0:
            raise ValueError(
                f"decode_prefill_chunk = {self.prefill_chunk}: must "
                "be >= 0 (0 = whole-prompt prefill)")
        if self.kv_dtype not in ("", "f32", "bf16"):
            raise ValueError(
                f"decode_kv_dtype = {self.kv_dtype!r}: expected f32 "
                "or bf16")
        if not 0 <= self.admin_port <= 65535:
            raise ValueError(
                f"serve_admin_port = {self.admin_port}: expected "
                "0 (off) or a port in 1..65535")
        if self.slo_p99_ms > 0.0 and not 0.0 < self.slo_avail < 1.0:
            raise ValueError(
                f"serve_slo_avail = {self.slo_avail}: must be in "
                "(0, 1) when serve_slo_p99_ms is set (1.0 leaves a "
                "zero error budget)")
        if self.slo_fast_sec <= 0 or self.slo_slow_sec <= 0:
            raise ValueError("serve_slo_*_sec windows must be > 0")

    @classmethod
    def from_pairs(cls, pairs: Sequence[Tuple[str, str]]) -> "ServeConfig":
        """Build from ordered config pairs (last occurrence wins, like
        every ``set_param`` consumer)."""
        last = {k: v for k, v in pairs
                if k.startswith("serve_") or k.startswith("decode_")
                or k == "spec_k"}
        kw = {}
        if "serve_shapes" in last:
            kw["shapes"] = tuple(parse_shapes(last["serve_shapes"]))
        for key, field, conv in (("serve_max_batch", "max_batch", int),
                                 ("serve_max_wait_ms", "max_wait_ms", float),
                                 ("serve_dtype", "dtype", str),
                                 ("serve_clients", "clients", int),
                                 ("serve_calib", "calib", int),
                                 ("serve_queue_depth", "queue_depth", int),
                                 ("serve_sentinel", "sentinel", int),
                                 ("serve_sentinel_window",
                                  "sentinel_window", float),
                                 ("serve_gen", "gen", int),
                                 ("decode_slots", "slots", int),
                                 ("decode_max_seqlen", "max_seqlen", int),
                                 ("serve_gen_tokens", "gen_tokens", int),
                                 ("serve_gen_sample", "gen_sample", str),
                                 ("serve_gen_temp", "gen_temp", float),
                                 ("serve_gen_topk", "gen_topk", int),
                                 ("serve_gen_seed", "gen_seed", int),
                                 ("serve_gen_eos", "gen_eos", int),
                                 ("serve_gen_prompt", "gen_prompt", int),
                                 ("serve_gen_batching",
                                  "gen_batching", str),
                                 ("serve_draft_model", "draft_model",
                                  str),
                                 ("spec_k", "spec_k", int),
                                 ("decode_prefill_chunk",
                                  "prefill_chunk", int),
                                 ("decode_kv_dtype", "kv_dtype", str),
                                 ("serve_admin_port", "admin_port", int),
                                 ("serve_slo_p99_ms", "slo_p99_ms",
                                  float),
                                 ("serve_slo_avail", "slo_avail", float),
                                 ("serve_slo_fast_sec", "slo_fast_sec",
                                  float),
                                 ("serve_slo_slow_sec", "slo_slow_sec",
                                  float),
                                 ("serve_slo_fast_burn", "slo_fast_burn",
                                  float),
                                 ("serve_slo_slow_burn", "slo_slow_burn",
                                  float),
                                 ("serve_flight_requests",
                                  "flight_requests", int),
                                 ("serve_flight_boost", "flight_boost",
                                  int)):
            if key in last:
                kw[field] = conv(last[key])
        return cls(**kw)
