"""Optimizers (updaters): sgd / nag / adam with LR + momentum schedules.

Reference: ``src/updater/sgd_updater-inl.hpp``, ``nag_updater-inl.hpp``,
``adam_updater-inl.hpp``, ``param.h`` (UpdaterParam schedules + tag-scoped
overrides like ``wmat:lr``).

TPU-native shape: each updater is a pure per-tensor transition function that
runs *inside* the jitted train step — the reference's per-weight async
push/pull machinery (``async_updater-inl.hpp``) collapses into the step
function, with cross-device gradient aggregation supplied by the mesh
(psum via sharded-batch jax.grad) rather than a parameter server.

Schedules are evaluated in-graph from the update-step counter (the reference
passes its ``epoch_counter`` — the number of *updates*, not rounds — into
``ScheduleEpoch``; nnet_impl-inl.hpp:181-184), so changing lr never triggers
recompilation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..analysis.schema import K

Params = Any

#: keys UpdaterHyper.set_param consumes (analysis/registry.py harvests
#: these; the lint pass additionally accepts the reference's tag-scoped
#: spellings ``wmat:<key>`` / ``bias:<key>``).  Keep in sync with the
#: set_param branches below.
HYPER_KEYS = (
    K("lr", "float", lo=0.0), K("eta", "float", lo=0.0),
    K("wd", "float"), K("momentum", "float"),
    K("clip_gradient", "float", lo=0.0),
    K("momentum_schedule", "int", lo=0, hi=1),
    K("base_momentum", "float"), K("final_momentum", "float"),
    K("saturation_epoch", "int", lo=0),
    K("beta1", "float"), K("beta2", "float"),
    K("lr:schedule", "enum",
      choices=("constant", "expdecay", "polydecay", "factor")),
    K("lr:gamma", "float"), K("lr:alpha", "float"),
    K("lr:step", "int", lo=1), K("lr:factor", "float"),
    K("lr:minimum_lr", "float"), K("lr:start_epoch", "int", lo=0),
    K("eta:schedule", "enum",
      choices=("constant", "expdecay", "polydecay", "factor")),
    K("eta:gamma", "float"), K("eta:alpha", "float"),
    K("eta:step", "int", lo=1), K("eta:factor", "float"),
    K("eta:minimum_lr", "float"), K("eta:start_epoch", "int", lo=0),
)


@dataclasses.dataclass
class UpdaterHyper:
    """Static hyperparameter group for one weight tag (UpdaterParam parity).

    One instance exists per (layer, tag); tag-scoped config keys
    (``wmat:lr``, ``bias:wd``) override the globals for that tag only
    (reference updater/param.h:100-105).
    """

    tag: str = "wmat"
    base_lr: float = 0.01
    wd: float = 0.0
    momentum: float = 0.9
    clip_gradient: float = 0.0
    # lr schedule: 0 constant, 1 expdecay, 2 polydecay, 3 factor
    lr_schedule: int = 0
    lr_step: int = 1
    lr_gamma: float = 0.5
    lr_alpha: float = 0.5
    lr_factor: float = 0.1
    lr_minimum: float = 1e-5
    start_epoch: int = 0
    # momentum schedule
    momentum_schedule: int = 0
    base_momentum: float = 0.5
    final_momentum: float = 0.9
    saturation_epoch: int = 0
    # adam decay rates (note: reference stores beta as "decay" = value passed)
    beta1: float = 0.1
    beta2: float = 0.001

    def set_param(self, name: str, val: str) -> None:
        # tag-prefix stripping: "wmat:lr" applies only when tag == "wmat"
        if name.startswith(self.tag + ":"):
            name = name[len(self.tag) + 1:]
        elif ":" in name and name.split(":", 1)[0] in ("wmat", "bias"):
            return  # scoped to a different tag
        if name in ("lr", "eta"):
            self.base_lr = float(val)
        elif name == "wd":
            self.wd = float(val)
        elif name == "momentum":
            self.momentum = float(val)
        elif name == "clip_gradient":
            self.clip_gradient = float(val)
        elif name == "momentum_schedule":
            self.momentum_schedule = int(val)
        elif name == "base_momentum":
            self.base_momentum = float(val)
        elif name == "final_momentum":
            self.final_momentum = float(val)
        elif name == "saturation_epoch":
            self.saturation_epoch = int(val)
        elif name == "beta1":
            self.beta1 = float(val)
        elif name == "beta2":
            self.beta2 = float(val)
        elif name.startswith("lr:") or name.startswith("eta:"):
            sub = name.split(":", 1)[1]
            if sub == "schedule":
                m = {"constant": 0, "expdecay": 1, "polydecay": 2, "factor": 3}
                if val not in m:
                    raise ValueError(f"unknown lr schedule {val!r}")
                self.lr_schedule = m[val]
            elif sub == "gamma":
                self.lr_gamma = float(val)
            elif sub == "alpha":
                self.lr_alpha = float(val)
            elif sub == "step":
                self.lr_step = int(val)
            elif sub == "factor":
                self.lr_factor = float(val)
            elif sub == "minimum_lr":
                self.lr_minimum = float(val)
            elif sub == "start_epoch":
                self.start_epoch = int(val)

    def schedule(self, epoch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """In-graph LR/momentum schedule (UpdaterParam::ScheduleEpoch)."""
        e = jnp.asarray(epoch, jnp.float32)
        if self.lr_schedule == 0:
            lr = jnp.float32(self.base_lr)
        elif self.lr_schedule == 1:
            lr = self.base_lr * jnp.power(self.lr_gamma, e / self.lr_step)
        elif self.lr_schedule == 2:
            lr = self.base_lr * jnp.power(
                1.0 + jnp.floor(e / self.lr_step) * self.lr_gamma, -self.lr_alpha)
        elif self.lr_schedule == 3:
            lr = self.base_lr * jnp.power(self.lr_factor,
                                          jnp.floor(e / self.lr_step))
        else:
            raise ValueError("unknown lr schedule type")
        lr = jnp.maximum(lr, self.lr_minimum)
        lr = jnp.where(e < self.start_epoch, self.base_lr, lr)
        mom = jnp.float32(self.momentum)
        if self.momentum_schedule and self.saturation_epoch:
            mom = mom + ((self.final_momentum - self.base_momentum)
                         / self.saturation_epoch * e + self.base_momentum)
        mom = jnp.minimum(mom, self.final_momentum) \
            if self.momentum_schedule else mom
        return lr, mom

    def clip(self, g: jnp.ndarray) -> jnp.ndarray:
        """NaN-zeroing clip (sgd_updater-inl.hpp:15-22)."""
        if self.clip_gradient == 0.0:
            return g
        g = jnp.where(jnp.isnan(g), 0.0, g)
        return jnp.clip(g, -self.clip_gradient, self.clip_gradient)


class Updater:
    """Pure per-tensor optimizer: state pytree in, state pytree out.

    Update arithmetic always runs in float32, optimizer state is float32
    regardless of model dtype, and non-float32 parameters carry a float32
    MASTER copy (``w32``) in the optimizer state: the update applies to the
    master and the working parameter is its cast.  Without the master,
    ``dtype = bfloat16`` training stalls once updates shrink below bf16's
    8-bit mantissa (|delta| < ~2^-9 |w| rounds to nothing in ``w += m`` —
    measured as an AlexNet loss plateau at ~6.78 on a memorization task
    that the mastered version drives to ~0).  The working params stay
    bf16, so every matmul still hits the MXU fast path."""

    name = ""

    def init_state(self, p: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        return {}

    def make_state(self, p: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        """Full optimizer state for one tensor: the subclass's state plus
        the float32 master copy for reduced-precision params."""
        s = self.init_state(p)
        if p.dtype != jnp.float32:
            s["w32"] = p.astype(jnp.float32)
        return s

    def _state32(self, p: jnp.ndarray) -> jnp.ndarray:
        return jnp.zeros(p.shape, jnp.float32)

    def apply(self, p: jnp.ndarray, g: jnp.ndarray,
              state: Dict[str, jnp.ndarray], hyper: UpdaterHyper,
              epoch) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        has_master = "w32" in state
        p32 = state["w32"] if has_master else p.astype(jnp.float32)
        sub = {k: v for k, v in state.items() if k != "w32"}
        q, new_state = self._apply32(
            p32, g.astype(jnp.float32), sub, hyper, epoch)
        new_state = dict(new_state)
        if has_master:
            new_state["w32"] = q
        return q.astype(p.dtype), new_state

    def _apply32(self, p: jnp.ndarray, g: jnp.ndarray,
                 state: Dict[str, jnp.ndarray], hyper: UpdaterHyper,
                 epoch) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        raise NotImplementedError


class SGDUpdater(Updater):
    """Momentum SGD: m = mom*m - lr*(clip(g) + wd*w); w += m
    (sgd_updater-inl.hpp:73-84)."""

    name = "sgd"

    def init_state(self, p):
        return {"m": self._state32(p)}

    def _apply32(self, p, g, state, hyper, epoch):
        lr, mom = hyper.schedule(epoch)
        g = hyper.clip(g)
        m = mom * state["m"] - lr * (g + hyper.wd * p)
        return p + m, {"m": m}


class NAGUpdater(Updater):
    """Nesterov momentum via old-momentum correction
    (nag_updater-inl.hpp:65-72): w += (1+mom)*m_new - mom*m_old."""

    name = "nag"

    def init_state(self, p):
        return {"m": self._state32(p)}

    def _apply32(self, p, g, state, hyper, epoch):
        lr, mom = hyper.schedule(epoch)
        g = hyper.clip(g)
        m_old = state["m"]
        m = mom * m_old - lr * (g + hyper.wd * p)
        return p + (1 + mom) * m - mom * m_old, {"m": m}


class AdamUpdater(Updater):
    """Adam with the reference's decay parameterization
    (adam_updater-inl.hpp:73-82): beta1/beta2 config values are the *decay*
    rates (defaults 0.1 / 0.001), ``grad -= wd*w`` (note the sign), and
    lr_t = lr * sqrt(1-(1-d2)^t) / (1-(1-d1)^t)."""

    name = "adam"

    def init_state(self, p):
        return {"m1": self._state32(p), "m2": self._state32(p)}

    @staticmethod
    def _lr_t(hyper, epoch):
        """Bias-corrected step size (adam_updater-inl.hpp:79-81)."""
        t = jnp.asarray(epoch, jnp.float32) + 1.0
        fix1 = 1.0 - jnp.power(1.0 - hyper.beta1, t)
        fix2 = 1.0 - jnp.power(1.0 - hyper.beta2, t)
        return hyper.base_lr * jnp.sqrt(fix2) / fix1

    def apply(self, p, g, state, hyper, epoch):
        from ..engine import opts
        if opts.fused_update == "1" and "w32" in state:
            from ..ops import pallas_kernels as pk
            if pk.fused_adam_supported(p):
                # one-sweep Pallas update: the bf16->f32 grad convert and
                # the master->bf16 param cast happen in-register instead
                # of as separate HBM round trips (the transformer
                # flagship's ~47.5 ms/step convert_reduce line — see
                # fused_adam_pallas)
                p_new, m1, m2, w32 = pk.fused_adam_pallas(
                    g, state["m1"], state["m2"], state["w32"],
                    self._lr_t(hyper, epoch),
                    d1=hyper.beta1, d2=hyper.beta2, wd=hyper.wd,
                    clip=hyper.clip_gradient, out_dtype=p.dtype)
                return p_new, {"m1": m1, "m2": m2, "w32": w32}
        return super().apply(p, g, state, hyper, epoch)

    def _apply32(self, p, g, state, hyper, epoch):
        d1, d2 = hyper.beta1, hyper.beta2
        g = hyper.clip(g)
        if hyper.wd > 0.0:
            g = g - hyper.wd * p
        lr_t = self._lr_t(hyper, epoch)
        m1 = state["m1"] + d1 * (g - state["m1"])
        m2 = state["m2"] + d2 * (jnp.square(g) - state["m2"])
        p = p - lr_t * (m1 / (jnp.sqrt(m2) + 1e-8))
        return p, {"m1": m1, "m2": m2}


_UPDATERS = {u.name: u for u in (SGDUpdater(), NAGUpdater(), AdamUpdater())}


def create_updater(name: str) -> Updater:
    """Factory (reference CreateUpdater, updater_impl-inl.hpp)."""
    if name not in _UPDATERS:
        raise ValueError(f"unknown updater {name!r}; known: {sorted(_UPDATERS)}")
    return _UPDATERS[name]
