from .updaters import (UpdaterHyper, create_updater, SGDUpdater, NAGUpdater,
                       AdamUpdater)

__all__ = ["UpdaterHyper", "create_updater", "SGDUpdater", "NAGUpdater",
           "AdamUpdater"]
