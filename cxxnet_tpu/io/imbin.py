"""Binary image-pack format + paged prefetching iterator + im2bin packer.

Reference: ``src/utils/io.h:254-326`` (BinaryPage: fixed 64MB pages with an
offset table), ``src/io/iter_thread_imbin-inl.hpp`` (background page
prefetch thread + jpeg decode), ``tools/im2bin.cpp`` (packer).

Our page format (fresh, documented; not byte-compatible with the reference):

    file   := header page*
    header := magic "CXTPUBIN" (8 bytes) | uint32 version | uint64 page_size
    page   := uint32 nrec | nrec * record | zero padding to page_size
    record := uint32 length | length bytes (raw jpeg)

Records never span pages (a record larger than a page is an error at pack
time).  Labels and instance indices come from the companion ``.lst`` file
("index label filename" lines, reference tools/im2bin.cpp), read in lockstep
like the reference's label loading (iter_thread_imbin-inl.hpp).

Multi-part shards: ``path_imgbin`` / ``path_imglst`` may contain ``%d`` with
``imgbin_count = N`` (reference's ``image_conf_prefix`` sharding), and
distributed workers take every k-th shard via ``dist_num_worker`` /
``dist_worker_rank`` (or the PS_RANK env var) —
iter_thread_imbin-inl.hpp:189-220.
"""

from __future__ import annotations

import os
import queue
import struct
import threading
from typing import List, Optional

import numpy as np

from ..analysis.schema import K
from ..monitor import log as mlog
from .data import DataInst, IIterator

MAGIC = b"CXTPUBIN"
VERSION = 1
DEFAULT_PAGE_SIZE = 64 << 20  # 64MB, reference page size


class BinaryPageWriter:
    def __init__(self, path: str, page_size: int = DEFAULT_PAGE_SIZE):
        # incremental page stream (push() per image, O(page) memory);
        # data-prep reruns on a torn shard, so no atomic_write staging
        self.f = open(path, "wb")  # disclint: ok(atomic-write)
        self.page_size = page_size
        self.f.write(MAGIC + struct.pack("<IQ", VERSION, page_size))
        self._recs: List[bytes] = []
        self._used = 4  # nrec field

    def push(self, payload: bytes) -> None:
        need = 4 + len(payload)
        assert need + 4 <= self.page_size, \
            f"record of {len(payload)} bytes exceeds page size {self.page_size}"
        if self._used + need > self.page_size:
            self._flush_page()
        self._recs.append(payload)
        self._used += need

    def _flush_page(self):
        buf = bytearray()
        buf += struct.pack("<I", len(self._recs))
        for r in self._recs:
            buf += struct.pack("<I", len(r)) + r
        assert len(buf) <= self.page_size
        buf += b"\x00" * (self.page_size - len(buf))
        self.f.write(bytes(buf))
        self._recs = []
        self._used = 4

    def close(self):
        if self._recs:
            self._flush_page()
        self.f.close()


def read_pages(path: str):
    """Yield lists of raw records, one list per page."""
    with open(path, "rb") as f:
        head = f.read(8 + 4 + 8)
        assert head[:8] == MAGIC, f"{path}: not a CXTPUBIN file"
        version, page_size = struct.unpack("<IQ", head[8:])
        assert version == VERSION
        while True:
            page = f.read(page_size)
            if not page:
                return
            assert len(page) == page_size, f"{path}: truncated page"
            (nrec,) = struct.unpack_from("<I", page, 0)
            off = 4
            recs = []
            for _ in range(nrec):
                (ln,) = struct.unpack_from("<I", page, off)
                off += 4
                recs.append(page[off:off + ln])
                off += ln
            yield recs


def pack_imbin(list_path: str, image_root: str, out_path: str,
               page_size: int = DEFAULT_PAGE_SIZE) -> int:
    """im2bin: pack jpegs named by a .lst file into a page file
    (reference tools/im2bin.cpp:6-67). Returns the number packed."""
    w = BinaryPageWriter(out_path, page_size)
    n = 0
    with open(list_path) as f:
        for line in f:
            toks = line.split()
            if len(toks) < 3:
                continue
            fname = toks[-1]
            with open(os.path.join(image_root, fname), "rb") as img:
                w.push(img.read())
            n += 1
    w.close()
    return n


def _decode_jpeg(buf: bytes) -> np.ndarray:
    """Decode to (c, y, x) float32 RGB (reference decodes with OpenCV)."""
    import cv2
    arr = cv2.imdecode(np.frombuffer(buf, np.uint8), cv2.IMREAD_COLOR)
    assert arr is not None, "jpeg decode failed"
    arr = cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)
    return arr.transpose(2, 0, 1).astype(np.float32)


class ImageBinIterator(IIterator):
    """Paged binary reader with background page prefetch
    (iter_thread_imbin-inl.hpp:16-283)."""
    config_keys = (
        K("image_bin", "path"), K("path_imgbin", "path"),
        K("image_list", "path"), K("path_imglst", "path"),
        K("imgbin_count", "int", lo=0),
        K("shuffle", "int", lo=0, hi=1),
        K("silent", "int", lo=0, hi=1),
        K("dist_num_worker", "int", lo=1),
        K("dist_worker_rank", "int", lo=0),
        K("label_width", "int", lo=1), K("seed_data", "int"),
        K("decode_thread_num", "int", lo=0),
    )

    def __init__(self):
        self.path_imgbin = ""
        self.path_imglst = ""
        self.imgbin_count = 0  # >0: paths contain %d
        self.shuffle = 0
        self.silent = 0
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.label_width = 1
        self.seed_data = 0
        self.decode_thread_num = 0
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        # racelint: atomic(int swap: bumped by the consumer in before_first; the producer re-reads it to detach stale generations)
        self._gen = 0

    def set_param(self, name, val):
        if name == "image_bin" or name == "path_imgbin":
            self.path_imgbin = val
        elif name == "image_list" or name == "path_imglst":
            self.path_imglst = val
        elif name == "imgbin_count":
            self.imgbin_count = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "seed_data":
            self.seed_data = int(val)
        elif name == "decode_thread_num":
            self.decode_thread_num = int(val)

    def init(self):
        rank = int(os.environ.get("PS_RANK", self.dist_worker_rank))
        if self.imgbin_count > 0:
            shard_ids = [i for i in range(self.imgbin_count)
                         if i % self.dist_num_worker == rank]
            self.bins = [self.path_imgbin % i for i in shard_ids]
            self.lsts = [self.path_imglst % i for i in shard_ids]
        else:
            assert self.dist_num_worker == 1, \
                "distributed sharding needs imgbin_count > 1 shards"
            self.bins = [self.path_imgbin]
            self.lsts = [self.path_imglst]
        self.labels: List[np.ndarray] = []
        self.indices: List[int] = []
        for lst in self.lsts:
            with open(lst) as f:
                for lineno, line in enumerate(f, 1):
                    toks = line.split()
                    if not toks:
                        continue  # blank line
                    if len(toks) < 3:
                        # silently skipping would desynchronize the
                        # label/record lockstep pairing for the whole shard
                        raise ValueError(
                            f"{lst} line {lineno}: expected 'index label... "
                            f"filename' (got {len(toks)} tokens)")
                    self.indices.append(int(toks[0]))
                    self.labels.append(
                        np.array([float(t) for t in
                                  toks[1:1 + self.label_width]], np.float32))
        if not self.silent:
            mlog.info(f"ImageBinIterator: {len(self.labels)} images in "
                      f"{len(self.bins)} shard(s)")

    def _page_offsets(self):
        """Global instance offset of each shard's first record (labels were
        read in shard order, so shard b's records pair with labels starting
        at offset[b])."""
        offs, pos = [], 0
        for lst in self.lsts:
            offs.append(pos)
            with open(lst) as f:
                pos += sum(1 for line in f if len(line.split()) >= 3)
        return offs

    def _producer(self, gen: int, q: "queue.Queue"):
        """Pages stream with their records' global label indices so shuffling
        permutes image and label *together* (the reference keeps labels in
        lockstep with the record stream, iter_thread_imbin_x-inl.hpp:208-233).
        Bounded puts re-check the generation so a stale producer exits
        instead of blocking on an orphaned queue."""
        shard_offsets = self._page_offsets()
        order = list(range(len(self.bins)))
        rng = None
        if self.shuffle:
            rng = np.random.RandomState(787 + self.seed_data + gen)
            rng.shuffle(order)
        for b in order:
            pos = shard_offsets[b]
            for recs in read_pages(self.bins[b]):
                idxs = list(range(pos, pos + len(recs)))
                pos += len(recs)
                if self.shuffle:
                    perm = rng.permutation(len(recs))
                    recs = [recs[j] for j in perm]
                    idxs = [idxs[j] for j in perm]
                item = list(zip(idxs, recs))
                while True:
                    if self._gen != gen:
                        return
                    try:
                        q.put(item, timeout=0.05)
                        break
                    except queue.Full:
                        continue
        while self._gen == gen:
            try:
                q.put(None, timeout=0.05)
                return
            except queue.Full:
                continue

    def before_first(self):
        self._gen = getattr(self, "_gen", 0) + 1
        if self._thread is not None:
            self._thread.join()
        self._queue = queue.Queue(maxsize=2)
        self._thread = threading.Thread(
            target=self._producer, args=(self._gen, self._queue),
            daemon=True, name="cxxnet-imbin-producer")
        self._thread.start()
        self._page = []
        self._page_pos = 0
        self._done = False

    def state(self):
        # the per-epoch shuffle is seeded ``787 + seed_data + gen``, so
        # the epoch counter IS the cross-round resume state (positions
        # rewind at each before_first; captured at a round boundary the
        # producer has exited after its None)
        return {"gen": int(self._gen)}

    def set_state(self, st):
        # retire any producer primed before resume state arrived, then
        # continue the killed run's epoch count so the next epoch's
        # shuffle order matches the unkilled run's
        self._gen += 1
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._queue = None
        self._gen = max(int(st.get("gen", 0)), self._gen)

    def close(self):
        self._gen += 1
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def next(self):
        if self._done:
            return None
        while self._page_pos >= len(self._page):
            item = self._queue.get()
            if item is None:
                self._done = True
                return None
            self._page = item
            self._page_pos = 0
            self._submit_pos = 0
        if self.decode_thread_num > 0:
            # two-stage pipeline (reference imgbinx,
            # iter_thread_imbin_x-inl.hpp:304-330): jpegs decode on a pool
            # (cv2 releases the GIL) while the consumer drains earlier
            # instances.  The submit window is bounded so decoded float32
            # arrays never accumulate page-wide ahead of the consumer.
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(
                    max_workers=self.decode_thread_num,
                    thread_name_prefix="cxxnet-imbin-decode")
            window = 2 * self.decode_thread_num
            while (self._submit_pos < len(self._page)
                   and self._submit_pos - self._page_pos < window):
                i = self._submit_pos
                li, buf = self._page[i]
                self._page[i] = (li, self._pool.submit(_decode_jpeg, buf))
                self._submit_pos += 1
        li, payload = self._page[self._page_pos]
        # drop the consumed entry so its decoded array is freed promptly
        self._page[self._page_pos] = None
        self._page_pos += 1
        data = payload.result() if self.decode_thread_num > 0 \
            else _decode_jpeg(payload)
        return DataInst(label=self.labels[li], data=data,
                        index=self.indices[li])


class ImageIterator(IIterator):
    """jpg-per-file list iterator (iter_img-inl.hpp:16-137)."""
    config_keys = (
        K("image_list", "path"), K("path_imglst", "path"),
        K("image_root", "path"), K("path_root", "path"),
        K("shuffle", "int", lo=0, hi=1),
        K("silent", "int", lo=0, hi=1),
        K("label_width", "int", lo=1), K("seed_data", "int"),
    )

    def __init__(self):
        self.path_imglst = ""
        self.path_root = ""
        self.shuffle = 0
        self.silent = 0
        self.label_width = 1
        self.seed_data = 0

    def set_param(self, name, val):
        if name == "image_list" or name == "path_imglst":
            self.path_imglst = val
        elif name == "image_root" or name == "path_root":
            self.path_root = val
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "seed_data":
            self.seed_data = int(val)

    def init(self):
        self.items = []
        with open(self.path_imglst) as f:
            for line in f:
                toks = line.split()
                if len(toks) < 3:
                    continue
                idx = int(toks[0])
                label = np.array(
                    [float(t) for t in toks[1:1 + self.label_width]],
                    np.float32)
                self.items.append((idx, label, toks[-1]))
        self.order = np.arange(len(self.items))
        self._epochs = 0
        if not self.silent:
            mlog.info(f"ImageIterator: {len(self.items)} images")

    def before_first(self):
        if self.shuffle:
            rng = np.random.RandomState(787 + self.seed_data)
            rng.shuffle(self.order)
            self._epochs += 1
        self._pos = 0

    def state(self):
        return {"epochs": int(getattr(self, "_epochs", 0))}

    def set_state(self, st):
        # the epoch-k order is the SAME fixed-seed permutation applied k
        # times to arange: replay it instead of storing the permutation
        # (a fresh RandomState(787 + seed_data) shuffles each epoch)
        k = int(st.get("epochs", 0))
        self.order = np.arange(len(self.items))
        for _ in range(k):
            np.random.RandomState(787 + self.seed_data).shuffle(self.order)
        self._epochs = k

    def next(self):
        if self._pos >= len(self.items):
            return None
        idx, label, fname = self.items[self.order[self._pos]]
        self._pos += 1
        with open(os.path.join(self.path_root, fname), "rb") as f:
            data = _decode_jpeg(f.read())
        return DataInst(label=label, data=data, index=idx)
