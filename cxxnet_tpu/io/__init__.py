from .data import DataBatch, DataInst, IIterator
from .factory import create_iterator, init_iterator

__all__ = ["DataBatch", "DataInst", "IIterator", "create_iterator",
           "init_iterator"]
