from .data import DataBatch, DataInst, IIterator
from .device_prefetch import (DevicePrefetcher, StagedBatch, StagedEvalGroup,
                              StagedGroup, StagedMeta, item_h2d_sec)
from .factory import create_iterator, init_iterator

__all__ = ["DataBatch", "DataInst", "IIterator", "create_iterator",
           "init_iterator", "DevicePrefetcher", "StagedBatch",
           "StagedGroup", "StagedEvalGroup", "StagedMeta", "item_h2d_sec"]
