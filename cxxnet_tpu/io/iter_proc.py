"""Batch adaptation, augmentation, prefetch, and buffering stages.

Reference: ``src/io/iter_batch_proc-inl.hpp`` (BatchAdaptIterator +
ThreadBufferIterator), ``iter_augment_proc-inl.hpp`` (crop/mirror/mean-sub
pipeline), ``iter_mem_buffer-inl.hpp`` (DenseBufferIterator),
``iter_attach_txt-inl.hpp`` (side-feature join).  The double-buffered
producer thread mirrors utils/thread_buffer.h with a bounded queue.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import sys
import threading
from typing import List, Optional

import numpy as np

from ..analysis.schema import K
from ..monitor import log as mlog
from .data import DataBatch, DataInst, IIterator
from .device_prefetch import ProducerError, generation_put

_AUG_RAND_MAGIC = 111


class BatchAdaptIterator(IIterator):
    """Packs DataInst into DataBatch (iter_batch_proc-inl.hpp:16-133).

    ``round_batch = 1`` wraps the epoch boundary with real instances from
    the epoch start and records ``num_batch_padd``; otherwise the tail
    partial batch is replica-padded and loss-masked (``tail_mask_padd``)
    so every real instance still trains (the reference's AdjustBatchSize
    semantics without shape polymorphism).  ``test_skipread = 1`` returns
    the same batch without reading (I/O isolation benchmark mode, :72-74).
    """

    config_keys = (
        K("batch_size", "int", lo=1),
        K("round_batch", "int", lo=0, hi=1),
        K("test_skipread", "int", lo=0, hi=1),
        K("label_width", "int", lo=1),
    )

    def __init__(self, base: IIterator):
        self.base = base
        self.batch_size = 0
        self.round_batch = 0
        self.test_skipread = 0
        self.label_width = 1
        self._head = True
        self._cached: Optional[DataBatch] = None
        self._wrap_insts: List[DataInst] = []

    def set_param(self, name, val):
        if name == "batch_size":
            self.batch_size = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "test_skipread":
            self.test_skipread = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        self.base.set_param(name, val)

    def init(self):
        assert self.batch_size > 0, "batch_size must be set"
        self.base.init()

    def before_first(self):
        self._epoch_done = False
        if self.test_skipread and self._cached is not None:
            return
        self.base.before_first()

    def state(self):
        return {"epoch_done": bool(getattr(self, "_epoch_done", False)),
                "base": self.base.state()}

    def set_state(self, st):
        self._epoch_done = bool(st.get("epoch_done", False))
        if "base" in st:
            self.base.set_state(st["base"])

    def _collect(self, n: int) -> List[DataInst]:
        out = []
        while len(out) < n:
            inst = self.base.next()
            if inst is None:
                break
            out.append(inst)
        return out

    def _pack(self, insts: List[DataInst], padd: int,
              mask_padd: int = 0) -> DataBatch:
        data = np.stack([i.data for i in insts]).astype(np.float32)
        label = np.stack([np.atleast_1d(i.label)[:self.label_width]
                          for i in insts]).astype(np.float32)
        index = np.array([i.index for i in insts], np.uint32)
        return DataBatch(data=data, label=label, index=index,
                         num_batch_padd=padd, tail_mask_padd=mask_padd)

    def next(self):
        if self.test_skipread and self._cached is not None:
            return self._cached
        if getattr(self, "_epoch_done", False):
            return None
        insts = self._collect(self.batch_size)
        if len(insts) == self.batch_size:
            b = self._pack(insts, 0)
        elif not insts:
            return None
        elif self.round_batch:
            # wrap around to the beginning of the epoch; the wrapped batch is
            # the epoch's last (the rewound base must not keep feeding)
            need = self.batch_size - len(insts)
            self.base.before_first()
            wrap = self._collect(need)
            assert len(wrap) == need, "round_batch: dataset smaller than batch"
            b = self._pack(insts + wrap, need)
            self._epoch_done = True
        else:
            # short tail: pad with replicas of the last instance and mask
            # them out of training/eval, so every real instance still
            # trains (the reference's AdjustBatchSize trains the tail by
            # re-plumbing shapes, neural_net-inl.hpp:266-277; a TPU step
            # is shape-static, so pad + loss-mask instead)
            need = self.batch_size - len(insts)
            b = self._pack(insts + [insts[-1]] * need, need, mask_padd=need)
        if self.test_skipread:
            self._cached = b
        return b


class AffineAugmenter:
    """Geometric augmentation via one warpAffine per instance (reference
    ``image_augmenter-inl.hpp:13-204``): random rotation (range or explicit
    ``rotate_list``), shear, aspect-ratio jitter, and a random square crop
    of side in [min_crop_size, max_crop_size] resized back to the target
    shape.  Skipped entirely when no geometric param is set (NeedProcess,
    :156-161)."""

    def __init__(self):
        self.rotate = -1.0           # fixed angle; -1 = off
        self.max_rotate_angle = 0.0
        self.max_shear_ratio = 0.0
        self.max_aspect_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.rotate_list: List[float] = []
        self.fill_value = 0.0

    def set_param(self, name, val) -> bool:
        if name == "rotate":
            self.rotate = float(val)
        elif name == "max_rotate_angle":
            self.max_rotate_angle = float(val)
        elif name == "max_shear_ratio":
            self.max_shear_ratio = float(val)
        elif name == "max_aspect_ratio":
            self.max_aspect_ratio = float(val)
        elif name == "min_crop_size":
            self.min_crop_size = int(val)
        elif name == "max_crop_size":
            self.max_crop_size = int(val)
        elif name == "rotate_list":
            self.rotate_list = [float(t) for t in val.split(",") if t.strip()]
        elif name == "fill_value":
            self.fill_value = float(val)
        else:
            return False
        return True

    @property
    def need_process(self) -> bool:
        return (self.rotate >= 0 or self.max_rotate_angle > 0
                or self.max_shear_ratio > 0 or self.max_aspect_ratio > 0
                or bool(self.rotate_list)
                or (self.min_crop_size > 0 and self.max_crop_size > 0))

    def process(self, d: np.ndarray, rnd: np.random.RandomState,
                target_yx) -> np.ndarray:
        """d is (c, y, x) float32; returns (c, ty, tx) when cropping, else
        the warped image at its original size."""
        import cv2
        img = d.transpose(1, 2, 0)  # HWC for cv
        h, w = img.shape[:2]
        if self.rotate >= 0:
            angle = self.rotate
        elif self.rotate_list:
            angle = self.rotate_list[rnd.randint(len(self.rotate_list))]
        else:
            a = self.max_rotate_angle
            angle = rnd.uniform(-a, a) if a > 0 else 0.0
        shear = rnd.uniform(-self.max_shear_ratio, self.max_shear_ratio) \
            if self.max_shear_ratio > 0 else 0.0
        if self.max_aspect_ratio > 0:
            ratio = 1.0 + rnd.uniform(0, self.max_aspect_ratio)
            if rnd.rand() < 0.5:
                ratio = 1.0 / ratio
            sx, sy = np.sqrt(ratio), 1.0 / np.sqrt(ratio)
        else:
            sx = sy = 1.0
        if angle != 0.0 or shear != 0.0 or sx != 1.0:
            rad = np.deg2rad(angle)
            cos, sin = np.cos(rad), np.sin(rad)
            # rotation @ shear @ aspect-scale, centered on the image
            lin = np.array([[cos, -sin], [sin, cos]], np.float64) \
                @ np.array([[1.0, shear], [0.0, 1.0]], np.float64) \
                @ np.diag([sx, sy])
            c = np.array([(w - 1) / 2.0, (h - 1) / 2.0])
            m = np.hstack([lin, (c - lin @ c).reshape(2, 1)])
            img = cv2.warpAffine(
                img, m, (w, h), flags=cv2.INTER_LINEAR,
                borderMode=cv2.BORDER_CONSTANT,
                borderValue=[self.fill_value] * img.shape[2])
        if self.min_crop_size > 0 and self.max_crop_size > 0:
            assert self.min_crop_size <= min(self.max_crop_size, h, w), \
                (f"augment: min_crop_size={self.min_crop_size} exceeds "
                 f"max_crop_size={self.max_crop_size} or image size {h}x{w}")
            cs = rnd.randint(self.min_crop_size,
                             min(self.max_crop_size, h, w) + 1)
            y0 = rnd.randint(0, max(h - cs, 0) + 1)
            x0 = rnd.randint(0, max(w - cs, 0) + 1)
            patch = img[y0:y0 + cs, x0:x0 + cs]
            ty, tx = target_yx
            img = cv2.resize(patch, (tx, ty), interpolation=cv2.INTER_LINEAR)
        if img.ndim == 2:
            img = img[:, :, None]
        return np.ascontiguousarray(img.transpose(2, 0, 1), np.float32)


class AugmentIterator(IIterator):
    """Per-instance augmentation (iter_augment_proc-inl.hpp:21-246):
    cv-affine stage (rotation/shear/aspect/crop-size, see AffineAugmenter),
    random/fixed crop, mirror, mean subtraction (mean image file generated on
    first use, :171-198, or mean_value RGB), scale."""

    config_keys = (
        K("rotate", "float"), K("max_rotate_angle", "float", lo=0),
        K("max_shear_ratio", "float", lo=0),
        K("max_aspect_ratio", "float", lo=0),
        K("min_crop_size", "int", lo=0),
        K("max_crop_size", "int", lo=0),
        K("rotate_list", "str", help="comma-separated angles"),
        K("fill_value", "float"),
        K("rand_crop", "int", lo=0, hi=1),
        K("rand_mirror", "int", lo=0, hi=1),
        K("mirror", "int", lo=0, hi=1),
        K("input_shape", "str", help="c,y,x"),
        K("image_mean", "path"), K("mean_value", "str"),
        K("scale", "float"),
        K("max_random_contrast", "float", lo=0),
        K("max_random_illumination", "float", lo=0),
        K("crop_y_start", "int", lo=0), K("crop_x_start", "int", lo=0),
    )

    def __init__(self, base: IIterator):
        self.base = base
        self.rand_crop = 0
        self.rand_mirror = 0
        self.mirror = 0
        self.input_shape = None  # (c, y, x)
        self.mean_file = ""
        self.mean_value: Optional[np.ndarray] = None
        self.scale = 1.0
        self.max_random_contrast = 0.0
        self.max_random_illumination = 0.0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.affine = AffineAugmenter()
        self.rnd = np.random.RandomState(_AUG_RAND_MAGIC)
        self._mean: Optional[np.ndarray] = None
        self._warned_mean_fallback = False

    def set_param(self, name, val):
        if self.affine.set_param(name, val):
            pass
        elif name == "rand_crop":
            self.rand_crop = int(val)
        elif name == "rand_mirror":
            self.rand_mirror = int(val)
        elif name == "mirror":
            self.mirror = int(val)
        elif name == "input_shape":
            self.input_shape = tuple(int(t) for t in val.split(","))
        elif name == "image_mean":
            self.mean_file = val
        elif name == "mean_value":
            self.mean_value = np.array(
                [float(t) for t in val.split(",")], np.float32)
        elif name == "scale":
            self.scale = float(val)
        elif name == "max_random_contrast":
            self.max_random_contrast = float(val)
        elif name == "max_random_illumination":
            self.max_random_illumination = float(val)
        elif name == "crop_y_start":
            self.crop_y_start = int(val)
        elif name == "crop_x_start":
            self.crop_x_start = int(val)
        self.base.set_param(name, val)

    def init(self):
        self.base.init()
        if self.mean_file:
            if os.path.exists(self.mean_file):
                self._mean = np.load(self.mean_file)["mean"]
            else:
                self._create_mean_img()

    def _create_mean_img(self):
        """Average all instances into a mean image (CreateMeanImg parity)."""
        self.base.before_first()
        acc = None
        n = 0
        while True:
            inst = self.base.next()
            if inst is None:
                break
            if acc is None:
                acc = inst.data.astype(np.float64)
            else:
                acc += inst.data
            n += 1
        assert n > 0, "augment: empty dataset, cannot build mean image"
        self._mean = (acc / n).astype(np.float32)
        np.savez(self.mean_file, mean=self._mean)
        mlog.info(f"AugmentIterator: saved mean image to {self.mean_file}")

    def before_first(self):
        self.base.before_first()

    def state(self):
        # the augment rng advances ACROSS epochs — the one piece of
        # cross-round iterator state an exact resume must restore (a
        # positional rewind alone would replay round 1's crops/mirrors)
        name, keys, pos, has_gauss, cached = self.rnd.get_state()
        return {"rnd": [name, np.asarray(keys).tolist(), int(pos),
                        int(has_gauss), float(cached)],
                "base": self.base.state()}

    def set_state(self, st):
        if "rnd" in st:
            name, keys, pos, has_gauss, cached = st["rnd"]
            self.rnd.set_state((name, np.asarray(keys, np.uint32),
                                int(pos), int(has_gauss), float(cached)))
        if "base" in st:
            self.base.set_state(st["base"])

    def next(self):
        inst = self.base.next()
        if inst is None:
            return None
        d = inst.data.astype(np.float32)
        if self.affine.need_process:
            target = self.input_shape[1:] if self.input_shape is not None \
                else d.shape[1:]
            d = self.affine.process(d, self.rnd, target)
        if self._mean is not None:
            m = self._mean
            if m.shape != d.shape:
                my, mx = m.shape[1], m.shape[2]
                dy, dx = d.shape[1], d.shape[2]
                if my >= dy and mx >= dx:
                    y0, x0 = (my - dy) // 2, (mx - dx) // 2
                    m = m[:, y0:y0 + dy, x0:x0 + dx]
                else:  # affine resized past the mean image: channel means
                    if not self._warned_mean_fallback:
                        self._warned_mean_fallback = True
                        mlog.warn(
                            f"AugmentIterator: mean image {m.shape} "
                            f"smaller than instance {d.shape}; falling "
                            "back to per-channel scalar means")
                    m = m.mean(axis=(1, 2), keepdims=True)
            d = d - m
        elif self.mean_value is not None:
            d = d - self.mean_value.reshape(-1, 1, 1)
        if self.max_random_contrast > 0:
            c = 1.0 + (self.rnd.rand() * 2 - 1) * self.max_random_contrast
            d = d * c
        if self.max_random_illumination > 0:
            d = d + (self.rnd.rand() * 2 - 1) * self.max_random_illumination
        if self.input_shape is not None and self.input_shape[1:] != d.shape[1:]:
            cy, cx = self.input_shape[1], self.input_shape[2]
            assert d.shape[1] >= cy and d.shape[2] >= cx, \
                f"augment: crop {cy}x{cx} larger than input {d.shape}"
            if self.rand_crop:
                y0 = self.rnd.randint(0, d.shape[1] - cy + 1)
                x0 = self.rnd.randint(0, d.shape[2] - cx + 1)
            else:
                y0 = self.crop_y_start if self.crop_y_start >= 0 \
                    else (d.shape[1] - cy) // 2
                x0 = self.crop_x_start if self.crop_x_start >= 0 \
                    else (d.shape[2] - cx) // 2
            d = d[:, y0:y0 + cy, x0:x0 + cx]
        if self.mirror or (self.rand_mirror and self.rnd.rand() < 0.5):
            d = d[:, :, ::-1].copy()
        if self.scale != 1.0:
            d = d * self.scale
        return DataInst(label=inst.label, data=d, index=inst.index)


class ThreadBufferIterator(IIterator):
    """Batch-level prefetch on a producer thread
    (iter_batch_proc-inl.hpp:136-224 over utils/thread_buffer.h).

    Each epoch gets its own queue + producer thread; a generation counter
    poisons stale producers, and before_first() joins the previous producer
    before rewinding the (shared) base iterator, so exactly one thread ever
    touches the base.  A producer exception is enqueued and re-raised in
    the consumer's next() — the epoch is dead until the next
    before_first(), never a hang.
    """

    config_keys = (K("buffer_size", "int", lo=1),)

    def __init__(self, base: IIterator, max_buffer: int = 4):
        self.base = base
        self.max_buffer = max_buffer
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._gen = 0
        self._failed: Optional[BaseException] = None

    def set_param(self, name, val):
        if name == "buffer_size":
            self.max_buffer = max(1, int(val))
        self.base.set_param(name, val)

    def init(self):
        self.base.init()
        # prime the first producer so next() works straight after init(),
        # like every other iterator (the reference's ThreadBuffer also starts
        # its thread at Init, thread_buffer.h:30-38)
        self.before_first()

    def _producer(self, gen: int, q: "queue.Queue"):
        while True:
            try:
                b = self.base.next()
            except BaseException as e:  # noqa: BLE001 — reach the consumer
                b = ProducerError(e)
            if not generation_put(self, gen, q, b):
                return
            if b is None or isinstance(b, ProducerError):
                return

    def before_first(self):
        self._gen += 1
        self._failed = None
        if self._thread is not None:
            self._thread.join()  # unblocks via the generation check
        self.base.before_first()
        q = queue.Queue(maxsize=self.max_buffer)
        self._queue = q
        self._thread = threading.Thread(
            target=self._producer, args=(self._gen, q),
            daemon=True, name="cxxnet-io-buffer-producer")
        self._thread.start()

    def next(self):
        assert self._queue is not None, "call before_first() first"
        if self._failed is not None:
            raise self._failed  # epoch is dead; rewind with before_first()
        v = self._queue.get()
        if isinstance(v, ProducerError):
            self._failed = v.exc
            raise v.exc
        return v

    def set_state(self, st):
        # quiesce the producer BEFORE touching the shared base (init()
        # primes a producer that is already reading it); the next
        # before_first() rewinds and restarts as usual, with the base's
        # cross-epoch state (augment rng, cache fill) restored
        self._gen += 1
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._queue = None
        if "base" in st:
            self.base.set_state(st["base"])

    def close(self):
        self._gen += 1
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self.base.close()


class DenseBufferIterator(IIterator):
    """Caches the first max_nbatch batches in RAM and loops over them
    (iter_mem_buffer-inl.hpp:16-76)."""

    config_keys = (K("max_nbatch", "int", lo=1),)

    def __init__(self, base: IIterator):
        self.base = base
        self.max_nbatch = 0
        self._cache: List[DataBatch] = []
        self._filled = False
        self._pos = 0
        self._prefill_base = None

    def set_param(self, name, val):
        if name == "max_nbatch":
            self.max_nbatch = int(val)
        self.base.set_param(name, val)

    def init(self):
        assert self.max_nbatch > 0, "membuffer: set max_nbatch"
        self.base.init()

    def before_first(self):
        self._pos = 0
        if not self._filled:
            # a producer stage above (threadbuffer) primes its thread at
            # init() and pulls a partial fill through us before the first
            # real epoch; rewinding the base under that partial cache
            # would pair each remaining item with the wrong rng draw —
            # drop it and restart the fill cleanly
            self._cache = []
            # the base's state at the instant the fill starts: a resumed
            # run rewinds to it before rebuilding the cache, so the
            # rebuild replays the ORIGINAL fill's rng draws
            self._prefill_base = self.base.state()
            self.base.before_first()

    def state(self):
        st = {"filled": bool(self._filled), "pos": int(self._pos),
              "base": self.base.state()}
        if self._prefill_base is not None:
            st["prefill_base"] = self._prefill_base
        return st

    def set_state(self, st):
        if st.get("prefill_base") is not None:
            self._prefill_base = st["prefill_base"]
        if st.get("filled") and not self._filled:
            # rebuild the cache deterministically (the original fill read
            # the base's first max_nbatch batches; after the fill the
            # base is never read again).  A producer stage above may
            # already have pulled through us before resume state arrived
            # (ThreadBufferIterator.init primes its thread): drop those
            # pulls and rewind the base to its recorded pre-fill state so
            # the rebuild reproduces the original cache — same batches,
            # same augment rng draws
            self._cache = []
            self._pos = 0
            if self._prefill_base is not None:
                self.base.set_state(self._prefill_base)
            self.base.before_first()
            while not self._filled and self.next() is not None:
                pass
            self._filled = True
        self._pos = int(st.get("pos", 0))
        if "base" in st:
            self.base.set_state(st["base"])

    def next(self):
        if self._filled:
            if self._pos >= len(self._cache):
                return None
            b = self._cache[self._pos]
            self._pos += 1
            return b
        if len(self._cache) >= self.max_nbatch:
            self._filled = True
            return None
        b = self.base.next()
        if b is None:
            self._filled = True
            return None
        self._cache.append(b)
        self._pos = len(self._cache)
        return b


class AttachTxtIterator(IIterator):
    """Joins per-instance side features from a text file into
    ``batch.extra_data``, keyed by instance index
    (iter_attach_txt-inl.hpp:15-99).  File format: each line is
    ``inst_index v1 v2 ... vk``; shape from ``extra_shape[i] = c,y,x``."""

    config_keys = (
        K("path_attach_txt", "path"), K("path_txt", "path"),
        K("extra_data_shape[*]", "str", help="c,y,x per side input"),
    )

    def __init__(self, base: IIterator):
        self.base = base
        self.path_txt = ""
        self.extra_shapes: List[tuple] = []
        self._table = {}

    def set_param(self, name, val):
        import re
        if name == "path_attach_txt" or name == "path_txt":
            self.path_txt = val
        m = re.match(r"^extra_data_shape\[(\d+)\]$", name)
        if m:
            idx = int(m.group(1))
            shape = tuple(int(t) for t in val.split(","))
            while len(self.extra_shapes) <= idx:
                self.extra_shapes.append(None)
            self.extra_shapes[idx] = shape
        self.base.set_param(name, val)

    def init(self):
        self.base.init()
        assert self.path_txt, "attachtxt: set path_attach_txt"
        with open(self.path_txt) as f:
            for line in f:
                toks = line.split()
                if not toks:
                    continue
                self._table[int(toks[0])] = np.array(
                    [float(t) for t in toks[1:]], np.float32)

    def before_first(self):
        self.base.before_first()

    def next(self):
        b = self.base.next()
        if b is None:
            return None
        feats = np.stack([self._table[int(i)] for i in b.index])
        extra = []
        if self.extra_shapes and self.extra_shapes[0] is not None:
            off = 0
            for shape in self.extra_shapes:
                size = int(np.prod(shape))
                extra.append(feats[:, off:off + size]
                             .reshape((len(feats),) + shape))
                off += size
        else:
            extra.append(feats.reshape(len(feats), 1, 1, -1))
        b.extra_data = extra
        return b


def s2d_np(x: np.ndarray, s: int, kh: int, kw: int, oh: int, ow: int,
           pad_y: int, pad_x: int) -> np.ndarray:
    """Numpy mirror of ops.nn.s2d_input: (n, c, h, w) -> the input_s2d
    delivery shape (n, c*s*s, hb, wb), channel order (c, sy, sx).
    Dtype-preserving (u8 stays u8 — a pure permutation)."""
    from ..ops.nn import s2d_staged_shape
    n, c, h, w = x.shape
    c2, hb, wb = s2d_staged_shape(c, s, kh, kw, oh, ow)
    xp = np.pad(x, ((0, 0), (0, 0),
                    (pad_y, max(0, hb * s - h - pad_y)),
                    (pad_x, max(0, wb * s - w - pad_x))))
    xp = xp[:, :, :hb * s, :wb * s]
    xb = xp.reshape(n, c, hb, s, wb, s)
    return np.ascontiguousarray(
        xb.transpose(0, 1, 3, 5, 2, 4)).reshape(n, c2, hb, wb)


class S2DEmitIterator(IIterator):
    """Host-side space-to-depth emission (the ``input_s2d`` pipeline
    contract): transform each batch ON THE HOST so the device staging
    fallback — a relayout transpose measured 5x off the HBM floor — never
    runs.  Wraps any assembled-batch iterator; installed by the CLI
    driver when the trainer reports an s2d geometry (main.py).

    u8 batches through a PADDED first conv are passed through
    untransformed (u8 cannot encode the normalized zero padding; the
    trainer's device path normalizes before padding instead)."""

    def __init__(self, base: IIterator, s2d_args):
        self.base = base
        (self.s, self.kh, self.kw, self.oh, self.ow,
         self.pad_y, self.pad_x) = s2d_args

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)

    def init(self) -> None:
        self.base.init()

    def before_first(self) -> None:
        self.base.before_first()

    def next(self):
        b = self.base.next()
        if b is None:
            return None
        if b.data.dtype == np.uint8 and (self.pad_y or self.pad_x):
            return b  # device path handles (normalize-then-pad)
        data = s2d_np(np.asarray(b.data), self.s, self.kh, self.kw,
                      self.oh, self.ow, self.pad_y, self.pad_x)
        return dataclasses.replace(b, data=data)
