"""Data iterator interfaces and batch types.

Reference: ``src/io/data.h`` — ``IIterator<DType>`` {Init, BeforeFirst, Next,
Value}, ``DataInst`` (label, data, index) and ``DataBatch`` with the
``num_batch_padd`` padding protocol (:85-87) and ``extra_data`` side inputs
(:93-94).  Python iterators here feed numpy arrays; device transfer happens
in the trainer (single H2D per step, like the reference's single
``Copy(nodes[0], hostBatch)`` at neural_net-inl.hpp:112).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional

import numpy as np


@dataclasses.dataclass
class DataInst:
    """One instance (data.h:41-56)."""

    label: np.ndarray  # (label_width,)
    data: np.ndarray   # (c, y, x)
    index: int


@dataclasses.dataclass
class DataBatch:
    """One mini-batch (data.h:79-110)."""

    data: np.ndarray                 # (n, c, y, x)
    label: np.ndarray                # (n, label_width)
    index: np.ndarray                # (n,) instance ids
    # number of trailing instances that are wrap-around padding; they are
    # trained on (they're real wrapped instances) but excluded from eval
    num_batch_padd: int = 0
    # number of trailing instances that are *replica* padding of a short
    # tail batch (round_batch=0): masked out of training losses AND eval.
    # Always <= num_batch_padd.  The reference instead re-plumbs node
    # shapes (AdjustBatchSize, neural_net-inl.hpp:266-277); padding with a
    # loss mask trains the same real instances without shape polymorphism.
    tail_mask_padd: int = 0
    extra_data: List[np.ndarray] = dataclasses.field(default_factory=list)

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


class IIterator:
    """Iterator interface (data.h:19-39)."""

    # keys this stage's set_param consumes — harvested by the lint
    # registry (analysis/registry.py); a name ending in "[*]" is a
    # numbered-key template (extra_data_shape[0], ...).  Keep in sync
    # with set_param.
    config_keys: tuple = ()

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self):
        """Return the next element or None at end of epoch."""
        raise NotImplementedError

    def close(self) -> None:
        """Release background resources (threads, pools).  Wrapper iterators
        forward to their base; safe to call more than once."""
        base = getattr(self, "base", None)
        if base is not None:
            base.close()

    # ----------------------------------------------- resumable position
    def state(self) -> dict:
        """JSON-able resume state of this stage + everything beneath it
        (the checkpoint manifest carries it; doc/checkpoint.md).  The
        contract is *positional*, like the reference's round-robin
        restart: stages record where they are (cursor, epoch-done flag,
        augment rng, cache fill) rather than buffered data.  Only valid
        at a quiescent point — a round boundary, after the epoch's
        ``next()`` returned None — so prefetching stages
        (ThreadBufferIterator, DevicePrefetcher) are drained and their
        base's position equals the consumer's.  Stages without
        cross-epoch state just delegate to their base."""
        base = getattr(self, "base", None)
        return {"base": base.state()} if base is not None else {}

    def set_state(self, st: dict) -> None:
        """Restore :meth:`state` (call after ``init()``, before the
        next ``before_first()``)."""
        base = getattr(self, "base", None)
        if base is not None and st and "base" in st:
            base.set_state(st["base"])

    def __iter__(self) -> Iterator:
        self.before_first()
        while True:
            v = self.next()
            if v is None:
                return
            yield v
