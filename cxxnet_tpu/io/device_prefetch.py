"""Device-side input staging: async double-buffered host->device prefetch.

Reference: the ThreadBuffer (``iter_batch_proc-inl.hpp:136-224`` over
``utils/thread_buffer.h``) kept the GPU queue full by producing batches on
a dedicated thread — but only host *decode* overlapped compute; the H2D
copy itself still ran synchronously inside Update
(``neural_net-inl.hpp:112``).  On TPU that copy (group ``np.stack``,
dtype cast, sharded ``jax.device_put``, the ``input_s2d`` staging
transform) is the remaining serial segment of the dispatch window.

:class:`DevicePrefetcher` moves all of it onto a producer thread running
``prefetch_device`` dispatches ahead of the train loop, holding a bounded
queue of device-resident staged batches — tf.data's prefetch-to-device
(Murray et al., 2021), the single highest-leverage input-pipeline
transform once host decode is off the critical path.  With ``depth = 0``
the same grouping + staging code runs synchronously on the consumer
thread (the ``prefetch_device = 0`` fallback), which still keeps the
stack/cast/transfer OUT of the dispatch timer — only the overlap is
lost, never the accounting.

The staged item types quack like :class:`~cxxnet_tpu.io.data.DataBatch`
where the trainer needs them to (``data``/``label``/``extra_data`` as
device arrays, ``batch_size``/``num_batch_padd``/``tail_mask_padd``
metadata), and carry the host-side label (``label_host`` / ``meta``) for
train-metric accumulation plus ``h2d_sec``, the host wall spent staging
— on the producer thread it overlaps device compute; synchronously it is
critical-path time the step records surface next to ``dispatch_sec``.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, List, Optional, Tuple, Union

import numpy as np

from .data import DataBatch, IIterator


@dataclasses.dataclass
class StagedMeta:
    """Host-side remnants of one staged batch: what the train loop's
    counters and the train metric need after the arrays moved to
    device."""

    batch_size: int
    num_batch_padd: int
    tail_mask_padd: int
    label: np.ndarray
    index: np.ndarray


@dataclasses.dataclass
class StagedBatch:
    """One device-resident batch.  ``data``/``label``/``extra_data`` are
    ``jax.Array``s (``label`` already float32, ``data`` already through
    the ``input_s2d`` staging transform); ``mask`` is the pre-staged tail
    loss mask when ``tail_mask_padd > 0``.  ``NetTrainer.update`` /
    ``predict`` / ``extract_feature`` accept it wherever they accept a
    ``DataBatch`` — the ``_device_put`` isinstance hook passes the
    already-resident arrays through untouched."""

    data: Any
    label: Any
    label_host: np.ndarray
    index: np.ndarray
    num_batch_padd: int = 0
    tail_mask_padd: int = 0
    extra_data: Tuple[Any, ...] = ()
    mask: Any = None
    h2d_sec: float = 0.0

    @property
    def batch_size(self) -> int:
        return int(self.data.shape[0])


@dataclasses.dataclass
class StagedGroup:
    """A uniform ``multi_step`` group staged as one device-resident
    ``(k, batch, ...)`` stack for ``NetTrainer.update_many`` — one
    dispatch, one D2H for the stacked eval outputs."""

    datas: Any
    labels: Any
    meta: List[StagedMeta]
    h2d_sec: float = 0.0


@dataclasses.dataclass
class StagedEvalGroup:
    """An evaluation group staged as one ``(k, batch, ...)`` stack for
    the scanned eval step (labels stay on the host — the metric consumes
    them there)."""

    datas: Any
    meta: List[StagedMeta]
    h2d_sec: float = 0.0


#: a work item: one dispatch window — either a staged multi-step group or
#: a list of per-batch staged batches (non-uniform flushes keep the
#: legacy one-window-many-updates shape so dispatch counting is stable)
StagedItem = Union[StagedBatch, StagedGroup, StagedEvalGroup,
                   List[StagedBatch]]


def item_h2d_sec(item: StagedItem) -> float:
    """Total staging wall of one work item."""
    if isinstance(item, list):
        return sum(b.h2d_sec for b in item)
    return item.h2d_sec


class ProducerError:
    """Producer-thread exception, queued for re-raise on the consumer
    (shared with :class:`~cxxnet_tpu.io.iter_proc.ThreadBufferIterator` —
    a raise on the producer must surface in the consumer's next(), never
    strand it on queue.get())."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def generation_put(owner, gen: int, q: "queue.Queue", v,
                   timeout: float = 0.05) -> bool:
    """Bounded put that re-checks ``owner._gen`` so a stale producer
    exits (returns False) instead of blocking forever on an orphaned
    queue.  Shared by every producer-thread iterator in this package."""
    while True:
        if owner._gen != gen:
            return False
        try:
            q.put(v, timeout=timeout)
            return True
        except queue.Full:
            continue


class DevicePrefetcher:
    """Pulls host batches from ``base``, groups them (``group_n`` mirrors
    the train loop's ``multi_step`` flush rules, or ``eval_group`` with
    ``for_eval=True``), stages them device-resident via the trainer's
    ``stage_batch`` / ``stage_group`` / ``stage_eval_group``, and holds a
    bounded queue of ``depth`` staged work items.

    Epoch protocol matches the iterator contract: ``before_first()``
    (re)starts a producer for one epoch, ``next()`` returns staged items
    until ``None`` at epoch end.  A generation counter poisons stale
    producers and ``before_first``/``close`` join the previous thread, so
    exactly one thread ever touches ``base`` (the ThreadBufferIterator
    discipline).  A producer exception is queued and re-raised in the
    consumer — never a silent hang.  ``close()`` joins the producer but
    does NOT close ``base``; its owner does.
    """

    def __init__(self, base: IIterator, stager, *, group_n: int = 1,
                 depth: int = 2, metrics=None, for_eval: bool = False):
        self.base = base
        self.stager = stager
        self.group_n = max(1, int(group_n))
        self.depth = int(depth)
        self.metrics = metrics
        self.for_eval = for_eval
        # sync mode: host-iterator wall behind the last item (the
        # consumer's next() wall minus this is staging time); async mode:
        # queue depth observed at the last get (staged items ready)
        self.last_wait_sec = 0.0
        self.last_depth = 0
        self._iter = None
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._gen = 0
        self._failed: Optional[BaseException] = None
        self._done = False
        # span tracing (monitor/spans.py, trace_sample-sampled): item
        # counters for the producer's staging span vs the consumer's
        # queue-wait span — the pair that shows whether the input
        # pipeline is producing ahead of the loop or the loop is
        # waiting on it
        # racelint: atomic(single-writer int bump: staged on the producer in async mode, on the consumer in sync mode — never both)
        self._span_staged = 0
        self._span_waited = 0

    @property
    def async_(self) -> bool:
        return self.depth > 0

    # ------------------------------------------------------------ staging
    def _stage(self, group: List[DataBatch]) -> StagedItem:
        s = self.stager
        if self.for_eval:
            if len(group) == 1:
                return s.stage_batch(group[0])
            return s.stage_eval_group(group)
        # grouping rules identical to the legacy inline loop: a group
        # dispatches as ONE on-device scan only when shapes are uniform,
        # nothing is tail-masked, and no batch carries extra-data side
        # inputs; otherwise the window falls back to per-batch updates
        uniform = all(
            b.data.shape == group[0].data.shape
            and b.label.shape == group[0].label.shape
            and b.tail_mask_padd == 0
            for b in group)
        if len(group) > 1 and uniform and not any(
                b.extra_data for b in group):
            return s.stage_group(group)
        return [s.stage_batch(b) for b in group]

    def _epoch_items(self):
        """One epoch's staged work items, each paired with the host
        iterator wall that fed it (used for the iter-wait split in sync
        mode; in async mode the producer absorbs that wait)."""
        pending: List[DataBatch] = []
        wait = 0.0
        while True:
            t0 = time.perf_counter()
            b = self.base.next()
            wait += time.perf_counter() - t0
            done = b is None
            if not done:
                if self.for_eval and b.extra_data:
                    # side-input batches take the per-batch eval path, in
                    # stream order (trainer.evaluate's legacy rule)
                    if pending:
                        group, pending = pending, []
                        yield self._stage_traced(group), wait
                        wait = 0.0
                    yield self._stage_traced([b]), wait
                    wait = 0.0
                    continue
                if self.for_eval and self.group_n > 1:
                    # eval groups stage at flush time: copy now, like the
                    # legacy eval loop — paged iterators may reuse the
                    # underlying buffer while the batch waits in a group
                    b = dataclasses.replace(b, data=np.array(b.data),
                                            label=np.array(b.label))
                pending.append(b)
            if pending and (done or len(pending) >= self.group_n):
                group, pending = pending, []
                yield self._stage_traced(group), wait
                wait = 0.0
            if done:
                return

    def _stage_traced(self, group: List[DataBatch]) -> StagedItem:
        """_stage plus the sampled ``prefetch_stage`` span (producer
        side: host stack/cast/device_put/input_s2d wall per item)."""
        tracer = getattr(self.metrics, "tracer", None)
        if tracer is not None and tracer.enabled:
            n = self._span_staged
            # racelint: ok(race_rmw) — async and sync staging are mutually exclusive modes; one context ever bumps this
            self._span_staged += 1
            if tracer.sampled(n):
                with tracer.span("prefetch_stage", batches=len(group),
                                 mode="async" if self.async_ else "sync"):
                    return self._stage(group)
        return self._stage(group)

    # ------------------------------------------------------ thread plumbing
    def before_first(self) -> None:
        self._failed = None
        self._done = False
        if not self.async_:
            self.base.before_first()
            self._iter = self._epoch_items()
            return
        self._gen += 1
        if self._thread is not None:
            self._thread.join()  # unblocks via the generation check
        self.base.before_first()
        q = queue.Queue(maxsize=self.depth)
        self._queue = q
        self._thread = threading.Thread(
            target=self._producer, args=(self._gen, q), daemon=True,
            name="cxxnet-device-prefetch")
        self._thread.start()

    def _producer(self, gen: int, q: "queue.Queue") -> None:
        try:
            for item, wait in self._epoch_items():
                if not generation_put(self, gen, q, (item, wait)):
                    return
            generation_put(self, gen, q, None)
        except BaseException as e:  # noqa: BLE001 — must reach the consumer
            generation_put(self, gen, q, ProducerError(e))

    def next(self) -> Optional[StagedItem]:
        """The next staged work item, or None at epoch end.  Re-raises a
        producer exception (and keeps re-raising until the next
        ``before_first()`` — the epoch is dead, never a hang)."""
        if self._failed is not None:
            raise self._failed
        if self._done:
            return None
        if not self.async_:
            assert self._iter is not None, "call before_first() first"
            try:
                item, self.last_wait_sec = next(self._iter)
            except StopIteration:
                self._done = True
                return None
            except BaseException as e:  # latch: sync epochs die like async
                self._failed = e
                raise
            return item
        assert self._queue is not None, "call before_first() first"
        # consumer-side span: the loop's wall blocked on the producer
        # (sampled; near-zero dur = producer is keeping up)
        tracer = getattr(self.metrics, "tracer", None)
        tok = None
        if tracer is not None and tracer.enabled:
            n = self._span_waited
            self._span_waited += 1
            if tracer.sampled(n):
                tok = tracer.begin("prefetch_wait")
        v = self._queue.get()
        if tok is not None:
            tracer.end(tok)
        if v is None:
            self._done = True
            return None
        if isinstance(v, ProducerError):
            self._failed = v.exc
            raise v.exc
        item, _ = v
        self.last_depth = self._queue.qsize()
        if self.metrics is not None:
            self.metrics.set_gauge("prefetch_depth", self.last_depth)
        return item

    def __iter__(self):
        self.before_first()
        while True:
            v = self.next()
            if v is None:
                return
            yield v

    def close(self) -> None:
        """Join the producer thread.  The BASE iterator is not closed —
        its owner (the task driver's iterator list) does that."""
        self._gen += 1
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._iter = None
        self._queue = None
