"""Tokenized-LM dataset path: binary token shards + document packing.

The reference framework's identity is its config-driven binary data
pipeline (im2bin pages + iterator chains); this module is the im2bin
analogue for language models, the modality the reference predates
entirely (SURVEY.md §5.7: data is fixed (N,C,H,W) images).

Token-shard format (``tools/tok2bin.py`` writes it; fresh, documented —
mirrors the CXTPUBIN header discipline of ``io/imbin.py``)::

    file   := header doc_index tokens
    header := magic "CXTPUTOK" (8 bytes) | uint32 version | uint32 itemsize
              | uint64 ndocs | uint64 ntokens
    doc_index := (ndocs + 1) uint64 token offsets (offsets[0] = 0,
              offsets[ndocs] = ntokens)
    tokens := ntokens little-endian unsigned ints of ``itemsize`` bytes

Tokens are read via ``np.memmap`` — a shard is never loaded whole; the
doc-offset index is the only eagerly-resident part.  Multi-part shards
use ``path_tok = prefix_%d.tok`` with ``tok_count = N`` and distributed
workers take every k-th shard (``dist_num_worker``/``dist_worker_rank``,
or PS_RANK), exactly like the imgbin sharding.

Two iterator stages build on it (registered in ``io/factory.py``):

* :class:`TextIterator` — base stage yielding one document per
  ``next()`` (a 1-D int32 token array in ``DataInst.data``), with
  deterministic seeded per-epoch shuffling of shard order AND document
  order (seed ``787 + seed_data + gen`` — the epoch counter IS the
  cross-round resume state, the ImageBinIterator discipline).
* :class:`PackedSeqIterator` — packs variable-length documents into
  fixed ``(batch, seqlen)`` rows.  Default mode (``pack_split = 1``)
  chops the concatenated document stream, so every emitted position is
  a real token (packing efficiency 1.0) and the leftover tail CARRIES
  ACROSS the epoch boundary in a ragged buffer instead of being padded
  away; ``pack_split = 0`` keeps documents whole per row (padding where
  the next document doesn't fit — the mode whose packing-efficiency
  number is non-trivial).  Each row carries three label fields laid out
  for ``label_vec`` routing::

      label[:, 0:S)   next-token targets; -1 marks positions whose
                      target crosses a document boundary or is padding
                      (the loss layer masks these: softmax_seq
                      ``packed = 1``)
      label[:, S:2S)  segment ids, 1..k per row in order of appearance;
                      0 = padding (attention ``segment_key`` blocks
                      cross-segment scores)
      label[:, 2S:3S) position within the document, reset at every
                      document start (embedding ``pos_key``)

Both stages implement the ``state()/set_state()`` resume contract
(doc/checkpoint.md): the packer serializes its ragged buffer so a
kill-resume replays the exact token/row pairing bitwise.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional

import numpy as np

from ..analysis.schema import K
from ..monitor import log as mlog
from .data import DataBatch, DataInst, IIterator

TOK_MAGIC = b"CXTPUTOK"
TOK_VERSION = 1
_HEADER_FMT = "<IIQQ"  # version, itemsize, ndocs, ntokens
_HEADER_SIZE = 8 + struct.calcsize(_HEADER_FMT)


def write_token_shard(path: str, docs, itemsize: int = 4) -> int:
    """Write one token shard (tools/tok2bin.py's engine).  ``docs`` is an
    iterable of int sequences; returns the number of documents written.
    ``itemsize`` 2 (uint16, vocab < 65536) or 4 (uint32).  The write
    goes through ``serializer.atomic_write`` — the repo's ONE copy of
    the tmp+fsync+replace+dir-fsync durability protocol."""
    assert itemsize in (2, 4), f"itemsize must be 2 or 4, got {itemsize}"
    offsets = [0]
    arrays = []
    le = "<u2" if itemsize == 2 else "<u4"
    for d in docs:
        a = np.asarray(d, np.int64)
        assert a.ndim == 1, "each document must be a 1-D token sequence"
        assert a.size > 0, "empty documents cannot be packed"
        assert a.min() >= 0, "token ids must be non-negative"
        assert a.max() < (1 << (8 * itemsize)), \
            f"token id {a.max()} exceeds itemsize {itemsize} range"
        arrays.append(np.ascontiguousarray(a.astype(le)))
        offsets.append(offsets[-1] + a.size)

    def _write(f):
        f.write(TOK_MAGIC + struct.pack(_HEADER_FMT, TOK_VERSION, itemsize,
                                        len(arrays), offsets[-1]))
        f.write(np.asarray(offsets, "<u8").tobytes())
        for a in arrays:
            f.write(a.tobytes())

    from ..utils.serializer import atomic_write
    atomic_write(path, _write)
    return len(arrays)


class TokenShard:
    """Memory-mapped reader of one token shard: the doc-offset index is
    eagerly resident, token data stays on disk behind ``np.memmap``."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            head = f.read(_HEADER_SIZE)
        assert head[:8] == TOK_MAGIC, f"{path}: not a CXTPUTOK file"
        version, itemsize, ndocs, ntokens = struct.unpack(
            _HEADER_FMT, head[8:])
        assert version == TOK_VERSION, \
            f"{path}: version {version} != {TOK_VERSION}"
        assert itemsize in (2, 4), f"{path}: bad itemsize {itemsize}"
        self.ndocs = int(ndocs)
        self.ntokens = int(ntokens)
        self.offsets = np.fromfile(path, "<u8", self.ndocs + 1,
                                   offset=_HEADER_SIZE)
        assert self.offsets.size == self.ndocs + 1, f"{path}: truncated index"
        assert int(self.offsets[-1]) == self.ntokens, \
            f"{path}: index/token count mismatch"
        dtype = np.dtype("<u2" if itemsize == 2 else "<u4")
        self.tokens = np.memmap(
            path, dtype=dtype, mode="r",
            offset=_HEADER_SIZE + 8 * (self.ndocs + 1), shape=(self.ntokens,))

    def doc(self, i: int) -> np.ndarray:
        a, b = int(self.offsets[i]), int(self.offsets[i + 1])
        return np.asarray(self.tokens[a:b], np.int32)


class TextIterator(IIterator):
    """Token-shard document reader with deterministic per-epoch shuffle.

    ``shuffle = 1`` reshuffles shard order and per-shard document order
    every epoch with seed ``787 + seed_data + gen``; the epoch counter
    ``gen`` is therefore the whole cross-round resume state (positions
    rewind at each ``before_first`` — the ImageBinIterator contract)."""

    config_keys = (
        K("path_tok", "path", help="token shard, %d with tok_count"),
        K("tok_count", "int", lo=0),
        K("shuffle", "int", lo=0, hi=1),
        K("silent", "int", lo=0, hi=1),
        K("seed_data", "int"),
        K("dist_num_worker", "int", lo=1),
        K("dist_worker_rank", "int", lo=0),
        K("text_max_docs", "int", lo=0,
          help="cap documents per epoch (0 = all; debug/CI sizing)"),
    )

    def __init__(self):
        self.path_tok = ""
        self.tok_count = 0
        self.shuffle = 0
        self.silent = 0
        self.seed_data = 0
        self.dist_num_worker = 1
        self.dist_worker_rank = 0
        self.text_max_docs = 0
        self._gen = 0

    def set_param(self, name, val):
        if name == "path_tok":
            self.path_tok = val
        elif name == "tok_count":
            self.tok_count = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "seed_data":
            self.seed_data = int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "text_max_docs":
            self.text_max_docs = int(val)

    def init(self):
        assert self.path_tok, "text: set path_tok"
        rank = int(os.environ.get("PS_RANK", self.dist_worker_rank))
        if self.tok_count > 0:
            shard_ids = [i for i in range(self.tok_count)
                         if i % self.dist_num_worker == rank]
            assert shard_ids, (
                f"text: worker rank {rank} of {self.dist_num_worker} maps "
                f"to no shards (tok_count = {self.tok_count}); a rank with "
                "zero data would dispatch no steps and hang the other "
                "replicas' collectives")
            paths = [self.path_tok % i for i in shard_ids]
        else:
            assert self.dist_num_worker == 1, \
                "distributed sharding needs tok_count > 1 shards"
            paths = [self.path_tok]
        self.shards = [TokenShard(p) for p in paths]
        # global doc id base per shard, so DataInst.index is stable under
        # shuffling (shard-local ordinal + base)
        self._doc_base = np.cumsum([0] + [s.ndocs for s in self.shards])
        self._ndocs = int(self._doc_base[-1])
        if not self.silent:
            ntok = sum(s.ntokens for s in self.shards)
            mlog.info(f"TextIterator: {self._ndocs} docs / {ntok} "
                      f"tokens in {len(self.shards)} shard(s)")

    def before_first(self):
        self._gen += 1
        order = []
        shard_order = list(range(len(self.shards)))
        rng = None
        if self.shuffle:
            rng = np.random.RandomState(787 + self.seed_data + self._gen)
            rng.shuffle(shard_order)
        for b in shard_order:
            docs = np.arange(self.shards[b].ndocs)
            if rng is not None:
                rng.shuffle(docs)
            order.extend((b, int(d)) for d in docs)
        if self.text_max_docs > 0:
            order = order[:self.text_max_docs]
        self._order = order
        self._pos = 0

    def next(self):
        if self._pos >= len(self._order):
            return None
        b, d = self._order[self._pos]
        self._pos += 1
        return DataInst(label=np.zeros((1,), np.float32),
                        data=self.shards[b].doc(d),
                        index=int(self._doc_base[b]) + d)

    def state(self):
        # captured at a round boundary (epoch drained): the per-epoch
        # shuffle is fully determined by gen, so the counter is the state
        return {"gen": int(self._gen)}

    def set_state(self, st):
        self._gen = max(int(st.get("gen", 0)), self._gen)


class PackedSeqIterator(IIterator):
    """Packs base documents into fixed ``(batch, seqlen)`` LM rows.

    ``pack_split = 1`` (default): the concatenated document stream is
    chopped into rows — zero padding, leftover tokens carry across the
    epoch boundary in the ragged buffer (serialized by :meth:`state` so
    kill-resume replays the exact pairing).  ``pack_split = 0``: whole
    documents per row, padded flush when the next document doesn't fit
    (documents longer than ``seqlen`` are truncated, counted in
    :meth:`stats`).

    Emits :class:`DataBatch` with ``data`` ``(b, 1, 1, S)`` float32
    token ids and ``label`` ``(b, 3S)`` = [targets | segments |
    positions] (module docstring has the exact field semantics)."""

    config_keys = (
        K("seqlen", "int", lo=2),
        K("batch_size", "int", lo=1),
        K("pack_split", "int", lo=0, hi=1,
          help="1 = chop the doc stream (no padding, ragged carry); "
               "0 = whole docs per row, padded flush"),
        K("silent", "int", lo=0, hi=1),
    )

    def __init__(self, base: IIterator):
        self.base = base
        self.seqlen = 0
        self.batch_size = 0
        self.pack_split = 1
        self.silent = 0
        # ragged stream buffer: parallel int64 arrays of (token, doc uid,
        # position-in-doc) — numpy on the hot path (per-token python
        # loops would dominate input time at real corpus scale);
        # state() converts to JSON-able int lists
        self._tok = np.zeros(0, np.int64)
        self._uid = np.zeros(0, np.int64)
        self._pos = np.zeros(0, np.int64)
        # pack_split = 0: finished-but-unemitted rows, each a dict of
        # three int64 arrays (already padded to seqlen)
        self._rows: List[dict] = []
        self._next_uid = 1
        self._batches_emitted = 0
        # counters behind stats()/packing efficiency
        self._real_tokens = 0
        self._total_positions = 0
        self._truncated_tokens = 0

    def set_param(self, name, val):
        if name == "seqlen":
            self.seqlen = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "pack_split":
            self.pack_split = int(val)
        elif name == "silent":
            self.silent = int(val)
        self.base.set_param(name, val)

    def init(self):
        assert self.seqlen >= 2, "packseq: set seqlen >= 2"
        assert self.batch_size > 0, "packseq: set batch_size"
        self.base.init()

    def before_first(self):
        # the ragged buffer deliberately survives the rewind: leftover
        # tokens from the previous epoch head the next one (no padding
        # wasted at epoch boundaries)
        self.base.before_first()

    # ------------------------------------------------------------ packing
    def _pull_doc(self) -> bool:
        inst = self.base.next()
        if inst is None:
            return False
        toks = np.asarray(inst.data, np.int64).reshape(-1)
        uid = self._next_uid
        self._next_uid += 1
        if self.pack_split:
            self._tok = np.concatenate([self._tok, toks])
            self._uid = np.concatenate(
                [self._uid, np.full(toks.size, uid, np.int64)])
            self._pos = np.concatenate(
                [self._pos, np.arange(toks.size, dtype=np.int64)])
        else:
            self._append_doc_nosplit(toks, uid)
        return True

    def _append_doc_nosplit(self, toks: np.ndarray, uid: int) -> None:
        s = self.seqlen
        if toks.size > s:
            self._truncated_tokens += toks.size - s
            toks = toks[:s]
        if self._tok.size + toks.size > s:
            self._flush_row_nosplit()
        self._tok = np.concatenate([self._tok, toks])
        self._uid = np.concatenate(
            [self._uid, np.full(toks.size, uid, np.int64)])
        self._pos = np.concatenate(
            [self._pos, np.arange(toks.size, dtype=np.int64)])

    def _flush_row_nosplit(self) -> None:
        """Pad the current (whole-docs) row out to seqlen and bank it."""
        if not self._tok.size:
            return
        pad = np.zeros(self.seqlen - self._tok.size, np.int64)
        self._rows.append({
            "tok": np.concatenate([self._tok, pad]),
            "uid": np.concatenate([self._uid, pad]),
            "pos": np.concatenate([self._pos, pad]),
        })
        self._tok = self._uid = self._pos = np.zeros(0, np.int64)

    def _row_arrays(self, tok, uid, pos, look_tok=None, look_uid=None):
        """(tokens, targets, segments, positions) for one row; target -1
        exactly where the next token belongs to another document or is
        padding.  ``look_tok``/``look_uid`` are the stream token right
        AFTER the row (split mode): a document continuing into the next
        row keeps its last-position target, so no supervision is lost at
        row boundaries."""
        s = self.seqlen
        tok = np.asarray(tok, np.int64)
        uid = np.asarray(uid, np.int64)
        pos = np.asarray(pos, np.int64)
        # renumber doc uids 1..k in order of appearance; 0 stays padding
        seg = np.zeros(s, np.int64)
        nz = uid != 0
        if nz.any():
            u, first, inv = np.unique(uid[nz], return_index=True,
                                      return_inverse=True)
            rank = np.empty(u.size, np.int64)
            rank[np.argsort(first)] = np.arange(1, u.size + 1)
            seg[nz] = rank[inv]
        tgt = np.full(s, -1, np.int64)
        same = (uid[:-1] == uid[1:]) & (uid[:-1] != 0)
        tgt[:-1][same] = tok[1:][same]
        if look_uid is not None and uid[-1] != 0 and look_uid == uid[-1]:
            tgt[-1] = look_tok
        self._real_tokens += int(nz.sum())
        self._total_positions += s
        return tok, tgt, seg, np.minimum(pos, s - 1)

    def _take_rows(self):
        """Up to batch_size packed rows, or None when the buffered stream
        cannot fill a whole batch (carry to the next epoch).  Split mode
        requires one token of LOOKAHEAD past the batch so every row-
        boundary target is known (the lookahead token stays buffered —
        it is the next batch's first token)."""
        b, s = self.batch_size, self.seqlen
        if self.pack_split:
            if self._tok.size < b * s + 1:
                return None
            rows = []
            for r in range(b):
                sl = slice(r * s, (r + 1) * s)
                la = (r + 1) * s
                rows.append(self._row_arrays(
                    self._tok[sl], self._uid[sl], self._pos[sl],
                    look_tok=int(self._tok[la]),
                    look_uid=int(self._uid[la])))
            self._tok = self._tok[b * s:]
            self._uid = self._uid[b * s:]
            self._pos = self._pos[b * s:]
            return rows
        if len(self._rows) < b:
            return None
        rows = [self._row_arrays(r["tok"], r["uid"], r["pos"])
                for r in self._rows[:b]]
        del self._rows[:b]
        return rows

    def next(self):
        while True:
            rows = self._take_rows()
            if rows is not None:
                break
            if not self._pull_doc():
                # epoch end: in nosplit mode bank the open row (its docs
                # are complete — only row-count, not content, is ragged);
                # if that completes a batch, emit it before ending
                if not self.pack_split and self._tok.size:
                    self._flush_row_nosplit()
                    rows = self._take_rows()
                    if rows is not None:
                        break
                return None
        b, s = self.batch_size, self.seqlen
        data = np.stack([r[0] for r in rows]).astype(np.float32)
        label = np.concatenate(
            [np.stack([r[1] for r in rows]),
             np.stack([r[2] for r in rows]),
             np.stack([r[3] for r in rows])], axis=1).astype(np.float32)
        idx = np.arange(self._batches_emitted * b,
                        self._batches_emitted * b + b, dtype=np.uint32)
        self._batches_emitted += 1
        return DataBatch(data=data.reshape(b, 1, 1, s), label=label,
                         index=idx)

    # ------------------------------------------------------------- metrics
    def stats(self) -> dict:
        """Packing counters: ``packing_efficiency`` is the real-token
        fraction of all emitted positions (1.0 in split mode)."""
        eff = (self._real_tokens / self._total_positions
               if self._total_positions else 0.0)
        return {"rows": self._total_positions // max(self.seqlen, 1),
                "real_tokens": self._real_tokens,
                "total_positions": self._total_positions,
                "truncated_tokens": self._truncated_tokens,
                "packing_efficiency": round(eff, 4)}

    # ------------------------------------------------------------- resume
    def state(self):
        st = {"tok": [int(t) for t in self._tok],
              "uid": [int(u) for u in self._uid],
              "pos": [int(p) for p in self._pos],
              "next_uid": int(self._next_uid),
              "emitted": int(self._batches_emitted),
              "real": int(self._real_tokens),
              "total": int(self._total_positions),
              "trunc": int(self._truncated_tokens),
              "base": self.base.state()}
        if not self.pack_split:
            st["rows"] = [{k: [int(x) for x in r[k]]
                           for k in ("tok", "uid", "pos")}
                          for r in self._rows]
        return st

    def set_state(self, st):
        self._tok = np.asarray(st.get("tok", []), np.int64)
        self._uid = np.asarray(st.get("uid", []), np.int64)
        self._pos = np.asarray(st.get("pos", []), np.int64)
        self._rows = [{k: np.asarray(r[k], np.int64)
                       for k in ("tok", "uid", "pos")}
                      for r in st.get("rows", [])]
        self._next_uid = int(st.get("next_uid", 1))
        self._batches_emitted = int(st.get("emitted", 0))
        self._real_tokens = int(st.get("real", 0))
        self._total_positions = int(st.get("total", 0))
        self._truncated_tokens = int(st.get("trunc", 0))
        if "base" in st:
            self.base.set_state(st["base"])
