"""ctypes bindings for the native (C++) data loader.

The reference's data pipeline is native C++ (paged pack reading +
prefetch threads + jpeg decode, ``src/io/iter_thread_imbin-inl.hpp``,
``src/utils/thread_buffer.h``, ``src/utils/decoder.h``); this module binds
our C++ equivalent (``native/imbin_iter.cc``) behind the same ``IIterator``
interface, so ``iter = imbin_native`` drops into any config where
``iter = imgbin`` works — but with decode, normalization, and batch
assembly off the Python interpreter (a per-instance Python loop cannot
feed a ~20k imgs/sec training step).

The shared library is built by ``make -C native``; :func:`load_library`
attempts that automatically once and raises with instructions if no
toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from ..analysis.schema import K
from .data import DataBatch, IIterator

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcxxnet_native.so")

_lib = None
_build_attempted = False


def load_library() -> ctypes.CDLL:
    """dlopen the native loader, building it on first use if needed."""
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and not _build_attempted:
        _build_attempted = True
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR],
                           check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError) as e:
            out = getattr(e, "stderr", b"") or b""
            raise RuntimeError(
                "native loader not built and `make -C native` failed "
                f"({out.decode(errors='replace')[-500:]}); build it manually "
                "or use the pure-Python `iter = imgbin`") from e
    lib = ctypes.CDLL(_LIB_PATH)
    if not hasattr(lib, "CXNIONativeIsU8"):
        # stale pre-u8 build on disk: rebuild once and reload (a missing
        # symbol would otherwise surface as a bare AttributeError)
        subprocess.run(["make", "-C", _NATIVE_DIR, "-B"],
                       check=True, capture_output=True)
        lib = ctypes.CDLL(_LIB_PATH)
    lib.CXNIONativeCreate.restype = ctypes.c_void_p
    lib.CXNIONativeCreate.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int]
    lib.CXNIONativeBeforeFirst.argtypes = [ctypes.c_void_p]
    lib.CXNIONativeNextBatch.restype = ctypes.c_int
    lib.CXNIONativeNextBatch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32)]
    lib.CXNIONativeShape.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_longlong)]
    lib.CXNIONativeNextBatchU8.restype = ctypes.c_int
    lib.CXNIONativeNextBatchU8.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32)]
    lib.CXNIONativeIsU8.restype = ctypes.c_int
    lib.CXNIONativeIsU8.argtypes = [ctypes.c_void_p]
    lib.CXNIONativeLastError.restype = ctypes.c_char_p
    lib.CXNIONativeLastError.argtypes = [ctypes.c_void_p]
    lib.CXNIONativeFree.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeImageBinIterator(IIterator):
    """Batch iterator backed by the C++ paged loader.

    Unlike the Python ``imgbin`` chain (base -> augment -> batch adapter),
    this produces finished batches directly: mean/scale normalization and
    round_batch/num_batch_padd handling happen in C++ (reference batch
    adapter semantics, iter_batch_proc-inl.hpp:89-106).
    """

    # the native loader consumes the imgbin + augment + batch-adapt
    # surface in C++ (the config text is forwarded wholesale); the
    # declaration mirrors the python chain it replaces
    config_keys = (
        K("image_bin", "path"), K("path_imgbin", "path"),
        K("image_list", "path"), K("path_imglst", "path"),
        K("batch_size", "int", lo=1),
        K("round_batch", "int", lo=0, hi=1),
        K("label_width", "int", lo=1),
        K("shuffle", "int", lo=0, hi=1),
        K("silent", "int", lo=0, hi=1), K("seed_data", "int"),
        K("input_shape", "str", help="c,y,x"),
        K("image_mean", "path"), K("mean_value", "str"),
        K("scale", "float"), K("output_u8", "int", lo=0, hi=1),
        K("rand_crop", "int", lo=0, hi=1),
        K("rand_mirror", "int", lo=0, hi=1),
        K("mirror", "int", lo=0, hi=1),
        K("decode_thread_num", "int", lo=0),
    )

    def __init__(self):
        self._cfg = []
        self._h: Optional[int] = None
        self._lib = None
        self._round_batch = 0

    def set_param(self, name: str, val: str) -> None:
        if name == "round_batch":
            self._round_batch = int(val)
        self._cfg.append((name, val))

    def init(self) -> None:
        self._lib = load_library()
        cfg_text = "\n".join(f"{k} = {v}" for k, v in self._cfg)
        err = ctypes.create_string_buffer(4096)
        h = self._lib.CXNIONativeCreate(cfg_text.encode(), err, len(err))
        if not h:
            raise RuntimeError(
                f"native iterator init failed: {err.value.decode()}")
        self._h = h
        shp = (ctypes.c_longlong * 6)()
        self._lib.CXNIONativeShape(self._h, shp)
        (self.batch_size, self.c, self.h, self.w,
         self.label_width, self.num_inst) = [int(x) for x in shp]

    def before_first(self) -> None:
        assert self._h is not None, "init() must be called first"
        self._lib.CXNIONativeBeforeFirst(self._h)

    def state(self):
        # the shuffle/cursor state lives C++-side with no capture API:
        # raising (instead of the silent {} default) makes the
        # checkpoint path warn that this iterator resumes cold
        raise NotImplementedError(
            "native iterator state lives in C++; resume restarts it cold")

    def set_state(self, st):
        raise NotImplementedError(
            "native iterator state lives in C++; resume restarts it cold")

    def next(self) -> Optional[DataBatch]:
        u8 = bool(self._lib.CXNIONativeIsU8(self._h))
        label = np.empty((self.batch_size, self.label_width), np.float32)
        index = np.empty((self.batch_size,), np.uint64)
        padd = ctypes.c_uint32(0)
        if u8:
            # device-side-normalization path: raw u8 straight through
            # (the trainer applies (x - mean_value) * scale on device)
            data = np.empty((self.batch_size, self.c, self.h, self.w),
                            np.uint8)
            got = self._lib.CXNIONativeNextBatchU8(
                self._h,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ctypes.byref(padd))
        else:
            data = np.empty((self.batch_size, self.c, self.h, self.w),
                            np.float32)
            got = self._lib.CXNIONativeNextBatch(
                self._h,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ctypes.byref(padd))
        if not got:
            err = self._lib.CXNIONativeLastError(self._h)
            if err:
                raise RuntimeError(f"native iterator: {err.decode()}")
            return None
        # without round_batch, trailing padding is replica padding of the
        # tail (C++ side pads with the last instance) — mask it out of
        # training; round_batch wrap rows are real data and train unmasked
        return DataBatch(data=data, label=label,
                         index=index.astype(np.uint32),
                         num_batch_padd=int(padd.value),
                         tail_mask_padd=0 if self._round_batch
                         else int(padd.value))

    def close(self) -> None:
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.CXNIONativeFree(self._h)
            self._h = None

    def __del__(self):
        self.close()
