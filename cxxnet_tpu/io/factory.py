"""Iterator chain factory (reference ``src/io/data.cpp:23-74``).

``iter = mnist|img|imgbin|text`` create base iterators (img/imgbin are
wrapped ``BatchAdapt(Augment(base))`` exactly like the reference; ``text``
yields token-shard documents, io/text.py); ``iter =
threadbuffer|membuffer|attachtxt|packseq`` stack on top (``packseq`` packs
documents into fixed (batch, seqlen) LM rows).  All config keys seen so
far in the section are forwarded to every stage (reference: SetParam on the
whole chain).
"""

from __future__ import annotations

from typing import List, Tuple

from .data import IIterator
from .imbin import ImageBinIterator, ImageIterator
from .iter_mnist import MNISTIterator
from .iter_proc import (AttachTxtIterator, AugmentIterator,
                        BatchAdaptIterator, DenseBufferIterator,
                        ThreadBufferIterator)
from .text import PackedSeqIterator, TextIterator

#: ``iter = <name>`` -> the python stage classes that name instantiates,
#: in wrap order.  The lint registry (analysis/registry.py) harvests each
#: stage's ``config_keys`` from here, so the accepted-key set of an
#: iterator section is derived from the same table the factory builds
#: from.  ``imbin_native`` is listed lazily below (its import pulls
#: ctypes/library loading).
ITER_STAGES = {
    "mnist": (MNISTIterator,),
    "img": (BatchAdaptIterator, AugmentIterator, ImageIterator),
    "imgbin": (BatchAdaptIterator, AugmentIterator, ImageBinIterator),
    "imgbinx": (BatchAdaptIterator, AugmentIterator, ImageBinIterator),
    "threadbuffer": (ThreadBufferIterator,),
    "membuffer": (DenseBufferIterator,),
    "attachtxt": (AttachTxtIterator,),
    "text": (TextIterator,),
    "packseq": (PackedSeqIterator,),
}


def iter_stage_classes(name: str):
    """Stage classes for one ``iter =`` value, or None when unknown."""
    if name == "imbin_native":
        from .native import NativeImageBinIterator
        return (NativeImageBinIterator,)
    return ITER_STAGES.get(name)


def iter_type_names():
    return sorted(ITER_STAGES) + ["imbin_native", "end"]


def create_iterator(cfg: List[Tuple[str, str]]) -> IIterator:
    it: IIterator = None
    pending: List[Tuple[str, str]] = []
    for name, val in cfg:
        if name == "iter":
            if val == "mnist":
                assert it is None, "mnist cannot chain over another iterator"
                it = MNISTIterator()
            elif val == "imgbin" or val == "imgbinx":
                assert it is None, "imgbin cannot chain over another iterator"
                it = BatchAdaptIterator(AugmentIterator(ImageBinIterator()))
                if val == "imgbinx":
                    # the reference's imgbinx adds a decode thread stage
                    # (iter_thread_imbin_x-inl.hpp); overridable by a later
                    # decode_thread_num key
                    it.set_param("decode_thread_num", "2")
            elif val == "imbin_native":
                # C++ loader: decode + normalize + batch assembly off-Python
                from .native import NativeImageBinIterator
                assert it is None, \
                    "imbin_native cannot chain over another iterator"
                it = NativeImageBinIterator()
            elif val == "img":
                assert it is None, "img cannot chain over another iterator"
                it = BatchAdaptIterator(AugmentIterator(ImageIterator()))
            elif val == "text":
                assert it is None, "text cannot chain over another iterator"
                it = TextIterator()
            elif val == "packseq":
                assert it is not None, "must specify input of packseq"
                it = PackedSeqIterator(it)
            elif val == "threadbuffer":
                assert it is not None, "must specify input of threadbuffer"
                it = ThreadBufferIterator(it)
            elif val == "membuffer":
                assert it is not None, "must specify input of membuffer"
                it = DenseBufferIterator(it)
            elif val == "attachtxt":
                assert it is not None, "must specify input of attachtxt"
                it = AttachTxtIterator(it)
            elif val == "end":
                continue
            else:
                raise ValueError(f"unknown iterator type {val!r}")
            for n, v in pending:
                it.set_param(n, v)
            continue
        if it is not None:
            it.set_param(name, val)
        else:
            pending.append((name, val))
    assert it is not None, "must specify iterator by iter=itername"
    return it


def init_iterator(it: IIterator, defcfg: List[Tuple[str, str]]) -> IIterator:
    """Apply global config then Init (reference InitIter)."""
    for n, v in defcfg:
        it.set_param(n, v)
    it.init()
    return it
