"""MNIST idx-ubyte iterator.

Reference: ``src/io/iter_mnist-inl.hpp`` — reads the gzip idx files, scales
pixels by 1/256, optional in-memory shuffle, emits fixed-size batches.
The tail beyond the last full batch is replica-padded and loss-masked
(``tail_mask_padd``) so every instance still trains; ``round_batch = 1``
instead wraps real instances from the epoch start and reports
``num_batch_padd`` (reference batch-adapter parity).
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from ..analysis.schema import K
from ..monitor import log as mlog
from .data import DataBatch, IIterator

_RAND_MAGIC = 27  # distinct fixed seed per subsystem, reference style


class MNISTIterator(IIterator):
    config_keys = (
        K("silent", "int", lo=0, hi=1), K("batch_size", "int", lo=1),
        K("input_flat", "int", lo=0, hi=1),
        K("shuffle", "int", lo=0, hi=1), K("index_offset", "int"),
        K("path_img", "path"), K("path_label", "path"),
        K("round_batch", "int", lo=0, hi=1),
        K("seed_data", "int"),
    )

    def __init__(self):
        self.silent = 0
        self.batch_size = 0
        self.input_flat = 1
        self.shuffle = 0
        self.index_offset = 0
        self.path_img = ""
        self.path_label = ""
        self.round_batch = 0
        self.seed_data = 0
        self.loc = 0

    def set_param(self, name, val):
        if name == "silent":
            self.silent = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "input_flat":
            self.input_flat = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "index_offset":
            self.index_offset = int(val)
        elif name == "path_img":
            self.path_img = val
        elif name == "path_label":
            self.path_label = val
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "seed_data":
            self.seed_data = int(val)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def init(self):
        with self._open(self.path_img) as f:
            magic, n, rows, cols = struct.unpack(">iiii", f.read(16))
            self.img = np.frombuffer(f.read(n * rows * cols), np.uint8) \
                .reshape(n, rows, cols).astype(np.float32) * (1.0 / 256.0)
        with self._open(self.path_label) as f:
            magic, n_lab = struct.unpack(">ii", f.read(8))
            self.labels = np.frombuffer(f.read(n_lab), np.uint8) \
                .astype(np.float32)
        self.inst = np.arange(len(self.labels), dtype=np.uint32) \
            + self.index_offset
        if self.shuffle:
            rnd = np.random.RandomState(_RAND_MAGIC + self.seed_data)
            order = rnd.permutation(len(self.labels))
            self.img = self.img[order]
            self.labels = self.labels[order]
            self.inst = self.inst[order]
        assert self.batch_size > 0, "mnist: batch_size must be set"
        if not self.silent:
            shape = (self.batch_size, 1, 1, self.img.shape[1] * self.img.shape[2]) \
                if self.input_flat else \
                (self.batch_size, 1, self.img.shape[1], self.img.shape[2])
            mlog.info(f"MNISTIterator: load {len(self.img)} images, "
                      f"shuffle={self.shuffle}, shape={shape}")

    def before_first(self):
        self.loc = 0

    def state(self):
        return {"loc": int(self.loc)}

    def set_state(self, st):
        self.loc = int(st.get("loc", 0))

    def _view(self, idx: np.ndarray) -> np.ndarray:
        d = self.img[idx]
        n = len(idx)
        if self.input_flat:
            return d.reshape(n, 1, 1, -1)
        return d.reshape(n, 1, d.shape[1], d.shape[2])

    def next(self):
        n = len(self.labels)
        bs = self.batch_size
        if self.loc + bs <= n:
            idx = np.arange(self.loc, self.loc + bs)
            self.loc += bs
            return DataBatch(data=self._view(idx),
                             label=self.labels[idx].reshape(bs, 1),
                             index=self.inst[idx])
        if self.loc < n:
            remain = n - self.loc
            if self.round_batch:
                # wrap with the epoch's first instances (real data,
                # eval-excluded but trained, reference parity)
                idx = np.concatenate([np.arange(self.loc, n),
                                      np.arange(0, bs - remain)])
                mask_padd = 0
            else:
                # pad with replicas of the last instance, masked out of
                # training (see io/iter_proc.py pad+mask rationale)
                idx = np.concatenate([np.arange(self.loc, n),
                                      np.full(bs - remain, n - 1)])
                mask_padd = bs - remain
            self.loc = n
            return DataBatch(data=self._view(idx),
                             label=self.labels[idx].reshape(bs, 1),
                             index=self.inst[idx],
                             num_batch_padd=bs - remain,
                             tail_mask_padd=mask_padd)
        return None
