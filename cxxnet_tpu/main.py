"""CLI task driver: train / finetune / pred / extract from a config file.

Reference: ``src/cxxnet_main.cpp`` (CXXNetLearnTask).  Usage parity:

    python -m cxxnet_tpu <config.conf> [key=value ...]

Tasks: ``task = train | finetune | pred | pred_raw | extract | serve |
check``; snapshots
``model_dir/%04d.model`` every ``save_model`` rounds; ``continue = 1``
resumes from the newest snapshot (SyncLastestModel, cxxnet_main.cpp:135-157);
``test_io = 1`` runs the loop without Update (I/O benchmark mode, :363-389).
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from . import ckpt as ckptlib
from .analysis.schema import K
from .ckpt import CKPT_KEYS
from .serve import SERVE_KEYS
from .io.device_prefetch import DevicePrefetcher, StagedGroup, item_h2d_sec
from .io.factory import create_iterator, init_iterator
from .monitor import TrainingDiverged, log as mlog
from .monitor.trace import ProfileWindow
from .nnet.trainer import NetTrainer
from .utils.config import parse_config_file, parse_keyval_args

#: keys LearnTask.set_param consumes — the task half of the config
#: surface (the trainer half is nnet/trainer.TRAINER_KEYS).  Harvested
#: by analysis/registry.py; keep in sync with set_param below.
TASK_KEYS = (
    K("print_step", "int", lo=1),
    K("continue", "int", lo=0, hi=1),
    K("save_model", "int", lo=0),
    K("start_counter", "int", lo=0),
    K("model_in", "path"), K("model_dir", "path"),
    K("num_round", "int", lo=0), K("max_round", "int", lo=0),
    K("silent", "int", lo=0, hi=1),
    K("task", "enum", choices=("train", "finetune", "pred", "pred_raw",
                               "extract", "check", "serve")),
    K("dev", "str"),
    K("test_io", "int", lo=0, hi=1),
    K("multi_step", "int", lo=0),
    K("prefetch_device", "int", lo=0),
    K("synth_device_data", "int", lo=0, hi=1),
    K("extract_node_name", "str"),
    K("eval_train", "int", lo=0, hi=1),
    K("prof", "path"),
    K("prof_start_step", "int", lo=-1),
    K("prof_num_steps", "int", lo=0),
    K("prof_every", "int", lo=0,
      help="recurring profiling windows: trace every Nth round"),
    K("sentinel", "int", lo=0, hi=1,
      help="EWMA regression sentinels over step time / comm_share / "
           "HBM high-water (anomaly records need metrics_sink)"),
    K("sentinel_rel", "float", lo=0.01, hi=10.0,
      help="relative deviation vs the EWMA that fires an anomaly "
           "(must be > 0: a zero threshold fires on every observation)"),
    K("sentinel_warmup", "int", lo=1),
    K("sentinel_ring", "int", lo=1,
      help="flight-recorder depth: last K step records dumped on an "
           "anomaly or TrainingDiverged"),
    # goodput ledger (monitor/ledger.py, doc/monitor.md): end-of-run
    # wall accounting, emitted from the task finally so a diverged run
    # still lands it; tools/obsv.py --diff compares two of them
    K("ledger", "int", lo=0, hi=1,
      help="emit the end-of-run goodput ledger record (default 1; "
           "needs metrics_sink, train/finetune tasks only)"),
    K("test_on_server", "int", lo=0, hi=1),
    # OOM pre-flight (analysis/memmodel.py, doc/memory.md): task=check
    # runs the analytic memory model against the target chip's HBM
    K("mem_check", "int", lo=0, hi=1,
      help="task=check: error when the estimated peak HBM exceeds the "
           "target chip's capacity (warn inside mem_margin_pct)"),
    K("mem_margin_pct", "float", lo=0, hi=90,
      help="pre-flight warning margin: warn when the estimate lands "
           "within this % of capacity (default 10)"),
    K("mem_chip", "str",
      help="pre-flight HBM capacity selector (v4/v5e/v5p/v6e or a "
           "full device_kind); defaults to dev= when it names a chip"),
    # SPMD deep lint (analysis/spmdlint.py, doc/check.md): collective-
    # consistency, donation audit, dtype-flow over the traced step
    K("spmd_check", "int", lo=0, hi=1,
      help="task=check: run the SPMD deep lint (default 1; 0 skips the "
           "collective/donation/dtype-flow pass)"),
    # the runtime deliberately tolerates unknown spellings (treated as
    # binary, with a warning) — soft keeps the lint at warn severity
    K("output_format", "enum", choices=("txt", "bin"), soft=True),
    K("dist_coordinator", "str"),
    K("dist_num_proc", "int", lo=1),
    K("dist_proc_rank", "int", lo=0),
    # serving keys (serve/__init__.py declares them next to their
    # consumer, ServeConfig.from_pairs; doc/serve.md) and checkpoint /
    # rollback keys (ckpt/__init__.py; doc/checkpoint.md)
) + SERVE_KEYS + CKPT_KEYS


class LearnTask:
    def __init__(self):
        self.task = "train"
        self.net_type = 0
        self.print_step = 100
        self.continue_training = 0
        self.save_period = 1
        # reference default 0 (cxxnet_main.cpp:27): the pre-training
        # snapshot is 0000.model and rounds 1..num_round then train —
        # starting at 1 would silently train one round fewer
        self.start_counter = 0
        self.name_model_in = "NULL"
        self.name_model_dir = "./"
        self.num_round = 10
        self.max_round = 2147483647
        self.silent = 0
        self.test_io = 0
        self.multi_step = 0
        # device-side input prefetch (doc/io.md): a producer thread stages
        # batches (stack/cast/sharded device_put/input_s2d) this many
        # dispatches ahead of the train loop, so H2D transfer overlaps
        # device compute.  0 = stage synchronously (still off the
        # dispatch timer)
        self.prefetch_device = 2
        self._eval_prefetchers: Optional[list] = None
        self._pred_prefetcher = None
        # diagnostic twin of test_io: test_io=1 isolates the input
        # pipeline (no device work); synth_device_data=1 isolates the
        # device loop (pre-staged on-device batches, no host transfer)
        self.synth_device_data = 0
        self.extract_node_name = ""
        self.prof_dir = ""
        # generalized profiling window (doc/monitor.md): start the trace
        # before global update step prof_start_step and run prof_num_steps
        # dispatches (0 = to round end).  The default -1 keeps the legacy
        # window — the whole round past compilation
        self.prof_start_step = -1
        self.prof_num_steps = 0
        # prof_every = N: recurring low-overhead profiling windows — a
        # fresh trace (and its trace/layer_profile records) every Nth
        # round instead of the single one-shot window (doc/monitor.md)
        self.prof_every = 0
        # regression sentinels + flight recorder (monitor/sentinel.py)
        self.sentinel = 0
        self.sentinel_rel = 0.2
        self.sentinel_warmup = 3
        self.sentinel_ring = 64
        self._sentinel_bank = None
        # goodput ledger (doc/monitor.md): fold the run's own records
        # into an end-of-run wall-accounting record from run()'s finally
        self.ledger = 1
        self._run_t0: Optional[float] = None
        # the sink appends: bytes already in the file at run start are
        # an earlier session's and must not fold into THIS run's ledger
        self._sink_offset = 0
        # fault-tolerant checkpoints (doc/checkpoint.md): ckpt_async=1
        # snapshots at round boundaries into atomic NNNN.ckpt dirs off
        # the training thread; save_opt carries optimizer state (exact
        # resume); ckpt_iter_state carries the train-iterator chain
        # state; ckpt_keep bounds retention; rollback=N auto-restores
        # the last good snapshot on TrainingDiverged and retries
        self.ckpt_async = 0
        self.ckpt_keep = 3
        self.rollback = 0
        self.save_opt = 1
        self.ckpt_iter_state = 1
        self._ckpt_writer = None
        self._ckpt_blocked_sec: dict = {}
        # guards _ckpt_blocked_sec: the train thread writes entries
        # around submit() while _ckpt_done pops them on the writer thread
        self._ckpt_lock = threading.Lock()
        self._resume_iter_state = None
        self._resume_sentinel_state = None
        self._warned_iter_capture = False
        # instruction->scope join, cached like trainer._step_aot_cache:
        # recurring prof_every windows must not re-scan the HLO text
        self._op_scopes_cache = None
        # the mem_profile table (monitor/memory.py) is the executable's
        # static truth — built once per trainer, re-emitted per window
        self._mem_profile_cache = None
        # wall seconds of the first train dispatch (jit trace + compile
        # happen synchronously inside it); None until it ran
        self.compile_sec: Optional[float] = None
        self.test_on_server = 0
        self.name_pred = "pred.txt"
        self.output_format = 1
        # default 1, reference nnet_impl-inl.hpp:22; gates both metric
        # accumulation (NetTrainer) and the train metric line below
        self.eval_train = 1
        self.device = "tpu"
        self.cfg: List[Tuple[str, str]] = []
        self.net: Optional[NetTrainer] = None
        self.itr_train = None
        self.itr_evals = []
        self.eval_names = []
        # racelint: atomic(whole-object swap published by init_data before the serve producer thread starts; the producer only reads)
        self.itr_pred = None

    def set_param(self, name: str, val: str) -> None:
        if val == "default":
            return
        if name == "print_step":
            self.print_step = int(val)
        elif name == "continue":
            self.continue_training = int(val)
        elif name == "save_model":
            self.save_period = int(val)
        elif name == "start_counter":
            self.start_counter = int(val)
        elif name == "model_in":
            self.name_model_in = val
        elif name == "model_dir":
            self.name_model_dir = val
        elif name == "num_round":
            self.num_round = int(val)
        elif name == "max_round":
            self.max_round = int(val)
        elif name == "silent":
            self.silent = int(val)
            mlog.set_silent(self.silent)
        elif name == "task":
            self.task = val
        elif name == "dev":
            self.device = val
        elif name == "test_io":
            self.test_io = int(val)
        elif name == "multi_step":
            self.multi_step = int(val)
        elif name == "prefetch_device":
            self.prefetch_device = int(val)
        elif name == "synth_device_data":
            self.synth_device_data = int(val)
        elif name == "extract_node_name":
            self.extract_node_name = val
        elif name == "eval_train":
            self.eval_train = int(val)
        elif name == "prof":
            self.prof_dir = val
        elif name == "prof_start_step":
            self.prof_start_step = int(val)
        elif name == "prof_num_steps":
            self.prof_num_steps = int(val)
        elif name == "prof_every":
            self.prof_every = int(val)
        elif name == "sentinel":
            self.sentinel = int(val)
        elif name == "sentinel_rel":
            self.sentinel_rel = float(val)
        elif name == "sentinel_warmup":
            self.sentinel_warmup = int(val)
        elif name == "sentinel_ring":
            self.sentinel_ring = int(val)
        elif name == "ledger":
            self.ledger = int(val)
        elif name == "ckpt_async":
            self.ckpt_async = int(val)
        elif name == "ckpt_keep":
            self.ckpt_keep = max(int(val), 1)
        elif name == "rollback":
            self.rollback = int(val)
        elif name == "save_opt":
            self.save_opt = int(val)
        elif name == "ckpt_iter_state":
            self.ckpt_iter_state = int(val)
        elif name == "test_on_server":
            self.test_on_server = int(val)
        elif name == "output_format":
            # Reference (cxxnet_main.cpp:100-102) treats anything non-"txt"
            # as binary; keep that contract but warn on unknown spellings.
            if val not in ("txt", "bin"):
                mlog.warn(f"output_format={val!r} not 'txt'/'bin'; "
                          "treating as binary")
            self.output_format = 1 if val == "txt" else 0
        self.cfg.append((name, val))

    # ----------------------------------------------------------------- init
    def _create_net(self) -> NetTrainer:
        net = NetTrainer()
        for k, v in self.cfg:
            net.set_param(k, v)
        return net

    def _sync_latest_model(self) -> bool:
        """SyncLastestModel (cxxnet_main.cpp:135-157), hardened: scan
        ``model_dir`` for the newest *loadable* snapshot — ``NNNN.ckpt``
        atomic dirs and legacy ``NNNN.model`` files — newest first,
        SKIPPING partial/corrupt ones (a manifest-less or
        checksum-failing dir is what a kill mid-write leaves; the
        previous snapshot is the resume point, and the next save
        overwrites the debris)."""
        cands = [(c, p) for c, p in
                 ckptlib.list_snapshots(self.name_model_dir)
                 if c >= self.start_counter]
        # same finite-params gate as rollback: a rollback that walked
        # past a NaN-poisoned snapshot leaves it on disk (crc-valid,
        # loadable) — a restart must not resume from it either
        return self._restore_newest_valid(
            cands, who="continue",
            reject=self._reject_nonfinite) is not None

    @staticmethod
    def _reject_nonfinite(net):
        """Reject hook for the resume scans: the divergence may predate
        a snapshot, and poisoned params would just diverge again."""
        import jax
        finite = all(bool(np.isfinite(np.asarray(leaf)).all())
                     for leaf in jax.tree.leaves(net.params))
        return None if finite else "carries non-finite params; walking back"

    def _restore_newest_valid(self, cands, who: str, reject=None):
        """Walk ``(counter, path)`` candidates NEWEST-first and restore
        the first loadable one into ``self.net``: partial/corrupt
        ``.ckpt`` dirs (what a kill mid-write leaves) are skipped with a
        warning, torn legacy files are skipped at load, and ``reject``
        — given the loaded trainer, returning a reason string or None —
        lets the rollback path refuse poisoned snapshots.  Shared by
        ``continue = 1`` and rollback so the two resume paths cannot
        drift.  Sets ``start_counter`` past the restored round, stashes
        iterator/sentinel resume state, and returns ``(counter, path)``
        or None."""
        for counter, path in reversed(cands):
            is_ckpt = path.endswith(".ckpt")
            if is_ckpt and ckptlib.validate_snapshot(path) is None:
                # one line per skipped snapshot, bounded candidate list
                mlog.warn(f"{who}: skipping partial/corrupt snapshot "  # disclint: ok(warn-once)
                          f"{path}")
                continue
            net = self._create_net()
            try:
                net.load_model(path, validated=is_ckpt)
            except Exception as e:  # noqa: BLE001 — torn legacy file
                net.metrics.close()
                mlog.warn(f"{who}: snapshot {path} failed to load "  # disclint: ok(warn-once)
                          f"({e}); trying the previous one")
                continue
            why = reject(net) if reject is not None else None
            if why:
                net.metrics.close()
                mlog.warn(f"{who}: snapshot {path} {why}")  # disclint: ok(warn-once)
                continue
            old, self.net = self.net, net
            if old is not None and old is not net:
                old.metrics.close()
            self.start_counter = counter + 1
            self._stash_resume_state(net.loaded_extra)
            return counter, path
        return None

    def _stash_resume_state(self, extra) -> None:
        """Hold a loaded snapshot's iterator / sentinel state until the
        consumers exist (iterators after ``_create_iterators``, the
        sentinel bank inside the train loop)."""
        if not extra:
            return
        if self.ckpt_iter_state:
            self._resume_iter_state = extra.get("iter_state")
        self._resume_sentinel_state = extra.get("sentinel_state")

    def _apply_iter_resume(self) -> None:
        st, self._resume_iter_state = self._resume_iter_state, None
        if st and self.itr_train is not None:
            try:
                self.itr_train.set_state(st)
            except Exception as e:  # noqa: BLE001 — resume best-effort
                mlog.warn(f"iterator state restore failed ({e}); the "
                          "train iterator resumes cold")

    def _maybe_init_distributed(self) -> None:
        """Join the JAX distributed runtime when a coordinator is configured
        (config keys dist_coordinator/dist_num_proc/dist_proc_rank; env vars
        CXN_COORDINATOR/CXN_NUM_PROC/CXN_PROC_RANK override so one config
        file serves every worker, like the reference's dist launcher —
        example/MNIST/mpi.conf, nnet_ps_server.cpp:41-48)."""
        cfg = dict(self.cfg)
        coord = os.environ.get("CXN_COORDINATOR",
                               cfg.get("dist_coordinator", ""))
        if not coord:
            return
        nproc = int(os.environ.get("CXN_NUM_PROC",
                                   cfg.get("dist_num_proc", "1")))
        rank = int(os.environ.get("CXN_PROC_RANK",
                                  cfg.get("dist_proc_rank", "0")))
        from .parallel import mesh as meshlib
        meshlib.init_distributed(coord, nproc, rank)
        # shard the data pipeline by process unless the config did already
        if "dist_num_worker" not in cfg:
            self.set_param("dist_num_worker", str(nproc))
            self.set_param("dist_worker_rank", str(rank))
        mlog.info(f"distributed: rank {rank}/{nproc} via {coord}, "
                  f"{len(__import__('jax').devices())} global devices")

    def init(self) -> None:
        self._maybe_init_distributed()
        if self.task == "train" and self.continue_training:
            if self._sync_latest_model():
                mlog.notice(
                    f"Init: Continue training from round {self.start_counter}")
                self._create_iterators()
                self._apply_iter_resume()
                return
            raise RuntimeError(
                "Init: cannot find models for continue training; "
                "specify model_in instead")
        self.continue_training = 0
        if self.name_model_in == "NULL":
            assert self.task == "train", "must specify model_in if not training"
            self.net = self._create_net()
            self.net.init_model()
        elif self.task == "finetune":
            self.net = self._create_net()
            self.net.init_model()
            self.net.copy_model_from(self.name_model_in)
        else:
            self.net = self._create_net()
            self.net.load_model(self.name_model_in)
            m = re.search(r"(\d+)\.(?:model|ckpt)$", self.name_model_in)
            if m:
                self.start_counter = int(m.group(1)) + 1
        self._create_iterators()

    def _create_iterators(self) -> None:
        """Section scanner (reference CreateIterators, cxxnet_main.cpp:214-264)."""
        if self.synth_device_data:
            return  # device-loop diagnostic: no input pipeline
        flag = 0
        evname = ""
        itcfg: List[Tuple[str, str]] = []
        defcfg: List[Tuple[str, str]] = []
        for name, val in self.cfg:
            if name == "data":
                flag = 1
                continue
            if name == "eval":
                evname = val
                flag = 2
                continue
            if name == "pred":
                flag = 3
                self.name_pred = val
                continue
            if name == "iter" and val == "end":
                assert flag != 0, "wrong configuration file"
                if flag == 1 and self.task != "pred":
                    assert self.itr_train is None, "can only have one data"
                    self.itr_train = create_iterator(itcfg)
                if flag == 2 and self.task != "pred":
                    self.itr_evals.append(create_iterator(itcfg))
                    self.eval_names.append(evname)
                if flag == 3 and self.task in ("pred", "pred_raw",
                                               "extract", "serve"):
                    assert self.itr_pred is None, "can only have one pred data"
                    self.itr_pred = create_iterator(itcfg)
                flag = 0
                itcfg = []
                continue
            (itcfg if flag != 0 else defcfg).append((name, val))
        # input_s2d: emit space-to-depth batches from the host pipeline
        # (the device staging transform is a measured-slow fallback);
        # wrapping happens BEFORE init so a ThreadBufferIterator's
        # producer thread runs the transform in the prefetch overlap
        self.itr_train = self._wrap_s2d(self.itr_train)
        self.itr_evals = [self._wrap_s2d(it) for it in self.itr_evals]
        self.itr_pred = self._wrap_s2d(self.itr_pred)
        for it in ([self.itr_train] if self.itr_train else []) + \
                self.itr_evals + ([self.itr_pred] if self.itr_pred else []):
            init_iterator(it, defcfg)

    def _wrap_s2d(self, it):
        s2d_args = getattr(self.net, "_s2d_args", None) if self.net else None
        if s2d_args is None or it is None:
            return it
        from .io.iter_proc import (DenseBufferIterator, S2DEmitIterator,
                                   ThreadBufferIterator)
        # splice beneath the DEEPEST buffering stage in the chain so the
        # transform runs in the prefetch producer thread (threadbuffer)
        # or once at cache fill (membuffer), not on the consumer path
        deepest = None
        cur = it
        while hasattr(cur, "base") and cur.base is not None:
            if isinstance(cur, (ThreadBufferIterator, DenseBufferIterator)):
                deepest = cur
            cur = cur.base
        if deepest is not None:
            deepest.base = S2DEmitIterator(deepest.base, s2d_args)
            return it
        return S2DEmitIterator(it, s2d_args)

    def _close_prefetchers(self) -> None:
        """Join every device-prefetch producer thread (train src is owned
        by task_train's own finally).  Idempotent — the task methods call
        it from their finally blocks so a mid-round exception
        (TrainingDiverged from ``monitor_nan = fatal``, an iterator
        error) can't leak staging threads past the task, and run() keeps
        it as a backstop for direct task_*() callers."""
        for pf in (self._eval_prefetchers or []) + \
                ([self._pred_prefetcher] if self._pred_prefetcher else []):
            pf.close()
        self._eval_prefetchers = None
        self._pred_prefetcher = None

    def _emit_trace_report(self, prof: ProfileWindow) -> None:
        """Reports from one closed profile window: per-step ``comm_sec``
        / ``overlap_frac`` gauges plus a ``trace`` record (the measured
        collective time the dp_overlap schedule is judged on) and a
        ``layer_profile`` record (per-layer device-time attribution with
        roofline distance, doc/monitor.md).  The window's xplane is
        parsed ONCE and feeds both.  Parse failures must never kill
        training."""
        metrics = self.net.metrics if self.net else None
        if metrics is None:
            return
        tdir = prof.last_window_dir or self.prof_dir
        steps = max(prof.last_window_steps, 1)
        try:
            from .monitor.trace import (comm_report_in, find_xplane,
                                        parse_xspace)
            planes = parse_xspace(find_xplane(tdir))
            rep = comm_report_in(planes, steps=steps)
        except Exception as e:  # noqa: BLE001 — telemetry only
            mlog.warn(f"trace summary of {tdir} failed: {e}")
            return
        metrics.set_gauge("comm_sec", rep["comm_sec"])
        metrics.set_gauge("overlap_frac", rep["overlap_frac"])
        if metrics.active:
            metrics.emit("trace", round=self.start_counter - 1, **rep)
            if self._sentinel_bank is not None:
                self._sentinel_bank.observe_trace(
                    dict(rep, round=self.start_counter - 1))
            self._emit_layer_profile(planes, steps)
            self._emit_mem_profile()

    def _emit_layer_profile(self, planes, steps: int) -> None:
        """Join the window's per-op device times against the stamped
        layer scopes (monitor/attribution.py) and the analytic cost
        model (analysis/costmodel.py); emit one ``layer_profile`` record
        carrying the whole table.  Runs only with an active sink, so
        the one extra AOT compile ``step_hlo_text`` pays (cached per
        trainer) is an explicit observability opt-in."""
        net = self.net
        metrics = net.metrics
        try:
            from .analysis import costmodel
            from .monitor import attribution
            scopes = net.layer_scopes()
            op_scopes = self._op_scopes_cache
            if op_scopes is None:
                hlo = net.step_hlo_text()
                op_scopes = attribution.hlo_op_scopes(hlo, scopes) \
                    if hlo else {}
                self._op_scopes_cache = op_scopes
            kind = net.devices[0].device_kind
            table = attribution.layer_table(
                planes, scopes, op_scopes, steps=steps,
                costs=costmodel.layer_costs(net.net),
                peak_flops=costmodel.peak_flops(kind),
                peak_bw=costmodel.peak_bw(kind))
            metrics.emit("layer_profile", round=self.start_counter - 1,
                         **table)
            if not mlog.is_silent() and table["rows"]:
                top = ", ".join(
                    f"{r['layer']} {r['device_ms']:.3g} ms"
                    for r in table["rows"][:3])
                mlog.info(
                    f"layer_profile: {table['attributed_ms']:.3g} of "
                    f"{table['device_total_ms']:.3g} ms/step attributed "
                    f"({table['coverage'] * 100:.0f}%); top: {top}")
        except Exception as e:  # noqa: BLE001 — telemetry only
            mlog.warn(f"layer attribution failed: {e}")

    def _emit_mem_profile(self) -> None:
        """The memory leg of the observatory (doc/memory.md): join the
        compiled step's buffer liveness (monitor/memory.py) against the
        trainer's placed param/opt trees and the analytic memory model
        (analysis/memmodel.py); emit one ``mem_profile`` record per
        closed profile window.  The HLO parse and the liveness walk are
        cached per trainer — recurring ``prof_every`` windows re-scan
        nothing — and the whole path rides the same cached AOT compile
        ``step_hlo_text`` already paid for layer attribution."""
        net = self.net
        metrics = net.metrics
        try:
            table = self._mem_profile_cache \
                if getattr(self, "_mem_profile_cache", None) is not None \
                else self._build_mem_profile()
            if table is None:
                return
            self._mem_profile_cache = table
            # measured gauges land fresh each window (the cached table
            # is the executable's static truth; the gauges are not)
            gauges = net.memory_gauges()
            table = dict(table, **gauges)
            metrics.emit("mem_profile", round=self.start_counter - 1,
                         **table)
            if not mlog.is_silent() and table["rows"]:
                top = ", ".join(
                    f"{r['layer']} {r['total_bytes'] / 1e6:.2f} MB"
                    for r in table["rows"][:3])
                mlog.info(
                    f"mem_profile: peak live "
                    f"{table['peak_live_bytes'] / 1e6:.2f} MB temps at "
                    f"{table['peak_frac']:.0%} of the step; top: {top}")
            # satellite (doc/monitor.md): on backends without
            # memory_stats() the HBM sentinel can never see a gauge —
            # the executable-derived temp total is its fallback
            # BASELINE.  The cached value is constant per executable
            # (so it cannot fire mid-run by itself); its worth is the
            # series it lands in the sink and the EWMA it seeds, which
            # a RESUMED run's first differing executable is judged
            # against (ckpt carries sentinel state)
            bank = self._sentinel_bank
            if bank is not None and not gauges:
                exec_stats = table.get("exec") or {}
                fb = exec_stats.get("temp_bytes") \
                    or table["peak_live_bytes"]
                if fb:
                    bank.observe_round({"round": self.start_counter - 1,
                                        "hbm_peak_bytes": int(fb)})
        except Exception as e:  # noqa: BLE001 — telemetry only
            mlog.warn(f"memory attribution failed: {e}")

    def _build_mem_profile(self):
        from .analysis import costmodel, memmodel
        from .monitor import memory as memlib
        net = self.net
        hlo = net.step_hlo_text()
        if not hlo:
            return None
        model = memmodel.layer_mem(net)
        table = memlib.mem_table(
            hlo, net.layer_scopes(),
            exec_stats=net.step_memory_stats(),
            param_rows=memmodel.param_rows(net),
            # the per-row model join compares like with like: the
            # measured total is param+opt+live-act, so the transient
            # grad term stays out of the per-row model_bytes
            model_rows={s: {k: v for k, v in r.items()
                            if k != "grad_bytes"}
                        for s, r in model.items()})
        table["model"] = memmodel.totals(net, model)
        cap = costmodel.hbm_bytes(net.devices[0].device_kind)
        if cap:
            table["hbm_capacity_bytes"] = int(cap)
        return table

    # ---------------------------------------------------------------- tasks
    def _ckpt_extra_state(self, capture_iter: bool = True) -> dict:
        """Non-trainer resume state riding in the snapshot: the train
        iterator chain's position/rng state (quiescent at a round
        boundary — the epoch's prefetchers have drained) and the
        sentinel EWMA/ring state.  ``capture_iter = False`` for the
        initial round-0 save: a threadbuffer's init()-primed producer is
        still pulling there, so state() would read racing cursors/rng —
        and a fresh iterator resuming cold IS its round-0 state."""
        extra = {}
        if capture_iter and self.ckpt_iter_state \
                and self.itr_train is not None:
            try:
                extra["iter_state"] = self.itr_train.state()
            except Exception as e:  # noqa: BLE001 — snapshot best-effort
                if not self._warned_iter_capture:
                    self._warned_iter_capture = True
                    mlog.warn(f"iterator state capture failed ({e}); "
                              "snapshots resume the iterator cold")
        if self._sentinel_bank is not None:
            extra["sentinel_state"] = self._sentinel_bank.state()
        return extra

    def _ckpt_done(self, stats: dict) -> None:
        """Writer-thread completion hook: the ``ckpt`` record lands as
        soon as the manifest committed, even while the train loop is
        mid-dispatch."""
        metrics = self.net.metrics
        with self._ckpt_lock:
            blocked = self._ckpt_blocked_sec.pop(stats["counter"], 0.0)
        metrics.counter_inc("ckpt_saves")
        metrics.emit("ckpt", round=stats["counter"], path=stats["path"],
                     async_write=1, shards=stats["shards"],
                     bytes=stats["bytes"],
                     write_sec=round(stats["write_sec"], 4),
                     blocked_sec=round(blocked, 4),
                     pruned=stats["pruned"], keep=self.ckpt_keep)
        mlog.info(f"checkpoint {stats['path']}: {stats['bytes']} bytes "
                  f"in {stats['write_sec']:.3f} sec off-thread "
                  f"(loop blocked {blocked:.3f} sec)")

    def _save_model(self, capture_iter: bool = True) -> None:
        if self._ckpt_writer is not None:
            # a writer failure latched since the last save surfaces at
            # the next round boundary, not silently at process exit
            self._ckpt_writer.poll()
        counter = self.start_counter
        self.start_counter += 1
        if self.save_period == 0 or counter % self.save_period != 0:
            return
        os.makedirs(self.name_model_dir, exist_ok=True)
        extra_state = self._ckpt_extra_state(capture_iter)
        metrics = self.net.metrics
        t0 = time.perf_counter()
        if self.ckpt_async:
            # async atomic snapshot: host pull on this thread (the
            # jitted step donates the device buffers), npz + manifest
            # commit + retention on the writer thread.  submit() blocks
            # only when a previous write is still in flight
            # (bounded-queue backpressure) and re-raises any latched
            # writer failure here, in the train loop
            from .ckpt.writer import AsyncCheckpointWriter
            if self._ckpt_writer is None:
                self._ckpt_writer = AsyncCheckpointWriter(
                    on_done=self._ckpt_done, tracer=metrics.tracer)
            shards, meta = self.net.checkpoint_payload(
                with_opt=bool(self.save_opt), extra_state=extra_state)
            path = ckptlib.snapshot_path(self.name_model_dir, counter)
            # stash the host-pull wall BEFORE submit so the completion
            # hook (writer thread) always finds an entry; fold in the
            # backpressure block after, if the record hasn't landed yet
            pull = time.perf_counter() - t0
            with self._ckpt_lock:
                self._ckpt_blocked_sec[counter] = pull
            block = self._ckpt_writer.submit(
                path, shards, meta, counter=counter, keep=self.ckpt_keep)
            with self._ckpt_lock:
                # the record may already have landed (fast writer): then
                # the entry is gone and its blocked_sec missed the submit
                # block — never re-insert, that entry would leak
                if counter in self._ckpt_blocked_sec:
                    self._ckpt_blocked_sec[counter] = pull + block
            # span: what the TRAIN thread actually paid for this
            # snapshot — the D2H host pull plus bounded-queue
            # backpressure (write_sec - this span is the async win)
            tr = metrics.tracer
            if tr is not None and tr.enabled:
                tr.emit("ckpt_blocked", t0, time.perf_counter(),
                        counter=counter)
            return
        # legacy single-file path, now atomic (tmp + os.replace) and
        # carrying opt state + exact-resume state by default
        path = os.path.join(self.name_model_dir, f"{counter:04d}.model")
        self.net.save_model(path, with_opt_state=bool(self.save_opt),
                            extra_state=extra_state)
        wall = time.perf_counter() - t0
        metrics.counter_inc("ckpt_saves")
        metrics.emit("ckpt", round=counter, path=path, async_write=0,
                     shards=1, bytes=os.path.getsize(path),
                     write_sec=round(wall, 4), blocked_sec=round(wall, 4),
                     pruned=0, keep=self.ckpt_keep)

    def task_train(self) -> None:
        """``task = train``: the train loop under the rollback guard.

        ``rollback = N`` closes the fault-tolerance loop: on
        ``TrainingDiverged`` (the ``monitor_nan = fatal`` guard, or any
        sentinel-confirmed NaN that escalates to it) the task restores
        the newest snapshot whose params are finite, reseeds the rng
        stream past the bad window (``NetTrainer.reseed_rng`` — the
        retried rounds draw different randomness, and later snapshots
        carry the folded key so their own resume stays exact), and
        re-enters the loop, up to N times before re-raising."""
        attempt = 0
        try:
            while True:
                try:
                    self._run_train_loop(initial_save=(attempt == 0))
                    break
                except TrainingDiverged as e:
                    if attempt >= self.rollback \
                            or not self._rollback_restore(e, attempt + 1):
                        raise
                    attempt += 1
            if self._ckpt_writer is not None:
                # drain + close on the success path OUTSIDE the finally:
                # a latched writer failure must fail the run (snapshots
                # silently not landing is the worst outcome)
                w, self._ckpt_writer = self._ckpt_writer, None
                w.close()
        finally:
            if self._ckpt_writer is not None:  # exception path: don't
                w, self._ckpt_writer = self._ckpt_writer, None  # mask
                try:
                    w.close()
                except Exception as ce:  # noqa: BLE001
                    mlog.warn(f"checkpoint writer close failed: {ce}")

    def _rollback_restore(self, exc: BaseException, attempt: int) -> bool:
        """Restore the newest loadable snapshot with all-finite params;
        returns False when none exists (the caller re-raises).  Emits a
        ``rollback`` record and resets ``start_counter`` so the loop
        re-enters at the restored round."""
        died_round = self.start_counter
        if self._ckpt_writer is not None:
            # an in-flight write must commit (or fail) before "newest
            # snapshot" means anything.  A latched writer failure
            # re-raises HERE, before any restore work: per the writer's
            # discipline it must fail the run, and retrying would only
            # hit the same latch at the retry's first _save_model poll
            self._ckpt_writer.drain()
        cands = [(c, p) for c, p in
                 ckptlib.list_snapshots(self.name_model_dir)
                 if c < died_round]
        restored = self._restore_newest_valid(
            cands, who="rollback", reject=self._reject_nonfinite)
        if restored is None:
            mlog.warn(f"rollback: no finite snapshot found in "
                      f"{self.name_model_dir}; re-raising")
            return False
        counter, path = restored
        self.net.reseed_rng(attempt)
        self._apply_iter_resume()
        self.net.metrics.counter_inc("rollbacks")
        self.net.metrics.emit(
            "rollback", retry=attempt, max_retry=self.rollback,
            from_round=died_round, restored_round=counter,
            path=path, reason=f"{type(exc).__name__}: {exc}")
        mlog.result(
            f"rollback {attempt}/{self.rollback}: {type(exc).__name__} "
            f"in round {died_round}; restored {path}, reseeded rng, "
            f"resuming from round {self.start_counter}")
        return True

    def _run_train_loop(self, initial_save: bool = True) -> None:
        start = time.time()
        metrics = self.net.metrics
        if initial_save and self.continue_training == 0 \
                and self.name_model_in == "NULL":
            # round-0 save: the iterator chain is NOT quiescent yet (a
            # threadbuffer's producer primed at init() is mid-pull)
            self._save_model(capture_iter=False)
        if self.synth_device_data:
            self._train_synth_device()
            return
        if self.itr_train is None:
            raise RuntimeError(
                "task=train but the config has no 'data = train' iterator "
                "section; add one (see example/MNIST/MNIST.conf) or use the "
                "wrapper API for in-memory data")
        if self.test_io:
            mlog.notice("start I/O test")
        cc = self.max_round
        rounds_done = 0
        if self.prof_every > 0 and self.prof_start_step >= 0:
            # lint surfaces this at check time too (doc/check.md):
            # a step-pinned one-shot window and a recurring round
            # cadence can't both own the profiler
            mlog.warn("prof_every ignored: prof_start_step pins a "
                      "one-shot step-addressed window")
            self.prof_every = 0
        prof = ProfileWindow(self.prof_dir, self.prof_start_step,
                             self.prof_num_steps, every=self.prof_every)
        if self.sentinel and metrics.active:
            from .monitor.sentinel import SentinelBank
            self._sentinel_bank = SentinelBank(
                metrics, rel=self.sentinel_rel,
                warmup=self.sentinel_warmup, ring=self.sentinel_ring)
            if self._resume_sentinel_state:
                # resumed run continues the pre-kill EWMA baselines
                # instead of re-warming from scratch
                self._sentinel_bank.set_state(self._resume_sentinel_state)
                self._resume_sentinel_state = None
            if not self.net.memory_gauges():
                # the HBM watcher would silently never arm here (no
                # memory_stats() on this backend, e.g. CPU CI) — say so
                # once.  With prof = <dir> the mem_profile path feeds
                # it the compiled step's temp bytes instead: a static
                # baseline series (one value per executable), not a
                # live high-water — it documents the footprint and
                # seeds a resumable EWMA, it cannot catch runtime
                # allocator growth
                mlog.warn(
                    "sentinel: this backend reports no memory_stats(); "
                    "the HBM watcher gets only the executable-derived "
                    "temp-byte baseline from profile windows (set "
                    "prof = <dir>), not a live high-water")
        elif self.sentinel:
            # every sentinel output goes to the sink; armed without one
            # it would only add a per-print-step D2H loss sync (lint
            # surfaces this at check time too — doc/check.md)
            mlog.warn("sentinel=1 without metrics_sink: sentinels "
                      "disarmed")
        bank = self._sentinel_bank
        # legacy window: profile the second round (past compilation) — or
        # the only round when just one will run; prof_start_step >= 0
        # pins the window to an exact global update step instead
        will_run = min(self.num_round - self.start_counter + 1,
                       self.max_round)
        prof_round = 1 if will_run > 1 else 0
        # prof_start_step / prof_num_steps both count DISPATCHES (a
        # multi_step group is one); trainer.sample_counter counts update
        # steps, which diverges from dispatches under grouping
        global_dispatch = 0
        # multi_step > 1 groups K batches into ONE device dispatch
        # (an on-device lax.scan), the TPU equivalent of the
        # reference's ThreadBuffer keeping the GPU queue full
        # (iter_batch_proc-inl.hpp:136-224); train metrics stay exact
        # (outputs come back stacked, one D2H per group)
        # pairtest nets stay on the per-batch path: grouped dispatch
        # would drop their step diagnostics (reference exceedance
        # reporting); monitored nets too (the scan path carries no
        # per-layer norm outputs)
        group_n = self.multi_step if (
            self.multi_step > 1 and self.test_io == 0
            and self.net.update_period == 1
            and not self.net.has_diagnostics
            and not self.net.monitor) else 1
        # staged item source: grouping + np.stack + dtype cast + sharded
        # device_put + input_s2d all happen OFF the dispatch window — on
        # a producer thread running prefetch_device dispatches ahead
        # (the reference's ThreadBuffer moved host decode off the
        # critical path; this moves the H2D transfer too), or inline
        # just before the dispatch timer when prefetch_device = 0
        src = None if self.test_io else DevicePrefetcher(
            self.itr_train, self.net, group_n=group_n,
            depth=self.prefetch_device, metrics=metrics)
        try:
            while self.start_counter <= self.num_round and cc > 0:
                cc -= 1
                mlog.info(f"update round {self.start_counter - 1}")
                prof.maybe_start_round(rounds_done, prof_round)
                round_t0 = time.time()
                sample_counter = 0
                n_round = 0
                t_mark = time.time()
                n_mark = 0
                # host wall split for input-bound detection: time blocked
                # on input (the host iterator, or the staging queue when
                # prefetching) vs time spent dispatching steps vs time
                # staging batches onto the device (h2d; off the critical
                # path when the producer thread runs it)
                iter_wait = dispatch_sec = h2d_total = 0.0
                iter_wait_mark = dispatch_mark = h2d_mark = 0.0
                depth_sum = depth_n = 0
                self.net.start_round(self.start_counter)
                if src is not None:
                    src.before_first()
                else:
                    self.itr_train.before_first()
                while True:
                    t0 = time.perf_counter()
                    first_dispatch = False
                    if src is None:
                        # test_io = 1: host pipeline only, no staging
                        batch = self.itr_train.next()
                        iter_wait_mark += time.perf_counter() - t0
                        if batch is None:
                            break
                        metas = (batch,)
                    else:
                        item = src.next()
                        wall = time.perf_counter() - t0
                        if item is None:
                            break
                        if src.async_:
                            # blocked on the staging queue; the transfer
                            # itself ran on the producer thread (h2d_mark
                            # tracks it leaving the critical path)
                            iter_wait_mark += wall
                            depth_sum += src.last_depth
                            depth_n += 1
                        else:
                            iter_wait_mark += src.last_wait_sec
                        h2d_mark += item_h2d_sec(item)
                        prof.maybe_start_step(global_dispatch)
                        global_dispatch += 1
                        first_dispatch = self.compile_sec is None
                        t0 = time.perf_counter()
                        if isinstance(item, StagedGroup):
                            self._update_group(item)
                            metas = item.meta
                        else:
                            for sb in item:
                                self.net.update(sb)
                            metas = item
                        dt = time.perf_counter() - t0
                        if first_dispatch:
                            # jit traces + compiles synchronously inside
                            # the first dispatch: report it separately and
                            # keep it out of the steady-state examples/sec
                            # window (the old code silently folded it into
                            # the first one)
                            self.compile_sec = dt
                            metrics.emit("compile", compile_sec=round(dt, 3),
                                         round=self.start_counter - 1)
                            mlog.info(f"compile: {dt:.1f} sec (first "
                                      "dispatch, excluded from examples/sec)")
                            t_mark, n_mark = time.time(), 0
                        else:
                            dispatch_mark += dt
                        if prof.after_step():
                            mlog.info("profile trace written to "
                                      f"{prof.last_window_dir}")
                            self._emit_trace_report(prof)
                    for b in metas:
                        sample_counter += 1
                        n_real = b.batch_size - b.num_batch_padd
                        n_round += n_real
                        if not first_dispatch:
                            n_mark += n_real
                        if sample_counter % self.print_step == 0:
                            now = time.time()
                            rate = n_mark / max(now - t_mark, 1e-9)
                            # metrics.active alone: the bank only arms
                            # with an active sink, and if the sink dies
                            # mid-run (emit's OSError guard) this also
                            # stops paying the D2H loss sync for
                            # records nobody will see
                            if metrics.active and self.test_io == 0:
                                loss = getattr(self.net, "_last_loss", None)
                                rec = dict(
                                    round=self.start_counter - 1,
                                    step=sample_counter,
                                    global_step=self.net.sample_counter,
                                    elapsed_sec=round(now - start, 3),
                                    examples_per_sec=round(rate, 1),
                                    iter_wait_sec=round(iter_wait_mark, 4),
                                    dispatch_sec=round(dispatch_mark, 4),
                                    h2d_sec=round(h2d_mark, 4),
                                    staging_depth=round(
                                        depth_sum / depth_n, 2)
                                    if depth_n else 0.0,
                                    loss=None if loss is None
                                    else float(np.asarray(loss)))
                                bub = getattr(self.net,
                                              "pipe_bubble_frac", 0.0)
                                if bub:
                                    # pipelined step: ledger carves the
                                    # fill/drain share out of dispatch
                                    rec["pipe_bubble_frac"] = round(bub, 4)
                                metrics.emit("step", **rec)
                                if bank is not None:
                                    bank.observe_step(rec)
                            t_mark, n_mark = now, 0
                            iter_wait += iter_wait_mark
                            dispatch_sec += dispatch_mark
                            h2d_total += h2d_mark
                            iter_wait_mark = dispatch_mark = h2d_mark = 0.0
                            depth_sum = depth_n = 0
                            mlog.info(
                                f"round {self.start_counter - 1:8d}:"
                                f"[{sample_counter:8d}] {int(now - start)} "
                                f"sec elapsed, {rate:.1f} examples/sec")
                            self._report_diagnostics()
                if prof.round_end():
                    mlog.info("profile trace written to "
                              f"{prof.last_window_dir}")
                    self._emit_trace_report(prof)
                rounds_done += 1
                iter_wait += iter_wait_mark
                dispatch_sec += dispatch_mark
                h2d_total += h2d_mark
                train_wall = time.time() - round_t0
                if self.test_on_server:
                    # per-round replica consistency check (the reference's
                    # test_on_server weight check,
                    # async_updater-inl.hpp:144-154)
                    drift = self.net.check_weight_consistency()
                    if drift != 0.0:
                        raise RuntimeError(
                            f"replica weights diverged (max abs diff {drift})")
                round_metrics = {}
                if self.test_io == 0:
                    line = f"[{self.start_counter}]"
                    # only print the train metric when the trainer actually
                    # accumulated it (eval_train also gates accumulation in
                    # NetTrainer.update — a 0 here would print all-zero
                    # metrics)
                    if self.eval_train:
                        line += self.net.train_eval_line("train")
                        round_metrics.update(
                            self.net.train_metric.values("train"))
                    for it, name in zip(self._eval_sources(),
                                        self.eval_names):
                        line += self.net.evaluate(it, name)
                        round_metrics.update(self.net.metric.values(name))
                    mlog.result(line)
                if metrics.active:
                    rec = dict(round=self.start_counter,
                               wall_sec=round(train_wall, 3),
                               eval_sec=round(
                                   time.time() - round_t0 - train_wall, 3),
                               examples=n_round,
                               examples_per_sec=round(
                                   n_round / max(train_wall, 1e-9), 1),
                               iter_wait_sec=round(iter_wait, 3),
                               dispatch_sec=round(dispatch_sec, 3),
                               h2d_sec=round(h2d_total, 3),
                               train_step_traces=metrics.counters.get(
                                   "train_step_traces", 0),
                               eval_step_traces=metrics.counters.get(
                                   "eval_step_traces", 0),
                               **round_metrics)
                    if rounds_done == 1 and self.compile_sec is not None:
                        rec["compile_sec"] = round(self.compile_sec, 3)
                    bub = getattr(self.net, "pipe_bubble_frac", 0.0)
                    if bub:
                        rec["pipe_bubble_frac"] = round(bub, 4)
                    rec.update(self.net.memory_gauges())
                    metrics.emit("round", **rec)
                    if bank is not None:
                        bank.observe_round(rec)
                self._save_model()
        except BaseException as e:
            # flight recorder: the last K step records — the run's final
            # approach into a TrainingDiverged or any mid-round failure —
            # land in the sink before the raise propagates
            if bank is not None:
                bank.flight_dump(f"{type(e).__name__}: {e}")
            raise
        finally:
            # producer threads must not outlive the task — a mid-round
            # raise (TrainingDiverged, iterator failure) joins the train
            # src AND the per-eval prefetchers here, not at process exit
            if src is not None:
                src.close()
            self._close_prefetchers()
            if prof.active:
                # a window the run never closed: prof_num_steps past the
                # last dispatch, test_io=1, or a mid-round raise landing
                # inside an open window (TrainingDiverged under
                # prof_every) — flush it so the incident window's trace
                # + layer_profile records survive, and the profiler
                # never runs into process exit.  Guarded: a flush
                # failure must not mask the in-flight exception.
                try:
                    prof.stop()
                    mlog.info("profile trace written to "
                              f"{prof.last_window_dir} "
                              "(window truncated at training end)")
                    self._emit_trace_report(prof)
                except Exception as pe:
                    mlog.warn(f"profile window flush failed: {pe}")
        mlog.info(f"\nupdating end, {int(time.time() - start)} sec in all")

    def _train_synth_device(self) -> None:
        """synth_device_data=1: run the REAL config-driven train loop on
        pre-staged device-resident synthetic batches — the device-side twin
        of ``test_io=1``.  Isolates the train-loop dispatch overhead from
        host->device link bandwidth (over a tunneled dev TPU the link would
        dominate any host-fed measurement); compare its examples/sec to
        bench.py's pre-staged number to see the CLI loop's own cost."""
        import jax.numpy as jnp
        net = self.net
        k = max(self.multi_step, 1)
        shape = net.net.node_shapes[0]
        nclass = net.net.node_shapes[net.net.final_node][-1]
        rnd = np.random.RandomState(0)
        datas = jnp.asarray(
            rnd.rand(k, *shape).astype(np.float32)).astype(net.dtype)
        labels = jnp.asarray(
            rnd.randint(0, nclass, (k, shape[0], 1)).astype(np.float32))
        start = time.time()
        while self.start_counter <= self.num_round:
            self.net.start_round(self.start_counter)
            t0 = time.time()
            losses = net.update_many(datas, labels)
            np.asarray(losses)
            dt = time.time() - t0
            mlog.info(f"round {self.start_counter - 1:8d}: synth-device "
                      f"{k} steps, {shape[0] * k / dt:.1f} examples/sec")
            net.metrics.emit(
                "step", round=self.start_counter - 1, step=k,
                global_step=net.sample_counter, synth_device=1,
                examples_per_sec=round(shape[0] * k / dt, 1),
                dispatch_sec=round(dt, 4), iter_wait_sec=0.0,
                loss=float(np.asarray(losses[-1])))
            self._save_model()
        mlog.info(f"\nupdating end, {int(time.time() - start)} sec in all")

    def _update_group(self, staged: StagedGroup) -> None:
        """Dispatch one staged multi-step group (a device-resident
        ``(k, batch, ...)`` stack — the ``np.stack`` + cast + transfer
        already ran off the dispatch window, on the prefetch producer
        thread or inline via ``NetTrainer.stage_group``) as one on-device
        scan, accumulating the train metric from the stacked eval
        outputs."""
        net = self.net
        want_outs = bool(net.eval_train and net.train_metric.evals)
        if want_outs:
            _, outs = net.update_many(staged.datas, staged.labels,
                                      with_outs=True)
            outs = {nid: np.asarray(v) for nid, v in outs.items()}
            for j, m in enumerate(staged.meta):
                net.accumulate_train_metric(
                    {nid: outs[nid][j] for nid in outs}, m.label)
        else:
            net.update_many(staged.datas, staged.labels)

    def _eval_sources(self):
        """Eval iterators, wrapped with device prefetchers (grouped to
        ``eval_group``, staged ``prefetch_device`` dispatches ahead) when
        prefetching is on; created once and reused every round."""
        if self.prefetch_device <= 0 or self.net is None:
            return self.itr_evals
        if self._eval_prefetchers is None:
            self._eval_prefetchers = [
                DevicePrefetcher(it, self.net,
                                 group_n=self.net.eval_group,
                                 depth=self.prefetch_device,
                                 metrics=self.net.metrics, for_eval=True)
                for it in self.itr_evals]
        return self._eval_prefetchers

    def _pred_source(self):
        """The pred iterator, staged one batch per item ahead of the
        inference loop when prefetching is on."""
        if self.prefetch_device <= 0 or self.itr_pred is None:
            return self.itr_pred
        if self._pred_prefetcher is None:
            self._pred_prefetcher = DevicePrefetcher(
                self.itr_pred, self.net, group_n=1,
                depth=self.prefetch_device, metrics=self.net.metrics,
                for_eval=True)
        return self._pred_prefetcher

    def _report_diagnostics(self) -> None:
        """Print step diagnostics (pairtest fwd/bwd/weight relative errors),
        flagging values over the reference's 1e-5 threshold the way the
        reference prints exceedances to stderr
        (pairtest_layer-inl.hpp:190-196)."""
        diags = getattr(self.net, "_last_diags", None)
        if not diags:
            return
        from .layers.pairtest import PAIRTEST_RTOL
        parts, bad = [], []
        for k in sorted(diags):
            v = float(np.asarray(diags[k]))
            parts.append(f"{k}={v:.3g}")
            if k.endswith("_rel_err") and not v <= PAIRTEST_RTOL:
                bad.append(f"{k}: err={v:g} exceeds {PAIRTEST_RTOL:g}")
        mlog.info("diag: " + " ".join(parts))
        for b in bad:  # one line per exceeded pairtest diag, bounded
            mlog.warn(b)  # disclint: ok(warn-once)

    def task_check(self) -> int:
        """``task = check``: static config lint + traced-graph lint.

        Runs in seconds with no device work and no data files: the
        config lint walks the declared-key registry, the jaxpr lint
        abstract-traces the configured step on CPU (skipped when the
        config has no netconfig block, e.g. pred-from-checkpoint).
        Exit code 1 iff any error-severity finding — a typo'd key fails
        the run *before* a compile-and-train cycle is spent on it."""
        from .analysis import run_check
        path = getattr(self, "_conf_path", "")
        findings, code = run_check(self.cfg, path=path, trace=True)
        counts = {"error": 0, "warn": 0, "info": 0}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
            emit = mlog.result if f.severity in ("error", "warn") \
                else mlog.info
            emit("check: " + f.format())
        mlog.result(
            f"check: {path or '<config>'}: {counts['error']} error(s), "
            f"{counts['warn']} warning(s), {counts['info']} info")
        # `check` record to the JSONL metrics sink (doc/monitor.md) so
        # CI lint results land in the same stream as train telemetry
        from .monitor.metrics import MetricsRegistry
        reg = MetricsRegistry()
        for k, v in self.cfg:
            if k == "metrics_sink":
                reg.configure_sink(v)
        reg.emit("check", config=path, n_error=counts["error"],
                 n_warn=counts["warn"], n_info=counts["info"],
                 findings=[f.to_dict() for f in findings])
        reg.close()
        return code

    def _observe_latency(self, op: str, sec: float) -> None:
        """Per-batch inference latency into the registry histogram —
        the p50/p95/p99 the serving path (ROADMAP item 1) is judged
        on."""
        self.net.metrics.observe(f"{op}_latency_sec", sec)

    def _emit_latency_record(self, op: str) -> None:
        """One ``latency`` record per pred/extract task: count + mean +
        percentiles of the per-batch dispatch+D2H wall (doc/monitor.md)."""
        metrics = self.net.metrics
        h = metrics.histograms.get(f"{op}_latency_sec")
        if h is None or not h.count:
            return
        s = h.summary()
        metrics.emit("latency", op=op, count=int(s["count"]),
                     **{k: round(s[k] * 1e3, 3)
                        for k in ("mean", "min", "max",
                                  "p50", "p95", "p99")},
                     unit="ms")

    def task_predict(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a pred iterator to generate predictions"
        mlog.notice("start predicting...")
        src = self._pred_source()
        try:
            # disclint: ok(atomic-write) — streamed product rows
            with open(self.name_pred, "w") as fo:
                src.before_first()
                while True:
                    batch = src.next()
                    if batch is None:
                        break
                    t0 = time.perf_counter()
                    pred = self.net.predict(batch)
                    self._observe_latency("pred",
                                          time.perf_counter() - t0)
                    for v in pred:
                        fo.write(f"{v:g}\n")
            self._emit_latency_record("pred")
        finally:
            self._close_prefetchers()
        mlog.notice(f"finished prediction, write into {self.name_pred}")

    def task_predict_raw(self) -> None:
        """task=pred_raw: write full output rows (e.g. softmax probabilities)
        space-separated, one instance per line (reference
        cxxnet_main.cpp TaskPredictRaw)."""
        assert self.itr_pred is not None, \
            "must specify a pred iterator to generate predictions"
        mlog.notice("start predicting raw scores...")
        src = self._pred_source()
        try:
            # disclint: ok(atomic-write) — streamed product rows
            with open(self.name_pred, "w") as fo:
                src.before_first()
                while True:
                    batch = src.next()
                    if batch is None:
                        break
                    t0 = time.perf_counter()
                    out = self.net.predict_raw(batch)
                    self._observe_latency("pred",
                                          time.perf_counter() - t0)
                    for row in out:
                        fo.write(" ".join(f"{v:g}" for v in row) + "\n")
            self._emit_latency_record("pred")
        finally:
            self._close_prefetchers()
        mlog.notice(f"finished prediction, write into {self.name_pred}")

    def task_extract(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a pred iterator for feature extraction"
        node = self.extract_node_name
        assert node, "must set extract_node_name"
        mlog.notice(f"start extracting feature from node {node} ...")
        binary = self.output_format == 0
        src = self._pred_source()
        try:
            with open(self.name_pred, "wb" if binary else "w") as fo:
                src.before_first()
                wrote_meta = False
                while True:
                    batch = src.next()
                    if batch is None:
                        break
                    t0 = time.perf_counter()
                    feat = self.net.extract_feature(batch, node)
                    self._observe_latency("extract",
                                          time.perf_counter() - t0)
                    if not wrote_meta:
                        with open(self.name_pred + ".meta", "w") as fm:  # disclint: ok(atomic-write)
                            fm.write(f"{feat.shape[1]}\n")
                        wrote_meta = True
                    if binary:
                        # raw little-endian float32 rows (reference
                        # cxxnet_main.cpp:316 fwrite path)
                        fo.write(np.ascontiguousarray(
                            feat, dtype="<f4").tobytes())
                    else:
                        for row in feat:
                            fo.write(" ".join(f"{v:g}" for v in row) + "\n")
            self._emit_latency_record("extract")
        finally:
            self._close_prefetchers()
        mlog.notice(f"finished extraction, write into {self.name_pred}")

    def task_serve_gen(self, cfg) -> None:
        """``task = serve`` + ``serve_gen = 1``: autoregressive
        generation through the KV-cache incremental-decode engine with
        token-level continuous batching (serve/decode.py, doc/serve.md
        "Incremental decode").  Each valid pred-iterator row's leading
        ``serve_gen_prompt`` token ids become one generation request;
        ``serve_clients`` threads submit them concurrently and the step
        scheduler keeps the ``decode_slots`` batch full.  Generated ids
        land in ``name_pred`` (space-separated per request); the run
        emits per-token + per-request ``latency`` records and one
        ``serve_gen`` record (tokens/sec, occupancy histogram, retrace
        count — the telemetry ``bench.py --lm-serve`` sweeps)."""
        from .serve.host import GenModel, ModelHost, load_draft_trainer
        metrics = self.net.metrics
        draft = None
        if cfg.spec_k >= 1 and not cfg.draft_model:
            raise ValueError(
                f"spec_k = {cfg.spec_k} without serve_draft_model: "
                "speculation needs a draft snapshot (doc/serve.md)")
        if cfg.draft_model:
            if cfg.spec_k >= 1:
                mlog.notice(
                    f"serve: loading draft model {cfg.draft_model} "
                    f"(speculative decoding, spec_k = {cfg.spec_k})")
                draft = load_draft_trainer(self.cfg, cfg.draft_model)
            else:
                mlog.warn("serve: serve_draft_model set but spec_k = 0 "
                          "— speculation stays off")
        gm = GenModel(self.net, cfg, draft_trainer=draft,
                      metrics=metrics)
        # admin plane (serve/admin.py): same lifecycle as task_serve —
        # endpoint up before warmup (503 /readyz through compilation),
        # ready only once both decode executables are pinned.  The
        # generation path has no sentinel reporter, so /statusz shows
        # live scheduler counters without a last-window row and the
        # SLO keys ride only the classic serve path (doc/serve.md)
        host = ModelHost()
        host.attach(gm, warmup=False)
        admin = None
        if cfg.admin_port:
            import dataclasses as _dc
            admin = host.start_admin(metrics, port=cfg.admin_port,
                                     config=_dc.asdict(cfg))
        n_exec = 2 + len(gm.engine.block_widths) \
            + (2 if gm.draft is not None else 0)
        mlog.notice(
            f"serve: warming decode engine ({cfg.slots} slot(s), "
            f"max_seqlen {gm.engine.max_seqlen}, {n_exec} "
            "executables) ...")
        gm.warmup()
        mlog.info(f"serve: decode warmup compiled in "
                  f"{gm.engine.warmup_sec:.1f} sec")
        if not host.mark_ready():
            mlog.warn("serve: host failed the ready admission check")
        footprint = gm.footprint()
        if footprint:
            metrics.set_gauge("serve_footprint_bytes",
                              footprint["total_bytes"])
            mlog.info(
                f"serve: decode footprint "
                f"{footprint['total_bytes'] / 1e6:.1f} MB/device "
                f"(KV cache {footprint['kv_cache_bytes'] / 1e6:.2f} MB "
                f"over {cfg.slots} slot(s))")
        import queue as _queue
        import threading
        results: dict = {}
        errors: List[BaseException] = []
        abort = threading.Event()
        work: "_queue.Queue" = _queue.Queue(maxsize=cfg.queue_depth)
        _DONE = object()
        n_total = [0]

        def _put(item) -> bool:
            while not abort.is_set():
                try:
                    work.put(item, timeout=0.05)
                    return True
                except _queue.Full:
                    continue
            return False

        def producer():
            try:
                self.itr_pred.before_first()
                idx = 0
                while True:
                    batch = self.itr_pred.next()
                    if batch is None:
                        break
                    valid = np.array(
                        batch.data[:batch.batch_size
                                   - batch.num_batch_padd], np.float32)
                    rows = valid.reshape(valid.shape[0], -1)
                    for i in range(rows.shape[0]):
                        prompt = rows[i, :cfg.gen_prompt].astype(np.int32)
                        if not _put((idx, prompt)):
                            return
                        idx += 1
                n_total[0] = idx
            except BaseException as e:  # noqa: BLE001 — reported below
                errors.append(e)
                abort.set()
            finally:
                for _ in range(cfg.clients):
                    if not _put(_DONE):
                        return

        def client():
            while True:
                try:
                    item = work.get(timeout=0.05)
                except _queue.Empty:
                    if abort.is_set():
                        return
                    continue
                if item is _DONE:
                    return
                i, prompt = item
                try:
                    results[i] = gm.generate(prompt)
                except BaseException as e:  # noqa: BLE001 — reported
                    errors.append(e)
                    abort.set()
                    return

        mlog.notice(f"serve: streaming generation over {cfg.clients} "
                    "client thread(s)")
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, daemon=True,
                                    name=f"cxxnet-serve-gen-{j}")
                   for j in range(cfg.clients)]
        prod = threading.Thread(target=producer, daemon=True,
                                name="cxxnet-serve-gen-producer")
        try:
            prod.start()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            prod.join()
            dur = time.perf_counter() - t0
            if errors:
                raise errors[0]
            # disclint: ok(atomic-write) — streamed product rows
            with open(self.name_pred, "w") as fo:
                for i in range(n_total[0]):
                    fo.write(" ".join(str(t) for t in results[i]) + "\n")
            self._emit_latency_record("token")
            self._emit_latency_record("gen")
            metrics.set_gauge("serve_retraces", gm.retraces)
            stats = gm.scheduler.stats()
            tps = stats["tokens"] / max(dur, 1e-9)
            if metrics.active:
                metrics.emit(
                    "serve_gen", model=gm.name,
                    duration_sec=round(dur, 3),
                    tokens_per_sec=round(tps, 1),
                    slots=cfg.slots, max_seqlen=gm.engine.max_seqlen,
                    gen_tokens=cfg.gen_tokens, clients=cfg.clients,
                    sample=cfg.gen_sample, retraces=gm.retraces,
                    **stats,
                    **({"footprint": footprint} if footprint else {}))
            if gm.retraces:
                mlog.warn(f"serve: {gm.retraces} decode retrace(s) past "
                          "warmup — a shape escaped the pinned "
                          "executable set (engine bug)")
            spec_note = (
                f", acceptance {stats['acceptance_rate']:.0%} over "
                f"{stats['verify_calls']} verify dispatch(es)"
                if "acceptance_rate" in stats else "")
            mlog.result(
                f"serve: generated {stats['tokens']} tokens for "
                f"{n_total[0]} requests in {dur:.2f} sec "
                f"({tps:.1f} tok/s, mean occupancy "
                f"{stats['mean_occupancy']}, "
                f"{stats['batching']} batching{spec_note}), "
                f"retraces {gm.retraces}")
        finally:
            host.close()   # not-ready first, scheduler drain, admin join
        mlog.notice(f"finished serving, wrote {self.name_pred}")

    def task_serve(self) -> None:
        """``task = serve``: host the loaded model behind the dynamic
        micro-batching predict engine and replay the ``pred`` iterator
        as a concurrent request stream — ``serve_clients`` threads each
        submitting single-row requests, the batcher coalescing them into
        shape-bucket dispatches (doc/serve.md).  Predictions land in
        ``name_pred`` exactly like ``task = pred``; the run emits the
        serving telemetry the observatory reads (one ``latency`` record
        with p50/p95/p99, a ``serve`` record with QPS / batch-size
        histogram / queue-depth stats, and the retrace gauge).
        ``serve_gen = 1`` routes to :meth:`task_serve_gen` — KV-cache
        incremental decode for LM netconfigs."""
        assert self.itr_pred is not None, (
            "task=serve requires a 'pred = <out>' iterator section "
            "(the request stream)")
        from .serve import ServeConfig
        from .serve.host import ServeModel
        cfg = ServeConfig.from_pairs(self.cfg)
        if cfg.gen:
            return self.task_serve_gen(cfg)
        metrics = self.net.metrics
        sm = ServeModel(self.net, cfg, metrics=metrics)
        # live control plane (serve/admin.py, doc/serve.md "Operating a
        # serve host"): the host carries the ready lifecycle and owns
        # the admin endpoint, which starts BEFORE warmup so /readyz
        # reads 503 while executables compile — the hot-swap admission
        # window a poller must see as not-yet-ready
        from .serve.host import ModelHost
        host = ModelHost()
        host.attach(sm, warmup=False)
        admin = None
        if cfg.admin_port:
            import dataclasses as _dc
            admin = host.start_admin(metrics, port=cfg.admin_port,
                                     config=_dc.asdict(cfg))
        mlog.notice(
            f"serve: warming {len(cfg.shapes)} shape bucket(s) "
            f"{list(cfg.shapes)}, dtype={cfg.dtype} ...")
        sm.warmup()
        mlog.info(f"serve: warmup compiled in {sm.engine.warmup_sec:.1f} "
                  "sec")
        # per-model executable footprint (doc/memory.md): what this
        # model costs the device pool resident — the serve record
        # carries it so a multi-model host can pack against capacity
        # instead of packing blind
        footprint = sm.footprint()
        if footprint:
            metrics.set_gauge("serve_footprint_bytes",
                              footprint["total_bytes"])
            mlog.info(
                f"serve: model footprint "
                f"{footprint['total_bytes'] / 1e6:.1f} MB/device "
                f"(weights {footprint['weight_bytes'] / 1e6:.1f} MB + "
                f"{footprint['buckets']} bucket executable(s))")
        # quantization pairtest on real request data (doc/serve.md):
        # the measured side of the declared SERVE_TOL envelope, run on
        # the first serve_calib batches before serving starts
        if cfg.dtype != "f32" and cfg.calib > 0:
            calib_rows: List[np.ndarray] = []
            self.itr_pred.before_first()
            while len(calib_rows) < cfg.calib:
                batch = self.itr_pred.next()
                if batch is None:
                    break
                calib_rows.append(np.array(
                    batch.data[:batch.batch_size - batch.num_batch_padd],
                    np.float32))
            if calib_rows:
                err = max(sm.engine.pairtest(r) for r in calib_rows)
                metrics.set_gauge("serve_quant_rel_err", err)
                from .serve.engine import SERVE_TOL
                mlog.result(
                    f"serve: {cfg.dtype} pairtest vs f32 on "
                    f"{len(calib_rows)} calibration batch(es): max rel "
                    f"err {err:.3g} (envelope {SERVE_TOL[cfg.dtype]:g})")
        # serve-side regression sentinels (doc/serve.md): a reporter
        # thread samples the batcher's window stats every
        # serve_sentinel_window seconds, emits one serve_window record,
        # and feeds the SentinelBank's serve watchers (p99 rise / QPS
        # drop / queue-depth rise) — the serving-regression signal the
        # hot-swap/rollback machinery (ROADMAP item 4) consumes
        bank = None
        sentinel_stop = None
        sentinel_thread = None
        if cfg.sentinel:
            if not metrics.active:
                mlog.warn("serve_sentinel = 1 without an active "
                          "metrics_sink: serve_window/anomaly records "
                          "have nowhere to land; sentinels disarmed")
            else:
                from .monitor.sentinel import SentinelBank
                bank = SentinelBank(metrics, rel=self.sentinel_rel,
                                    warmup=self.sentinel_warmup,
                                    ring=self.sentinel_ring)
                sm.batcher.track_window = True
        # SLO burn-rate alerting (monitor/slo.py) + anomaly-triggered
        # flight capture (serve/admin.py) ride the sentinel reporter's
        # serve_window stream: the batcher counts per-window budget
        # violations, the tracker evaluates fast/slow burn windows,
        # and either a burn or a sentinel anomaly arms ONE flight —
        # trace_sample boosted for the next serve_flight_requests
        # requests, then a serve_flight record with the window ring
        # and the captured trace_id range
        slo = None
        flight = None
        if bank is not None:
            from .serve.admin import FlightCapture
            flight = FlightCapture(
                metrics, lambda: sm.batcher.n_requests, model=sm.name,
                boost=cfg.flight_boost, requests=cfg.flight_requests,
                stats_fn=sm.batcher.stats)
            bank.on_anomaly = lambda hit: flight.trigger(
                f"anomaly: {hit['metric']} {hit['direction']} "
                f"{hit['rel_dev']:+.0%}")
            if cfg.slo_p99_ms > 0.0:
                from .monitor.slo import SloSpec, SloTracker
                sm.batcher.slo_ms = cfg.slo_p99_ms
                slo = SloTracker(
                    SloSpec(p99_ms=cfg.slo_p99_ms, avail=cfg.slo_avail,
                            fast_sec=cfg.slo_fast_sec,
                            slow_sec=cfg.slo_slow_sec,
                            fast_burn=cfg.slo_fast_burn,
                            slow_burn=cfg.slo_slow_burn),
                    cfg.sentinel_window, metrics=metrics,
                    model=sm.name,
                    on_burn=lambda rec: flight.trigger(
                        f"slo: {rec['tier']} burn {rec['burn']:.1f} "
                        f">= {rec['threshold']:g}"))
        elif cfg.slo_p99_ms > 0.0:
            mlog.warn("serve_slo_p99_ms without serve_sentinel = 1 "
                      "(and an active metrics_sink): the SLO evaluates "
                      "over the sentinel reporter's serve_window "
                      "stream; targets ignored")
        if admin is not None:
            admin.slo = slo
            admin.flight = flight
            # even without sentinels, the reporter feeds /statusz its
            # last-window QPS/p99 — scraping needs the window stream
            sm.batcher.track_window = True
        # stream the request iterator: each VALID row of each pred batch
        # becomes one single-row request (round_batch padding excluded,
        # like predict_raw) fed through a BOUNDED work queue — the
        # batcher, not the file layout, decides the dispatch batching,
        # and host memory stays O(queue), not O(dataset) (task=pred's
        # streaming discipline)
        mlog.notice(f"serve: streaming requests over {cfg.clients} "
                    "client thread(s)")
        import queue as _queue
        import threading
        results: dict = {}          # idx -> raw output rows
        errors: List[BaseException] = []
        abort = threading.Event()
        work: "_queue.Queue" = _queue.Queue(
            maxsize=max(cfg.queue_depth, 2 * cfg.max_batch))
        _DONE = object()
        n_total = [0]

        def _put(item) -> bool:
            while not abort.is_set():
                try:
                    work.put(item, timeout=0.05)
                    return True
                except _queue.Full:
                    continue
            return False

        def producer():
            try:
                self.itr_pred.before_first()
                idx = 0
                while True:
                    batch = self.itr_pred.next()
                    if batch is None:
                        break
                    valid = np.array(
                        batch.data[:batch.batch_size
                                   - batch.num_batch_padd], np.float32)
                    for i in range(valid.shape[0]):
                        if not _put((idx, valid[i:i + 1])):
                            return
                        idx += 1
                n_total[0] = idx
            except BaseException as e:  # noqa: BLE001 — reported below
                errors.append(e)
                abort.set()
            finally:
                for _ in range(cfg.clients):
                    if not _put(_DONE):
                        return

        def client():
            while True:
                try:
                    item = work.get(timeout=0.05)
                except _queue.Empty:
                    if abort.is_set():
                        return
                    continue
                if item is _DONE:
                    return
                i, row = item
                try:
                    results[i] = sm.predict(row)
                except BaseException as e:  # noqa: BLE001 — reported below
                    errors.append(e)
                    abort.set()
                    return

        def reporter(stop_evt):
            win = 0
            last_t = time.perf_counter()

            def tick():
                nonlocal win, last_t
                ws = sm.batcher.window_stats()
                now = time.perf_counter()
                # qps over the ACTUAL elapsed window, not the nominal
                # one: the tail tick at stop covers a partial window,
                # and dividing by the full width would deflate qps and
                # fire a spurious drop anomaly on every clean shutdown
                dt, last_t = max(now - last_t, 1e-6), now
                win += 1
                rec = {"model": sm.name, "window": win,
                       "window_sec": round(dt, 3),
                       "requests": ws["requests"],
                       "qps": round(ws["requests"] / dt, 2),
                       "queue_depth": ws["queue_depth"]}
                if "viol" in ws:
                    rec["viol"] = ws["viol"]
                for k in ("p50_ms", "p95_ms", "p99_ms"):
                    if k in ws:
                        rec[k] = ws[k]
                metrics.emit("serve_window", **rec)
                # the admin plane caches the window for /statusz (and
                # the flight ring) via whole-object swaps — the scrape
                # path reads it without ever touching this thread's
                # locks
                if admin is not None:
                    admin.note_window(sm.name, rec)
                elif flight is not None:
                    flight.note_window(rec)
                # every window feeds the bank: an idle one (requests=0,
                # so qps/p99 are falsy and skipped inside observe_serve)
                # still drives the queue-depth watcher — a dispatcher
                # stall grows the queue while NOTHING completes, the
                # exact window the depth sentinel exists for
                if bank is not None:
                    bank.observe_serve(rec)
                if slo is not None:
                    slo.observe(rec)
                if flight is not None:
                    flight.tick()

            try:
                while not stop_evt.wait(cfg.sentinel_window):
                    tick()
                # drain the tail window at stop so a run shorter than
                # one window still lands its serving stats
                tick()
            except BaseException as e:  # noqa: BLE001 — must surface
                # telemetry must not kill serving, but a silently dead
                # sentinel is worse than none (thread-exc contract)
                mlog.warn(f"serve sentinel reporter died: {e!r}; "
                          "serve_window records stop here")

        # admission: every executable pinned, calibration done, zero
        # retraces — /readyz flips 200 here and a poller may now route
        if not host.mark_ready():
            mlog.warn("serve: host failed the ready admission check")
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, daemon=True,
                                    name=f"cxxnet-serve-client-{j}")
                   for j in range(cfg.clients)]
        prod = threading.Thread(target=producer, daemon=True,
                                name="cxxnet-serve-producer")
        if bank is not None or admin is not None:
            # the reporter drives sentinels AND the admin plane's
            # last-window cache; either consumer starts it
            sentinel_stop = threading.Event()
            sentinel_thread = threading.Thread(
                target=reporter, args=(sentinel_stop,), daemon=True,
                name="cxxnet-serve-sentinel")
            sentinel_thread.start()
        try:
            prod.start()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            prod.join()
            dur = time.perf_counter() - t0
            if errors:
                if bank is not None:
                    bank.flight_dump("serve aborted: " + repr(errors[0]))
                raise errors[0]
            # disclint: ok(atomic-write) — streamed product rows
            with open(self.name_pred, "w") as fo:
                for i in range(n_total[0]):
                    row = results[i][0]
                    v = float(row.argmax()) if row.shape[0] > 1 \
                        else float(row[0])
                    fo.write(f"{v:g}\n")
            self._emit_latency_record("serve")
            metrics.set_gauge("serve_retraces", sm.retraces)
            stats = sm.batcher.stats()
            qps = n_total[0] / max(dur, 1e-9)
            if metrics.active:
                metrics.emit(
                    "serve", model=sm.name, duration_sec=round(dur, 3),
                    qps=round(qps, 1), dtype=cfg.dtype,
                    shapes=list(cfg.shapes), clients=cfg.clients,
                    retraces=sm.retraces,
                    **stats,
                    **({"footprint": footprint} if footprint else {}),
                    **({"quant_rel_err": metrics.gauges[
                        "serve_quant_rel_err"]}
                       if "serve_quant_rel_err" in metrics.gauges else {}))
            if sm.retraces:
                mlog.warn(f"serve: {sm.retraces} retrace(s) past warmup "
                          "— a request shape escaped the declared "
                          "buckets (serve_shapes)")
            mlog.result(
                f"serve: {n_total[0]} requests in {dur:.2f} sec "
                f"({qps:.1f} req/s), {stats['batches']} dispatches "
                f"(mean batch {stats['mean_batch']}), retraces "
                f"{sm.retraces}")
            if bank is not None and bank.anomalies:
                mlog.warn(f"serve: {len(bank.anomalies)} sentinel "
                          "anomaly(ies) — see the anomaly records "
                          "(tools/obsv.py)")
        finally:
            if sentinel_stop is not None:
                sentinel_stop.set()
                sentinel_thread.join()
            # host.close() flips /readyz to 503 BEFORE the batcher
            # drains, then joins the admin endpoint last
            host.close()
        mlog.notice(f"finished serving, wrote {self.name_pred}")

    def _emit_ledger(self) -> None:
        """End-of-run goodput ledger (monitor/ledger.py): re-read the
        run's own sink file (flushed per record, so everything the run
        emitted — including a TrainingDiverged flight dump — is on
        disk) and fold it into one ``ledger`` record.  Called from
        run()'s finally BEFORE the sink closes, so a diverged run still
        lands its ledger; the same fold recomputes post-hoc in
        ``tools/obsv.py`` for historical JSONLs that lack one."""
        if not self.ledger or self.task not in ("train", "finetune"):
            return
        net = self.net
        if net is None or not net.metrics.active or self._run_t0 is None:
            return
        try:
            from .monitor import ledger as ledgerlib
            recs = ledgerlib.load_records(net.metrics.sink.path,
                                          who="ledger",
                                          offset=self._sink_offset)
            led = ledgerlib.build_ledger(
                recs, wall_sec=time.perf_counter() - self._run_t0)
            if led is None:
                return
            net.metrics.emit("ledger", **led)
            mlog.info("ledger: " + ledgerlib.format_ledger(led))
        except Exception as e:  # noqa: BLE001 — telemetry only
            mlog.warn(f"ledger emit failed: {e}")

    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            mlog.notice("Usage: python -m cxxnet_tpu <config> [key=value ...]")
            return 0
        # ledger wall starts here: init, iterator construction, and
        # compile are all part of the run the ledger accounts for
        self._run_t0 = time.perf_counter()
        for k, v in parse_config_file(argv[0]):
            self.set_param(k, v)
        for k, v in parse_keyval_args(argv[1:]):
            self.set_param(k, v)
        self._conf_path = argv[0]
        # anchor the ledger at the sink's current size: the JSONL sink
        # appends, so a reused path still carries earlier sessions —
        # even ones killed before their own ledger record could bound
        # them (build_ledger's last-ledger slice covers the clean case)
        spec = dict(self.cfg).get("metrics_sink", "")
        if spec.startswith("jsonl:"):
            sink_path = spec[len("jsonl:"):]
            try:
                self._sink_offset = os.path.getsize(sink_path)
            except OSError:
                self._sink_offset = 0
        if self.task == "check":
            # lint-only: no iterators, no device, no data files
            return self.task_check()
        try:
            self.init()
            mlog.info("initializing end, start working")
            if self.task in ("train", "finetune"):
                self.task_train()
            elif self.task == "pred":
                self.task_predict()
            elif self.task == "pred_raw":
                self.task_predict_raw()
            elif self.task == "extract":
                self.task_extract()
            elif self.task == "serve":
                self.task_serve()
            else:
                raise ValueError(f"unknown task {self.task!r}")
        finally:
            # each close guarded: the broken iterator that aborted the
            # task often fails its close() too, and that must neither
            # mask the original exception nor starve the closes after it
            try:
                self._close_prefetchers()  # backstop; tasks close own
            except Exception as ce:
                mlog.warn(f"prefetcher close failed: {ce}")
            for it in ([self.itr_train] if self.itr_train else []) + \
                    self.itr_evals + ([self.itr_pred] if self.itr_pred else []):
                try:
                    it.close()
                except Exception as ce:
                    mlog.warn(f"iterator close failed: {ce}")  # disclint: ok(warn-once)
            # task-level sink teardown: flush+close HERE, after the
            # task's own emits (flight dumps, trace reports, latency
            # records) ran — a TrainingDiverged or mid-round iterator
            # failure must still land its final records and must not
            # leak the descriptor past the task (the PR-4 prefetcher
            # leak class, applied to telemetry).  The goodput ledger is
            # the run's LAST record: it folds everything above it,
            # including the exception path's flight dump
            self._emit_ledger()  # guards its own failures
            if self.net is not None:
                self.net.metrics.close()
        return 0


def main() -> int:
    return LearnTask().run(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
