"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence axis at all (SURVEY.md §5.7 — data is fixed
4-D images), but this framework treats long-context as first-class: the
``seq`` mesh axis shards the sequence dimension across devices, and
attention runs as a ring — each device holds its local Q block resident
while K/V blocks rotate around the ring via ``ppermute`` over ICI, with
flash-style online-softmax accumulation so no device ever materialises the
full (s, s) score matrix.  Communication overlaps with the block matmuls
(XLA pipelines the ppermute DMA with the next block's compute).

``ring_attention`` must run *inside* ``shard_map`` (it uses
``lax.axis_index`` / ``lax.ppermute``); ``dense_attention`` is the
single-device oracle used by the layer when no seq axis is configured and
by the differential tests.

Segment-aware masking (document packing, ``io/text.py``): every path
accepts an optional ``seg`` array of per-position segment ids ``(b, s)``
(0 = padding).  The mask rule — shared verbatim with the Pallas
triangular-flash segment kernels (``ops/pallas_kernels.py``), which are
pairtested against this fallback — is::

    allowed(iq, jk) = causal(iq >= jk)
                      & ((seg_q == seg_k & seg_q != 0) | iq == jk)

i.e. block-diagonal causal attention with the diagonal unconditionally
allowed, so padding rows (seg 0) attend themselves and the online
softmax never sees a fully-masked row (NEG_INF-only rows would renorm
exp(0) garbage).  In the ring form, segment ids rotate around the ring
with their K/V blocks.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/where() NaN-free


def _block_scores(q: jnp.ndarray, k: jnp.ndarray, scale: float,
                  q_off, k_off, causal: bool,
                  seg_q: Optional[jnp.ndarray] = None,
                  seg_k: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """(b,h,sq,d) x (b,h,sk,d) -> (b,h,sq,sk) float32 scores with causal
    and segment masking in *global* positions (offsets account for ring
    rotation).  ``seg_q``/``seg_k`` are (b, sq)/(b, sk) int segment ids
    (see module docstring for the shared mask rule)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    qpos = q_off + jnp.arange(q.shape[2])
    kpos = k_off + jnp.arange(k.shape[2])
    diag = qpos[:, None] == kpos[None, :]
    if seg_q is not None:
        same = (seg_q[:, :, None] == seg_k[:, None, :]) \
            & (seg_q[:, :, None] != 0)
        allowed = same | diag[None]
        if causal:
            allowed = allowed & (qpos[:, None] >= kpos[None, :])[None]
        s = jnp.where(allowed[:, None], s, NEG_INF)
    elif causal:
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    return s


def _online_update(s, v, acc, m, l):
    """One flash-attention accumulation step in float32."""
    new_m = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - new_m)
    corr = jnp.exp(m - new_m)
    l = l * corr + p.sum(axis=-1, keepdims=True)
    acc = acc * corr + jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                                  preferred_element_type=jnp.float32)
    return acc, new_m, l


def _accumulate_block(q, k, v, scale, q_off, k_off, causal, acc, m, l,
                      seg_q=None, seg_k=None):
    """Fold one K/V block into the (acc, m, l) online-softmax state.

    Chunks the block's key axis under ``lax.scan`` when it is long, so
    peak memory stays O(s_q · chunk) regardless of the block size — used
    both by the single-device chunked path and by each ring rotation step
    (whose local blocks are s/ring long and would otherwise materialise
    (s_local, s_local) f32 scores)."""
    s_len = k.shape[2]
    chunk = _chunk_for(s_len)
    if chunk == s_len or s_len <= CHUNKED_ATTN_THRESHOLD:
        s = _block_scores(q, k, scale, q_off, k_off, causal, seg_q, seg_k)
        return _online_update(s, v, acc, m, l)
    n_chunks = s_len // chunk
    kc = jnp.moveaxis(
        k.reshape(k.shape[0], k.shape[1], n_chunks, chunk, k.shape[3]), 2, 0)
    vc = jnp.moveaxis(
        v.reshape(v.shape[0], v.shape[1], n_chunks, chunk, v.shape[3]), 2, 0)
    segc = None if seg_k is None else jnp.moveaxis(
        seg_k.reshape(seg_k.shape[0], n_chunks, chunk), 1, 0)

    def step(carry, inp):
        acc, m, l, off = carry
        kb, vb = inp[0], inp[1]
        sb = inp[2] if seg_k is not None else None
        s = _block_scores(q, kb, scale, q_off, off, causal, seg_q, sb)
        acc, m, l = _online_update(s, vb, acc, m, l)
        return (acc, m, l, off + chunk), None

    xs = (kc, vc) if segc is None else (kc, vc, segc)
    (acc, m, l, _), _ = lax.scan(
        step, (acc, m, l, jnp.asarray(k_off, jnp.int32)), xs)
    return acc, m, l


CHUNKED_ATTN_THRESHOLD = 2048  # above this seq len, never materialize s x s


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    seg: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain softmax attention, (b, h, s, d) -> (b, h, s, d).

    Short sequences take the direct path; past ``CHUNKED_ATTN_THRESHOLD``
    the K/V axis is processed in online-softmax chunks under ``lax.scan``
    so peak memory is O(s·chunk) instead of O(s²) — the single-chip
    long-context path (ring_attention is the multi-chip one).  ``seg``
    (b, s) applies the shared segment mask rule (module docstring)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s_len = k.shape[2]
    if s_len <= CHUNKED_ATTN_THRESHOLD:
        s = _block_scores(q, k, scale, 0, 0, causal, seg, seg)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(p.dtype)).astype(q.dtype)
    acc = jnp.zeros(q.shape[:3] + (v.shape[3],), jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    acc, m, l = _accumulate_block(q, k, v, scale, 0, 0, causal, acc, m, l,
                                  seg_q=seg, seg_k=seg)
    return (acc / l).astype(q.dtype)


def _chunk_for(s_len: int) -> int:
    """Largest power-of-two chunk <= 1024 dividing the sequence length."""
    c = 1024
    while c > 1 and s_len % c != 0:
        c //= 2
    return c


def _axis_size(axis_name: str) -> int:
    """Static mesh-axis size.  ``lax.axis_size`` appeared after jax
    0.4.x; there, ``jax.core.axis_frame`` returns the size directly."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)
    size = frame if isinstance(frame, int) else getattr(frame, "size", None)
    if size is None:
        raise RuntimeError(
            f"cannot determine size of mesh axis {axis_name!r} on this jax "
            "version (no lax.axis_size, axis_frame returned "
            f"{type(frame).__name__})")
    return size


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   seg: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Blockwise ring attention over mesh axis ``axis_name``.

    Args are the *local shards* (b, h, s_local, d); the sequence axis is
    sharded over ``axis_name``.  K/V rotate around the ring; every device
    accumulates its Q block's output with online softmax.  Exact (not
    approximate) — matches ``dense_attention`` on the gathered arrays.
    ``seg`` is the local (b, s_local) segment-id shard; it rotates with
    its K/V block so cross-document scores are blocked ring-wide.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_off = my * s_local
    acc = jnp.zeros(q.shape[:3] + (v.shape[3],), jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    seg_k = seg
    # static unrolled ring: n is a mesh constant, so XLA sees a straight-line
    # pipeline of (matmul, ppermute) pairs it can overlap
    for i in range(n):
        src = (my - i) % n  # the shard whose K/V block we currently hold
        acc, m, l = _accumulate_block(q, k, v, scale, q_off,
                                      src * k.shape[2], causal, acc, m, l,
                                      seg_q=seg, seg_k=seg_k)
        if i + 1 < n:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
            if seg_k is not None:
                seg_k = lax.ppermute(seg_k, axis_name, perm)
    return (acc / l).astype(q.dtype)


def sharded_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, causal: bool = False,
                      seq_axis: str = "seq",
                      seg: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """shard_map wrapper: global (b, h, s, d) arrays in, attention computed
    as a ring over ``seq_axis`` (batch stays sharded over "data" and heads
    over "model" when those axes exist).  ``seg`` (b, s) shards over
    (data, seq) and rides the ring with its K/V blocks."""
    dp = "data" if "data" in mesh.axis_names else None
    hp = ("model" if "model" in mesh.axis_names
          and q.shape[1] % mesh.shape["model"] == 0 else None)
    spec = P(dp, hp, seq_axis, None)
    from .pipeline import shard_map  # version shim (check_rep/check_vma)
    if seg is None:
        fn = functools.partial(ring_attention, axis_name=seq_axis,
                               causal=causal)
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
    seg_spec = P(dp, seq_axis)

    def fn(q_, k_, v_, seg_):
        return ring_attention(q_, k_, v_, axis_name=seq_axis,
                              causal=causal, seg=seg_)

    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
                     out_specs=spec, check_vma=False)(q, k, v, seg)
