"""Bucketed backward-overlapped gradient reduction for data parallelism.

Reference: the ``async_updater`` (``src/updater/async_updater-inl.hpp``)
issues a per-layer gradient Push/PullReq to the parameter server the
moment that layer's backward finishes, with priority ``-layer_index`` so
the transfers behind the rest of backprop hide the communication — the
mechanism behind cxxnet's "nearly linear speedup" claim.  The implicit
DP path here (``mesh = data:N`` + ``jax.grad``'s psum) leaves all-reduce
placement entirely to XLA's scheduler; this module makes the schedule
EXPLICIT, the way bucketed-allreduce DDP (Li et al., VLDB'20) and
parameter servers (Li et al., OSDI'14) do:

* the net's connections are partitioned into contiguous segments whose
  owned-parameter footprint targets ``dp_bucket_mb`` MiB, walking
  REVERSE layer order (the last layer's gradients are ready first, so
  buckets fill in backward-completion order — the async_updater's
  priority rule);
* the train step runs under ``shard_map`` over the ``data`` axis: the
  forward chains one ``jax.vjp`` per segment (the same layered-vjp
  slicing the pipeline/remat paths use via
  :func:`nnet.pipeline_net.make_stage_fns`), and the backward walks the
  segments in reverse, issuing each bucket's cross-chip reduction
  (``lax.psum``, or ``lax.psum_scatter`` for ZeRO-sharded leaves) the
  moment that segment's vjp returns — so bucket L's reduction is
  data-independent of segment L-1's backward and XLA's latency-hiding
  scheduler overlaps the two, exactly the async_updater schedule;
* on a multi-axis mesh (``mesh = data:N,model:M``) the schedule composes
  with the model axis instead of bailing: parameters sharded over
  ``model`` at rest (fullc/moe NamedShardings) enter the shard_map as
  shards, each segment **all-gathers its own model-sharded leaves at its
  forward entry** (the gathers interleave with forward compute, placed
  by the same segment walk that places the reductions), backward slices
  the cotangent back to the shard for free (compute is replicated across
  ``model``, so every replica's cotangent is identical and each keeps
  the slice its shard owns), and the bucketed data-axis ``psum`` fires
  exactly as in the pure-DP case — the lowered step carries the model
  all-gathers composed with the per-bucket data all-reduces;
* ``dp_reduce_dtype = bf16`` casts gradients to bf16 for the wire and
  back for the f32 master apply (half the comm volume);
* with ``update_period > 1`` and ``dp_reduce_at = apply`` (the default)
  micro-steps accumulate LOCAL gradients and the bucketed reduction runs
  once per apply — 1/update_period the communication (DDP ``no_sync``
  semantics; the cross-chip sum reassociates, so trajectories match the
  implicit path to FP-reassociation tolerance rather than bitwise);
  ``dp_reduce_at = step`` reduces every micro-step and stays bitwise.

At ``dp_reduce_dtype = f32`` (and ``dp_reduce_at = step`` when
accumulating) the trajectory is BITWISE identical to the implicit-psum
step: per-device forward/backward runs the same local ops GSPMD would
partition, the loss lowers as the same local-sum + all-reduce, and
wgrad contractions reduce in the same order — asserted over tail-mask /
update_period / shard_opt_state configs in tests/test_overlap.py on the
CPU mesh.  Dropout nets are the exception: the per-device RNG folds in
``axis_index`` (like ``batch_split`` folds per chunk), so masked neurons
differ from the implicit path's partitioned key stream.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..layers.base import ForwardContext, LabelInfo, as_mat
from .pipeline import shard_map

#: dp_reduce_dtype spellings -> wire dtype (None = reduce at native dtype)
REDUCE_DTYPES = {"f32": None, "bf16": jnp.bfloat16}


def model_axis(mesh) -> Optional[str]:
    """The weight-sharding axis the overlap schedule composes with, or
    ``None`` on a pure-DP mesh."""
    if "model" in mesh.axis_names and mesh.shape["model"] > 1:
        return "model"
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gather_model_leaf(x, axis: str, size: int):
    """Model-sharded leaf (local shard) -> full tensor, inside shard_map.

    Forward is a plain tiled all-gather over ``axis``.  Backward takes
    the SLICE of the cotangent the shard owns rather than the all_gather
    transpose (psum_scatter): the computation consuming the gathered
    weight is replicated across ``axis`` (same data shard, same gathered
    weights on every replica), so each replica's full-tensor cotangent
    is already the complete gradient — a psum_scatter would sum ``size``
    identical copies and scale the gradient by the axis size."""
    return lax.all_gather(x, axis, axis=0, tiled=True)


def _gml_fwd(x, axis, size):
    return _gather_model_leaf(x, axis, size), None


def _gml_bwd(axis, size, _res, ct):
    shard = ct.shape[0] // size
    idx = lax.axis_index(axis)
    return (lax.dynamic_slice_in_dim(ct, idx * shard, shard, axis=0),)


_gather_model_leaf.defvjp(_gml_fwd, _gml_bwd)


class OverlapPlan:
    """Static bucket plan over one built network.

    ``stages`` are forward-order ``[s0, s1)`` connection ranges (one per
    bucket); ``stage_keys[s]`` / ``tail_keys`` are the param-group keys
    each segment's vjp produces gradients for (a key can appear in two
    segments — e.g. a pool carrying a deferred conv bias — the per-
    segment cotangents then have disjoint support and sum exactly);
    ``frontier`` is the node frontier entering the loss tail.
    """

    __slots__ = ("stages", "body_end", "stage_keys", "tail_keys",
                 "frontier", "bucket_bytes")

    def __init__(self, stages, body_end, stage_keys, tail_keys, frontier,
                 bucket_bytes):
        self.stages = stages
        self.body_end = body_end
        self.stage_keys = stage_keys
        self.tail_keys = tail_keys
        self.frontier = frontier
        self.bucket_bytes = bucket_bytes


def _group_bytes(group) -> int:
    return sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
               for leaf in jax.tree.leaves(group))


def _keys_read(net, lo: int, hi: int, params) -> List[str]:
    """Param-group keys the connections in [lo, hi) read: their own key
    plus any deferred-bias key (the relu->pool reorder moves a conv's
    bias add — and therefore its bias gradient — into the pool)."""
    keys: List[str] = []
    for j in range(lo, hi):
        c = net.connections[j]
        if c.param_key in params and c.param_key not in keys:
            keys.append(c.param_key)
        dk = getattr(c.layer, "deferred_bias_key", None)
        if dk is not None and dk in params and dk not in keys:
            keys.append(dk)
    return keys


def plan_buckets(net, params, bucket_mb: float,
                 eval_ids: Sequence[int]) -> Optional[OverlapPlan]:
    """Partition the graph body into buckets of ~``bucket_mb`` MiB of
    owned parameters, filled in reverse layer order.  Returns ``None``
    when a train-metric eval node sits before the loss-tail frontier
    (the caller falls back to the implicit step, like the pipeline
    path's tail-visibility rule)."""
    from ..nnet import pipeline_net
    conns = net.connections
    assert any(not c.layer.is_loss for c in conns), \
        "dp_overlap: network has no non-loss body"
    body_end = max(i for i, c in enumerate(conns)
                   if not c.layer.is_loss) + 1
    visible = set(pipeline_net.frontier_nodes(net, body_end))
    for c in conns[body_end:]:
        visible.update(c.nindex_out)
    if not set(eval_ids) <= visible:
        return None
    bucket_bytes = max(float(bucket_mb) * 2 ** 20, 1.0)
    owned = {i: _group_bytes(params[c.param_key])
             for i, c in enumerate(conns[:body_end])
             if c.owns_params and c.param_key in params}
    cuts: List[int] = []
    acc = 0.0
    # reverse walk: close a bucket once it holds >= the target, cutting
    # BEFORE the connection that filled it (backward reaches that
    # connection's grads last within the bucket)
    for i in range(body_end - 1, 0, -1):
        acc += owned.get(i, 0)
        if acc >= bucket_bytes:
            cuts.append(i)
            acc = 0.0
    bounds = [0] + sorted(cuts) + [body_end]
    stages = [(bounds[j], bounds[j + 1]) for j in range(len(bounds) - 1)]
    return OverlapPlan(
        stages=stages, body_end=body_end,
        stage_keys=[_keys_read(net, s0, s1, params) for s0, s1 in stages],
        tail_keys=_keys_read(net, body_end, len(conns), params),
        frontier=pipeline_net.frontier_nodes(net, body_end),
        bucket_bytes=bucket_bytes)


def _split(tree: Dict[str, Any], keys: Sequence[str]) -> Dict[str, Any]:
    return {k: tree[k] for k in keys}


def _reduce_leaf(g, scatter: bool, rdtype):
    cast = rdtype is not None and g.dtype != rdtype
    x = g.astype(rdtype) if cast else g
    if scatter:
        x = lax.psum_scatter(x, "data", scatter_dimension=0, tiled=True)
    else:
        x = lax.psum(x, "data")
    return x.astype(g.dtype) if cast else x


def _merge(parts: List[Dict[str, Any]], params) -> Dict[str, Any]:
    """Sum per-segment grad dicts into one params-ordered dict.  Keys
    shared across segments (deferred bias) have disjoint support, so the
    adds combine exact zeros — bitwise-safe."""
    merged: Dict[str, Any] = {}
    for part in parts:
        for k, grp in part.items():
            merged[k] = grp if k not in merged else \
                jax.tree.map(jnp.add, merged[k], grp)
    return {k: merged[k] for k in params}


def _run(trainer, params, data, label_vec, epoch, rng, eval_ids, mask,
         grad_acc, *, reduce: bool, scatter_ok: bool):
    """The shard_map body builder shared by every overlap entry point.

    Returns ``(loss, outs, grads)`` as GLOBAL arrays: ``loss`` is the
    psum'd scalar, ``outs`` the batch-sharded eval-node outputs, and
    ``grads`` either the bucket-reduced gradients (``reduce=True``;
    replicated, or data-sharded where ZeRO reduce-scatter applies) or
    the updated per-device local accumulator (``reduce=False``; leading
    device axis, sharded over "data").
    """
    from .. import engine
    from ..nnet import pipeline_net
    from ..nnet.net import conn_params
    plan = trainer._dp_overlap_plan()
    net = trainer.net
    mesh = trainer.mesh
    rdtype = REDUCE_DTYPES[engine.opts.dp_reduce_dtype]
    with_mask = mask is not None
    with_acc = grad_acc is not None
    stages, body_end = plan.stages, plan.body_end
    zero = trainer.dp_zero_grads if scatter_ok else \
        jax.tree.map(lambda _: False, trainer.dp_zero_grads)
    # model-axis composition: model-sharded leaves enter as shards
    # (their param PartitionSpec), get all-gathered at their segment's
    # forward entry, and their gradients leave as shards again
    maxis = model_axis(mesh)
    msize = mesh.shape["model"] if maxis else 1
    msharded = trainer.dp_model_sharded
    assert maxis is None or not with_acc, (
        "dp_overlap: the deferred local-accumulator path is pure-DP "
        "(the trainer gates dp_reduce_at=apply off on model meshes)")

    def _gather_split(sp: Dict[str, Any]) -> Dict[str, Any]:
        """Split params dict -> same dict with model-sharded leaves
        gathered to full tensors (no-op on pure-DP meshes)."""
        if maxis is None:
            return sp
        return {k: jax.tree.map(
            lambda x, m: _gather_model_leaf(x, maxis, msize) if m else x,
            grp, msharded[k]) for k, grp in sp.items()}

    def spmd(params, data, label_vec, epoch, rng, *rest):
        rest = list(rest)
        acc = rest.pop(0) if with_acc else None
        mask_l = rest.pop(0) if with_mask else None
        # decorrelate dropout across devices (batch_split precedent:
        # rng trajectories differ from the implicit path; nets without
        # dropout are unaffected — the fold is dead code for them)
        rng_l = None if rng is None else \
            jax.random.fold_in(rng, lax.axis_index("data"))
        x = trainer._normalize_input(data).astype(trainer.dtype)
        fields = {name: label_vec[:, a:b]
                  for name, a, b in trainer._label_fields} \
            if label_vec is not None else {}
        extra = {"fields": fields, "mask": mask_l}
        stage_fns = pipeline_net.make_stage_fns(
            net, stages, body_end, train=True, epoch=epoch,
            loss_scale=trainer.loss_scale, rng=rng_l, mesh=None)
        # ---- forward: one vjp per bucket segment, residuals per stage.
        # Model-sharded leaves all-gather INSIDE each segment's vjp-traced
        # forward (at that segment's entry — the async_updater walk in
        # reverse), so backward hands their cotangents back as shards
        val = ((x,), jnp.float32(0.0), extra)
        vjps = []
        for s, fn in enumerate(stage_fns):
            val, vjp_fn = jax.vjp(
                lambda sp, v, fn=fn: fn(_gather_split(sp), v, 0),
                _split(params, plan.stage_keys[s]), val)
            vjps.append(vjp_fn)

        def tail_fn(tp, v):
            tp = _gather_split(tp)
            acts, aux, ex = v
            nodes = dict(zip(plan.frontier, acts))
            fl, mk = ex["fields"], ex["mask"]
            ctx = ForwardContext(
                train=True, rng=rng_l,
                labels=LabelInfo(fields=fl, mask=mk)
                if fl or mk is not None else None,
                epoch=epoch, loss_scale=trainer.loss_scale, mesh=None)
            for conn in net.connections[body_end:]:
                ins = [nodes[n] for n in conn.nindex_in]
                outs_, _ = conn.layer.forward(
                    conn_params(tp, conn), {}, ins, ctx)
                for n, v_ in zip(conn.nindex_out, outs_):
                    nodes[n] = v_
            total = aux
            for l in ctx.losses:
                total = total + l
            outs_eval = {nid: as_mat(nodes[nid]).astype(jnp.float32)
                         for nid in eval_ids}
            return total, outs_eval

        (loss_local, outs_eval), tail_vjp = jax.vjp(
            tail_fn, _split(params, plan.tail_keys), val)
        loss = lax.psum(loss_local, "data")
        # ---- backward: walk segments in reverse; each bucket's
        # reduction is issued the moment its vjp returns, so it carries
        # no data dependence on the remaining backward and the scheduler
        # can overlap it (the async_updater priority = -layer_index rule)
        consumed = set()

        def fold_acc(g: Dict[str, Any]) -> Dict[str, Any]:
            """Add the local accumulator into a segment's grads — once
            per key (a deferred-bias key spans two segments)."""
            if acc is None:
                return g
            out = {}
            for k, grp in g.items():
                if k in consumed:
                    out[k] = grp
                else:
                    consumed.add(k)
                    out[k] = jax.tree.map(lambda a, x: a[0] + x,
                                          acc[k], grp)
            return out

        def reduce_bucket(g: Dict[str, Any], keys) -> Dict[str, Any]:
            return jax.tree.map(
                lambda x, z: _reduce_leaf(x, bool(z), rdtype),
                g, _split(zero, keys))

        parts: List[Dict[str, Any]] = []
        g_tail, val_bar = tail_vjp(
            (jnp.float32(1.0), jax.tree.map(jnp.zeros_like, outs_eval)))
        g_tail = fold_acc(g_tail)
        parts.append(reduce_bucket(g_tail, plan.tail_keys)
                     if reduce else g_tail)
        for s in range(len(stages) - 1, -1, -1):
            g_s, val_bar = vjps[s](val_bar)
            g_s = fold_acc(g_s)
            parts.append(reduce_bucket(g_s, plan.stage_keys[s])
                         if reduce else g_s)
        grads = _merge(parts, params)
        if not reduce:
            # unreduced local sums, restacked under the device axis for
            # the next micro-step's accumulator
            grads = jax.tree.map(lambda x: x[None], grads)
        return loss, outs_eval, grads

    def leaf_spec(z, s):
        """Gradient out-spec for one leaf: model-sharded leaves keep
        their param spec (the backward returns the shard), ZeRO leaves
        data-scatter, everything else replicates."""
        if maxis is not None and len(s.spec) and s.spec[0] == maxis:
            return s.spec
        return P("data") if (scatter_ok and z) else P()

    if reduce:
        grad_specs = {k: jax.tree.map(
            leaf_spec, zero[k], trainer.param_shardings[k])
            for k in params}
    else:
        grad_specs = jax.tree.map(lambda _: P("data"), params)
    param_specs = {k: jax.tree.map(lambda s: s.spec,
                                   trainer.param_shardings[k],
                                   is_leaf=lambda s: hasattr(s, "spec"))
                   for k in params}
    in_specs = [param_specs, P("data"), P("data"), P(), P()]
    args = [params, data, label_vec, epoch, rng]
    if with_acc:
        in_specs.append(P("data"))
        args.append(grad_acc)
    if with_mask:
        in_specs.append(P("data"))
        args.append(mask)
    fn = shard_map(spmd, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=(P(), P("data"), grad_specs),
                   check_rep=False)
    return fn(*args)


# --------------------------------------------------------- trainer entry

def loss_and_grads(trainer, params, buffers, data, label_vec, epoch, rng,
                   eval_ids, mask=None, scatter_ok=True):
    """Drop-in for the implicit ``jax.value_and_grad`` path inside
    :meth:`NetTrainer._loss_and_grads`: same contract —
    ``((loss, (buffers, outs, diags)), grads)`` — with the gradients
    already bucket-reduced at their grad-ready points."""
    loss, outs, grads = _run(trainer, params, data, label_vec, epoch, rng,
                             eval_ids, mask, None, reduce=True,
                             scatter_ok=scatter_ok)
    return (loss, (buffers, outs, {})), grads


def accumulate_local(trainer, params, data, label_vec, epoch, rng,
                     eval_ids, mask, grad_acc):
    """``dp_reduce_at = apply`` micro-step: no reduction at all — the
    per-device local gradient sums accumulate under a leading device
    axis (sharded over "data", so the footprint matches one replicated
    copy).  Returns ``(loss, outs, new_acc)``."""
    return _run(trainer, params, data, label_vec, epoch, rng, eval_ids,
                mask, grad_acc, reduce=False, scatter_ok=False)


def apply_reduce(trainer, params, data, label_vec, epoch, rng, eval_ids,
                 mask, grad_acc):
    """``dp_reduce_at = apply`` apply-step: the accumulated local sums
    join the final micro-step's backward and each bucket reduces ONCE —
    1/update_period the communication of the implicit path.  Returns
    ``(loss, outs, grads)`` with globally-reduced gradients."""
    return _run(trainer, params, data, label_vec, epoch, rng, eval_ids,
                mask, grad_acc, reduce=True, scatter_ok=True)
