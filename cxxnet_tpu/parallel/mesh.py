"""Device mesh + sharding: the TPU-native replacement for mshadow-ps.

Reference: the multi-device path in ``src/nnet/nnet_impl-inl.hpp`` splits the
batch across per-device threads and aggregates gradients via the
``"local"``/``"dist"`` parameter server (InitParamServer :376-390,
``async_updater-inl.hpp``).  Here the same data parallelism is one SPMD
program over a ``jax.sharding.Mesh``: the batch is sharded on the ``data``
axis, parameters are replicated (or sharded on ``model`` for the
fullc_gather-style tensor-parallel mode), and XLA inserts the psum over ICI —
no keys, no async callbacks, no server.  Multi-host runs the same program on
a global mesh (DCN between hosts), which is the ``param_server = dist``
equivalent.

The axes are named, not hard-coded to "batch", so sequence/context/expert
axes can attach later (survey §5.7 note).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_device_spec(dev: str) -> Dict:
    """Parse ``dev = cpu | tpu | tpu:0 | tpu:0-3 | gpu:1,3`` (reference
    nnet_impl-inl.hpp:32-51 parses the gpu:0-3 form)."""
    dev = dev.strip()
    if ":" not in dev:
        return {"platform": dev, "ids": None}
    platform, rng = dev.split(":", 1)
    ids: List[int] = []
    for part in rng.split(","):
        if "-" in part:
            a, b = part.split("-")
            ids.extend(range(int(a), int(b) + 1))
        else:
            ids.append(int(part))
    return {"platform": platform, "ids": ids}


def ensure_host_platform_devices(n: int) -> None:
    """Best-effort: ask XLA's host platform for ``n`` CPU devices (a
    ``dev = cpu:0-3`` + ``mesh = data:2,model:2`` config needs them).
    Only effective BEFORE the first backend initialization — call it
    before anything touches ``jax.devices()``/``jax.process_count()``;
    afterwards it is a harmless no-op and callers must check the visible
    count themselves."""
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


def select_devices(dev: str) -> List[jax.Device]:
    spec = parse_device_spec(dev)
    platform = spec["platform"]
    if platform == "cpu":
        # force the CPU backend before any backend initializes; environments
        # that tunnel a TPU pin JAX_PLATFORMS, so plain env vars don't stick
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backends already initialized; fall through to selection
    if platform in ("tpu", "gpu", "cpu"):
        try:
            devices = jax.devices(platform)
        except RuntimeError:
            devices = jax.devices()  # axon/tunnel platforms report differently
    else:
        devices = jax.devices()
    if spec["ids"] is None:
        return list(devices[:1])
    for i in spec["ids"]:
        if i >= len(devices):
            raise ValueError(
                f"device id {i} out of range: only {len(devices)} "
                f"{platform} devices visible")
    return [devices[i] for i in spec["ids"]]


#: mesh axis names the framework gives semantics to: ``data`` shards the
#: batch, ``model`` shards fullc/moe weights (tensor/weight parallelism),
#: ``seq`` ring attention, ``expert`` MoE dispatch, ``pipe`` pipeline
#: stages.  ``mesh=`` is a first-class config key; an unknown axis name
#: would silently shard nothing, so parse rejects it with a suggestion.
KNOWN_AXES = ("data", "model", "seq", "expert", "pipe")


@dataclasses.dataclass
class MeshSpec:
    """Named mesh axes, e.g. {"data": 4, "model": 2}."""

    axes: Dict[str, int]

    @classmethod
    def parse(cls, s: str) -> "MeshSpec":
        """Parse ``mesh = data:4,model:2`` config syntax.  Raises
        ``ValueError`` on unknown/duplicate axis names or non-positive
        sizes (surfaced as a config-lint error by graftlint and as an
        init-time error by the trainer)."""
        axes: Dict[str, int] = {}
        for part in s.split(","):
            name, sep, size = part.partition(":")
            name = name.strip()
            if not sep:
                raise ValueError(
                    f"mesh axis {part.strip()!r}: expected name:size")
            if name not in KNOWN_AXES:
                from ..analysis.schema import did_you_mean
                sugg = did_you_mean(name, KNOWN_AXES)
                raise ValueError(
                    f"unknown mesh axis {name!r} (axes with semantics: "
                    f"{', '.join(KNOWN_AXES)})"
                    + (f"; did you mean {sugg!r}?" if sugg else ""))
            if name in axes:
                raise ValueError(f"duplicate mesh axis {name!r}")
            try:
                n = int(size)
            except ValueError:
                raise ValueError(
                    f"mesh axis {name}: size {size.strip()!r} is not an "
                    "integer") from None
            if n < 1:
                raise ValueError(f"mesh axis {name}: size must be >= 1, "
                                 f"got {n}")
            axes[name] = n
        return cls(axes)

    @property
    def size(self) -> int:
        n = 1
        for v in self.axes.values():
            n *= v
        return n

    def axis_size(self, name: str) -> int:
        """Size of ``name`` (1 when the axis is absent)."""
        return self.axes.get(name, 1)


def mesh_axis_sizes(mesh: Mesh) -> Dict[str, int]:
    """Axis name -> size for a BUILT mesh — the axis metadata the SPMD
    deep lint (analysis/spmdlint.py) checks traced collective axis names
    against.  One accessor so the checker and the runtime can never
    disagree about which axes exist or how wide they are (a collective
    on an axis missing here is the multi-host deadlock class)."""
    return {str(name): int(size) for name, size in mesh.shape.items()}


def build_mesh(devices: Sequence[jax.Device],
               spec: Optional[MeshSpec] = None) -> Mesh:
    """Build a Mesh; default one-axis "data" mesh over all given devices."""
    if spec is None:
        spec = MeshSpec({"data": len(devices)})
    assert spec.size == len(devices), \
        f"mesh axes {spec.axes} need {spec.size} devices, got {len(devices)}"
    arr = np.array(devices).reshape(tuple(spec.axes.values()))
    return Mesh(arr, tuple(spec.axes.keys()))


def batch_pspec(mesh: Mesh) -> P:
    """Batch dim sharded over "data" (if present), rest replicated."""
    if "data" in mesh.axis_names:
        return P("data")
    return P()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(mesh))


def init_distributed(coordinator: str, num_processes: int, process_id: int,
                     local_device_ids=None) -> None:
    """Multi-host bring-up: join the JAX distributed runtime so all
    processes see one global device set and compiled programs run SPMD
    across hosts (collectives ride ICI within a slice, DCN across).

    This replaces the reference's parameter-server topology
    (``param_server = dist`` + launcher, nnet_ps_server.cpp:162-170): there
    is no server process — every host runs the same program on its shard of
    the global mesh.  Config keys (see main.py): ``dist_coordinator``
    (host:port of process 0), ``dist_num_proc``, ``dist_proc_rank``; the
    env vars CXN_COORDINATOR / CXN_NUM_PROC / CXN_PROC_RANK override, so
    one config file serves every worker like the reference's single conf
    (nnet_ps_server.cpp:41-48).
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)


def global_devices_for(platform: str) -> List[jax.Device]:
    """All devices across processes for a platform (multi-host meshes need
    the global list; jax.devices() is already global after
    init_distributed)."""
    try:
        return list(jax.devices(platform))
    except RuntimeError:
        return list(jax.devices())
