"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

No reference counterpart: the reference scales across devices only by data
parallelism through its parameter server (SURVEY.md §2.8); pipeline
parallelism is part of this framework's TPU-native scaling surface
(dp/tp/sp/ep/pp).  The implementation is the canonical SPMD pipeline: each
device along the ``pipe`` axis owns one stage's parameters (a stacked
(S, ...) pytree sharded on its leading dim), microbatches enter at stage 0,
activations rotate stage-to-stage with ``lax.ppermute`` inside a
``lax.scan`` of ``n_micro + S - 1`` ticks (the pipeline bubble), and
outputs are collected from the last stage.  Autodiff just works: the
transpose of ``ppermute`` is the reverse rotation, so ``jax.grad`` of a
loss over :func:`pipeline_apply` runs the backward pipeline in the same
schedule — one jitted SPMD program, exactly like every other parallel mode
here.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # deprecated path, removed in newer jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = "check_rep"
except ImportError:  # pragma: no cover
    _shard_map = jax.shard_map  # a function on the jax namespace
    _REP_KW = "check_vma"


def shard_map(f, **kw):
    """Version shim: the replication-check kwarg was renamed
    check_rep -> check_vma when shard_map left jax.experimental.
    Accepts either spelling and forwards whichever this jax takes."""
    for alias in ("check_rep", "check_vma"):
        if alias in kw and _REP_KW != alias:
            kw[_REP_KW] = kw.pop(alias)
    return _shard_map(f, **kw)


def stack_stage_params(params_list) -> Any:
    """[per-stage pytree, ...] -> one pytree with a leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable, stacked_params: Any, x: jnp.ndarray,
                   *, mesh: Mesh, axis: str = "pipe") -> jnp.ndarray:
    """Run ``x`` through S pipelined stages.

    ``stage_fn(params, mb)``: one stage on one microbatch (shape-preserving
    across stages so activations can rotate).  ``stacked_params``: leaves
    (S, ...) — sharded on ``axis`` by the caller (or left to GSPMD).
    ``x``: (n_micro, mb, ...) microbatched input, replicated over ``axis``.
    Returns (n_micro, mb, ...) outputs, replicated over ``axis``.
    """
    n_stage = mesh.shape[axis]
    n_micro = x.shape[0]
    ticks = n_micro + n_stage - 1
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def spmd(params, xs):
        # inside shard_map: params leaves (1, ...) = this device's stage
        p_local = jax.tree.map(lambda a: a[0], params)
        idx = lax.axis_index(axis)

        def tick(carry, t):
            state = carry  # (mb, ...) activation arriving at this stage
            # stage 0 ingests microbatch t (clamped; bubble ticks compute
            # garbage that is masked out at collection)
            inject = xs[jnp.clip(t, 0, n_micro - 1)]
            x_in = jnp.where(idx == 0, inject, state)
            y = stage_fn(p_local, x_in)
            return lax.ppermute(y, axis, perm), y

        init = jnp.zeros_like(x[0])
        _, ys = lax.scan(tick, init, jnp.arange(ticks))
        # microbatch m leaves the last stage at tick m + S - 1
        out_last = ys[n_stage - 1:]                      # (n_micro, mb, ...)
        mask = (idx == n_stage - 1).astype(out_last.dtype)
        return lax.psum(out_last * mask, axis)

    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(),
        check_rep=False)(stacked_params, x)


def pipeline_apply_hetero(stage_fns, params, x, *, mesh: Mesh,
                          axis: str = "pipe", data_spec: P = P(),
                          extra=None
                          ) -> "tuple[tuple, jnp.ndarray]":
    """GPipe schedule over *heterogeneous* stages (different activation
    shapes and per-stage parameter structures) — the form a real layered
    network needs (a conv stack's stage boundaries are pool/flatten shapes,
    not one repeated block).

    ``stage_fns[s](params, value, m)``: stage ``s`` maps its input-boundary
    ``(acts, aux_loss, extra)`` value to its output-boundary value for
    microbatch index ``m`` (for per-microbatch randomness); ``acts`` is the
    tuple of frontier activations crossing the boundary (stage 0 receives
    a bare microbatch array).  The scalar aux-loss accumulator rides along
    the pipeline so mid-body loss contributors (MoE load-balance terms,
    aux-head losses) are not dropped.  ``params`` is passed whole and
    replicated over ``axis``; each branch uses only its own stage's
    slices.  ``x``: (n_micro, mb, ...) microbatches.  Returns
    ``(outs, aux_losses)``: a tuple of (n_micro, mb, ...) stacks of the
    LAST stage's boundary activations and an (n_micro,) vector of
    per-microbatch aux losses (summed over any data-axis shards,
    replicated on return).  ``extra``, when given, is a pytree with
    (n_micro, mb, ...) leaves (label fields / tail-batch loss mask),
    sliced per microbatch and threaded to every stage.

    Mechanics: the scan carry holds one activation buffer per stage
    boundary (a K-tuple, since shapes differ a single rotating buffer can't
    serve).  Each tick, every device runs exactly its own stage via
    ``lax.switch`` on the pipe index, writes boundary ``s``, and all
    buffers rotate one hop with ``ppermute`` — microbatch ``m`` leaves
    stage K-1 at tick ``m + K - 1``.  Autodiff runs the reverse pipeline
    through the transposed ppermute, as in :func:`pipeline_apply`.
    ``data_spec`` shards the per-microbatch batch dim over a "data" axis
    for combined dp x pp meshes.
    """
    n_stage = mesh.shape[axis]
    assert len(stage_fns) == n_stage, \
        f"{len(stage_fns)} stages for a {axis}:{n_stage} mesh"
    n_micro = x.shape[0]
    ticks = n_micro + n_stage - 1
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    data_axes = [a for d in data_spec if d is not None
                 for a in (d if isinstance(d, tuple) else (d,))]

    def spmd(params, xs, *erest):
        idx = lax.axis_index(axis)

        def extra_at(m):
            # label fields / tail-batch mask are sliced from the sharded
            # operand by each stage's own microbatch index — they do NOT
            # ride the rotating boundary buffers (no ppermute/psum cost)
            return jax.tree.map(lambda a: a[m], erest[0]) if erest \
                else {"fields": {}, "mask": None}

        def run_stage(s, inp, m):
            acts, loss = inp
            y = stage_fns[s](params, (acts, loss, extra_at(m)), m)
            return y[0], y[1]

        # boundary shapes, derived on the *local* (possibly data-sharded)
        # microbatch without running anything
        bshapes = []
        cur = jax.eval_shape(lambda: (xs[0], jnp.float32(0.0)))
        for s, fn in enumerate(stage_fns):
            cur = jax.eval_shape(lambda p, v, s=s: run_stage(s, v, 0),
                                 params, cur)
            bshapes.append(cur)

        def tick(bufs, t):
            def mk_branch(s):
                def branch(bufs):
                    inp = (xs[jnp.clip(t, 0, n_micro - 1)],
                           jnp.float32(0.0)) if s == 0 else bufs[s - 1]
                    m = jnp.clip(t - s, 0, n_micro - 1)
                    y = run_stage(s, inp, m)
                    return tuple(y if j == s else b
                                 for j, b in enumerate(bufs))
                return branch

            bufs = lax.switch(idx, [mk_branch(s) for s in range(n_stage)],
                              bufs)
            y_last = bufs[n_stage - 1]
            bufs = tuple(
                jax.tree.map(lambda a: lax.ppermute(a, axis, perm), b)
                for b in bufs)
            return bufs, y_last

        init = tuple(jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), b)
                     for b in bshapes)
        _, ys = lax.scan(tick, init, jnp.arange(ticks))
        # microbatch m leaves the last stage at tick m + S - 1
        out_last = jax.tree.map(lambda a: a[n_stage - 1:], ys)
        valid = idx == n_stage - 1
        out_last = jax.tree.map(
            lambda a: a * valid.astype(a.dtype), out_last)
        out, losses = lax.psum(out_last, axis)
        # per-microbatch aux losses were computed on this device's data
        # shard; sum them so the return value is replicated
        if data_axes:
            losses = lax.psum(losses, tuple(data_axes))
        return out, losses

    pspec = jax.tree.map(lambda _: P(), params)
    xspec = P(None, *data_spec)
    operands, in_specs = (params, x), (pspec, xspec)
    if extra is not None:
        operands += (extra,)
        # one spec leaf prefixing the whole extra subtree: microbatch dim
        # unsharded, per-microbatch batch dim sharded like the data
        in_specs += (P(None, *list(data_spec)[:1]),)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=in_specs, out_specs=(xspec, P(None)),
        check_rep=False)(*operands)


def pipeline_1f1b(stage_fn, loss_fn, stacked_params, x, labels, *,
                  mesh: Mesh, axis: str = "pipe"):
    """One-forward-one-backward pipeline schedule: forward AND backward
    interleave in a single scan, so each stage holds at most ``2S-1``
    saved microbatch inputs (a ring buffer) instead of the GPipe
    fill-drain's ``n_micro`` — the activation footprint stops scaling
    with microbatch count (VERDICT r3 weak 6).

    Differentiating :func:`pipeline_apply` gives the reverse fill-drain
    schedule: ``jax.grad`` runs the whole forward scan first, storing
    residuals for every tick.  1F1B cannot be expressed that way, so this
    function computes the gradients itself: each stage saves only its
    input activation, and re-runs ``jax.vjp(stage_fn)`` at the microbatch's
    backward tick (per-stage recompute, the standard trade).  Schedule:
    stage ``s`` forwards microbatch ``t - s`` and backwards microbatch
    ``t - (2S - 2 - s)`` at tick ``t`` — the last stage backwards a
    microbatch on the same tick it forwards it, cotangents rotate with
    the reverse ppermute.

    ``stage_fn(p, mb)`` is shape-preserving (as in :func:`pipeline_apply`);
    ``loss_fn(y, lab)`` maps the last stage's output + one microbatch of
    labels to a scalar.  Returns ``(loss, grads)`` where ``loss`` is the
    SUM of per-microbatch losses and ``grads`` matches ``stacked_params``
    ((S, ...) leaves, stage-sharded).
    """
    n_stage = mesh.shape[axis]
    n_micro = x.shape[0]
    ticks = n_micro + 2 * n_stage - 2
    ring = 2 * n_stage - 1
    fwd_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    bwd_perm = [(i, (i - 1) % n_stage) for i in range(n_stage)]

    def spmd(params, xs, labs):
        p_local = jax.tree.map(lambda a: a[0], params)
        idx = lax.axis_index(axis)

        def tick(carry, t):
            fwd_state, bwd_state, saved, grad_acc, loss_acc = carry
            # ---- forward half: stage idx runs microbatch mf = t - idx
            mf = t - idx
            f_on = (mf >= 0) & (mf < n_micro)
            x_in = jnp.where(idx == 0,
                             xs[jnp.clip(mf, 0, n_micro - 1)], fwd_state)
            y = stage_fn(p_local, x_in)
            # save the stage input in its ring slot; inactive ticks write
            # the scratch slot (index ``ring``) so they cannot clobber a
            # slot still awaiting its backward
            slot = jnp.where(f_on, jnp.clip(mf, 0, n_micro - 1) % ring,
                             ring)
            saved = lax.dynamic_update_slice_in_dim(
                saved, x_in[None], slot, axis=0)
            # ---- backward half: microbatch mb = t - (2S - 2 - idx)
            mb = t - (2 * n_stage - 2 - idx)
            b_on = (mb >= 0) & (mb < n_micro)
            mb_c = jnp.clip(mb, 0, n_micro - 1)
            x_saved = lax.dynamic_index_in_dim(saved, mb_c % ring, axis=0,
                                               keepdims=False)
            # last stage seeds the cotangent from the loss on the output
            # it just produced (its fwd and bwd of a microbatch share the
            # tick); other stages consume the rotated cotangent and skip
            # the loss computation entirely (lax.cond on the per-device
            # stage index — loss_fn contains no collectives)
            loss_m, dl = lax.cond(
                idx == n_stage - 1,
                lambda: jax.value_and_grad(
                    lambda yv: loss_fn(yv, labs[mb_c]).astype(
                        jnp.float32))(y),
                lambda: (jnp.float32(0.0), jnp.zeros_like(y)))
            g_in = jnp.where(idx == n_stage - 1, dl.astype(y.dtype),
                             bwd_state)
            _, vjp = jax.vjp(stage_fn, p_local, x_saved)
            dp, dx = vjp(g_in)
            # where-mask, not multiply: bubble ticks run the vjp on
            # zero/garbage activations, and 0 * NaN would poison the
            # accumulator permanently
            grad_acc = jax.tree.map(
                lambda a, d: jnp.where(b_on, a + d.astype(a.dtype), a),
                grad_acc, dp)
            loss_acc = loss_acc + jnp.where(
                b_on & (idx == n_stage - 1), loss_m, 0.0)
            return (lax.ppermute(y, axis, fwd_perm),
                    lax.ppermute(dx, axis, bwd_perm),
                    saved, grad_acc, loss_acc), None

        zero_act = jnp.zeros_like(x[0])
        init = (zero_act, zero_act,
                jnp.zeros((ring + 1,) + x[0].shape, x.dtype),
                jax.tree.map(lambda a: jnp.zeros(a.shape[1:], jnp.float32),
                             params),
                jnp.float32(0.0))
        carry, _ = lax.scan(tick, init, jnp.arange(ticks))
        _, _, _, grad_acc, loss_acc = carry
        loss = lax.psum(loss_acc, axis)
        grads = jax.tree.map(lambda g: g[None], grad_acc)
        return loss, grads

    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(pspec, P(), P()), out_specs=(P(), pspec),
        check_rep=False)(stacked_params, x, labels)


def pipeline_1f1b_hetero(stage_fns, tail_loss_fn, params, x, *, mesh: Mesh,
                         axis: str = "pipe", data_spec: P = P(),
                         extra=None, buckets=None, reduce_dtype=None):
    """1F1B schedule over *heterogeneous* stages — the netconfig-integrated
    counterpart of :func:`pipeline_1f1b` (``pipe_schedule = 1f1b``).

    ``stage_fns`` are :func:`cxxnet_tpu.nnet.pipeline_net.make_stage_fns`
    callables (boundary value = ``(acts tuple, aux-loss scalar, extra)``);
    ``tail_loss_fn(params, (acts, aux), extra_m, m)`` maps the LAST stage's
    output boundary for one microbatch to the scalar training loss
    (trailing loss connections + the threaded aux terms).  ``x`` is
    ``(n_micro, mb, ...)`` microbatches; ``extra`` the per-microbatch
    label-fields/mask pytree.  Returns ``(loss, grads, outs, auxs)``:
    summed per-microbatch loss, parameter gradients (f32, summed over
    pipe + data axes, replicated), the stacked last-boundary activations
    (``(n_micro, mb, ...)`` per frontier node) for train-metric eval,
    and the ``(n_micro,)`` per-microbatch aux-loss vector (mid-body loss
    terms, summed over data shards).

    Schedule identical to :func:`pipeline_1f1b` (stage ``s`` forwards
    microbatch ``t - s`` and backwards ``t - (2S - 2 - s)`` at tick
    ``t``).  Because boundary shapes differ per stage, the rotating
    buffers and saved-input rings are K-tuples (every device carries all
    K — the uniform-SPMD-program requirement); stage ``s``'s saved-input
    ring holds ``2(S - 1 - s) + 1`` slots (its forward-to-backward gap),
    so the total in-flight activation footprint averages S microbatch
    sets per boundary and is flat in ``n_micro``, where GPipe-by-autodiff
    stores all ``n_micro`` tick residuals.  Per-stage forward recompute
    inside ``jax.vjp`` is the standard 1F1B trade; randomness keys match
    the forward half (``fold_in(rng, m * S + s)`` in make_stage_fns), so
    dropout masks agree between the two passes.

    Phasing: the first ``T - S`` ticks (warmup + steady 1F1B interleave)
    run under one ``lax.scan``; the last ``S`` ticks — the cooldown,
    where stage ``S-1-k`` completes its final backward on cooldown tick
    ``k`` — are unrolled so a gradient reduction can be ISSUED at each
    stage's grad-ready point.  ``buckets``, when given, is a list of
    ``(param_keys, stage)`` pairs: after cooldown tick ``k`` every
    bucket whose owning stage just completed is ``psum``'d over
    ``(pipe, data)`` (dp_overlap composed with the pipe axis — the
    async_updater schedule, bucket k's wire overlapping stage k-1's
    remaining backward ticks).  A key read by several stages must be
    assigned to the LOWEST stage index reading it: lower stages complete
    later, so every contribution is final when its bucket fires.
    ``buckets = None`` reduces the whole tree once after the last tick
    (the implicit step).  Both placements reduce the same per-device
    accumulators, so at ``reduce_dtype = None`` (f32 wire) the
    trajectories are bitwise identical — asserted in
    tests/test_pipeline_1f1b.py.  ``reduce_dtype`` casts bucket wires
    (``dp_reduce_dtype = bf16``: half the comm volume, f32 master apply).
    """
    n_stage = mesh.shape[axis]
    n_micro = x.shape[0]
    ticks = n_micro + 2 * n_stage - 2
    # stage s's forward of microbatch m lands at tick m + s, its backward
    # at m + 2(S-1) - s: the ring only needs the gap + 1 slots (plus one
    # scratch slot inactive ticks write into)
    rings_len = [2 * (n_stage - 1 - s) + 1 for s in range(n_stage)]
    fwd_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    bwd_perm = [(i, (i - 1) % n_stage) for i in range(n_stage)]
    data_axes = [a for d in data_spec if d is not None
                 for a in (d if isinstance(d, tuple) else (d,))]
    red_axes = (axis, *data_axes)
    if buckets is not None:
        covered = [k for keys, _ in buckets for k in keys]
        assert sorted(covered) == sorted(params), (
            "pipeline buckets must cover every param key exactly once",
            sorted(covered), sorted(params))

    def reduce_bucket(sub):
        """psum a grad subtree over (pipe, data), optionally over a
        narrower wire dtype (cast back for the f32 master apply)."""
        def leaf(g):
            cast = reduce_dtype is not None and g.dtype != reduce_dtype
            r = lax.psum(g.astype(reduce_dtype) if cast else g, red_axes)
            return r.astype(g.dtype) if cast else r
        return jax.tree.map(leaf, sub)

    def spmd(params, xs, *erest):
        idx = lax.axis_index(axis)

        def extra_at(m):
            return jax.tree.map(lambda a: a[m], erest[0]) if erest \
                else {"fields": {}, "mask": None}

        def run_fwd(s, p, acts, aux, m):
            y = stage_fns[s](p, (acts, aux, extra_at(m)), m)
            return y[0], y[1]

        # boundary shapes via the shape-only chain (no compute)
        bshapes = []
        cur = jax.eval_shape(lambda: ((xs[0],), jnp.float32(0.0)))
        in_shapes = []
        for s in range(n_stage):
            in_shapes.append(cur)
            cur = jax.eval_shape(
                lambda p, v, s=s: run_fwd(s, p, v[0], v[1], 0), params, cur)
            bshapes.append(cur)

        def zeros_of(tree):
            return jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), tree)

        def tick(carry, t):
            fwd_bufs, ct_bufs, rings, grad_acc, loss_acc = carry

            def mk_branch(s):
                ring = rings_len[s]

                def fwd_half(carry):
                    fwd_bufs, ct_bufs, rings, grad_acc, loss_acc = carry
                    mf_c = jnp.clip(t - s, 0, n_micro - 1)
                    inp = ((xs[mf_c],), jnp.float32(0.0)) if s == 0 \
                        else fwd_bufs[s - 1]
                    rings = tuple(
                        jax.tree.map(
                            lambda buf, v: lax.dynamic_update_slice_in_dim(
                                buf, v[None], mf_c % ring, axis=0),
                            rings[j], inp)
                        if j == s else rings[j] for j in range(n_stage))
                    y = run_fwd(s, params, inp[0], inp[1], mf_c)
                    fwd_bufs = tuple(y if j == s else fwd_bufs[j]
                                     for j in range(n_stage))
                    return fwd_bufs, ct_bufs, rings, grad_acc, loss_acc

                def bwd_half(carry):
                    fwd_bufs, ct_bufs, rings, grad_acc, loss_acc = carry
                    mb_c = jnp.clip(t - (2 * n_stage - 2 - s), 0,
                                    n_micro - 1)
                    saved = jax.tree.map(
                        lambda buf: lax.dynamic_index_in_dim(
                            buf, mb_c % ring, axis=0, keepdims=False),
                        rings[s])
                    if s == n_stage - 1:
                        # fwd and bwd of a microbatch share the tick on
                        # the last stage: seed the cotangent chain from
                        # the loss directly (value_and_grad through the
                        # stage + loss tail in one go)
                        def with_tail(p, acts, aux):
                            ya, yl = run_fwd(s, p, acts, aux, mb_c)
                            return tail_loss_fn(
                                p, (ya, yl), extra_at(mb_c),
                                mb_c).astype(jnp.float32)
                        loss_m, (dp, da, dl) = jax.value_and_grad(
                            with_tail, argnums=(0, 1, 2))(
                                params, saved[0], saved[1])
                    else:
                        _, vjp = jax.vjp(
                            lambda p, acts, aux: run_fwd(
                                s, p, acts, aux, mb_c),
                            params, saved[0], saved[1])
                        dp, da, dl = vjp(ct_bufs[s])
                        loss_m = jnp.float32(0.0)
                    grad_acc = jax.tree.map(
                        lambda a, d: a + d.astype(a.dtype), grad_acc, dp)
                    loss_acc = loss_acc + loss_m
                    if s >= 1:
                        ct_bufs = tuple((da, dl) if j == s - 1 else ct_bufs[j]
                                        for j in range(n_stage))
                    return fwd_bufs, ct_bufs, rings, grad_acc, loss_acc

                def br(carry):
                    # each half gated by a RUNTIME conditional, not a
                    # mask: XLA executes only the taken branch, so
                    # warmup/cooldown bubble ticks cost one half (or
                    # nothing) instead of a full fwd+bwd — the classic
                    # (M + S - 1)-slot wall, and the reason the measured
                    # bubble share lands on (S-1)/(M+S-1) instead of
                    # twice that.  (It also means bubble ticks never run
                    # a vjp on garbage activations.)
                    mf = t - s
                    mb = t - (2 * n_stage - 2 - s)
                    f_on = (mf >= 0) & (mf < n_micro)
                    b_on = (mb >= 0) & (mb < n_micro)
                    carry = lax.cond(f_on, fwd_half, lambda c: c, carry)
                    return lax.cond(b_on, bwd_half, lambda c: c, carry)
                return br

            carry = lax.switch(idx, [mk_branch(s) for s in range(n_stage)],
                               carry)
            fwd_bufs, ct_bufs, rings, grad_acc, loss_acc = carry
            y_last = fwd_bufs[n_stage - 1]
            fwd_bufs = tuple(
                jax.tree.map(lambda a: lax.ppermute(a, axis, fwd_perm), b)
                for b in fwd_bufs)
            ct_bufs = tuple(
                jax.tree.map(lambda a: lax.ppermute(a, axis, bwd_perm), b)
                for b in ct_bufs)
            return (fwd_bufs, ct_bufs, rings, grad_acc, loss_acc), y_last

        carry = (tuple(zeros_of(b) for b in bshapes),
                 tuple(zeros_of(b) for b in bshapes),
                 tuple(jax.tree.map(
                     lambda a: jnp.zeros((rings_len[s] + 1,) + a.shape,
                                         a.dtype),
                     in_shapes[s]) for s in range(n_stage)),
                 jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                              params),
                 jnp.float32(0.0))
        # warmup + steady interleave under one scan; the S cooldown ticks
        # unroll so bucket reductions can issue at grad-ready points
        carry, ys = lax.scan(tick, carry, jnp.arange(ticks - n_stage))
        cool_y = []
        reduced = {}
        for k in range(n_stage):
            carry, y_last = tick(carry, jnp.int32(ticks - n_stage + k))
            cool_y.append(y_last)
            if buckets is not None:
                done = n_stage - 1 - k  # the stage this tick completed
                grad_acc = carry[3]
                for keys, st in buckets:
                    if st == done:
                        reduced.update(reduce_bucket(
                            {key: grad_acc[key] for key in keys}))
        _, _, _, grad_acc, loss_acc = carry
        # microbatch m leaves the last stage at tick m + S - 1; the last
        # one (m = n_micro - 1) exits on the FIRST cooldown tick
        out_last = jax.tree.map(
            lambda a, b: jnp.concatenate(
                [a[n_stage - 1:n_stage - 1 + n_micro - 1], b[None]], 0),
            ys, cool_y[0])
        valid = idx == n_stage - 1
        out_last = jax.tree.map(
            lambda a: a * valid.astype(a.dtype), out_last)
        outs, auxs = lax.psum(out_last, axis)
        loss = lax.psum(loss_acc, axis)
        if buckets is not None:
            grads = {key: reduced[key] for key in params}
        else:
            grads = lax.psum(grad_acc, red_axes)
        if data_axes:
            loss = lax.psum(loss, tuple(data_axes))
            auxs = lax.psum(auxs, tuple(data_axes))
        return loss, grads, outs, auxs

    pspec = jax.tree.map(lambda _: P(), params)
    xspec = P(None, *data_spec)
    operands, in_specs = (params, x), (pspec, xspec)
    if extra is not None:
        operands += (extra,)
        in_specs += (P(None, *list(data_spec)[:1]),)
    gspec = jax.tree.map(lambda _: P(), params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=in_specs, out_specs=(P(), gspec, xspec, P(None)),
        check_rep=False)(*operands)


def pipeline_train_step(stage_fn, loss_fn, stacked_params, x, labels, *,
                        mesh, axis="pipe", lr=0.1):
    """One jitted pipelined SGD step: forward pipeline, loss on the last
    stage's outputs, backward through the reverse pipeline, update.
    Returns (new_params, loss)."""
    def objective(params):
        out = pipeline_apply(stage_fn, params, x, mesh=mesh, axis=axis)
        return loss_fn(out, labels)

    loss, grads = jax.value_and_grad(objective)(stacked_params)
    new_params = jax.tree.map(lambda p, g: p - lr * g, stacked_params, grads)
    return new_params, loss
