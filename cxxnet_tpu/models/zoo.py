"""Config-text builders for the model zoo.

Each function returns the text of a ``netconfig=start/end`` block plus the
``input_shape`` (and, for sequence models, ``label_vec``) lines.  Global
training keys (batch_size, eta, dev, ...) are the caller's business — same
split as the reference's config files, where the net block and the training
section are independent (``src/nnet/nnet_config.h:255-287``).
"""

from __future__ import annotations

from typing import List, Sequence


def mlp(num_class: int = 10, input_dim: int = 784,
        hidden: Sequence[int] = (100,)) -> str:
    """Fully-connected softmax classifier (the MNIST.conf MLP shape).

    Hidden layers are named ``fc1..fcN``, the classifier head ``fcN+1``.
    """
    lines = ["netconfig=start"]
    for i, nh in enumerate(hidden):
        lines += [f"layer[+1] = fullc:fc{i + 1}", f"  nhidden = {nh}",
                  "layer[+0] = relu"]
    lines += [f"layer[+1] = fullc:fc{len(hidden) + 1}",
              f"  nhidden = {num_class}",
              "layer[+0] = softmax",
              "netconfig=end",
              f"input_shape = 1,1,{input_dim}"]
    return "\n".join(lines) + "\n"


def lenet(num_class: int = 10) -> str:
    """LeNet-style MNIST convnet (the MNIST_CONV.conf shape): two
    conv+pool stages and a 500-wide hidden layer."""
    return f"""
netconfig=start
layer[0->1] = conv:conv1
  kernel_size = 5
  nchannel = 20
layer[1->2] = max_pooling
  kernel_size = 2
  stride = 2
layer[2->3] = relu
layer[3->4] = conv:conv2
  kernel_size = 5
  nchannel = 50
layer[4->5] = max_pooling
  kernel_size = 2
  stride = 2
layer[5->6] = relu
layer[6->7] = flatten
layer[7->8] = fullc:fc1
  nhidden = 500
layer[8->9] = relu
layer[9->10] = fullc:fc2
  nhidden = {num_class}
layer[10->10] = softmax
netconfig=end
input_shape = 1,28,28
"""


def alexnet(num_class: int = 1000) -> str:
    """AlexNet (the ImageNet.conf:26-95 architecture): 5 conv stages with
    grouped conv2/4/5, LRN after conv1/2, three 4096/4096/num_class fullc
    layers with dropout."""
    return f"""
netconfig=start
layer[0->1] = conv:conv1
  kernel_size = 11
  stride = 4
  nchannel = 96
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 3
  stride = 2
layer[3->4] = lrn
  local_size = 5
  alpha = 0.001
  beta = 0.75
  knorm = 1
layer[4->5] = conv:conv2
  ngroup = 2
  kernel_size = 5
  pad = 2
  nchannel = 256
layer[5->6] = relu
layer[6->7] = max_pooling
  kernel_size = 3
  stride = 2
layer[7->8] = lrn
  local_size = 5
  alpha = 0.001
  beta = 0.75
  knorm = 1
layer[8->9] = conv:conv3
  kernel_size = 3
  pad = 1
  nchannel = 384
layer[9->10] = relu
layer[10->11] = conv:conv4
  ngroup = 2
  kernel_size = 3
  pad = 1
  nchannel = 384
layer[11->12] = relu
layer[12->13] = conv:conv5
  ngroup = 2
  kernel_size = 3
  pad = 1
  nchannel = 256
layer[13->14] = relu
layer[14->15] = max_pooling
  kernel_size = 3
  stride = 2
layer[15->16] = flatten
layer[16->17] = fullc:fc6
  nhidden = 4096
layer[17->18] = relu
layer[18->18] = dropout
  threshold = 0.5
layer[18->19] = fullc:fc7
  nhidden = 4096
layer[19->20] = relu
layer[20->20] = dropout
  threshold = 0.5
layer[20->21] = fullc:fc8
  nhidden = {num_class}
layer[21->21] = softmax
netconfig=end
input_shape = 3,227,227
"""


def _conv_relu(lines: List[str], bottom: str, top: str, name: str,
               nchannel: int, ksize: int, pad: int = 0,
               stride: int = 1, init: str = "xavier") -> str:
    lines += [f"layer[{bottom}->{top}] = conv:{name}",
              f"  kernel_size = {ksize}",
              f"  nchannel = {nchannel}",
              f"  random_type = {init}"]
    if stride != 1:
        lines.append(f"  stride = {stride}")
    if pad:
        lines.append(f"  pad = {pad}")
    lines.append("layer[+0] = relu")
    return top


def _inception(lines: List[str], name: str, bottom: str,
               n1x1: int, n3x3red: int, n3x3: int,
               n5x5red: int, n5x5: int, proj: int,
               init: str = "xavier") -> str:
    """Append a GoogLeNet inception module; returns the top node name.

    4-way split -> {1x1, 1x1->3x3, 1x1->5x5, pool->1x1} -> ch_concat (the
    concat layer's 4-input cap, concat_layer-inl.hpp, is exactly the branch
    count).  The pool branch relies on padded pooling — a superset of the
    reference's pooling, needed to keep the branch same-size.
    """
    sp = [f"{name}_sp{i}" for i in range(4)]
    lines.append(f"layer[{bottom}->{','.join(sp)}] = split")
    b0 = _conv_relu(lines, sp[0], f"{name}_b0", f"{name}_1x1", n1x1, 1,
                    init=init)
    _conv_relu(lines, sp[1], f"{name}_r3", f"{name}_3x3r", n3x3red, 1,
               init=init)
    b1 = _conv_relu(lines, f"{name}_r3", f"{name}_b1", f"{name}_3x3",
                    n3x3, 3, pad=1, init=init)
    _conv_relu(lines, sp[2], f"{name}_r5", f"{name}_5x5r", n5x5red, 1,
               init=init)
    b2 = _conv_relu(lines, f"{name}_r5", f"{name}_b2", f"{name}_5x5",
                    n5x5, 5, pad=2, init=init)
    lines += [f"layer[{sp[3]}->{name}_p] = max_pooling",
              "  kernel_size = 3", "  stride = 1", "  pad = 1"]
    b3 = _conv_relu(lines, f"{name}_p", f"{name}_b3", f"{name}_proj",
                    proj, 1, init=init)
    lines.append(f"layer[{b0},{b1},{b2},{b3}->{name}] = ch_concat")
    return name


def _aux_head(lines: List[str], name: str, bottom: str,
              num_class: int, init: str = "xavier") -> str:
    """GoogLeNet v1 auxiliary classifier: avgpool5/s3 -> 1x1 conv 128 ->
    fc1024 -> dropout 0.7 -> fc -> softmax at grad_scale 0.3.  Returns the
    trunk-continuation node.  The aux gradient injection is what lets the
    22-layer trunk train under plain SGD (measured: without the heads a
    512-sample memorization stalls at loss ~5.9; with them it collapses)."""
    main, aux = f"{name}_main", f"{name}_in"
    lines += [f"layer[{bottom}->{main},{aux}] = split",
              f"layer[{aux}->{name}_ap] = avg_pooling",
              "  kernel_size = 5", "  stride = 3"]
    _conv_relu(lines, f"{name}_ap", f"{name}_cv", f"{name}_conv", 128, 1,
               init=init)
    lines += [f"layer[{name}_cv->{name}_fl] = flatten",
              f"layer[{name}_fl->{name}_fc1] = fullc:{name}_fc1",
              "  nhidden = 1024",
              f"layer[+1:{name}_r] = relu",
              f"layer[{name}_r->{name}_r] = dropout",
              "  threshold = 0.7",
              f"layer[{name}_r->{name}_fc2] = fullc:{name}_fc2",
              f"  nhidden = {num_class}",
              f"layer[{name}_fc2->{name}_fc2] = softmax",
              "  grad_scale = 0.3"]
    return main


def googlenet(num_class: int = 1000, aux_heads: bool = True,
              init: str = "xavier") -> str:
    """GoogLeNet v1: 9 inception modules + the two auxiliary classifiers
    (after i4a and i4d, grad_scale 0.3 — the v1 recipe).

    No reference config exists (SURVEY.md §6: config-to-write, not
    config-to-port); channel plan is the canonical v1 table.
    """
    lines = ["netconfig=start"]
    _conv_relu(lines, "0", "c1", "conv1", 64, 7, pad=3, stride=2, init=init)
    lines += ["layer[c1->p1] = max_pooling",
              "  kernel_size = 3", "  stride = 2",
              "layer[p1->n1] = lrn",
              "  local_size = 5", "  alpha = 0.0001", "  beta = 0.75",
              "  knorm = 1"]
    _conv_relu(lines, "n1", "c2r", "conv2r", 64, 1, init=init)
    _conv_relu(lines, "c2r", "c2", "conv2", 192, 3, pad=1, init=init)
    lines += ["layer[c2->n2] = lrn",
              "  local_size = 5", "  alpha = 0.0001", "  beta = 0.75",
              "  knorm = 1",
              "layer[n2->p2] = max_pooling",
              "  kernel_size = 3", "  stride = 2"]
    top = _inception(lines, "i3a", "p2", 64, 96, 128, 16, 32, 32, init=init)
    top = _inception(lines, "i3b", top, 128, 128, 192, 32, 96, 64, init=init)
    lines += [f"layer[{top}->p3] = max_pooling",
              "  kernel_size = 3", "  stride = 2"]
    top = _inception(lines, "i4a", "p3", 192, 96, 208, 16, 48, 64, init=init)
    if aux_heads:
        top = _aux_head(lines, "aux1", top, num_class, init=init)
    top = _inception(lines, "i4b", top, 160, 112, 224, 24, 64, 64, init=init)
    top = _inception(lines, "i4c", top, 128, 128, 256, 24, 64, 64, init=init)
    top = _inception(lines, "i4d", top, 112, 144, 288, 32, 64, 64, init=init)
    if aux_heads:
        top = _aux_head(lines, "aux2", top, num_class, init=init)
    top = _inception(lines, "i4e", top, 256, 160, 320, 32, 128, 128, init=init)
    lines += [f"layer[{top}->p4] = max_pooling",
              "  kernel_size = 3", "  stride = 2"]
    top = _inception(lines, "i5a", "p4", 256, 160, 320, 32, 128, 128, init=init)
    top = _inception(lines, "i5b", top, 384, 192, 384, 48, 128, 128, init=init)
    lines += [f"layer[{top}->gp] = avg_pooling",
              "  kernel_size = 7", "  stride = 1",
              "layer[gp->gp] = dropout",
              "  threshold = 0.4",
              "layer[gp->fl] = flatten",
              "layer[fl->fc] = fullc:fc",
              f"  nhidden = {num_class}",
              "layer[fc->fc] = softmax",
              "netconfig=end",
              "input_shape = 3,224,224",
              # global default so the fullc heads (aux fc1/fc2, final fc)
              # follow the chosen init too; per-layer conv settings above
              # are explicit
              f"random_type = {init}"]
    return "\n".join(lines) + "\n"


def transformer(vocab: int, seq: int, dim: int, nlayer: int,
                nhead: int, causal: int = 1, ffn_mult: int = 4,
                packed: bool = False, moe_experts: int = 0,
                moe_capacity: float = 2.0) -> str:
    """Pre-norm decoder-only transformer LM.

    Input node is (b,1,1,seq) token ids, labels are per-position targets via
    ``label_vec[0,seq)``.  No reference counterpart (SURVEY.md §5.7) — this
    is the long-context model family; attention runs as ring attention when
    the trainer mesh has a ``seq`` axis.

    ``packed = True`` targets the document-packed LM data path
    (``io/text.py``): labels carry three fields
    (``label_vec[0,s)=label``, ``[s,2s)=segment``, ``[2s,3s)=position``),
    attention masks cross-document scores (``segment_key``), positional
    embeddings reset per document (``pos_key``), and the loss masks
    boundary/padding targets (``packed = 1``).

    ``moe_experts = E > 0`` replaces each block's dense FFN with a
    sparse ``moe`` layer (top-1 switch routing, ``layers/moe.py``) — the
    ``data x expert`` flagship family.
    """
    lines = ["netconfig=start",
             "layer[0->x0] = embedding:embed",
             f"  vocab_size = {vocab}",
             f"  nhidden = {dim}",
             "  pos_embed = 1",
             "  init_sigma = 0.02"]
    if packed:
        lines.append("  pos_key = position")
    top = "x0"
    for i in range(nlayer):
        a, m, nxt = f"b{i}a", f"b{i}m", f"x{i + 1}"
        lines += [
            f"layer[{top}->{a}_r,{a}_in] = split",
            f"layer[{a}_in->{a}_n] = layernorm:l{i}_ln1",
            f"layer[{a}_n->{a}_o] = attention:l{i}_att",
            f"  nhead = {nhead}",
            f"  causal = {causal}",
        ]
        if packed:
            lines.append("  segment_key = segment")
        lines += [
            f"layer[{a}_r,{a}_o->{m}] = eltsum",
        ]
        if moe_experts > 0:
            # the moe layer carries its own residual (y = x + gate*E(x)),
            # so no split/eltsum pair is needed around it — the
            # THREE_AXIS_NET idiom (__graft_entry__.py)
            lines += [
                f"layer[{m}->{m}_n] = layernorm:l{i}_ln2",
                f"layer[{m}_n->{nxt}] = moe:l{i}_moe",
                f"  num_expert = {moe_experts}",
                f"  nhidden = {ffn_mult * dim}",
                f"  capacity_factor = {moe_capacity}",
            ]
        else:
            lines += [
                f"layer[{m}->{m}_r,{m}_in] = split",
                f"layer[{m}_in->{m}_n] = layernorm:l{i}_ln2",
                f"layer[{m}_n->{m}_h] = seq_fullc:l{i}_ffn1",
                f"  nhidden = {ffn_mult * dim}",
                "layer[+0] = gelu",
                f"layer[{m}_h->{m}_o] = seq_fullc:l{i}_ffn2",
                f"  nhidden = {dim}",
                f"layer[{m}_r,{m}_o->{nxt}] = eltsum",
            ]
        top = nxt
    lines += [f"layer[{top}->fin] = layernorm:final_ln",
              "layer[fin->logits] = seq_fullc:head",
              f"  nhidden = {vocab}",
              "  no_bias = 1",
              "layer[+0] = softmax_seq"]
    if packed:
        lines.append("  packed = 1")
    lines += ["netconfig=end",
              f"input_shape = 1,1,{seq}",
              f"label_vec[0,{seq}) = label"]
    if packed:
        lines += [f"label_vec[{seq},{2 * seq}) = segment",
                  f"label_vec[{2 * seq},{3 * seq}) = position"]
    return "\n".join(lines) + "\n"


def _res_block(lines: List[str], name: str, bottom: str, w: int,
               stride: int, project: bool) -> str:
    """Basic residual block: two 3x3 conv+bn with an identity (or 1x1
    projected) shortcut summed by eltsum.  Fan-out goes through an explicit
    split layer, same idiom as the transformer blocks above."""
    lines += [f"layer[{bottom}->{name}_sc,{name}_in] = split",
              f"layer[{name}_in->{name}_c1] = conv:{name}_conv1",
              "  kernel_size = 3", "  pad = 1",
              f"  stride = {stride}", f"  nchannel = {w}", "  no_bias = 1",
              f"layer[{name}_c1->{name}_c1] = batch_norm:{name}_bn1",
              f"layer[{name}_c1->{name}_c1] = relu",
              f"layer[{name}_c1->{name}_c2] = conv:{name}_conv2",
              "  kernel_size = 3", "  pad = 1",
              f"  nchannel = {w}", "  no_bias = 1",
              f"layer[{name}_c2->{name}_c2] = batch_norm:{name}_bn2"]
    sc = f"{name}_sc"
    if project:
        lines += [f"layer[{sc}->{name}_p] = conv:{name}_proj",
                  "  kernel_size = 1",
                  f"  stride = {stride}", f"  nchannel = {w}", "  no_bias = 1",
                  f"layer[{name}_p->{name}_p] = batch_norm:{name}_bnp"]
        sc = f"{name}_p"
    lines += [f"layer[{sc},{name}_c2->{name}] = eltsum",
              f"layer[{name}->{name}] = relu"]
    return name


def resnet(num_class: int = 10, depth: int = 20,
           widths=(16, 32, 64), input_side: int = 32) -> str:
    """CIFAR-style ResNet (depth = 6n+2): three stages of basic blocks with
    widths 16/32/64, global average pooling, softmax head.

    No reference counterpart (the reference predates residual nets); the
    layer zoo's split/eltsum/batch_norm make it expressible, so this
    builder exists to exercise that family end-to-end.
    """
    assert (depth - 2) % 6 == 0, "resnet: depth must be 6n+2"
    n = (depth - 2) // 6
    lines = ["netconfig=start",
             "layer[0->stem] = conv:stem",
             "  kernel_size = 3", "  pad = 1",
             f"  nchannel = {widths[0]}", "  no_bias = 1",
             "layer[stem->stem] = batch_norm:stem_bn",
             "layer[stem->stem] = relu"]
    top = "stem"
    side = input_side
    for si, w in enumerate(widths):
        for bi in range(n):
            stride = 2 if (si > 0 and bi == 0) else 1
            # k=3/pad=1 conv: out side is ceil(side/stride), not floor
            side = (side + 2 - 3) // stride + 1
            top = _res_block(lines, f"s{si}b{bi}", top, w,
                             stride, project=stride != 1)
    lines += [f"layer[{top}->gp] = avg_pooling",
              f"  kernel_size = {side}", f"  stride = {side}",
              "layer[gp->fl] = flatten",
              "layer[fl->fc] = fullc:fc",
              f"  nhidden = {num_class}",
              "layer[fc->fc] = softmax",
              "netconfig=end",
              f"input_shape = 3,{input_side},{input_side}"]
    return "\n".join(lines) + "\n"


def vgg(num_class: int = 1000, depth: int = 16) -> str:
    """VGG-11/13/16/19: stacked 3x3 convs with 2x2 max pooling, three fullc
    layers with dropout.  Expressible entirely with the reference's layer
    zoo (conv/relu/max_pooling/fullc/dropout/softmax); no reference config
    exists, so this builder is authored like googlenet above."""
    plans = {11: (1, 1, 2, 2, 2), 13: (2, 2, 2, 2, 2),
             16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}
    assert depth in plans, f"vgg: depth must be one of {sorted(plans)}"
    widths = (64, 128, 256, 512, 512)
    lines = ["netconfig=start"]
    for si, (reps, w) in enumerate(zip(plans[depth], widths)):
        for ri in range(reps):
            lines += [f"layer[+1] = conv:s{si}c{ri}",
                      "  kernel_size = 3", "  pad = 1", f"  nchannel = {w}"]
            lines += ["layer[+0] = relu"]
        lines += ["layer[+1] = max_pooling", "  kernel_size = 2",
                  "  stride = 2"]
    lines += ["layer[+1] = flatten"]
    for i, nh in enumerate((4096, 4096)):
        lines += [f"layer[+1] = fullc:fc{i + 6}", f"  nhidden = {nh}",
                  "layer[+0] = relu", "layer[+0] = dropout",
                  "  threshold = 0.5"]
    lines += [f"layer[+1] = fullc:fc8", f"  nhidden = {num_class}",
              "layer[+0] = softmax",
              "netconfig=end",
              "input_shape = 3,224,224",
              "random_type = xavier"]
    return "\n".join(lines) + "\n"
