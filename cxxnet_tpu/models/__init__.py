"""Model zoo: config-text builders for the parity model families.

The reference ships models as hand-written config files
(``example/MNIST/MNIST.conf``, ``example/MNIST/MNIST_CONV.conf``,
``example/ImageNet/ImageNet.conf``); GoogLeNet has no reference config but
its layer zoo (split/ch_concat/padded pooling) makes it expressible
(SURVEY.md §6).  These builders emit the same ``netconfig=start/end`` config
language, so everything downstream (NetConfig, trainer, checkpointing,
wrapper) treats zoo models identically to user-written config files.
"""

from .zoo import alexnet, googlenet, lenet, mlp, resnet, transformer, vgg

__all__ = ["alexnet", "googlenet", "lenet", "mlp", "resnet",
           "transformer", "vgg"]
