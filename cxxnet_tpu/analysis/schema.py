"""Declared-key schema + lint findings: the shared vocabulary of graftlint.

The config surface is the framework's API (``name = value`` pairs,
SURVEY.md §5.6) and the reference's worst contract rule is that unknown
keys are silently ignored (``layers/base.py`` Layer.set_param).  The
lint pass needs every subsystem to *declare* the keys it consumes;
:class:`KeySpec` is the declaration record and :class:`Finding` the
structured lint result.  This module is intentionally dependency-free —
layers, iterators, updaters, the engine, and the trainer all import it
to declare their keys without creating cycles with ``analysis/``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

#: finding severities, most severe first; ``error`` findings make
#: ``task=check`` / tools/graftlint.py exit nonzero
SEVERITIES = ("error", "warn", "info")


@dataclasses.dataclass(frozen=True)
class KeySpec:
    """One accepted config key.

    ``kind`` drives value validation: ``int`` / ``float`` parse checks
    (with optional ``lo``/``hi`` range), ``enum`` membership in
    ``choices``, ``str``/``path`` accept anything.  ``soft = True``
    downgrades a value violation from error to warn (for keys whose
    consumer deliberately tolerates odd spellings, e.g.
    ``output_format``).  ``check`` overrides everything: a callable
    ``val -> error message or None`` (the engine options reuse their own
    validators through it).
    """

    name: str
    kind: str = "str"  # str | path | int | float | enum
    choices: Tuple[str, ...] = ()
    lo: Optional[float] = None
    hi: Optional[float] = None
    soft: bool = False
    help: str = ""
    check: Optional[Callable[[str], Optional[str]]] = None


def K(name: str, kind: str = "str", **kw) -> KeySpec:
    """Terse KeySpec constructor for declaration tables."""
    return KeySpec(name=name, kind=kind, **kw)


@dataclasses.dataclass
class Finding:
    """One structured lint result (config lint and jaxpr lint share it)."""

    severity: str          # error | warn | info
    key: str               # offending config key ("" for graph findings)
    message: str
    suggestion: str = ""   # did-you-mean replacement, when one exists
    scope: str = ""        # "global" | "iter:<name>" | "layer:<type>" | "jaxpr"

    def to_dict(self) -> dict:
        d = {"severity": self.severity, "key": self.key,
             "message": self.message}
        if self.suggestion:
            d["suggestion"] = self.suggestion
        if self.scope:
            d["scope"] = self.scope
        return d

    def format(self) -> str:
        loc = f" [{self.scope}]" if self.scope else ""
        key = f" {self.key}:" if self.key else ""
        sugg = f" (did you mean {self.suggestion!r}?)" if self.suggestion \
            else ""
        return f"{self.severity:5s}{loc}{key} {self.message}{sugg}"


def check_value(spec: KeySpec, val: str) -> Optional[Tuple[str, str]]:
    """Validate ``val`` against ``spec``; returns (severity, message) on a
    violation, None when the value is acceptable."""
    if spec.check is not None:
        msg = spec.check(val)
        return (("warn" if spec.soft else "error"), msg) if msg else None
    if spec.kind == "int":
        try:
            x = int(val)
        except ValueError:
            return ("warn" if spec.soft else "error",
                    f"expected an integer, got {val!r}")
        return _range_check(spec, x)
    if spec.kind == "float":
        try:
            x = float(val)
        except ValueError:
            return ("warn" if spec.soft else "error",
                    f"expected a number, got {val!r}")
        return _range_check(spec, x)
    if spec.kind == "enum":
        if val not in spec.choices:
            return ("warn" if spec.soft else "error",
                    f"expected one of {'/'.join(spec.choices)}, got {val!r}")
    return None


def _range_check(spec: KeySpec, x) -> Optional[Tuple[str, str]]:
    # range violations are warnings: the parse succeeded, the consumer may
    # still clamp or tolerate the value — the type error above is the hard
    # contract
    if spec.lo is not None and x < spec.lo:
        return ("warn", f"value {x} below minimum {spec.lo}")
    if spec.hi is not None and x > spec.hi:
        return ("warn", f"value {x} above maximum {spec.hi}")
    return None


def edit_distance(a: str, b: str, limit: int = 4) -> int:
    """Levenshtein distance with an early-out band (small strings only)."""
    if a == b:
        return 0
    if abs(len(a) - len(b)) > limit:
        return limit + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        if min(cur) > limit:
            return limit + 1
        prev = cur
    return prev[-1]


def did_you_mean(name: str, candidates) -> str:
    """Closest declared key within a length-scaled edit distance, or ''."""
    limit = 2 if len(name) >= 5 else (1 if len(name) >= 3 else 0)
    if limit == 0:
        return ""
    best, best_d = "", limit + 1
    for c in candidates:
        d = edit_distance(name, c, limit)
        if d < best_d or (d == best_d and c < best):
            best, best_d = c, d
    return best if best_d <= limit else ""
