#!/usr/bin/env python3
"""racelint — guarded-by concurrency lint for the host-side thread fleet.

Third leg of the static-analysis stack: graftlint checks configs,
spmdlint checks the device program, racelint checks the host program.
The serving/checkpoint/io planes run a fleet of Python threads
(MicroBatcher/StepScheduler dispatchers, DevicePrefetcher producers,
AsyncCheckpointWriter, the serve-sentinel reporter, AdminServer's
acceptor and per-connection handlers).  The same bug class — an
attribute touched from two threads without a declared discipline — has
been re-found by hand at least four times.  racelint encodes the
discipline once and enforces it tree-wide.

Model
-----
Per class, discover every *thread context*:

* ``threading.Thread(target=self._m)``  → worker context ``_m``
* ``threading.Thread(target=local_fn)`` → worker context ``local_fn``
  (a function defined in the same method)
* a ``run()`` override on a ``Thread`` subclass
* a request-handler class nested in a method (``BaseHTTPRequestHandler``
  subclass reaching the owner through an ``alias = self`` binding) —
  context ``handler``, which counts as *many* threads (ThreadingHTTPServer
  spawns one per connection)
* an explicit ``# racelint: thread(<name>)`` marker on a ``def`` — for
  entry points invoked from foreign threads the AST cannot see (e.g.
  ``Histogram.observe`` called from every serve client).  The reserved
  name ``shared`` means "many concurrent threads at once".

Everything not reachable from a worker entry runs in the ``client``
context (the constructing/driving thread).  ``__init__`` (and the
iterator contract's pre-thread ``init``/``set_param``) is *construction*:
its writes declare attributes, they are not mutations.

Any attribute written post-construction and touched from more than one
context must carry a policy comment on its declaration line::

    self._pending = 0        # racelint: guarded-by(self._lock)
    self.n_requests = 0      # racelint: atomic(plain-int bump, single writer)
    self._failed = None      # racelint: latch(write-once then read)

``guarded-by`` is verified lexically: every access must sit inside a
``with`` on one of the named locks (several spellings may alias one lock,
e.g. a ``Condition`` wrapping it).  ``atomic`` documents the GIL-atomic
whitelist (plain-int bumps with a single writer, whole-object swaps,
``copy_racy`` reads); a read-modify-write on an atomic attribute from
more than one context is still an error — the whitelist does not cover
lost updates.  ``latch`` is the failure-latch idiom: whole-object
write-once-ish stores, racy reads tolerated by design.

Findings (all ERROR severity; stable ids):

==================== ====================================================
race_undeclared      attribute mutated cross-thread with no policy
race_unguarded       guarded-by attribute touched outside its lock
race_check_then_act  guarded test and dependent write under different
                     lock acquisitions
race_rmw             read-modify-write of an atomic/latch attribute from
                     concurrent contexts
race_thread_name     ``Thread(...)`` without a ``cxxnet-*`` name
race_bad_decl        malformed policy (empty reason, unknown lock, ...)
race_pragma_reason   suppression pragma without a written reason
race_parse           file does not parse
==================== ====================================================

Escape hatch (a reason is mandatory — satellite rule: no pragma without
a written reason)::

    x = f()  # racelint: ok(race_unguarded) — watermark is a GIL-atomic read
    # racelint: ok-file(race_thread_name) — fixture threads are anonymous

Zero third-party imports; runnable standalone (``python
cxxnet_tpu/analysis/racelint.py --json``) so ``tools/lint.sh`` and the
tier-1 gate pay no framework import cost.  ``monitor/threadcheck.py``
(the runtime lock-witness) reuses :func:`collect_policies` to learn
which attributes are guarded by which locks.
"""

# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES = (
    "race_undeclared", "race_unguarded", "race_check_then_act",
    "race_rmw", "race_thread_name", "race_bad_decl",
    "race_pragma_reason", "race_parse",
)

# construction contexts: the object-isn't-shared-yet window.  __init__ by
# definition; init/set_param by the iterator contract (factory calls them
# before before_first starts any producer thread).
CONSTRUCTION_METHODS = ("__init__", "__post_init__", "init", "set_param")

# context names with more than one concurrent thread behind them: a
# single-context RMW is still a lost update there
SHARED_CONTEXTS = ("handler", "shared")

# mutating container methods: ``self._ring.append(x)`` is a write to
# ``_ring`` even though the attribute itself is only Load-ed.  Queue
# put/get are deliberately absent (queue.Queue is internally locked).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "add", "update",
    "sort", "reverse", "rotate",
})
# single C-level dict ops: mutations, but check-and-act in one bytecode —
# they cannot lose a concurrent update, so they satisfy ``atomic``
_ATOMIC_MUTATORS = frozenset({"setdefault"})

_PRAGMA = re.compile(
    r"#\s*racelint:\s*(ok-file|ok)\s*"
    r"(?:\(([^)]*)\))?\s*(?:[—–-]+\s*(\S.*))?")
_POLICY = re.compile(
    r"#\s*racelint:\s*(guarded-by|atomic|latch)\s*\(([^)]*)\)")
_THREAD_MARK = re.compile(r"#\s*racelint:\s*thread\s*\(([^)]*)\)")
_ANY_DIRECTIVE = re.compile(r"#\s*racelint:")

DEFAULT_PATHS = ("cxxnet_tpu", "tools", "bench.py")


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class Policy:
    kind: str            # guarded-by | atomic | latch
    args: Tuple[str, ...]  # lock attr names for guarded-by, (reason,) else
    line: int
    comment_only: bool = True  # directive on its own line (may attach to
    #                            the assignment BELOW); a trailing
    #                            directive only covers its own line


@dataclasses.dataclass
class Access:
    attr: str
    kind: str            # "read" | "write"
    rmw: bool            # read-modify-write (AugAssign / container mutator)
    line: int
    ctx_method: str      # method the access lexically lives in
    locks: Tuple[str, ...]   # self-attr locks held (enclosing with blocks)
    with_id: Optional[int]   # id of innermost lock-with (check-then-act)


# --------------------------------------------------------------------------
# source-comment harvesting


def _pragmas(src: str):
    """Return (per_line, file_wide, reasonless_lines).

    per_line: {lineno: set(rules) or None (= all rules)}
    file_wide: set(rules) or None
    reasonless_lines: pragma sites missing the mandatory reason text.
    """
    per_line: Dict[int, Optional[Set[str]]] = {}
    file_wide: Optional[Set[str]] = set()
    has_file_wide = False
    reasonless: List[int] = []
    for i, text in enumerate(src.splitlines(), start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        which, rules, reason = m.group(1), m.group(2), m.group(3)
        ruleset = (set(r.strip() for r in rules.split(",") if r.strip())
                   if rules else None)
        if not (reason and reason.strip()):
            reasonless.append(i)
        if which == "ok-file":
            has_file_wide = True
            if ruleset is None:
                file_wide = None
            elif file_wide is not None:
                file_wide |= ruleset
        else:
            per_line[i] = ruleset
    if not has_file_wide:
        file_wide = set()
    return per_line, file_wide, reasonless


def _suppressed(f: Finding, per_line, file_wide) -> bool:
    if file_wide is None or f.rule in file_wide:
        return True
    for ln in (f.line, f.line - 1):
        if ln in per_line:
            rules = per_line[ln]
            if rules is None or f.rule in rules:
                return True
    return False


def _line_directives(src: str):
    """Map lineno -> (policy | thread-mark | pragma | malformed)."""
    policies: Dict[int, Policy] = {}
    thread_marks: Dict[int, str] = {}
    malformed: List[Tuple[int, str]] = []
    for i, text in enumerate(src.splitlines(), start=1):
        if not _ANY_DIRECTIVE.search(text):
            continue
        m = _POLICY.search(text)
        if m:
            kind, raw = m.group(1), m.group(2)
            args = tuple(a.strip() for a in raw.split(",")) \
                if kind == "guarded-by" else (raw.strip(),)
            policies[i] = Policy(kind, args, i,
                                 text.lstrip().startswith("#"))
            continue
        m = _THREAD_MARK.search(text)
        if m:
            thread_marks[i] = m.group(1).strip()
            continue
        if _PRAGMA.search(text):
            continue
        malformed.append((i, text.strip()))
    return policies, thread_marks, malformed


# --------------------------------------------------------------------------
# AST helpers


def _set_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._racelint_parent = node  # type: ignore[attr-defined]


def _parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_racelint_parent", None)


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return True
    if isinstance(fn, ast.Name) and fn.id == "Thread":
        return True
    return False


def _thread_name_ok(call: ast.Call) -> bool:
    """name= must be a literal (or f-string head) starting with cxxnet-."""
    for kw in call.keywords:
        if kw.arg != "name":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return v.value.startswith("cxxnet-")
        if isinstance(v, ast.JoinedStr) and v.values:
            head = v.values[0]
            return (isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    and head.value.startswith("cxxnet-"))
        return False  # dynamic name: cannot verify, demand a literal head
    return False


def _self_attr(node: ast.AST, selves: Set[str]) -> Optional[str]:
    """``self.x`` (or ``alias.x`` for a known self-alias) -> ``x``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id in selves:
        return node.attr
    return None


# --------------------------------------------------------------------------
# per-class analysis


class _ClassScan:
    """One class: methods, entries, call edges, accesses."""

    def __init__(self, cls: ast.ClassDef, policies: Dict[int, Policy],
                 thread_marks: Dict[int, str]):
        self.cls = cls
        self.name = cls.name
        self.methods: Dict[str, ast.AST] = {}
        # entry method -> (context name, shared?)
        self.entries: Dict[str, Tuple[str, bool]] = {}
        self.edges: Dict[str, Set[str]] = {}
        self.accesses: List[Access] = []
        self.policy: Dict[str, Policy] = {}      # attr -> policy
        self.decl_lines: Dict[str, int] = {}     # attr -> first decl line
        self.lock_attrs: Set[str] = set()        # attrs ever used as a lock
        self.assigned_attrs: Set[str] = set()
        self._policies = policies
        self._thread_marks = thread_marks
        # nodes that are Thread(target=...) references, NOT call edges
        self._target_refs: Set[int] = set()
        self._is_thread_subclass = any(
            (isinstance(b, ast.Name) and b.id == "Thread") or
            (isinstance(b, ast.Attribute) and b.attr == "Thread")
            for b in cls.bases)
        self._collect_methods()
        self._discover_entries()
        self._walk_methods()

    # -- structure -----------------------------------------------------

    def _collect_methods(self) -> None:
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node

    def _marker_for(self, fn: ast.AST) -> Optional[str]:
        """thread(<name>) marker on the def line or the line above it
        (decorators shift lineno, so scan decorator lines too)."""
        lines = [fn.lineno, fn.lineno - 1]
        for dec in getattr(fn, "decorator_list", []):
            lines += [dec.lineno, dec.lineno - 1]
        for ln in lines:
            if ln in self._thread_marks:
                return self._thread_marks[ln]
        return None

    def _discover_entries(self) -> None:
        if self._is_thread_subclass and "run" in self.methods:
            self.entries["run"] = ("run", False)
        for mname, fn in self.methods.items():
            mark = self._marker_for(fn)
            if mark:
                self.entries[mname] = (mark, mark in SHARED_CONTEXTS)
            local_defs = {n.name for n in ast.walk(fn)
                          if isinstance(n, ast.FunctionDef) and n is not fn}
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and _is_thread_ctor(node)):
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tgt = kw.value
                    attr = _self_attr(tgt, {"self"})
                    if attr and attr in self.methods:
                        self.entries.setdefault(attr, (attr, False))
                        self._target_refs.add(id(tgt))
                    elif isinstance(tgt, ast.Name) and \
                            tgt.id in local_defs:
                        self.entries.setdefault(
                            f"{mname}.{tgt.id}", (tgt.id, False))

    # -- body walk -----------------------------------------------------

    def _walk_methods(self) -> None:
        for mname, fn in self.methods.items():
            self._walk_body(fn, ctx_method=mname, selves={"self"})

    def _walk_body(self, fn: ast.AST, ctx_method: str,
                   selves: Set[str]) -> None:
        """Collect accesses/edges for one method, recursing into nested
        defs (worker-target closures get their own context; other
        closures inherit), and nested handler classes (alias = self)."""
        selves = set(selves)
        lock_stack: List[Tuple[str, int]] = []  # (lock attr, with-node id)

        nested_entries = {
            key.split(".", 1)[1] for key in self.entries
            if key.startswith(ctx_method + ".")}

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.FunctionDef) and node is not fn:
                if node.name in nested_entries and "." not in ctx_method:
                    # worker-target closure: its own thread context
                    self._walk_body(node, f"{ctx_method}.{node.name}",
                                    selves)
                else:  # plain closure: runs in the enclosing context
                    for child in ast.iter_child_nodes(node):
                        visit(child)
                return
            if isinstance(node, ast.ClassDef):
                self._walk_handler_class(node, ctx_method, selves)
                return
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in selves:
                for t in node.targets:  # alias = self
                    if isinstance(t, ast.Name):
                        selves.add(t.id)
            if isinstance(node, ast.With):
                entered = []
                for item in node.items:
                    lk = _self_attr(item.context_expr, selves)
                    if lk is not None:
                        entered.append(lk)
                        self.lock_attrs.add(lk)
                for lk in entered:
                    lock_stack.append((lk, id(node)))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                for _ in entered:
                    lock_stack.pop()
                return
            self._record(node, ctx_method, selves, lock_stack)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for child in ast.iter_child_nodes(fn):
            visit(child)

    def _walk_handler_class(self, cls: ast.ClassDef, ctx_method: str,
                            selves: Set[str]) -> None:
        """A request-handler class nested in a method: its methods run on
        per-connection server threads; the outer object is reached via an
        ``alias = self`` captured name, never ``self`` (which rebinds to
        the handler instance).  Non-handler nested classes just inherit
        the enclosing context."""
        outer = selves - {"self"}
        is_handler = any("Handler" in ast.dump(b) for b in cls.bases)
        for node in cls.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if is_handler and outer:
                self.entries.setdefault(
                    f"handler.{node.name}", ("handler", True))
                self._walk_body(node, f"handler.{node.name}", outer)
            else:
                self._walk_body(node, ctx_method, selves - {"self"})

    def _record(self, node: ast.AST, ctx_method: str, selves: Set[str],
                lock_stack) -> None:
        attr = _self_attr(node, selves)
        if attr is None:
            return
        if id(node) in self._target_refs:
            return  # Thread(target=self._m): context seed, not a call
        if attr in self.methods:
            # self.m(...) call or self.prop read: a call-graph edge (the
            # callee runs in this context), not a data access
            self.edges.setdefault(ctx_method, set()).add(attr)
            return
        parent = _parent(node)
        locks = tuple(lk for lk, _ in lock_stack)
        with_id = lock_stack[-1][1] if lock_stack else None
        line = node.lineno

        def add(kind: str, rmw: bool = False) -> None:
            self.accesses.append(Access(
                attr, kind, rmw, line, ctx_method, locks, with_id))

        if isinstance(node.ctx, (ast.Store, ast.Del)):  # type: ignore
            self.assigned_attrs.add(attr)
            if attr not in self.decl_lines:
                self.decl_lines[attr] = line
            pol = self._policies.get(line)
            if pol is None:
                prev = self._policies.get(line - 1)
                if prev is not None and prev.comment_only:
                    pol = prev
            if pol and attr not in self.policy:
                self.policy[attr] = pol
            rmw = isinstance(parent, ast.AugAssign)
            add("write", rmw=rmw)
            if rmw:
                add("read")
            return
        # Load context: classify container mutation / subscript store
        if isinstance(parent, ast.Subscript) and parent.value is node:
            gp = _parent(parent)
            sub_store = isinstance(parent.ctx, (ast.Store, ast.Del))
            sub_aug = isinstance(gp, ast.AugAssign) and gp.target is parent
            if sub_store or sub_aug:
                add("write", rmw=sub_aug)
                if sub_aug:
                    add("read")
                return
        if isinstance(parent, ast.Attribute) and parent.value is node:
            gp = _parent(parent)
            if isinstance(gp, ast.Call) and gp.func is parent and \
                    parent.attr in _MUTATORS:
                add("write", rmw=True)
                return
            if isinstance(gp, ast.Call) and gp.func is parent and \
                    parent.attr in _ATOMIC_MUTATORS:
                add("write", rmw=False)
                return
        add("read")

    # -- context propagation -------------------------------------------

    def contexts(self) -> Dict[str, Set[Tuple[str, bool]]]:
        """method-or-entry key -> set of (context, shared) it runs in."""
        ctx: Dict[str, Set[Tuple[str, bool]]] = \
            {m: set() for m in self.methods}
        for key in self.edges:
            ctx.setdefault(key, set())
        for key, (cname, shared) in self.entries.items():
            ctx.setdefault(key, set()).add((cname, shared))
        # client seeds: plain methods nobody in-class calls and that are
        # not worker entries — they are driven by the owning thread
        called: Set[str] = set()
        for tos in self.edges.values():
            called |= tos
        for m in self.methods:
            if m not in self.entries and m not in called:
                ctx[m].add(("client", False))
        # fixpoint over call edges (nested-entry keys "m.f" call through
        # their own edges entry if any)
        changed = True
        while changed:
            changed = False
            for frm, tos in self.edges.items():
                src = ctx.get(frm, set())
                for to in tos:
                    if to in ctx and not src <= ctx[to]:
                        ctx[to] |= src
                        changed = True
        return ctx


def _ctx_weight(ctxs: Set[Tuple[str, bool]]) -> int:
    """Concurrency degree of a context set: distinct names, shared
    contexts counting double."""
    n = 0
    for _, shared in ctxs:
        n += 2 if shared else 1
    return n


def _lint_class(scan: _ClassScan, path: str,
                findings: List[Finding]) -> None:
    ctx_of = scan.contexts()

    def ctxs_at(acc: Access) -> Set[Tuple[str, bool]]:
        return ctx_of.get(acc.ctx_method, {("client", False)})

    has_worker = any(
        c != "client" for cs in ctx_of.values() for c, _ in cs)

    # policy sanity — verified even in worker-less classes so stale
    # annotations cannot rot silently
    for attr, pol in scan.policy.items():
        if pol.kind == "guarded-by":
            bad = [a for a in pol.args
                   if not a.startswith("self.")
                   or a[5:] not in scan.assigned_attrs]
            if bad or not pol.args or not pol.args[0]:
                findings.append(Finding(
                    path, pol.line, "race_bad_decl",
                    f"{scan.name}.{attr}: guarded-by names "
                    f"{', '.join(bad) or 'nothing'} — each must be a "
                    "self.<lock> assigned in this class"))
        elif not pol.args[0]:
            findings.append(Finding(
                path, pol.line, "race_bad_decl",
                f"{scan.name}.{attr}: {pol.kind}() needs a written "
                "reason (the whitelist is documented, not assumed)"))

    by_attr: Dict[str, List[Access]] = {}
    for acc in scan.accesses:
        by_attr.setdefault(acc.attr, []).append(acc)

    for attr, accs in sorted(by_attr.items()):
        pol = scan.policy.get(attr)
        live = [a for a in accs
                if a.ctx_method.split(".", 1)[0]
                not in CONSTRUCTION_METHODS]
        if pol is not None and pol.kind == "guarded-by":
            locks = {a[5:] for a in pol.args if a.startswith("self.")}
            for a in live:
                if not (set(a.locks) & locks):
                    findings.append(Finding(
                        path, a.line, "race_unguarded",
                        f"{scan.name}.{attr} touched outside its "
                        f"declared lock ({', '.join(sorted(locks))}) — "
                        "hold the lock, or re-declare the policy"))
            _check_then_act(scan, attr, locks, path, findings)
            continue
        # cross-thread mutation detection
        writes = [a for a in live if a.kind == "write"]
        if not writes:
            continue
        all_ctxs: Set[Tuple[str, bool]] = set()
        for a in live:
            all_ctxs |= ctxs_at(a)
        if _ctx_weight(all_ctxs) < 2 or not has_worker:
            continue
        write_ctxs: Set[Tuple[str, bool]] = set()
        for a in writes:
            write_ctxs |= ctxs_at(a)
        if pol is None:
            names = sorted({c for c, _ in all_ctxs})
            findings.append(Finding(
                path, scan.decl_lines.get(attr, writes[0].line),
                "race_undeclared",
                f"{scan.name}.{attr} is mutated across thread contexts "
                f"({', '.join(names)}) with no declared policy — "
                "annotate guarded-by(self.<lock>) / atomic(reason) / "
                "latch(reason) on its declaration"))
            continue
        # atomic / latch: RMW from concurrent contexts is a lost update
        rmw_ctxs: Set[Tuple[str, bool]] = set()
        for a in writes:
            if a.rmw:
                rmw_ctxs |= ctxs_at(a)
        if rmw_ctxs and _ctx_weight(rmw_ctxs) >= 2:
            a = next(x for x in writes if x.rmw)
            findings.append(Finding(
                path, a.line, "race_rmw",
                f"{scan.name}.{attr} is declared {pol.kind} but is "
                "read-modify-written from concurrent contexts "
                f"({', '.join(sorted(c for c, _ in rmw_ctxs))}) — the "
                "GIL-atomic whitelist does not cover lost updates; "
                "guard it with a lock"))


def _check_then_act(scan: _ClassScan, attr: str, locks: Set[str],
                    path: str, findings: List[Finding]) -> None:
    """A guarded test and a guarded dependent write under *different*
    lock acquisitions: each access is locked, the decision is not."""
    reads = {a.line: a for a in scan.accesses
             if a.attr == attr and a.kind == "read" and a.with_id}
    writes = [a for a in scan.accesses
              if a.attr == attr and a.kind == "write" and a.with_id]
    for node in ast.walk(scan.cls):
        if not isinstance(node, ast.If):
            continue
        test_accs = [reads[n.lineno] for n in ast.walk(node.test)
                     if _self_attr(n, {"self"}) == attr
                     and n.lineno in reads]
        if not test_accs:
            continue
        body_lines = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if hasattr(sub, "lineno"):
                    body_lines.add(sub.lineno)
        for w in writes:
            if w.line in body_lines and \
                    w.with_id != test_accs[0].with_id:
                findings.append(Finding(
                    path, w.line, "race_check_then_act",
                    f"{scan.name}.{attr}: the test at line "
                    f"{test_accs[0].line} and this write hold "
                    f"{'/'.join(sorted(locks))} separately — the "
                    "condition can go stale between them; widen to one "
                    "acquisition"))


# --------------------------------------------------------------------------
# file / tree driver


def lint_file(path: str, src: Optional[str] = None) -> List[Finding]:
    if src is None:
        with open(path, encoding="utf-8") as fo:
            src = fo.read()
    findings: List[Finding] = []
    per_line, file_wide, reasonless = _pragmas(src)
    for ln in reasonless:
        findings.append(Finding(
            path, ln, "race_pragma_reason",
            "suppression pragma without a reason — write one: "
            "`# racelint: ok(rule) — why this is safe`"))
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        findings.append(Finding(
            path, e.lineno or 1, "race_parse",
            f"file does not parse: {e.msg}"))
        return findings
    _set_parents(tree)
    policies, thread_marks, malformed = _line_directives(src)
    for ln, text in malformed:
        findings.append(Finding(
            path, ln, "race_bad_decl",
            f"unrecognized racelint directive: {text!r}"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_thread_ctor(node) \
                and not _thread_name_ok(node):
            findings.append(Finding(
                path, node.lineno, "race_thread_name",
                "Thread without a literal cxxnet-* name= — unnamed "
                "threads are unattributable in span/flight captures"))
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            scan = _ClassScan(node, policies, thread_marks)
            _lint_class(scan, path, findings)
    return [f for f in findings
            if not _suppressed(f, per_line, file_wide)]


def collect_policies(path: str, src: Optional[str] = None
                     ) -> Dict[str, Dict[str, Policy]]:
    """{class name: {attr: Policy}} for one file — the lock-witness
    sanitizer (monitor/threadcheck.py) derives its attr→lock map from
    the same parser the lint uses, so the two can never disagree."""
    if src is None:
        with open(path, encoding="utf-8") as fo:
            src = fo.read()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return {}
    _set_parents(tree)
    policies, thread_marks, _ = _line_directives(src)
    out: Dict[str, Dict[str, Policy]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            scan = _ClassScan(node, policies, thread_marks)
            if scan.policy:
                out[node.name] = dict(scan.policy)
    return out


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in sorted(dirs)
                           if d != "__pycache__"]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    paths = argv or [p for p in DEFAULT_PATHS if os.path.exists(p)]
    findings: List[Finding] = []
    n_files = 0
    for path in iter_py_files(paths):
        n_files += 1
        findings.extend(lint_file(path))
    code = 1 if findings else 0
    if as_json:
        print(json.dumps({
            "kind": "racelint", "n_files": n_files, "exit": code,
            "findings": [dataclasses.asdict(f) for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f)
        print(f"racelint: {n_files} files, {len(findings)} finding(s)")
    return code


if __name__ == "__main__":
    raise SystemExit(main())
