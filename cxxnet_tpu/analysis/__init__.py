"""Static analysis: config lint (conflint) + traced-graph lint (jaxpr_lint).

``run_check`` is the shared driver behind ``task = check`` (main.py) and
``tools/graftlint.py``.  Only the dependency-free schema is imported
eagerly; the lint passes import the full framework lazily so
``layers/base.py`` (which imports :mod:`.schema` for its key
declarations) never cycles through here.
"""

from __future__ import annotations

from typing import List, Tuple

from .schema import Finding, K, KeySpec  # noqa: F401 (re-export)


def run_check(cfg, path: str = "", trace: bool = True,
              spmd: bool = None) -> Tuple[List[Finding], int]:
    """Lint an ordered config-pair list; returns (findings, exit_code).

    Static config lint always runs; the traced-graph lint additionally
    builds the configured net on CPU and walks the step jaxpr when the
    config carries a ``netconfig`` block (pred-from-checkpoint configs
    don't) and ``trace`` is on.  The SPMD deep lint
    (analysis/spmdlint.py: collective-consistency, donation audit,
    dtype-flow) rides the same traced pass; ``spmd = None`` follows the
    config's ``spmd_check`` key (default on).  Exit code 1 iff any
    error-severity finding."""
    from . import conflint
    findings = conflint.lint_pairs(cfg, path=path)
    has_net = any(k.startswith("layer[") for k, _ in cfg)
    # warn about --no-trace starving the SPMD lint only when it was
    # EXPLICITLY requested (--spmd or spmd_check = 1 in the config) —
    # the default-on case would turn every fast config-lint-only sweep
    # into one noise line per config (the mem_check guard's rule)
    spmd_explicit = spmd is True or dict(cfg).get("spmd_check") == "1"
    if spmd is None:
        spmd = dict(cfg).get("spmd_check", "1") == "1"
    if spmd_explicit and not trace and has_net:
        findings.append(Finding(
            "warn", "spmd_check",
            "the SPMD deep lint needs the traced-graph pass; --no-trace "
            "disables it", scope="spmd"))
    if dict(cfg).get("mem_check", "0") == "1" \
            and (not trace or not has_net):
        findings.append(Finding(
            "warn", "mem_check",
            "the OOM pre-flight needs the traced-graph pass (it models "
            "the built net); " + ("--no-trace disables it"
                                  if not trace else
                                  "this config has no netconfig block"),
            scope="mem"))
    if not trace:
        pass
    elif not has_net:
        findings.append(Finding(
            "info", "", "no netconfig block in this config; "
            "traced-graph lint skipped", scope="jaxpr"))
    else:
        findings.extend(_trace_findings(cfg, spmd=spmd))
    n_err = sum(1 for f in findings if f.severity == "error")
    return findings, (1 if n_err else 0)


def _ensure_host_devices(n: int) -> None:
    """Best-effort: ask XLA's host platform for >= ``n`` CPU devices so a
    mesh config can trace (parallel/mesh.ensure_host_platform_devices).
    Only effective before the first backend initialization (graftlint.py
    sets the flag at process start; under pytest the conftest already
    forces 8) — callers must still check ``len(jax.devices())``
    afterwards and skip gracefully."""
    from ..parallel.mesh import ensure_host_platform_devices
    ensure_host_platform_devices(max(n, 8))


def _trace_findings(cfg, spmd: bool = True) -> List[Finding]:
    """Build the configured trainer on CPU and lint its traced step.
    Build failures become findings instead of crashes: a config whose net
    cannot even be constructed (bad shapes, undefined nodes) is exactly
    what ``task=check`` exists to report."""
    from . import jaxpr_lint
    from .. import engine
    from ..monitor import log as mlog
    from ..nnet.trainer import NetTrainer
    from ..utils.config import ConfigError
    from .schema import Finding as F
    net = NetTrainer()
    was_silent = mlog.is_silent()
    # engine options are a process-global singleton the config mutates at
    # build time; the trace must run WITH this config's options, but a
    # multi-config graftlint run must not leak them into the next config
    engine_snap = engine.snapshot()
    try:
        try:
            for k, v in cfg:
                # the lint builds the trainer only to trace it: opening
                # the config's telemetry sink would drop a "run" header
                # into the linter's CWD for a run that never happens
                # (task=check emits its own `check` record instead)
                if k == "metrics_sink":
                    continue
                net.set_param(k, v)
            # no device work: abstract tracing on the host platform.
            # "cpu" wins over the config's dev= because set_param assigns
            # directly; the build chatter (net description) is lint noise.
            # A mesh config needs its axis product in CPU devices — force
            # the host platform count (no-op once a backend initialized)
            # and skip the trace rather than erroring when short.  A
            # multi-device dev= WITHOUT a mesh= key counts too: the
            # runtime auto-builds a data:N mesh over it, and the memory
            # pre-flight must see the same per-device shards (modeling
            # a tpu:0-7 job on one emulated chip would charge 8 chips'
            # activations to one HBM and spuriously fail the check)
            need = net.mesh_spec.size if net.mesh_spec is not None else 1
            try:
                from ..parallel.mesh import parse_device_spec
                ids = parse_device_spec(
                    dict(cfg).get("dev", "cpu"))["ids"]
                if ids:
                    need = max(need, len(ids))
            except ValueError:
                pass  # an unparseable dev= fails at init_model below
            if need > 1:
                _ensure_host_devices(need)
                import jax
                try:
                    jax.config.update("jax_platforms", "cpu")
                except RuntimeError:
                    pass  # backends already initialized
                try:
                    n_vis = len(jax.devices("cpu"))
                except RuntimeError:
                    n_vis = len(jax.devices())
                if n_vis < need:
                    skipped = [F(
                        "info", "mesh",
                        f"traced-graph lint skipped: mesh needs {need} "
                        f"devices, {n_vis} visible on the host platform "
                        "(config lint above still ran)", scope="jaxpr")]
                    if dict(cfg).get("mem_check", "0") == "1":
                        # a CI gate relying on the pre-flight must not
                        # read exit 0 as "it fits" when the check never
                        # ran — and big-mesh configs are exactly the
                        # ones most likely to OOM
                        skipped.append(F(
                            "warn", "mem_check",
                            "the OOM pre-flight did NOT run: it needs "
                            "the traced-graph pass, which this host "
                            f"cannot emulate ({need} mesh devices, "
                            f"{n_vis} visible)", scope="mem"))
                    return skipped
                net.set_param("dev", f"cpu:0-{need - 1}")
            else:
                net.set_param("dev", "cpu")
            net.set_param("silent", "1")
            net.init_model()
        except (ConfigError, AssertionError, ValueError, KeyError) as e:
            return [F("error", "", f"net build failed: {e}", scope="jaxpr")]
        except Exception as e:  # noqa: BLE001 — environment, not config
            return [F("warn", "", "traced-graph lint skipped: could not "
                      f"build the train step on cpu ({e})", scope="jaxpr")]
        out: List[Finding] = []
        closed = None
        try:
            # trace ONCE: the jaxpr lint and the SPMD deep lint walk the
            # same closed jaxpr (a second abstract trace of a flagship
            # net is seconds of pure waste per config)
            closed = jaxpr_lint.trace_step(net)
            out.extend(jaxpr_lint.lint_trainer(net, closed=closed))
        except Exception as e:  # noqa: BLE001 — lint must not crash check
            out.append(F("warn", "", f"traced-graph lint failed: {e}",
                        scope="jaxpr"))
        # OOM pre-flight (mem_check = 1, doc/memory.md): the analytic
        # memory model vs the target chip's HBM, on the SAME built
        # trainer — an over-budget config fails here, before a
        # compile-and-train cycle is spent discovering it on chip
        try:
            from . import memmodel
            out.extend(memmodel.preflight(net, cfg))
        except Exception as e:  # noqa: BLE001 — lint must not crash check
            out.append(F("warn", "mem_check",
                         f"memory pre-flight failed: {e}", scope="mem"))
        # SPMD deep lint (spmdlint.py): collective-consistency over the
        # same traced jaxpr, donation audit off the step's alias map,
        # dtype-flow vs the declared precision contracts.  Runs inside
        # the engine-snapshot window so dp_reduce_dtype reflects THIS
        # config, not the previous one in a multi-config graftlint run
        if spmd and closed is not None:
            try:
                from . import spmdlint
                out.extend(spmdlint.lint_trainer(net, closed, cfg))
            except Exception as e:  # noqa: BLE001 — must not crash check
                out.append(F("warn", "spmd_check",
                             f"SPMD lint failed: {e}", scope="spmd"))
        return out
    finally:
        # silence stays on through the lint passes too: the SPMD
        # donation audit lowers the step, which re-triggers build-time
        # chatter (bucket plans) that is lint noise here
        mlog.set_silent(1 if was_silent else 0)
        for k, v in engine_snap.items():
            setattr(engine.opts, k, v)
