"""Config lint: unknown keys, value violations, cross-key constraints.

The reference's config contract silently ignores unknown keys
(``layers/base.py`` Layer.set_param), so a typo'd ``dp_bucket_mb`` or a
misspelled layer key costs a full compile-and-train cycle before anyone
notices.  ``lint_pairs`` walks an ordered config-pair list with the same
sectioning rules the runtime uses (``main._create_iterators`` for
``data``/``eval``/``pred`` blocks, ``NetConfig.configure`` for the
netconfig block) and checks every key against the declared-key registry:

* **unknown everywhere** → error with a did-you-mean suggestion;
* **known globally but not consumed here** (e.g. an ``img``-only key in
  an ``imgbin`` section) → warning, because the runtime will silently
  drop it;
* **value violations** → type/enum failures are errors, range
  excursions warnings (schema.check_value);
* **cross-key constraints** → the interaction rules the subsystems
  enforce with trace-time warnings or silent fallbacks (dp_overlap
  vs batch_split/pipe, monitor vs multi_step, ...), surfaced before any
  device work.

Structural netconfig problems (undefined nodes, shared-layer params)
are caught by running ``NetConfig.configure`` itself and converting its
exceptions into findings.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from . import registry
from .schema import Finding, check_value, did_you_mean

ConfigPairs = Sequence[Tuple[str, str]]

# structural sectioning keys handled by position, not by the registry
_SECTION_HEADS = {"data": 1, "eval": 2, "pred": 3}


def lint_pairs(pairs: ConfigPairs, path: str = "") -> List[Finding]:
    findings: List[Finding] = []
    flag = 0                      # 0 global, else inside data/eval/pred
    sect_name = ""
    sect: List[Tuple[str, str]] = []
    netcfg_mode = 0               # NetConfig.configure's state machine
    cur_layer: Optional[Tuple[str, str]] = None  # (type, name)
    layer_types: List[str] = []
    sections_seen: Dict[int, int] = {}

    for name, val in pairs:
        if flag != 0:
            if name in _SECTION_HEADS:
                findings.append(Finding(
                    "error", name, f"new {name!r} section opened before "
                    f"'iter = end' closed the {sect_name!r} section",
                    scope=f"iter:{sect_name}"))
                _lint_section(sect_name, sect, findings)
                flag, sect = _SECTION_HEADS[name], []
                sect_name = val if name == "eval" else name
                sections_seen[flag] = sections_seen.get(flag, 0) + 1
                continue
            if name == "iter" and val == "end":
                _lint_section(sect_name, sect, findings)
                flag, sect = 0, []
                continue
            sect.append((name, val))
            continue
        if name in _SECTION_HEADS:
            flag = _SECTION_HEADS[name]
            sect_name = val if name == "eval" else name
            sections_seen[flag] = sections_seen.get(flag, 0) + 1
            sect = []
            continue
        if name == "iter":
            findings.append(Finding(
                "error", name, "'iter = %s' outside a data/eval/pred "
                "section" % val))
            continue
        if name == "netconfig":
            if val not in ("start", "end"):
                findings.append(Finding(
                    "error", name, f"netconfig = {val!r}: expected start "
                    "or end"))
            netcfg_mode = 1 if val == "start" else 0
            cur_layer = None
            continue
        if name.startswith("layer["):
            cur_layer = _lint_layer_line(name, val, findings)
            if cur_layer is not None:
                layer_types.append(cur_layer[0])
            netcfg_mode = 2
            continue
        if netcfg_mode == 2 and cur_layer is not None:
            _lint_layer_key(cur_layer, name, val, findings)
            continue
        # global region (netcfg_mode 0 or 1, and layer lines the parser
        # rejected): the broadcast scope
        _lint_global_key(name, val, findings)

    if flag != 0:
        findings.append(Finding(
            "error", "iter", f"{sect_name!r} section never closed with "
            "'iter = end'", scope=f"iter:{sect_name}"))
        _lint_section(sect_name, sect, findings)

    findings.extend(_structural_findings(pairs))
    _cross_key_rules(pairs, layer_types, sections_seen, findings)
    return findings


# --------------------------------------------------------------- pieces
def _lint_global_key(name: str, val: str, findings: List[Finding]) -> None:
    scope = registry.global_scope()
    specs = scope.match(name)
    if not specs:
        sugg = did_you_mean(name, scope.names())
        findings.append(Finding(
            "error", name, "unknown config key (no layer, iterator, "
            "updater, engine, or task declares it); it would be silently "
            "ignored", suggestion=sugg, scope="global"))
        return
    _lint_value(specs, name, val, "global", findings)


def _lint_value(specs, name: str, val: str, scope_name: str,
                findings: List[Finding]) -> None:
    viols = []
    for sp in specs:
        v = check_value(sp, val)
        if v is None:
            return
        viols.append(v)
    sev, msg = viols[0]
    findings.append(Finding(sev, name, msg, scope=scope_name))


def _lint_section(sect_name: str, entries: ConfigPairs,
                  findings: List[Finding]) -> None:
    from ..io import factory
    scope_name = f"iter:{sect_name}"
    chain = tuple(v for k, v in entries if k == "iter")
    for t in chain:
        if factory.iter_stage_classes(t) is None and t != "end":
            findings.append(Finding(
                "error", "iter", f"unknown iterator type {t!r}",
                suggestion=did_you_mean(t, factory.iter_type_names()),
                scope=scope_name))
    scope = registry.iterator_scope(chain)
    for k, v in entries:
        if k == "iter":
            continue
        specs = scope.match(k)
        if specs:
            _lint_value(specs, k, v, scope_name, findings)
        elif registry.known_anywhere(k):
            findings.append(Finding(
                "warn", k, "not consumed by any stage of this iterator "
                f"chain ({'+'.join(chain) or 'empty'}); it will be "
                "silently ignored here", scope=scope_name))
        else:
            findings.append(Finding(
                "error", k, "unknown config key",
                suggestion=did_you_mean(
                    k, scope.names() or registry.global_scope().names()),
                scope=scope_name))


def _layer_type_known(tname: str) -> bool:
    from ..layers import registry as lreg
    if tname.startswith("pairtest-"):
        rest = tname[len("pairtest-"):]
        if "-" not in rest:
            return False
        master, slave = rest.split("-", 1)
        return _layer_type_known(master) and _layer_type_known(slave)
    return tname in lreg._REGISTRY


def _lint_layer_line(name: str, val: str, findings: List[Finding]
                     ) -> Optional[Tuple[str, str]]:
    """Validate one ``layer[..] = type[:name]`` line; returns the
    (type, name) of the declared layer, or None when keys that follow
    should not be linted (shared/unparsable layers)."""
    from ..layers import registry as lreg
    from ..nnet.netconfig import _LAYER_ARROW, _LAYER_PLUS
    if _LAYER_PLUS.match(name) is None and _LAYER_ARROW.match(name) is None:
        findings.append(Finding(
            "error", name, "invalid layer declaration (expected "
            "layer[+N], layer[+N:tag], or layer[in->out])"))
        return None
    if val.startswith("share"):
        return None  # shared layer: params on it are a structural error
    tname, _, lname = val.partition(":")
    if not _layer_type_known(tname):
        findings.append(Finding(
            "error", name, f"unknown layer type {tname!r}",
            suggestion=did_you_mean(tname, lreg.layer_type_names())))
        return None
    return (tname, lname)


def _lint_layer_key(cur_layer: Tuple[str, str], name: str, val: str,
                    findings: List[Finding]) -> None:
    tname, lname = cur_layer
    scope_name = f"layer:{tname}" + (f":{lname}" if lname else "")
    if registry.layer_scope(tname) is None:
        return  # unresolvable plugin surface: don't guess
    specs = registry.layer_key_match(tname, name)
    if specs:
        _lint_value(specs, name, val, scope_name, findings)
        return
    if registry.known_anywhere(name):
        findings.append(Finding(
            "warn", name, f"not consumed by layer type {tname!r}; it "
            "will be silently ignored here", scope=scope_name))
        return
    scope = registry.layer_scope(tname)
    findings.append(Finding(
        "error", name, "unknown config key",
        suggestion=did_you_mean(
            name, scope.names() or registry.global_scope().names()),
        scope=scope_name))


def _structural_findings(pairs: ConfigPairs) -> List[Finding]:
    """Run the real NetConfig parser: undefined input nodes, duplicate
    layer names, params on shared layers, malformed shapes."""
    from ..nnet.netconfig import NetConfig
    from ..utils.config import ConfigError
    if not any(k.startswith("layer[") for k, _ in pairs):
        return []  # no netconfig block (pred-from-checkpoint configs)
    try:
        NetConfig().configure(list(pairs))
    except (ConfigError, AssertionError) as e:
        return [Finding("error", "netconfig", f"net structure invalid: {e}")]
    except ValueError as e:
        return [Finding("error", "netconfig",
                        f"net structure invalid: {e}")]
    return []


# ------------------------------------------------------ cross-key rules
def _as_int(last: Dict[str, str], key: str, default: int = 0) -> int:
    try:
        return int(last.get(key, default))
    except ValueError:
        return default


def _as_float(last: Dict[str, str], key: str,
              default: float = 0.0) -> float:
    try:
        return float(last.get(key, default))
    except ValueError:
        return default


def _cross_key_rules(pairs: ConfigPairs, layer_types: List[str],
                     sections_seen: Dict[int, int],
                     findings: List[Finding]) -> None:
    last = dict(pairs)  # last occurrence wins, like sequential set_param
    task = "train"
    for k, v in pairs:
        if k == "task" and v != "check":
            task = v
    add = findings.append

    update_period = _as_int(last, "update_period", 1)
    multi_step = _as_int(last, "multi_step", 0)
    monitor = _as_int(last, "monitor", 0)
    batch_split = _as_int(last, "batch_split", 1)
    batch_size = _as_int(last, "batch_size", 0)

    if last.get("dp_overlap") == "1":
        if batch_split > 1 or _as_int(last, "remat", 0) > 0:
            add(Finding("warn", "dp_overlap",
                        "dp_overlap = 1 with batch_split/remat: these "
                        "paths schedule their own backward, so the run will "
                        "fall back to the implicit-psum step"))
        if "dp_reduce_at" in last and last["dp_reduce_at"] == "apply" \
                and update_period <= 1:
            add(Finding("warn", "dp_reduce_at",
                        "dp_reduce_at = apply has no effect without "
                        "update_period > 1 (there is only one reduce per "
                        "apply either way)"))
    elif "dp_reduce_dtype" in last:
        add(Finding("warn", "dp_reduce_dtype",
                    "dp_reduce_dtype only changes the wire dtype of the "
                    "explicit dp_overlap = 1 bucketed reduction; without "
                    "dp_overlap the key is silently ignored (the "
                    "implicit GSPMD psum reduces in the gradient dtype)"))
    _mesh_rules(last, layer_types, update_period, batch_size, add)
    if monitor and multi_step > 1:
        add(Finding("warn", "multi_step",
                    "monitor = 1 forces per-batch dispatch; multi_step "
                    f"= {multi_step} grouping will be disabled"))
    if multi_step > 1 and update_period > 1:
        add(Finding("warn", "multi_step",
                    "multi_step grouping requires update_period = 1; "
                    "the run will dispatch per batch"))
    if "monitor_nan" in last and not monitor:
        add(Finding("warn", "monitor_nan",
                    "the NaN/inf loss guard is only checked when "
                    "monitor = 1; monitor_nan has no effect here"))
    # --- observatory knobs (doc/monitor.md: prof_every / sentinel) ---
    prof_every = _as_int(last, "prof_every", 0)
    if prof_every > 0:
        if _as_int(last, "prof_start_step", -1) >= 0:
            add(Finding("warn", "prof_every",
                        "prof_every opens recurring round windows but "
                        "prof_start_step pins a one-shot step-addressed "
                        "window; prof_every will be ignored"))
        if not last.get("prof", ""):
            add(Finding("warn", "prof_every",
                        "prof_every has no effect without prof = <dir> "
                        "(no trace directory, no profiling windows)"))
        if monitor and multi_step > 1:
            add(Finding("warn", "prof_every",
                        "monitor = 1 disables multi_step grouped "
                        "dispatch, so every prof_every window will "
                        "profile per-batch dispatch — not the grouped "
                        "steady state the run would otherwise have"))
    sink_on = last.get("metrics_sink", "") not in ("", "none", "0")
    # host-side span tracing (doc/monitor.md): the trace_sample value
    # itself is bounds-checked by its KeySpec (int, 0..1e6); here only
    # the cross-key dependency — spans ride the JSONL sink
    if _as_int(last, "trace_sample", 0) > 0 and not sink_on:
        add(Finding("warn", "trace_sample",
                    "trace_sample > 0 without metrics_sink: span "
                    "records have nowhere to land, so the tracer stays "
                    "disarmed; set metrics_sink = jsonl:<path>"))
    if _as_int(last, "sentinel", 0):
        if not sink_on:
            add(Finding("warn", "sentinel",
                        "sentinel = 1 without metrics_sink: anomaly and "
                        "flight-recorder records have nowhere to land; "
                        "set metrics_sink = jsonl:<path>"))
    else:
        for k in ("sentinel_rel", "sentinel_warmup", "sentinel_ring"):
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect without sentinel = 1"))
                break
    # goodput ledger (doc/monitor.md): default-on and silent when the
    # defaults apply — only an EXPLICIT setting that cannot take effect
    # is worth a finding
    if "ledger" in last:
        if _as_int(last, "ledger", 1) and not sink_on:
            add(Finding("warn", "ledger",
                        "ledger = 1 without metrics_sink: the "
                        "end-of-run goodput ledger record has nowhere "
                        "to land; set metrics_sink = jsonl:<path>"))
        if _as_int(last, "ledger", 1) and task not in ("train",
                                                       "finetune"):
            # ledger = 0 off-task is a harmless no-op, not a finding
            add(Finding("warn", "ledger",
                        f"ledger has no effect under task = {task}: "
                        "only train/finetune runs emit the end-of-run "
                        "ledger record"))
    if batch_split > 1 and batch_size and batch_size % batch_split:
        add(Finding("error", "batch_split",
                    f"batch_size = {batch_size} is not divisible by "
                    f"batch_split = {batch_split}"))
    pipe_mb = _as_int(last, "pipe_microbatch", 0)
    if pipe_mb > 0 and batch_size and batch_size % pipe_mb:
        add(Finding("error", "pipe_microbatch",
                    f"batch_size = {batch_size} is not divisible by "
                    f"pipe_microbatch = {pipe_mb}"))
    if "pipe_schedule" in last and not last.get("mesh"):
        add(Finding("warn", "pipe_schedule",
                    f"pipe_schedule = {last['pipe_schedule']} has no "
                    "effect without a mesh = ...,pipe:K axis"))
    if last.get("dtype") == "bfloat16" \
            and last.get("pallas_ln", "1") not in ("0", "x") \
            and any(t == "layernorm" or t.startswith("pairtest-")
                    and "layernorm" in t for t in layer_types):
        add(Finding("info", "pallas_ln",
                    "bf16 + pallas_ln: the output-derived layernorm "
                    "backward amplifies rounding for columns with "
                    "|beta| >> |gamma| (doc/pallas_ln.md); pallas_ln = x "
                    "is the input-saving escape hatch"))
    if _as_int(last, "continue", 0) and \
            last.get("model_in", "NULL") != "NULL":
        add(Finding("warn", "model_in",
                    "continue = 1 resumes from the newest snapshot; "
                    "model_in is ignored"))
    if task in ("train", "finetune") and sections_seen.get(1, 0) == 0:
        add(Finding("warn", "data",
                    f"task = {task} but the config has no 'data = ...' "
                    "iterator section (fine for bench/netconfig-only "
                    "configs; task = train will fail at init)"))
    if task in ("pred", "pred_raw", "extract", "serve"):
        if sections_seen.get(3, 0) == 0:
            add(Finding("error", "pred",
                        f"task = {task} requires a 'pred = <out>' "
                        "iterator section"
                        + (" (the request stream)"
                           if task == "serve" else "")))
        if last.get("model_in", "NULL") == "NULL":
            add(Finding("error", "model_in",
                        f"task = {task} requires model_in "
                        + ("(a model snapshot to serve)"
                           if task == "serve" else "")))
        if task == "extract" and not last.get("extract_node_name", ""):
            add(Finding("error", "extract_node_name",
                        "task = extract requires extract_node_name"))
    _serve_rules(last, task, add)
    _ckpt_rules(last, task, monitor, add)
    _text_rules(pairs, last, layer_types, add)
    _decode_rules(pairs, last, layer_types, task, add)
    _mem_rules(last, task, add)


def _mem_rules(last: Dict[str, str], task: str, add) -> None:
    """Cross-key rules for the OOM pre-flight (doc/memory.md).  The
    pre-flight itself runs inside ``task=check``'s traced-graph pass
    (analysis/memmodel.py); these rules catch configurations where it
    silently models the wrong thing or nothing at all."""
    mem_check = last.get("mem_check", "0") == "1"
    if mem_check:
        if task not in ("train", "finetune"):
            add(Finding("warn", "mem_check",
                        f"the pre-flight models the TRAIN step's memory; "
                        f"task = {task} serves/predicts with a different "
                        "(smaller) footprint — the estimate does not "
                        "describe this run"))
        if _as_int(last, "remat", 0) > 1:
            add(Finding("info", "mem_check",
                        "remat > 1: the pre-flight assumes only "
                        "segment-boundary activations persist; XLA may "
                        "keep more, so treat mem_margin_pct as softer "
                        "(doc/memory.md)"))
        from .costmodel import resolve_chip
        sel = last.get("mem_chip", "") or last.get("dev", "")
        if resolve_chip(sel) is None:
            add(Finding("warn", "mem_chip",
                        f"mem_check = 1 but mem_chip/dev = {sel!r} names "
                        "no known chip; the pre-flight has no HBM "
                        "capacity to check against (set mem_chip, e.g. "
                        "v5e)"))
    else:
        for k in ("mem_margin_pct", "mem_chip"):
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect without mem_check = 1"))
                break


def _ckpt_rules(last: Dict[str, str], task: str, monitor: int, add) -> None:
    """Cross-key rules for the checkpoint / rollback subsystem
    (doc/checkpoint.md).  ``continue = 1`` skipping partial/corrupt
    snapshots is runtime behavior documented in doc/checkpoint.md, not a
    lint rule — there is nothing to check statically."""
    rollback = _as_int(last, "rollback", 0)
    ckpt_keep = _as_int(last, "ckpt_keep", 3)
    if task not in ("train", "finetune"):
        for k in ("ckpt_async", "ckpt_keep", "rollback", "save_opt",
                  "ckpt_iter_state"):
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect without task = "
                            "train/finetune (checkpoints are written by "
                            "the train loop)"))
                break
        return
    if rollback > 0:
        if not monitor or last.get("monitor_nan", "warn") != "fatal":
            add(Finding("warn", "rollback",
                        "rollback only triggers on TrainingDiverged, "
                        "which is raised by monitor_nan = fatal under "
                        "monitor = 1; with the current settings the "
                        "divergence is never raised and rollback never "
                        "runs"))
        if "model_dir" not in last:
            add(Finding("warn", "rollback",
                        "rollback restores snapshots from model_dir; "
                        "set it explicitly (the default './' litters the "
                        "working directory and is rarely intended)"))
        if _as_int(last, "save_model", 1) == 0:
            add(Finding("error", "rollback",
                        "rollback needs snapshots to restore, but "
                        "save_model = 0 disables them"))
        if _as_int(last, "save_opt", 1) == 0:
            add(Finding("info", "save_opt",
                        "save_opt = 0 with rollback: the restored run "
                        "restarts optimizer moments from zero, so the "
                        "retried window is not the checkpointed "
                        "trajectory"))
        if ckpt_keep < 2:
            add(Finding("warn", "ckpt_keep",
                        "ckpt_keep = 1 with rollback: if the newest "
                        "snapshot carries the divergence (or a kill "
                        "corrupts it) there is no older one to fall "
                        "back to; keep at least 2"))
    if "ckpt_keep" in last and _as_int(last, "ckpt_async", 0) == 0:
        add(Finding("warn", "ckpt_keep",
                    "ckpt_keep prunes NNNN.ckpt snapshot dirs, which "
                    "only ckpt_async = 1 writes; legacy .model files "
                    "are never pruned"))
    if "ckpt_iter_state" in last and _as_int(last, "save_model", 1) == 0:
        add(Finding("warn", "ckpt_iter_state",
                    "ckpt_iter_state has no effect with save_model = 0 "
                    "(no snapshots carry it)"))


def _serve_rules(last: Dict[str, str], task: str, add) -> None:
    """Cross-key rules for the serving subsystem (doc/serve.md).  The
    ``serve_shapes`` value itself (sorted/positive) is validated by its
    KeySpec check (serve.shapes_check), so a malformed spec is already
    an error before these rules run."""
    if task != "serve":
        for k in ("serve_shapes", "serve_max_batch", "serve_max_wait_ms",
                  "serve_dtype", "serve_clients", "serve_calib",
                  "serve_queue_depth", "serve_sentinel",
                  "serve_sentinel_window", "serve_admin_port",
                  "serve_slo_p99_ms", "serve_slo_avail",
                  "serve_slo_fast_sec", "serve_slo_slow_sec",
                  "serve_slo_fast_burn", "serve_slo_slow_burn",
                  "serve_flight_requests", "serve_flight_boost"):
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect without task = serve"))
                break
        return
    if _as_int(last, "serve_sentinel", 0):
        if last.get("metrics_sink", "") in ("", "none", "0"):
            add(Finding("warn", "serve_sentinel",
                        "serve_sentinel = 1 without metrics_sink: "
                        "serve_window and anomaly records have nowhere "
                        "to land, so the sentinels disarm; set "
                        "metrics_sink = jsonl:<path>"))
    elif "serve_sentinel_window" in last:
        add(Finding("warn", "serve_sentinel_window",
                    "serve_sentinel_window has no effect without "
                    "serve_sentinel = 1"))
    if last.get("serve_dtype", "f32") == "int8" \
            and _as_int(last, "serve_calib", 0) <= 0:
        add(Finding("warn", "serve_dtype",
                    "serve_dtype = int8 without calibration batches "
                    "(serve_calib = N): the quantized variant ships "
                    "without its pairtest-vs-f32 error being measured "
                    "on real request data"))
    # -- live control plane (serve/admin.py, monitor/slo.py).  The
    # serve_admin_port RANGE is the KeySpec's lo/hi (0..65535, an
    # error at schema level); these rules cover the cross-key wiring.
    if _as_float(last, "serve_slo_p99_ms", 0.0) > 0.0 \
            and not _as_int(last, "serve_sentinel", 0):
        add(Finding("warn", "serve_slo_p99_ms",
                    "serve_slo_p99_ms without serve_sentinel = 1: the "
                    "SLO burn rates evaluate over the sentinel "
                    "reporter's serve_window stream, so the targets "
                    "are ignored"))
    win = _as_float(last, "serve_sentinel_window", 1.0)
    if win > 0:
        for k in ("serve_slo_fast_sec", "serve_slo_slow_sec"):
            if k not in last:
                continue
            sec = _as_float(last, k, 0.0)
            ratio = sec / win
            if sec > 0 and abs(ratio - round(ratio)) > 1e-9:
                add(Finding("error", k,
                            f"{k} = {sec:g} is not an integer multiple "
                            f"of serve_sentinel_window ({win:g}): the "
                            "burn window is a whole number of reporter "
                            "windows, so a fractional multiple "
                            "silently rounds"))
    fast = _as_float(last, "serve_slo_fast_sec", 60.0)
    slow = _as_float(last, "serve_slo_slow_sec", 600.0)
    if ("serve_slo_fast_sec" in last or "serve_slo_slow_sec" in last) \
            and fast >= slow:
        add(Finding("warn", "serve_slo_fast_sec",
                    f"serve_slo_fast_sec ({fast:g}) >= "
                    f"serve_slo_slow_sec ({slow:g}): the fast tier "
                    "should be the SHORTER window (acute outages), "
                    "the slow one the longer (simmering regressions)"))
    if ("serve_flight_requests" in last or "serve_flight_boost" in last) \
            and not _as_int(last, "serve_sentinel", 0):
        add(Finding("warn", "serve_flight_requests",
                    "serve_flight_* keys without serve_sentinel = 1: "
                    "flight capture triggers from sentinel anomalies "
                    "or SLO burns, which both ride the sentinel "
                    "reporter"))
    shapes_str = last.get("serve_shapes", "")
    if shapes_str:
        from ..serve import shapes_check
        if shapes_check(shapes_str) is None:
            buckets = [int(p) for p in shapes_str.split(",") if p.strip()]
            mb = _as_int(last, "serve_max_batch", 0)
            if mb > max(buckets):
                add(Finding("warn", "serve_max_batch",
                            f"serve_max_batch = {mb} exceeds the largest "
                            f"bucket ({max(buckets)}); coalescing caps at "
                            "the bucket and larger requests split across "
                            "dispatches"))


#: layer types that consume/produce (b, 1, s, d) sequence nodes — the
#: set the seq-mesh-axis rule checks for
_SEQ_LAYER_TYPES = ("attention", "embedding", "seq_fullc", "softmax_seq",
                    "moe")


def _text_rules(pairs: ConfigPairs, last: Dict[str, str],
                layer_types: List[str], add) -> None:
    """Cross-key rules for the tokenized text / packed-LM path
    (io/text.py, doc/io.md "Tokenized text datasets"):

    * a ``seq`` mesh axis with no sequence layer in the net warns (the
      axis shards nothing — devices replicate work);
    * the sequence length must divide by the ``seq`` axis, or attention
      falls back to dense with a full-sequence gather (runtime warns;
      surfaced here before any compile);
    * a ``packseq`` data section requires segment-aware consumers:
      ``softmax_seq`` without ``packed = 1`` trains on cross-document
      targets and ``attention`` without ``segment_key`` leaks
      cross-document scores — both errors;
    * the packer's ``seqlen`` must equal the netconfig input width.
    """
    from ..parallel.mesh import MeshSpec
    seq_ax = 1
    mesh_str = last.get("mesh", "")
    if mesh_str:
        try:
            seq_ax = MeshSpec.parse(mesh_str).axes.get("seq", 1)
        except ValueError:
            seq_ax = 1  # unparsable mesh: its own KeySpec's problem

    # scan sections for packseq chains + their seqlen; track the layer
    # keys that make packing safe (the same positional walk lint_pairs
    # does — sections must be skipped before layer keys are attributed)
    flag = 0
    pack_sections = []  # (section kind flag, seqlen value or None)
    cur_chain: List[str] = []
    cur_seqlen: Optional[str] = None
    # a seqlen OUTSIDE any section (file-global or CLI override) is
    # applied to the chain LAST by init_iterator's defcfg pass, so it
    # overrides every section's value — the lint must check the value
    # the runtime will actually use
    global_seqlen: Optional[str] = None
    cur_layer = ""
    n_attention = 0
    n_att_seg = 0
    softmax_seq_packed = False
    for name, val in pairs:
        if name in _SECTION_HEADS:
            flag = _SECTION_HEADS[name]
            cur_chain, cur_seqlen = [], None
            continue
        if flag:
            if name == "iter":
                if val == "end":
                    if "packseq" in cur_chain:
                        pack_sections.append(cur_seqlen)
                    flag = 0
                else:
                    cur_chain.append(val)
            elif name == "seqlen":
                cur_seqlen = val
            continue
        if name == "seqlen":
            global_seqlen = val
            continue
        if name.startswith("layer["):
            cur_layer = val.split(":", 1)[0]
            if cur_layer == "attention":
                n_attention += 1
            continue
        if cur_layer == "attention" and name == "segment_key" and val:
            n_att_seg += 1
        elif cur_layer == "softmax_seq" and name == "packed" \
                and val.strip() == "1":
            softmax_seq_packed = True
    if global_seqlen is not None:
        pack_sections = [global_seqlen for _ in pack_sections]

    has_seq_layer = any(t in _SEQ_LAYER_TYPES for t in layer_types)
    if seq_ax > 1 and layer_types and not has_seq_layer:
        add(Finding("warn", "mesh",
                    f"mesh = {mesh_str} carries a seq axis but the net "
                    "has no sequence layer (attention/embedding/"
                    "seq_fullc): the axis shards nothing and its devices "
                    "replicate work"))
    # sequence length divisibility: the packer's seqlen and the
    # netconfig input width both shard over the seq axis
    in_shape = last.get("input_shape", "")
    in_width = None
    if in_shape:
        try:
            in_width = int(in_shape.split(",")[-1])
        except ValueError:
            pass  # malformed input_shape: NetConfig's structural error
    seqlens = []  # one entry PER packseq section — a mismatch in any
    for sl in pack_sections:  # section must surface, not just the last
        if sl is not None:
            try:
                seqlens.append(int(sl))
            except ValueError:
                pass  # type error already reported by the KeySpec
    if seq_ax > 1 and has_seq_layer:
        for key, w in ([("input_shape", in_width)]
                       if in_width is not None else []) \
                + [("seqlen", w) for w in seqlens]:
            if w % seq_ax:
                add(Finding("warn", key,
                            f"sequence length {w} is not divisible by "
                            f"the seq mesh axis ({seq_ax}); attention "
                            "falls back to dense and gathers the full "
                            "sequence on one device"))
                break
    if not pack_sections or not layer_types:
        return
    if in_width is not None:
        for w in seqlens:
            if w != in_width:
                add(Finding("error", "seqlen",
                            f"packseq seqlen = {w} but the netconfig "
                            f"input width is {in_width}; the packed "
                            "rows will not fit the input node"))
                break
    if not softmax_seq_packed and "softmax_seq" in layer_types:
        add(Finding("error", "packed",
                    "packseq data section but softmax_seq has no "
                    "'packed = 1': cross-document and padding targets "
                    "would train as real next-token targets; set "
                    "packed = 1 on the loss layer (doc/io.md)"))
    if n_attention and n_att_seg < n_attention:
        add(Finding("error", "segment_key",
                    f"packseq data section but {n_attention - n_att_seg} "
                    f"of {n_attention} attention layer(s) have no "
                    "segment_key: cross-document attention leaks across "
                    "packed rows; set segment_key = <segment field> "
                    "(doc/io.md)"))


#: keys the incremental-decode path consumes (serve/decode.py); the
#: first one present off-task carries the "no effect" warn
_DECODE_KEYS = ("serve_gen", "decode_slots", "decode_max_seqlen",
                "serve_gen_tokens", "serve_gen_sample", "serve_gen_temp",
                "serve_gen_topk", "serve_gen_seed", "serve_gen_eos",
                "serve_gen_prompt", "serve_gen_batching",
                "serve_draft_model", "spec_k", "decode_prefill_chunk",
                "decode_kv_dtype")


def _decode_rules(pairs: ConfigPairs, last: Dict[str, str],
                  layer_types: List[str], task: str, add) -> None:
    """Cross-key rules for KV-cache incremental decode (serve/decode.py,
    doc/serve.md "Incremental decode"):

    * decode/generation keys without ``task = serve`` warn (first
      match), and ``decode_*``/``serve_gen_*`` detail keys without
      ``serve_gen = 1`` warn — they configure a path that never runs;
    * ``serve_gen = 1`` needs an LM netconfig — embedding + attention +
      softmax_seq — and every attention layer ``causal = 1`` (the cache
      is append-only; a bidirectional layer would need future
      positions);
    * ``decode_max_seqlen`` must equal the netconfig input width (the
      prefill executable runs the net at its declared width) and any
      packseq ``seqlen`` — both mismatches are errors before a compile;
    * the KV cache (2 x layers x slots x seqlen x dim x dtype) over the
      selected chip's HBM capacity is the same pre-flight rejection
      ``task=check``'s memory pass makes for train steps (doc/memory.md)
      — surfaced analytically here, no trace needed;
    * sampling detail keys that the selected ``serve_gen_sample`` kind
      ignores warn;
    * speculative decoding: ``spec_k`` without ``serve_draft_model``
      errors, a missing draft snapshot errors at check time (info when
      ``model_in`` is missing too — an untrained example tree), a draft
      with ``spec_k = 0`` warns, and non-greedy sampling + speculation
      gets the rejection-sampling reproducibility note;
    * ``decode_prefill_chunk`` that does not divide the cache length
      warns (the last chunk pads dead columns).
    """
    gen = _as_int(last, "serve_gen", 0)
    if task != "serve":
        for k in _DECODE_KEYS:
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect without task = serve"))
                break
        return
    if not gen:
        for k in _DECODE_KEYS[1:]:
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect without serve_gen = 1"))
                break
        return
    # --- LM netconfig structure: walk the layer keys positionally (the
    # _text_rules discipline) for causal flags and the embedding dim
    cur_layer = ""
    n_attention = 0
    n_causal = 0
    embed_dim = None
    for name, val in pairs:
        if name.startswith("layer["):
            cur_layer = val.split(":", 1)[0]
            if cur_layer == "attention":
                n_attention += 1
            continue
        if cur_layer == "attention" and name == "causal" \
                and val.strip() == "1":
            n_causal += 1
        elif cur_layer == "embedding" and name == "nhidden":
            try:
                embed_dim = int(val)
            except ValueError:
                pass  # type error already reported by the KeySpec
    missing = [t for t in ("embedding", "attention", "softmax_seq")
               if t not in layer_types]
    if layer_types and missing:
        add(Finding("error", "serve_gen",
                    "serve_gen = 1 needs an LM netconfig but the net "
                    f"has no {'/'.join(missing)} layer(s); incremental "
                    "decode only speaks token-id transformers "
                    "(doc/serve.md)"))
        return
    if n_attention and n_causal < n_attention:
        add(Finding("error", "causal",
                    f"serve_gen = 1 but {n_attention - n_causal} of "
                    f"{n_attention} attention layer(s) are not "
                    "causal = 1: the KV cache is append-only, so "
                    "bidirectional attention cannot decode "
                    "incrementally"))
    # --- cache geometry vs the declared input width / packseq seqlen
    in_width = None
    in_shape = last.get("input_shape", "")
    if in_shape:
        try:
            in_width = int(in_shape.split(",")[-1])
        except ValueError:
            pass
    max_seqlen = _as_int(last, "decode_max_seqlen", 0)
    if max_seqlen:
        if in_width is not None and max_seqlen != in_width:
            add(Finding("error", "decode_max_seqlen",
                        f"decode_max_seqlen = {max_seqlen} but the "
                        f"netconfig input width is {in_width}; the "
                        "prefill executable runs the net at its "
                        "declared width, so the two must match"))
        sl = _as_int(last, "seqlen", 0)
        if sl and max_seqlen != sl:
            add(Finding("error", "decode_max_seqlen",
                        f"decode_max_seqlen = {max_seqlen} but the "
                        f"packer's seqlen is {sl}; prompts tokenized "
                        "at one length cannot fill a cache sized for "
                        "another"))
    # --- KV-cache HBM pre-flight (doc/memory.md): the analytic bytes
    # the live engine's footprint() reports, checked against the
    # selected chip's capacity without tracing anything
    eff_seqlen = max_seqlen or in_width
    if n_attention and embed_dim and eff_seqlen:
        from .costmodel import HBM_BYTES, resolve_chip
        chip = resolve_chip(last.get("mem_chip", "")
                            or last.get("dev", ""))
        if chip is not None:
            cap = HBM_BYTES[chip]
            slots = _as_int(last, "decode_slots", 4)
            itemsize = 2 if last.get("dtype", "") == "bfloat16" else 4
            kv = 2 * n_attention * slots * eff_seqlen * embed_dim \
                * itemsize
            if kv > cap:
                add(Finding("error", "decode_slots",
                            f"KV cache needs {kv / 1e9:.2f} GB "
                            f"({slots} slot(s) x {eff_seqlen} positions "
                            f"x {n_attention} attention layer(s) x dim "
                            f"{embed_dim}) but {chip} holds "
                            f"{cap / 1e9:.1f} GB HBM — before weights; "
                            "shrink decode_slots or decode_max_seqlen "
                            "(doc/memory.md)"))
    # --- sampling knob consistency
    kind = last.get("serve_gen_sample", "greedy")
    if kind == "greedy":
        for k in ("serve_gen_temp", "serve_gen_topk"):
            if k in last:
                add(Finding("warn", k,
                            f"{k} has no effect under serve_gen_sample "
                            "= greedy (argmax ignores it)"))
                break
    elif kind == "temperature" and "serve_gen_topk" in last:
        add(Finding("warn", "serve_gen_topk",
                    "serve_gen_topk has no effect under "
                    "serve_gen_sample = temperature; set "
                    "serve_gen_sample = topk"))
    elif kind == "topk" and "serve_gen_topk" not in last:
        add(Finding("warn", "serve_gen_sample",
                    "serve_gen_sample = topk without serve_gen_topk: "
                    "the cutoff defaults to the full vocabulary "
                    "(plain temperature sampling)"))
    # --- speculative decoding + chunked prefill (doc/serve.md)
    spec_k = _as_int(last, "spec_k", 0)
    draft = last.get("serve_draft_model", "")
    if spec_k >= 1 and not draft:
        add(Finding("error", "spec_k",
                    f"spec_k = {spec_k} without serve_draft_model: "
                    "speculation needs a draft snapshot to propose "
                    "tokens (doc/serve.md)"))
    if draft:
        if not os.path.exists(draft):
            model_in = last.get("model_in", "NULL")
            have_flagship = model_in != "NULL" \
                and os.path.exists(model_in)
            # an example tree checked in without trained weights lints
            # the conf shape, not the filesystem: downgrade when the
            # flagship snapshot is missing too
            sev = "error" if have_flagship else "info"
            add(Finding(sev, "serve_draft_model",
                        f"draft snapshot {draft!r} does not exist"
                        + ("" if have_flagship else
                           " (neither does model_in — train both "
                           "before serving)")))
        if spec_k < 1:
            add(Finding("warn", "serve_draft_model",
                        "serve_draft_model configured but spec_k is "
                        f"{spec_k}: the draft loads for nothing — "
                        "speculation stays off without spec_k >= 1"))
        elif kind != "greedy":
            add(Finding("info", "spec_k",
                        f"speculation under serve_gen_sample = {kind} "
                        "uses rejection sampling off the verified "
                        "distribution — the output law matches plain "
                        "sampling but the token stream is not "
                        "reproducible against a non-speculative run "
                        "(greedy is bitwise-identical; doc/serve.md)"))
    chunk = _as_int(last, "decode_prefill_chunk", 0)
    if chunk and eff_seqlen and eff_seqlen % chunk:
        add(Finding("warn", "decode_prefill_chunk",
                    f"decode_prefill_chunk = {chunk} does not divide "
                    f"the cache length ({eff_seqlen}): the last chunk "
                    "of a full-length prompt pads dead columns — pick "
                    "a divisor to keep every chunk dispatch full"))


def _mesh_rules(last: Dict[str, str], layer_types: List[str],
                update_period: int, batch_size: int, add) -> None:
    """Cross-key rules for the first-class ``mesh`` key: axis product vs
    the device selection, batch divisibility by the data axis, the
    dp_overlap x mesh combinations (surfaced at check time instead of as
    the trainer's trace-time warn-once fallback), and a dead model axis.
    Unknown axis NAMES are value errors handled by the ``mesh`` KeySpec
    check (MeshSpec.parse with did-you-mean), so a spec that fails to
    parse is skipped here — the error is already reported."""
    mesh_str = last.get("mesh", "")
    if not mesh_str:
        return
    from ..parallel.mesh import MeshSpec, parse_device_spec
    try:
        axes = MeshSpec.parse(mesh_str).axes
    except ValueError:
        return
    total = 1
    for v in axes.values():
        total *= v
    dev = last.get("dev", "")
    ids = None
    if dev:
        try:
            ids = parse_device_spec(dev)["ids"]
        except (ValueError, IndexError):
            ids = None  # malformed dev: its own KeySpec's problem
    if ids is not None and len(ids) != total:
        add(Finding("error", "mesh",
                    f"mesh = {mesh_str} needs {total} device(s) (axis "
                    f"product) but dev = {dev} selects {len(ids)}"))
    ndata = axes.get("data", 1)
    if batch_size and ndata > 1 and batch_size % ndata:
        add(Finding("error", "mesh",
                    f"batch_size = {batch_size} is not divisible by the "
                    f"data axis ({ndata}); the batch shards over it"))
    if axes.get("model", 1) > 1 and last.get("fullc_gather", "0") != "1" \
            and "moe" not in layer_types:
        add(Finding("info", "mesh",
                    "the model axis shards nothing here (fullc_gather = 0 "
                    "and no moe layer): model-axis devices replicate "
                    "work; set fullc_gather = 1 to shard fullc weights"))
    # pipeline-axis rules (ahead of the 1F1B graduation, ROADMAP item 5):
    # a pipe axis needs a net deep enough to cut into that many stages —
    # layer count is the static proxy for stage-able boundaries
    npipe = axes.get("pipe", 1)
    if npipe > 1:
        if not layer_types:
            add(Finding("warn", "mesh",
                        f"mesh = {mesh_str} carries a pipe axis of "
                        f"{npipe} stages but the config has no netconfig "
                        "block: there is nothing to cut into stages"))
        elif len(layer_types) < npipe:
            add(Finding("warn", "mesh",
                        f"mesh = {mesh_str} asks for {npipe} pipeline "
                        f"stages but the net declares only "
                        f"{len(layer_types)} layer(s); stages would sit "
                        "empty — shrink the pipe axis or deepen the net"))
        pipe_mb = _as_int(last, "pipe_microbatch", 0)
        n_micro = pipe_mb or 2 * npipe
        if n_micro % npipe:
            add(Finding("error", "pipe_microbatch",
                        f"pipe_microbatch = {n_micro} is not divisible "
                        f"by the pipe axis ({npipe}): the schedule "
                        "staggers one microbatch per stage, so ragged "
                        "counts leave permanent extra bubble ticks — "
                        "use a multiple of the axis"))
        if pipe_mb == 0 and batch_size and batch_size % n_micro:
            # the explicit-pipe_microbatch case is the keyed
            # divisibility error above (lint_pairs); this covers the
            # DEFAULTED count 2*S the trainer will actually use
            add(Finding("error", "pipe_microbatch",
                        f"batch_size = {batch_size} is not divisible by "
                        f"the defaulted pipe_microbatch = {n_micro} "
                        f"(2x the pipe axis); set pipe_microbatch "
                        "explicitly or pad the batch"))
        if _as_int(last, "remat", 0):
            add(Finding("info", "remat",
                        "remat with a pipe axis: the trainer rejects "
                        "the combination — the pipeline schedule "
                        "already recomputes each stage's forward "
                        "inside its backward tick, so remat would "
                        "recompute twice; drop remat"))
    elif "pipe_schedule" in last:
        add(Finding("warn", "pipe_schedule",
                    f"pipe_schedule = {last['pipe_schedule']} has no "
                    f"effect: mesh = {mesh_str} carries no pipe axis "
                    "wider than 1"))
    if last.get("dp_overlap") != "1":
        return
    extra_ax = [a for a, s in axes.items()
                if a not in ("data", "model") and s > 1]
    if "pipe" in extra_ax:
        # pipe_schedule = 1f1b COMPOSES with dp_overlap (bucketed
        # (pipe, data) psums at cooldown grad-ready ticks) — no finding;
        # only the gpipe fill-drain, whose backward is autodiff-
        # scheduled, still takes the trainer's warn-once fallback
        if last.get("pipe_schedule", "gpipe") != "1f1b":
            add(Finding("info", "dp_overlap",
                        "dp_overlap = 1 with the gpipe pipeline "
                        "schedule: its backward is autodiff-scheduled, "
                        "so the trainer keeps the implicit-psum step; "
                        "set pipe_schedule = 1f1b to compose bucketed "
                        "reductions with the pipe axis "
                        "(doc/multichip.md)"))
        extra_ax = [a for a in extra_ax if a != "pipe"]
    if extra_ax:
        add(Finding("warn", "dp_overlap",
                    f"dp_overlap = 1 with mesh axes {'/'.join(extra_ax)}: "
                    "ring-attention/expert/pipeline collectives are "
                    "GSPMD-placed, so the run will fall back to the "
                    "implicit-psum step"))
    elif ndata < 2:
        add(Finding("warn", "dp_overlap",
                    f"dp_overlap = 1 but mesh = {mesh_str} has no data "
                    "axis wider than 1; there is nothing to reduce and "
                    "the run falls back to the implicit step"))
    elif axes.get("model", 1) > 1 and "moe" in layer_types:
        add(Finding("warn", "dp_overlap",
                    "dp_overlap = 1 with a moe layer on a model mesh "
                    "axis: the model axis hosts the experts and their "
                    "dispatch/combine all-to-alls are GSPMD-placed, so "
                    "the run will fall back to the implicit-psum step"))
    elif axes.get("model", 1) > 1 \
            and last.get("dp_reduce_at", "apply") == "apply" \
            and update_period > 1:
        add(Finding("info", "dp_reduce_at",
                    "dp_reduce_at = apply is pure-DP; the model mesh "
                    "axis reduces every micro-step instead "
                    "(dp_reduce_at = step semantics)"))


# ----------------------------------------------- strict_config reporting
_reported: set = set()


def report_ignored_layer_key(layer, name: str, val: str) -> None:
    """``strict_config = 1`` hook (layers/base.py): a key reached the
    base set_param unconsumed.  Silent when the layer type declares it
    (subclasses that consume a key and still call super) or when any
    subsystem declares it (globals are broadcast to every layer); warns
    once per (type, key) otherwise."""
    if name in _SECTION_HEADS or name in ("iter", "netconfig") \
            or name.startswith("layer["):
        return  # sectioning keys are consumed structurally, not by scopes
    tname = layer.type_names[0] if layer.type_names else type(layer).__name__
    if (tname, name) in _reported:
        return
    if registry.layer_key_match(tname, name):
        return
    if registry.layer_scope(tname) is None or registry.known_anywhere(name):
        return
    _reported.add((tname, name))
    from ..monitor import log as mlog
    scope = registry.layer_scope(tname)
    sugg = did_you_mean(name, scope.names())
    mlog.warn(
        f"strict_config: layer {layer.name or tname!s} ({tname}) ignores "
        f"unknown key {name!r}"
        + (f" (did you mean {sugg!r}?)" if sugg else ""))
