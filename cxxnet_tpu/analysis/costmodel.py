"""Analytic per-layer cost model: flops / bytes / chip peaks.

One place for the numbers the perf tooling keeps re-deriving: bench.py's
MFU denominator (it imports :func:`peak_flops` from here), the
layer-attribution roofline columns (monitor/attribution.py), and the
GoogLeNet-style "measured vs modeled" distance ROADMAP item 4 is chased
with.  The model is deliberately COARSE — the same 2*MACs convention
BASELINE.md's lowering campaigns use:

* conv / fullc: ``2 * MACs`` forward; everything else is counted as one
  flop per input+output element (elementwise/reduction layers are
  bandwidth-, not compute-bound, so their flops only matter for the
  bytes-side roofline anyway);
* bytes: activations in + out + parameters, 4 bytes each (f32; bf16
  runs are ~2x better than this floor — the model is a per-layer
  RANKING aid, not a calibrated simulator);
* training multiplier 3x (fwd + input-grad + weight-grad), the
  convention bench.py reports MFU with.

Shapes come from the built :class:`~cxxnet_tpu.nnet.net.Network` (batch
included), keyed by the SAME scope strings the net builder stamps
(layers/base.conn_scope_name), so attribution joins by dict lookup.
"""

from __future__ import annotations

from typing import Dict, Optional

#: advertised bf16 peak per chip (matmul flops/sec)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
    "TPU v5p": 459e12, "TPU v6e": 918e12,
}

#: advertised HBM bandwidth per chip (bytes/sec)
PEAK_BW = {
    "TPU v5 lite": 819e9, "TPU v5e": 819e9, "TPU v4": 1228e9,
    "TPU v5p": 2765e9, "TPU v6e": 1640e9,
}

#: HBM capacity per chip (bytes) — the denominator of the OOM
#: pre-flight (analysis/memmodel.py) and the mem_profile capacity
#: column (doc/memory.md)
HBM_BYTES = {
    "TPU v5 lite": 16e9, "TPU v5e": 16e9, "TPU v4": 32e9,
    "TPU v5p": 95e9, "TPU v6e": 32e9,
}

TRAIN_FLOP_MULT = 3.0  # fwd + dgrad + wgrad, the bench.py convention


def peak_flops(device_kind: str) -> Optional[float]:
    """Chip bf16 peak, or None for unknown kinds (CPU hosts) — callers
    omit MFU columns rather than report against a made-up peak."""
    return next((v for k, v in PEAK_FLOPS.items() if k in device_kind),
                None)


def peak_bw(device_kind: str) -> Optional[float]:
    return next((v for k, v in PEAK_BW.items() if k in device_kind),
                None)


def hbm_bytes(device_kind: str) -> Optional[float]:
    """Chip HBM capacity, or None for unknown kinds (CPU hosts)."""
    return next((v for k, v in HBM_BYTES.items() if k in device_kind),
                None)


def resolve_chip(selector: str) -> Optional[str]:
    """Resolve a chip selector (``v5e``, ``tpu v4``, a full
    ``device_kind`` string...) to its canonical HBM-table key, or None.
    Case-insensitive.  A selector resolves only when it is unambiguous:
    a full table key, a device_kind string CONTAINING one, or the
    key's short alias (``v5e`` for "TPU v5e").  Anything matching
    zero or several keys — ``v5``, ``tpu``, a typo — returns None so
    the caller warns instead of silently checking against the wrong
    chip's capacity."""
    s = " ".join(selector.strip().lower().split())
    if not s:
        return None
    hits = set()
    for k in HBM_BYTES:
        kl = k.lower()
        alias = kl[len("tpu "):] if kl.startswith("tpu ") else kl
        if kl in s or s in (kl, alias, "tpu " + alias):
            hits.add(k)
    return hits.pop() if len(hits) == 1 else None


def _elems(shape) -> float:
    n = 1.0
    for d in shape:
        n *= d
    return n


def layer_costs(net, train: bool = True) -> Dict[str, Dict[str, float]]:
    """Per-connection analytic cost: scope -> {flops, bytes} per STEP
    (the global batch is in the node shapes).  Shared connections get
    their own entry (they execute separately even though parameters
    alias)."""
    from ..layers.base import conn_scope_name
    from ..layers.conv import ConvolutionLayer
    from ..layers.fullc import FullConnectLayer
    mult = TRAIN_FLOP_MULT if train else 1.0
    out: Dict[str, Dict[str, float]] = {}
    for i, conn in enumerate(net.connections):
        l = conn.layer
        in_elems = sum(_elems(net.node_shapes[n]) for n in conn.nindex_in)
        out_elems = sum(_elems(net.node_shapes[n])
                        for n in conn.nindex_out)
        param_elems = 0.0
        if isinstance(l, ConvolutionLayer):
            n, co, oh, ow = net.node_shapes[conn.nindex_out[0]]
            ci = net.node_shapes[conn.nindex_in[0]][1]
            p = l.param
            macs = (n * co * oh * ow * (ci // p.num_group)
                    * p.kernel_height * p.kernel_width)
            flops = 2.0 * macs
            param_elems = (co * (ci // p.num_group)
                           * p.kernel_height * p.kernel_width)
        elif isinstance(l, FullConnectLayer):
            shp_in = net.node_shapes[conn.nindex_in[0]]
            nin = shp_in[1] * shp_in[2] * shp_in[3]
            nout = l.param.num_hidden
            flops = 2.0 * shp_in[0] * nin * nout
            param_elems = float(nin) * nout
        else:
            flops = in_elems + out_elems
        out[conn_scope_name(i, conn)] = {
            "flops": mult * flops,
            "bytes": (mult / 2.0) * 4.0 * (in_elems + out_elems
                                           + param_elems),
        }
    return out
