"""Declared-key registry: harvest accepted config keys from the code.

Every subsystem that consumes ``name = value`` pairs declares its keys
next to its ``set_param`` (``LAYER_PARAM_KEYS`` / ``extra_config_keys``
in the layers, ``config_keys`` on iterator stages, ``HYPER_KEYS`` in the
updaters, ``TRAINER_KEYS`` / ``TASK_KEYS`` on the trainer and CLI
driver, ``engine.key_specs()`` for the lowering toggles).  This module
assembles those declarations into matchable scopes:

* :func:`global_scope` — keys legal outside any section.  Per the
  reference contract globals are broadcast to every layer, updater, and
  iterator, so this is the union of everything (a key "known anywhere"
  is never a global typo).
* :func:`layer_scope` — keys a ``layer[..] = type`` section accepts:
  the layer type's own keys plus the per-layer updater-hyper overrides.
* :func:`iterator_scope` — keys a ``data``/``eval``/``pred`` section
  accepts for its ``iter =`` stage chain.

Keys whose declared name ends in ``[*]`` are numbered/templated
(``extra_data_shape[0]``, ``metric[field,node]``, ``label_vec[0,4)``)
and match structurally.
"""

from __future__ import annotations

import functools
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .schema import KeySpec

# weight-tag prefixes for tag-scoped hyper overrides (``wmat:lr``,
# ``bias:wd`` — updater/param.h:100-105); the zoo's extra tags included
TAG_PREFIXES = ("wmat", "bias", "gate", "wmat2", "bias2",
                "wqkv", "wout", "bqkv", "wpos")

# templated key name -> full-match regex
_TEMPLATES = {
    "extra_data_shape[*]": r"extra_data_shape\[\d+\]",
    "metric[*]": r"metric\[[^\]]+\]",
    "label_vec[*]": r"label_vec\[\d+,\d+\)",
}


class KeyScope:
    """A matchable set of declared keys."""

    def __init__(self, name: str, specs: Sequence[KeySpec]):
        self.name = name
        self._exact: Dict[str, List[KeySpec]] = {}
        self._patterns: List[Tuple[re.Pattern, KeySpec]] = []
        for sp in specs:
            if sp.name.endswith("[*]") or sp.name in _TEMPLATES:
                pat = _TEMPLATES.get(
                    sp.name, re.escape(sp.name[:-3]) + r"\[[^\]]*\]")
                self._patterns.append((re.compile(pat + r"\Z"), sp))
            else:
                self._exact.setdefault(sp.name, []).append(sp)

    def match(self, key: str) -> List[KeySpec]:
        """Specs accepting ``key``, honoring templates and the tag-scoped
        ``wmat:``/``bias:`` prefix spellings.  Empty list = undeclared."""
        got = self._exact.get(key)
        if got:
            return got
        for pat, sp in self._patterns:
            if pat.match(key):
                return [sp]
        head, _, tail = key.partition(":")
        if tail and head in TAG_PREFIXES:
            return self.match(tail)
        return []

    def names(self) -> List[str]:
        """Exact key names (did-you-mean candidates)."""
        return sorted(self._exact)


def _netcfg_keys() -> Tuple[KeySpec, ...]:
    from ..updater.updaters import _UPDATERS
    from .schema import K
    return (
        K("netconfig", "enum", choices=("start", "end")),
        K("updater", "enum", choices=tuple(sorted(_UPDATERS))),
        K("sync", "str"),
        K("input_shape", "str", help="c,y,x"),
        K("extra_data_num", "int", lo=0),
        K("extra_data_shape[*]", "str", help="c,y,x"),
        K("label_vec[*]", "str", help="label field name for columns [a,b)"),
    )


def _all_iterator_keys() -> Tuple[KeySpec, ...]:
    from ..io import factory
    out: List[KeySpec] = []
    seen = set()
    stages = [c for classes in factory.ITER_STAGES.values() for c in classes]
    for cls in stages:
        for sp in getattr(cls, "config_keys", ()):
            if (cls.__name__, sp.name) not in seen:
                seen.add((cls.__name__, sp.name))
                out.append(sp)
    return tuple(out)


def _all_layer_keys() -> Tuple[KeySpec, ...]:
    from ..layers import registry as lreg
    from ..layers.base import LAYER_PARAM_KEYS
    out: List[KeySpec] = list(LAYER_PARAM_KEYS)
    for entry in lreg._REGISTRY.values():
        if isinstance(entry, type):
            for klass in entry.__mro__:
                out.extend(klass.__dict__.get("extra_config_keys", ()))
    return tuple(out)


@functools.lru_cache(maxsize=1)
def global_scope() -> KeyScope:
    from .. import engine
    from ..main import TASK_KEYS
    from ..nnet.trainer import TRAINER_KEYS
    from ..updater.updaters import HYPER_KEYS
    specs = (tuple(TASK_KEYS) + tuple(TRAINER_KEYS) + engine.key_specs()
             + tuple(HYPER_KEYS) + _netcfg_keys() + _all_iterator_keys()
             + _all_layer_keys())
    return KeyScope("global", specs)


@functools.lru_cache(maxsize=64)
def layer_scope(type_name: str) -> Optional[KeyScope]:
    """Scope for one layer section, or None when the type's key surface
    is unknowable here (unresolvable plugin) — the caller then skips key
    lint for that section rather than guessing."""
    from ..layers import registry as lreg
    from ..updater.updaters import HYPER_KEYS
    specs = _layer_type_specs(type_name)
    if specs is None:
        return None
    return KeyScope(f"layer:{type_name}", tuple(specs) + tuple(HYPER_KEYS))


def _layer_type_specs(type_name: str):
    from ..layers import registry as lreg
    if type_name.startswith("pairtest-"):
        rest = type_name[len("pairtest-"):]
        if "-" not in rest:
            return None
        master, slave = rest.split("-", 1)
        m, s = _layer_type_specs(master), _layer_type_specs(slave)
        if m is None or s is None:
            return None
        # master:/slave: routed spellings resolve through the tagless
        # union; PairTestLayer broadcasts untagged keys to both sides
        return list(m) + list(s)
    if type_name == "torch":
        try:
            from ..plugin.torch_adapter import TorchLayer
            return list(TorchLayer.config_keys())
        except Exception:  # noqa: BLE001 — optional plugin
            return None
    entry = lreg._REGISTRY.get(type_name)
    if not isinstance(entry, type):
        return None
    return list(entry.config_keys())


def layer_key_match(type_name: str, key: str) -> List[KeySpec]:
    """Match a layer-section key, honoring pairtest ``master:``/``slave:``
    routing prefixes."""
    scope = layer_scope(type_name)
    if scope is None:
        return []
    head, _, tail = key.partition(":")
    if tail and head in ("master", "slave") \
            and type_name.startswith("pairtest-"):
        return layer_key_match(type_name, tail) or scope.match(key)
    return scope.match(key)


def iterator_scope(chain: Tuple[str, ...]) -> KeyScope:
    from ..io import factory
    specs: List[KeySpec] = []
    for t in chain:
        classes = factory.iter_stage_classes(t)
        for cls in classes or ():
            specs.extend(getattr(cls, "config_keys", ()))
    return KeyScope("iter:" + "+".join(chain), specs)


def known_anywhere(key: str) -> bool:
    return bool(global_scope().match(key))
