"""SPMD deep lint: collective-consistency, donation, and dtype-flow.

Third ``task=check`` pass (after the config lint and the traced-graph
lint): the bug classes that are invisible until chips are burning — and
on a multi-host pod show up as a silent hang, not a stack trace.  The
reference's multi-machine story (mshadow-ps, ``CreateSharedModel
("dist")``) has no static checker either; this pass gives its TPU
replacement one.  Three analyses over the SAME traced step the jaxpr
lint walks (``jaxpr_lint.trace_step`` — traced once per check):

* **collective-consistency** — walk the jaxpr (recursing through
  ``shard_map``/``scan``/``cond``/``while`` bodies), extract the ordered
  collective sequence per mesh axis (psum / reduce_scatter / all_gather
  / all_to_all / ppermute), check every named axis against the built
  mesh's axis metadata (``parallel.mesh.mesh_axis_sizes``), and ERROR
  when ``cond`` branches carry different collective sequences — the
  replica-divergence deadlock class: if the predicate ever differs
  across replicas, the ranks issue mismatched collectives and the pod
  hangs.  A collective on a size-1 axis is statically certain waste
  (``spmd_dead_axis``); an axis the mesh doesn't carry at all would
  deadlock multi-host (``spmd_unknown_axis``).
* **donation/aliasing audit** — compare the step's input/output alias
  map (the cached AOT compile's ``input_output_alias`` header when
  ``step_hlo_text``/``step_memory_stats`` already paid for it, else the
  aliasing attributes of the un-optimized lowered module — no XLA
  compile) against the param/opt tree and ERROR on any param-sized leaf
  that is not donated: a 2x HBM tax the memory pre-flight
  (analysis/memmodel.py) currently just prices in.
* **dtype-flow** — verify the declared precision contracts against what
  the traced program does: a direct f32->bf16->f32 convert round-trip
  (precision thrown away for nothing, outside the dp_reduce_dtype wire
  segment whose pattern is convert -> psum -> convert), bf16
  accumulation chains deeper than :data:`BF16_ACC_DEPTH` (the sum/dot
  reduction-depth heuristic), and f32 collectives on the data axis when
  the config declared ``dp_reduce_dtype = bf16`` (the wire contract the
  run would silently break).

Finding ids are stable (the ``key`` field): tests/test_spmdlint.py
asserts them, doc/check.md catalogues them.  Severity policy: statically
certain contract violations are errors (divergent cond collectives,
dead/unknown axes, undonated param leaves, f32-wire-despite-bf16,
downcast-then-deep-accumulate); heuristics are warnings (native-bf16
deep reductions — shipped bf16 flagships do this in conv bias grads and
converge) or info (deep bf16 dot contractions — the MXU accumulates
those in f32).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

try:  # jax >= 0.4.34
    from jax.extend.core import ClosedJaxpr, Jaxpr
except ImportError:  # pragma: no cover — older jax
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore

from .schema import Finding

#: a single bf16 reduce summing more than this many elements is flagged
#: (bf16 carries 8 mantissa bits; the worst-case relative error of an
#: N-deep naive sum grows ~N * 2^-8, so thousands-deep chains can lose
#: every trailing bit)
BF16_ACC_DEPTH = 4096

#: bf16 dot_general contraction depth that earns the info note (MXU
#: hardware accumulates matmuls in f32, so this is advisory only)
BF16_DOT_DEPTH = 16384

#: f32 collectives smaller than this are exempt from the bf16-wire rule
#: (the overlap step's psum'd scalar loss is f32 by design)
F32_WIRE_MIN_BYTES = 1 << 16

#: collective primitives with named-axis semantics (lax.psum_scatter
#: traces as ``reduce_scatter``)
COLLECTIVE_PRIMS = ("psum", "reduce_scatter", "psum_scatter", "all_gather",
                    "all_to_all", "ppermute", "pbroadcast", "pgather")

#: finding id -> one-line meaning (doc/check.md renders this catalogue)
FINDING_IDS = {
    "spmd_unknown_axis": "collective names a mesh axis the built mesh "
                         "does not carry — a trace error today, a "
                         "deadlock on a multi-host pod",
    "spmd_dead_axis": "collective on a size-1 mesh axis — pure latency, "
                      "reduces/rotates nothing",
    "spmd_divergent_cond": "cond branches carry different collective "
                           "sequences — the replica-divergence deadlock "
                           "class",
    "spmd_undonated": "param-sized step input is not donated — the "
                      "executable holds input and output copies (2x HBM "
                      "for that leaf)",
    "spmd_f32_wire": "f32 collective on the data axis despite "
                     "dp_reduce_dtype = bf16 — the declared wire "
                     "contract is not what the trace does",
    "spmd_bf16_acc": "bf16 reduction deeper than the accumulation-depth "
                     "threshold",
    "spmd_bf16_dot": "bf16 dot contraction deeper than the advisory "
                     "threshold (MXU accumulates in f32)",
    "spmd_cast_roundtrip": "direct f32->bf16->f32 convert round-trip — "
                           "precision lost with no wire/collective in "
                           "between",
    "spmd_collectives": "per-axis collective sequence summary",
    "spmd_donation": "donation audit summary / skip notice",
    "spmd_dist_round_len": "dist_num_worker-sharded iterator feeds a "
                           "step whose per-round batch count derives "
                           "from LOCAL iterator length — unequal shards "
                           "issue divergent collective counts (the "
                           "multi-host hang class)",
}


@dataclasses.dataclass
class CollectiveOp:
    """One collective eqn in program order."""

    prim: str
    axes: Tuple[str, ...]
    dtype: str
    shape: Tuple[int, ...]
    nbytes: int

    def sig(self) -> Tuple:
        """Deadlock-relevant signature: two replicas agreeing on this
        tuple issue compatible collectives."""
        return (self.prim, self.axes, self.dtype, self.shape)


# ------------------------------------------------------------ jaxpr walk
def _sub_jaxprs(v) -> Iterable[Jaxpr]:
    """Jaxpr bodies nested inside an eqn params value (pjit/scan/while/
    shard_map/custom_vjp ...), in declaration order.  ONE body-discovery
    rule for both lint passes: this delegates to jaxpr_lint._jaxprs_in
    (which also wraps shard_map's plain Jaxpr), so a new body-carrying
    primitive is handled in one place."""
    from .jaxpr_lint import _jaxprs_in
    for cj in _jaxprs_in(v):
        yield cj.jaxpr


def _axis_names(params: Dict[str, Any]) -> Tuple[str, ...]:
    """NAMED axes of a collective eqn (``axes`` on psum, ``axis_name``
    elsewhere; either may be one name or a tuple).  Positional (int)
    axes are array dimensions, not mesh axes — dropped."""
    raw = params.get("axes", params.get("axis_name", ()))
    if isinstance(raw, (str, int)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def _op_of(eqn) -> CollectiveOp:
    aval = eqn.invars[0].aval if eqn.invars else None
    shape = tuple(int(d) for d in getattr(aval, "shape", ()))
    dtype = str(getattr(aval, "dtype", "?"))
    n = 1
    for d in shape:
        n *= d
    try:
        itemsize = np.dtype(getattr(aval, "dtype", np.float32)).itemsize
    except TypeError:
        itemsize = 4
    return CollectiveOp(prim=eqn.primitive.name, axes=_axis_names(eqn.params),
                        dtype=dtype, shape=shape, nbytes=n * itemsize)


def collective_walk(jaxpr: Jaxpr, ops: List[CollectiveOp],
                    findings: List[Finding]) -> None:
    """Append the ordered collective sequence of ``jaxpr`` (recursing
    through nested bodies) to ``ops``; divergent ``cond`` branches
    append an error finding and contribute their longest branch as the
    representative sequence."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            op = _op_of(eqn)
            if op.axes:  # axis-less psums (shard_map rep rewrites,
                ops.append(op)  # positional reductions) move nothing
            continue
        if name == "cond":
            branch_ops: List[List[CollectiveOp]] = []
            for br in eqn.params.get("branches", ()):
                sub: List[CollectiveOp] = []
                for bj in _sub_jaxprs(br):
                    collective_walk(bj, sub, findings)
                branch_ops.append(sub)
            if branch_ops:
                sigs = [[op.sig() for op in b] for b in branch_ops]
                if any(s != sigs[0] for s in sigs[1:]):
                    findings.append(Finding(
                        "error", "spmd_divergent_cond",
                        "cond branches carry different collective "
                        "sequences ("
                        + " vs ".join(
                            "[" + ", ".join(
                                f"{op.prim}@{'/'.join(op.axes)}"
                                for op in b) + "]"
                            for b in branch_ops)
                        + "): if the predicate ever differs across "
                        "replicas, ranks issue mismatched collectives "
                        "and a multi-host pod deadlocks (single-host: "
                        "wrong math); hoist the collectives out of the "
                        "branch or make both branches issue the same "
                        "sequence", scope="spmd"))
                ops.extend(max(branch_ops, key=len))
            continue
        for sub in _sub_jaxprs(eqn.params):
            collective_walk(sub, ops, findings)


def axis_findings(ops: Sequence[CollectiveOp],
                  axis_sizes: Dict[str, int]) -> List[Finding]:
    """Dead/unknown-axis findings (deduped per axis+primitive)."""
    out: List[Finding] = []
    seen = set()
    for op in ops:
        for ax in op.axes:
            key = (ax, op.prim)
            if key in seen:
                continue
            seen.add(key)
            if ax not in axis_sizes:
                out.append(Finding(
                    "error", "spmd_unknown_axis",
                    f"{op.prim} over mesh axis {ax!r} which the built "
                    f"mesh does not carry (axes: "
                    f"{', '.join(axis_sizes) or 'none'}); on a "
                    "multi-host pod a rank waiting on an axis nobody "
                    "else joins is a deadlock, not an error",
                    suggestion=_closest_axis(ax, axis_sizes),
                    scope="spmd"))
            elif axis_sizes[ax] == 1:
                out.append(Finding(
                    "error", "spmd_dead_axis",
                    f"{op.prim} over mesh axis {ax!r} of size 1: the "
                    "collective moves nothing and costs launch latency "
                    "every step; widen the axis in mesh= or drop the "
                    "collective path", scope="spmd"))
    return out


def _closest_axis(name: str, axis_sizes: Dict[str, int]) -> str:
    from .schema import did_you_mean
    return did_you_mean(name, list(axis_sizes))


def sequence_summary(ops: Sequence[CollectiveOp]) -> Finding:
    """One info finding: the ordered per-axis collective census."""
    if not ops:
        return Finding(
            "info", "spmd_collectives",
            "traced step carries no explicit collectives (GSPMD-placed "
            "collectives materialize after partitioning and are not "
            "visible to this pass)", scope="spmd")
    per_axis: Dict[str, List[str]] = {}
    for op in ops:
        for ax in op.axes:
            per_axis.setdefault(ax, []).append(op.prim)
    parts = []
    for ax in sorted(per_axis):
        counts: Dict[str, int] = {}
        for p in per_axis[ax]:
            counts[p] = counts.get(p, 0) + 1
        parts.append(ax + ": " + ", ".join(
            f"{p} x{n}" for p, n in sorted(counts.items())))
    return Finding(
        "info", "spmd_collectives",
        f"{len(ops)} collective(s) in the traced step — " +
        "; ".join(parts), scope="spmd")


# ------------------------------------------------------------ dtype flow
def _iter_jaxprs(jaxpr: Jaxpr) -> Iterable[Jaxpr]:
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn.params):
            yield from _iter_jaxprs(sub)


def _is_f32(aval) -> bool:
    return str(getattr(aval, "dtype", "")) == "float32"


def _is_bf16(aval) -> bool:
    return str(getattr(aval, "dtype", "")) == "bfloat16"


def dtype_flow_findings(closed: ClosedJaxpr,
                        acc_depth: int = BF16_ACC_DEPTH) -> List[Finding]:
    """Cast round-trips + deep bf16 accumulation over every nesting
    level of the traced step."""
    roundtrips = 0
    warn_reduces: List[Tuple[int, Tuple[int, ...]]] = []
    err_reduces: List[Tuple[int, Tuple[int, ...]]] = []
    deep_dots = 0
    max_dot_depth = 0
    for jaxpr in _iter_jaxprs(closed.jaxpr):
        # producer map for this nesting level: outvar id -> eqn
        produced: Dict[int, Any] = {}
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "convert_element_type":
                src, dst = eqn.invars[0].aval, eqn.outvars[0].aval
                if _is_bf16(src) and _is_f32(dst):
                    prod = produced.get(id(eqn.invars[0]))
                    if prod is not None \
                            and prod.primitive.name == "convert_element_type" \
                            and _is_f32(prod.invars[0].aval):
                        roundtrips += 1
            elif name == "reduce_sum" and _is_bf16(eqn.invars[0].aval):
                shape = tuple(int(d) for d in eqn.invars[0].aval.shape)
                depth = 1
                for a in eqn.params.get("axes", ()):
                    depth *= shape[a]
                if depth > acc_depth:
                    prod = produced.get(id(eqn.invars[0]))
                    downcast = (
                        prod is not None
                        and prod.primitive.name == "convert_element_type"
                        and _is_f32(prod.invars[0].aval))
                    (err_reduces if downcast else warn_reduces).append(
                        (depth, shape))
            elif name == "dot_general" and _is_bf16(eqn.outvars[0].aval):
                (lhs_c, _), _ = eqn.params["dimension_numbers"]
                shape = tuple(int(d) for d in eqn.invars[0].aval.shape)
                depth = 1
                for a in lhs_c:
                    depth *= shape[a]
                if depth > BF16_DOT_DEPTH:
                    deep_dots += 1
                    max_dot_depth = max(max_dot_depth, depth)
            for v in eqn.outvars:
                produced[id(v)] = eqn
    out: List[Finding] = []
    if err_reduces:
        depth, shape = max(err_reduces)
        out.append(Finding(
            "error", "spmd_bf16_acc",
            f"{len(err_reduces)} reduction(s) sum f32 values through a "
            f"deliberate bf16 downcast, up to {depth} elements deep "
            f"(operand {shape}): an N-deep bf16 sum loses ~N*2^-8 "
            "relative precision — accumulate in f32 and cast the "
            "result, or keep the chain under "
            f"{acc_depth}", scope="spmd"))
    if warn_reduces:
        depth, shape = max(warn_reduces)
        out.append(Finding(
            "warn", "spmd_bf16_acc",
            f"{len(warn_reduces)} bf16 reduction(s) deeper than "
            f"{acc_depth} (max {depth}, operand {shape}): bf16 carries "
            "8 mantissa bits, so thousands-deep sums (bias grads, "
            "pooled statistics) shed trailing bits; consider an f32 "
            "accumulation dtype on those chains", scope="spmd"))
    if deep_dots:
        out.append(Finding(
            "info", "spmd_bf16_dot",
            f"{deep_dots} bf16 dot contraction(s) deeper than "
            f"{BF16_DOT_DEPTH} (max {max_dot_depth}); MXU hardware "
            "accumulates matmuls in f32, so this is advisory — only a "
            "vector-unit lowering would accumulate in bf16",
            scope="spmd"))
    if roundtrips:
        out.append(Finding(
            "warn", "spmd_cast_roundtrip",
            f"{roundtrips} direct f32->bf16->f32 convert round-trip(s) "
            "in the traced step: the value loses 16 mantissa bits and "
            "gains nothing (no collective/wire between the casts) — "
            "outside the dp_reduce_dtype wire segment this is a "
            "precision bug, not a bandwidth saving", scope="spmd"))
    return out


def wire_findings(ops: Sequence[CollectiveOp], wire_bf16: bool
                  ) -> List[Finding]:
    """f32 reductions on the data axis when the config declared a bf16
    wire (``dp_reduce_dtype = bf16``)."""
    if not wire_bf16:
        return []
    bad = [op for op in ops
           if op.prim in ("psum", "reduce_scatter", "psum_scatter")
           and "data" in op.axes and op.dtype == "float32"
           and op.nbytes >= F32_WIRE_MIN_BYTES]
    if not bad:
        return []
    total_mb = sum(op.nbytes for op in bad) / 2**20
    worst = max(bad, key=lambda op: op.nbytes)
    return [Finding(
        "error", "spmd_f32_wire",
        f"dp_reduce_dtype = bf16 declares a bf16 wire, but {len(bad)} "
        f"data-axis reduction(s) move f32 ({total_mb:.1f} MiB per step, "
        f"largest {worst.shape} {worst.prim}): the declared comm saving "
        "never happens — cast to bf16 before the reduce (the "
        "_reduce_leaf pattern) or drop the dp_reduce_dtype claim",
        scope="spmd")]


# -------------------------------------------------------- donation audit
def donation_findings(report: Optional[Dict[str, Any]]) -> List[Finding]:
    """Audit a :meth:`NetTrainer.step_donation_report` result: every
    param-sized leaf (params/opt_state trees, plus the param-shaped grad
    accumulator) must be donated into the step, or the executable holds
    an input copy AND an output copy — the 2x HBM tax the memory
    pre-flight (doc/memory.md) can only price in, not remove."""
    if report is None:
        return [Finding(
            "info", "spmd_donation",
            "donation audit skipped: the executed step cannot be "
            "reproduced by AOT lowering here (input_s2d staging or the "
            "dp_reduce_at=apply two-step path)", scope="spmd")]
    out: List[Finding] = []
    rows = report["leaves"]
    for tree, severity in (("params", "error"), ("opt_state", "error"),
                           ("grad_acc", "warn"), ("buffers", "warn")):
        missing = [r for r in rows if r["tree"] == tree
                   and not r["donated"]]
        if not missing:
            continue
        total_mb = sum(r["bytes"] for r in missing) / 2**20
        names = ", ".join(r["path"] for r in missing[:3])
        if len(missing) > 3:
            names += f", ... ({len(missing) - 3} more)"
        out.append(Finding(
            severity, "spmd_undonated",
            f"{len(missing)} {tree} leaf/leaves not donated into the "
            f"compiled step ({total_mb:.1f} MiB held twice: {names}); "
            "every param-sized operand must ride donate_argnums with an "
            "output of identical shape+dtype so XLA can alias it — a "
            "dtype/shape mismatch between the leaf and its update "
            "silently voids the donation", scope="spmd"))
    donated = [r for r in rows if r["donated"]]
    out.append(Finding(
        "info", "spmd_donation",
        f"donation audit: {len(donated)}/{len(rows)} state leaves "
        f"donated ({report['alias_bytes'] / 2**20:.1f} MiB aliased, "
        f"source={report['source']})", scope="spmd"))
    return out


# --------------------------------------------------------------- driver
def dist_round_findings(cfg, ops: Sequence[CollectiveOp]) -> List[Finding]:
    """Seed rule for the multi-host hang class (ROADMAP item 2).

    When the iterator is sharded ``dist_num_worker`` ways, every rank
    runs the trainer's round loop — which terminates when the *local*
    iterator runs dry (``batch = itr.next(); if batch is None: break``
    in ``main.py``).  The per-round step count, and with it the number
    of collectives each rank issues, therefore derives from the local
    shard length: ranks with unequal shard sizes issue divergent
    collective counts, and the longer ranks hang in their next psum
    waiting on peers that already left the round.  The iterators'
    empty-rank assert (``io/text.py`` / ``io/imbin.py`` init) only
    catches the degenerate zero-shard case, not unequal nonzero ones —
    hence the WARN whenever sharding meets a collective-bearing step."""
    try:
        nworker = int(dict(cfg).get("dist_num_worker", "1"))
    except (TypeError, ValueError):
        return []
    if nworker <= 1 or not ops:
        return []
    return [Finding(
        "warn", "spmd_dist_round_len",
        f"iterator is sharded dist_num_worker = {nworker} ways but each "
        "training round ends when the LOCAL iterator is exhausted, so "
        f"the number of collectives a rank issues per round ({len(ops)} "
        "per step x local step count) derives from its own shard "
        "length; ranks with unequal shard sizes issue divergent "
        "collective counts and the longer ranks hang in the next psum",
        suggestion="keep per-rank shard counts equal (shard count a "
                   "multiple of dist_num_worker, equal-length shards); "
                   "the iterator init asserts only the zero-shard case "
                   "('a rank with zero data would dispatch no steps and "
                   "hang the other replicas' collectives'), not unequal "
                   "nonzero ones",
        scope="spmd")]


def lint_trainer(trainer, closed: ClosedJaxpr, cfg) -> List[Finding]:
    """Run all three SPMD analyses over a built trainer and its traced
    step.  Reads the wire contract from the engine options the config
    just configured (the caller runs inside the engine-snapshot window
    ``analysis.run_check`` maintains)."""
    from .. import engine
    from ..parallel.mesh import mesh_axis_sizes
    findings: List[Finding] = []
    ops: List[CollectiveOp] = []
    collective_walk(closed.jaxpr, ops, findings)
    findings.extend(axis_findings(ops, mesh_axis_sizes(trainer.mesh)))
    findings.append(sequence_summary(ops))
    findings.extend(dtype_flow_findings(closed))
    findings.extend(wire_findings(
        ops, wire_bf16=engine.opts.dp_reduce_dtype == "bf16"))
    findings.extend(donation_findings(trainer.step_donation_report()))
    findings.extend(dist_round_findings(cfg, ops))
    return findings
