"""Analytic per-layer memory model + the OOM pre-flight.

The byte-side twin of :mod:`analysis.costmodel` (which models time):
per-layer parameter / gradient / optimizer-state / activation byte
formulas over a BUILT :class:`~cxxnet_tpu.nnet.trainer.NetTrainer`,
keyed by the same ``conn_scope_name`` strings the whole observatory
joins on.  Two consumers:

* the ``mem_profile`` record (monitor/memory.py) carries each row's
  ``model_bytes`` / ``model_x`` the same way ``layer_profile`` carries
  roofline columns — measured-vs-model distance per layer;
* ``task=check`` runs :func:`preflight` against the target chip's HBM
  capacity (costmodel.HBM_BYTES) and errors when the estimated peak
  exceeds it (warns inside ``mem_margin_pct``), with remediation in
  the finding text (doc/memory.md).

Accounting is PER DEVICE: parameter/optimizer leaves are measured
through their actual shardings (a ZeRO-sharded or model-sharded leaf
counts its shard, not the logical array — never double-counted), and
activations divide the global batch by the mesh's data axis.  The model
is deliberately coarse on the same terms as the cost model: a ranking
aid and a conservative pre-flight ceiling, not a calibrated simulator.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from . import costmodel
from .schema import Finding

#: unmodeled-temp slack the pre-flight adds on top of the analytic sum
#: (fusion scratch, collective staging, allocator fragmentation)
WORKSPACE_FRAC = 0.10


def leaf_device_bytes(leaf) -> int:
    """Per-device bytes of one placed array: the shard this device
    holds (sharding-aware), not the logical array."""
    try:
        shape = leaf.sharding.shard_shape(leaf.shape)
    except Exception:  # noqa: BLE001 — unplaced / numpy leaf
        shape = getattr(leaf, "shape", ())
    n = 1
    for d in shape:
        n *= int(d)
    return n * leaf.dtype.itemsize


def tree_device_bytes(tree) -> int:
    """Per-device bytes of a (possibly nested) param tree — the ONE
    shard-aware accounting rule (serve footprints import it too)."""
    total = 0
    for v in tree.values():
        total += tree_device_bytes(v) if isinstance(v, dict) \
            else leaf_device_bytes(v)
    return total


def param_rows(trainer) -> Dict[str, Dict[str, int]]:
    """scope -> ``{param_bytes, opt_bytes}``, per device, from the
    trainer's ACTUAL placed trees (shardings included).  Shared
    connections contribute nothing — their parameters alias the
    primary's (the not-double-counted contract)."""
    from ..layers.base import conn_scope_name
    out: Dict[str, Dict[str, int]] = {}
    for i, conn in enumerate(trainer.net.connections):
        if not conn.owns_params or conn.param_key not in trainer.params:
            continue
        out[conn_scope_name(i, conn)] = {
            "param_bytes": tree_device_bytes(
                trainer.params[conn.param_key]),
            "opt_bytes": tree_device_bytes(
                trainer.opt_state[conn.param_key]),
        }
    return out


def _data_shards(trainer) -> int:
    try:
        return int(trainer.mesh.shape.get("data", 1))
    except Exception:  # noqa: BLE001
        return 1


def layer_mem(trainer) -> Dict[str, Dict[str, int]]:
    """scope -> per-device ``{param_bytes, grad_bytes, opt_bytes,
    act_bytes}`` for EVERY connection (shared ones carry activations
    but no params).  ``act_bytes`` is the connection's output
    activation — what it costs while live between forward and backward;
    remat/batch_split residency corrections happen at the totals level
    (:func:`totals`), where they are properties of the schedule, not of
    one layer."""
    import jax.numpy as jnp
    from ..layers.base import conn_scope_name
    itemsize = jnp.dtype(trainer.dtype).itemsize
    ndata = _data_shards(trainer)
    prows = param_rows(trainer)
    out: Dict[str, Dict[str, int]] = {}
    for i, conn in enumerate(trainer.net.connections):
        scope = conn_scope_name(i, conn)
        act = 0
        for nid in conn.nindex_out:
            shp = trainer.net.node_shapes[nid]
            n = 1
            for d in shp:
                n *= int(d)
            act += (n // max(ndata, 1)) * itemsize
        pr = prows.get(scope, {})
        pbytes = int(pr.get("param_bytes", 0))
        out[scope] = {
            "param_bytes": pbytes,
            # gradients materialize in the parameter dtype during
            # backward — transient, but live together near the apply
            "grad_bytes": pbytes,
            "opt_bytes": int(pr.get("opt_bytes", 0)),
            "act_bytes": act,
        }
    return out


def totals(trainer, per_layer: Optional[Dict[str, Dict[str, int]]] = None
           ) -> Dict[str, int]:
    """Per-device byte totals + the estimated peak the pre-flight
    checks.  Schedule-aware corrections:

    * ``remat = K``: only segment-boundary activations persist across
      the backward; within a segment one recompute window is live at a
      time — held = each segment's LAST activation, live = the largest
      segment's sum;
    * ``batch_split = K``: activations divide by K (one sub-batch chain
      live at a time);
    * ``update_period > 1``: the gradient accumulator persists between
      micro-steps (parameter-shaped; halved by
      ``dp_reduce_dtype = bf16`` when parameters are f32, the
      remediation the pre-flight suggests).
    """
    per_layer = per_layer or layer_mem(trainer)
    acts = [v["act_bytes"] for v in per_layer.values()]
    param = sum(v["param_bytes"] for v in per_layer.values())
    grad = sum(v["grad_bytes"] for v in per_layer.values())
    opt = sum(v["opt_bytes"] for v in per_layer.values())
    act = sum(acts)
    remat = int(getattr(trainer, "remat", 0) or 0)
    if remat > 1 and len(acts) >= remat:
        k = remat
        chunk = max(len(acts) // k, 1)
        segs = [acts[j: j + chunk] for j in range(0, len(acts), chunk)]
        held = sum(s[-1] for s in segs if s)
        live = max(sum(s) for s in segs)
        # capped: on shallow nets boundary + window can exceed the plain
        # sum (the boundary of the live window counts twice) — remat
        # never costs MORE than keeping everything in this model
        act = min(held + live, act)
    bsplit = int(getattr(trainer, "batch_split", 1) or 1)
    if bsplit > 1:
        act = act // bsplit
    acc = 0
    if int(getattr(trainer, "update_period", 1)) > 1:
        acc = param
        from .. import engine
        if getattr(engine.opts, "dp_reduce_dtype", "f32") == "bf16":
            acc = acc // 2
    buffers = tree_device_bytes(getattr(trainer, "buffers", {}) or {})
    est = param + grad + opt + acc + act + buffers
    est += int(est * WORKSPACE_FRAC)
    return {"param_bytes": param, "grad_bytes": grad,
            "opt_bytes": opt, "acc_bytes": acc, "act_bytes": act,
            "buffer_bytes": buffers, "est_peak_bytes": est}


def _fmt_gb(b: float) -> str:
    return f"{b / 1e9:.2f} GB"


def _remediations(trainer, tot: Dict[str, int]) -> List[str]:
    """Ordered did-you-mean-style knob suggestions, biggest modeled
    saving first (doc/memory.md 'When the pre-flight fires')."""
    out: List[Tuple[int, str]] = []
    act, opt, acc = tot["act_bytes"], tot["opt_bytes"], tot["acc_bytes"]
    if int(getattr(trainer, "remat", 0) or 0) <= 1 and act:
        out.append((act // 2, "remat = 2..4 (checkpoint activations; "
                    f"~{_fmt_gb(act / 2)} off)"))
    if int(getattr(trainer, "batch_split", 1) or 1) <= 1 and act:
        out.append((act // 2, "batch_split = 2 (halve live "
                    f"activations; ~{_fmt_gb(act / 2)} off)"))
    if not int(getattr(trainer, "shard_opt_state", 0) or 0) \
            and _data_shards(trainer) > 1 and opt:
        nd = _data_shards(trainer)
        save = opt - opt // nd
        out.append((save, "shard_opt_state = 1 (ZeRO over the data "
                    f"axis; ~{_fmt_gb(save)} off)"))
    if acc:
        from .. import engine
        if getattr(engine.opts, "dp_reduce_dtype", "f32") != "bf16":
            out.append((acc // 2, "dp_reduce_dtype = bf16 (halve the "
                        f"grad accumulator; ~{_fmt_gb(acc / 2)} off)"))
    out.sort(key=lambda kv: -kv[0])
    return [s for _, s in out]


def preflight(trainer, cfg_pairs) -> List[Finding]:
    """The OOM pre-flight behind ``task=check`` (``mem_check = 1``,
    doc/memory.md): run the analytic model against the target chip's
    HBM and report BEFORE a compile-and-train cycle is spent.

    Chip resolution: ``mem_chip`` (``v5e``, ``tpu v4``, a full
    device_kind), else the config's ``dev`` string when it names a
    known chip.  An unresolvable chip returns no findings here — the
    conflint rule (``_mem_rules``) already warns about it on every
    check run, traced or not, and one message beats two.  Estimated
    peak over capacity is an ERROR; within ``mem_margin_pct`` (default
    10) of capacity is a WARNING; otherwise one info finding records
    the headroom.  Remediation knobs ride in the finding text, largest
    modeled saving first."""
    last = dict(cfg_pairs)
    if last.get("mem_check", "0") != "1":
        return []
    sel = last.get("mem_chip", "") or last.get("dev", "")
    chip = costmodel.resolve_chip(sel)
    if chip is None:
        return []
    cap = costmodel.HBM_BYTES[chip]
    try:
        margin = float(last.get("mem_margin_pct", "10"))
    except ValueError:
        margin = 10.0
    tot = totals(trainer)
    est = tot["est_peak_bytes"]
    parts = (f"params {_fmt_gb(tot['param_bytes'])} + grads "
             f"{_fmt_gb(tot['grad_bytes'])} + opt "
             f"{_fmt_gb(tot['opt_bytes'])} + acts "
             f"{_fmt_gb(tot['act_bytes'])}"
             + (f" + acc {_fmt_gb(tot['acc_bytes'])}"
                if tot["acc_bytes"] else "")
             + f" + {int(WORKSPACE_FRAC * 100)}% workspace")
    findings: List[Finding] = []
    if est > cap:
        fix = _remediations(trainer, tot)
        msg = (f"estimated peak HBM {_fmt_gb(est)} exceeds {chip} "
               f"capacity {_fmt_gb(cap)} per device ({parts})")
        if fix:
            msg += "; did you mean: " + "; ".join(fix)
        findings.append(Finding("error", "mem_check", msg, scope="mem"))
    elif est > cap * (1.0 - margin / 100.0):
        fix = _remediations(trainer, tot)
        findings.append(Finding(
            "warn", "mem_check",
            f"estimated peak HBM {_fmt_gb(est)} is within "
            f"{margin:g}% of {chip} capacity {_fmt_gb(cap)} "
            f"({parts}); consider: " + "; ".join(fix[:2]), scope="mem"))
    else:
        findings.append(Finding(
            "info", "mem_check",
            f"estimated peak HBM {_fmt_gb(est)} of {chip} "
            f"{_fmt_gb(cap)} ({est / cap:.0%} full; {parts})",
            scope="mem"))
    # the remat-softens-the-estimate caveat is the conflint rule's job
    # (_mem_rules fires it with or without the traced pass)
    return findings
