"""Traced-graph lint: abstract-trace the train step, walk the jaxpr.

The config lint catches what a key *says*; this pass catches what the
traced program *does* — the bug classes the telemetry layer
(doc/monitor.md) can only observe after a device run:

* **large baked-in constants** — an array closure-captured into the
  step (instead of flowing through params/buffers/inputs) is burned
  into every compiled executable: it re-uploads per compilation,
  defeats donation, and silently pins HBM.  Flagged above 1 MiB.
* **silent f32→f64 promotions** — a stray python float / numpy f64
  under ``jax_enable_x64`` doubles memory and falls off the TPU fast
  path; flagged per primitive.
* **weak-typed state leaves** — a param/optimizer/buffer leaf created
  from a bare python scalar traces weakly; the first real update
  returns a strongly-typed array and the second call silently retraces
  (the retrace-counter gauge would show it a round too late).
* **gradient leaves escaping the dp reduction** — under
  ``dp_overlap = 1`` every parameter gradient must live in some
  reduction bucket; a leaf outside the plan would apply an unreduced
  (per-device) gradient and the replicas drift.

Everything runs on CPU with ``jax.make_jaxpr`` over ShapeDtypeStructs —
seconds, no device, no data files.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.4.34
    from jax.extend.core import ClosedJaxpr, Jaxpr
except ImportError:  # pragma: no cover — older jax
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore

from .schema import Finding

#: closure-captured constants larger than this are findings
CONST_BYTES_LIMIT = 1 << 20


# ------------------------------------------------------------ jaxpr walk
def _jaxprs_in(v) -> Iterable:
    """ClosedJaxpr values nested inside an eqn params value.  shard_map
    carries a PLAIN Jaxpr in its ``jaxpr`` param (no consts) — wrap it so
    the walk reaches collective/compute eqns inside the SPMD body too."""
    if isinstance(v, ClosedJaxpr):
        yield v
    elif isinstance(v, Jaxpr):
        yield ClosedJaxpr(v, ())
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _jaxprs_in(x)
    elif isinstance(v, dict):
        for x in v.values():
            yield from _jaxprs_in(x)


def iter_closed_jaxprs(closed: "ClosedJaxpr") -> Iterable["ClosedJaxpr"]:
    """The closed jaxpr and every closed jaxpr nested in its eqn params
    (pjit bodies, scan/cond/while bodies, custom_vjp branches, ...)."""
    yield closed
    for eqn in closed.jaxpr.eqns:
        for v in eqn.params.values():
            for sub in _jaxprs_in(v):
                yield from iter_closed_jaxprs(sub)


def _const_entries(closed: "ClosedJaxpr") -> List[Tuple[Any, Any]]:
    """(const value, constvar aval) pairs across all nesting levels."""
    out = []
    for cj in iter_closed_jaxprs(closed):
        for var, const in zip(cj.jaxpr.constvars, cj.consts):
            out.append((const, getattr(var, "aval", None)))
    return out


def _nbytes(x) -> int:
    try:
        return int(x.size) * int(np.dtype(x.dtype).itemsize)
    except (TypeError, ValueError, AttributeError):
        return 0


def jaxpr_findings(closed: "ClosedJaxpr",
                   const_bytes_limit: int = CONST_BYTES_LIMIT
                   ) -> List[Finding]:
    """Lint one closed jaxpr: large/weak constants + f64 promotions."""
    findings: List[Finding] = []
    seen_const_ids = set()
    for const, aval in _const_entries(closed):
        if id(const) in seen_const_ids:
            continue
        seen_const_ids.add(id(const))
        nb = _nbytes(const)
        if nb > const_bytes_limit:
            shape = tuple(getattr(const, "shape", ()))
            findings.append(Finding(
                "error", "",
                f"closure-captured constant {shape} "
                f"{getattr(const, 'dtype', '?')} ({nb / 2**20:.1f} MiB) "
                "baked into the traced step: it re-uploads with every "
                "compilation and pins HBM — thread it through "
                "params/buffers/inputs instead", scope="jaxpr"))
        elif nb and getattr(aval, "weak_type", False) \
                and getattr(const, "ndim", 0) > 0:
            findings.append(Finding(
                "warn", "",
                f"weak-typed constant {tuple(const.shape)} in the traced "
                "step (created from a bare python scalar?): the first "
                "strongly-typed value that replaces it forces a silent "
                "retrace", scope="jaxpr"))
    f64 = {}
    for cj in iter_closed_jaxprs(closed):
        for eqn in cj.jaxpr.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if getattr(aval, "dtype", None) == jnp.float64:
                    f64[eqn.primitive.name] = f64.get(
                        eqn.primitive.name, 0) + 1
    for prim, n in sorted(f64.items()):
        findings.append(Finding(
            "warn", "",
            f"float64 values produced by {n} '{prim}' op(s) in the "
            "traced step — a silent f32→f64 promotion doubles memory "
            "and leaves the accelerator fast path", scope="jaxpr"))
    return findings


# ------------------------------------------------------- trainer driver
def weak_leaf_findings(trees: dict) -> List[Finding]:
    """Weak-typed leaves in the trainer's state pytrees (params /
    opt_state / buffers): these retrace the step on the second call."""
    findings = []
    for tree_name, tree in trees.items():
        paths = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in paths:
            if getattr(leaf, "weak_type", False):
                findings.append(Finding(
                    "warn", "",
                    f"{tree_name} leaf {jax.tree_util.keystr(path)} is "
                    "weak-typed (built from a python scalar?); the "
                    "updated strongly-typed array will force a silent "
                    "retrace on the second step", scope="jaxpr"))
    return findings


def dp_coverage_findings(param_keys: Sequence[str],
                         covered_keys: Sequence[str]) -> List[Finding]:
    """Param groups whose gradients escape the dp_overlap bucket plan."""
    missing = sorted(set(param_keys) - set(covered_keys))
    return [Finding(
        "error", "",
        f"gradient of param group {k!r} escapes the dp_overlap bucket "
        "plan: it would apply an unreduced per-device gradient and the "
        "replicas drift", scope="jaxpr") for k in missing]


def _dp_findings(trainer) -> List[Finding]:
    from .. import engine
    if engine.opts.dp_overlap != "1":
        return []
    if not trainer._dp_overlap_active():
        # 1F1B composes through its own plan: per-stage buckets whose
        # (pipe, data) psums fire at cooldown grad-ready ticks — audit
        # that plan's coverage instead of reporting the fallback
        pipe_plan = trainer._pipe_bucket_plan() \
            if trainer._pipelined else None
        if pipe_plan is not None:
            covered = [k for ks, _ in pipe_plan for k in ks]
            return dp_coverage_findings(list(trainer.params), covered)
        return [Finding(
            "info", "", "dp_overlap = 1 is configured but inactive on "
            "this build (see the fallback warning above); bucket "
            "coverage not checked", scope="jaxpr")]
    plan = trainer._dp_overlap_plan()
    covered: List[str] = list(plan.tail_keys)
    for ks in plan.stage_keys:
        covered.extend(ks)
    return dp_coverage_findings(list(trainer.params), covered)


def trace_step(trainer) -> "ClosedJaxpr":
    """Abstract-trace the configured train step to a closed jaxpr.

    The step body is traced directly (the same ``_loss_and_grads`` +
    ``_apply_update`` composition the jitted step wraps) so that
    closure-captured values surface as jaxpr constants while
    params/opt_state/buffers — passed as arguments — stay invars.
    Shared by :func:`lint_trainer` and the SPMD deep lint
    (analysis/spmdlint.py): ``task=check`` traces once and every pass
    walks the same program."""
    eval_ids = tuple(dict.fromkeys(trainer.eval_node_ids))
    net = trainer.net
    data_shape = net.node_shapes[0]
    if trainer._s2d_args is not None:
        # input_s2d = 1: the step consumes pre-space-to-depth batches;
        # derive the emitted shape from the real staging transform
        from ..ops import nn as N_ops
        data_shape = jax.eval_shape(
            lambda x: N_ops.s2d_input(x, *trainer._s2d_args)[0],
            jax.ShapeDtypeStruct(data_shape, jnp.float32)).shape
    data = jax.ShapeDtypeStruct(data_shape, jnp.float32)
    label = jax.ShapeDtypeStruct(
        (trainer.batch_size, trainer.netcfg.label_width()), jnp.float32)
    extras = tuple(
        jax.ShapeDtypeStruct(net.node_shapes[1 + i], jnp.float32)
        for i in range(trainer.netcfg.extra_data_num))
    epoch = jax.ShapeDtypeStruct((), jnp.int32)
    rng = jax.random.PRNGKey(0)

    def step(params, opt_state, buffers, data, label_vec, extras, rng,
             epoch):
        (loss, (new_buffers, outs, _diags)), grads = \
            trainer._loss_and_grads(params, buffers, data, label_vec,
                                    extras, epoch, rng, eval_ids)
        new_p, new_s = trainer._apply_update(params, opt_state, grads,
                                             epoch)
        return loss, new_p, new_s, new_buffers, outs

    return jax.make_jaxpr(step)(
        trainer.params, trainer.opt_state, trainer.buffers, data, label,
        extras, rng, epoch)


def lint_trainer(trainer, closed: "ClosedJaxpr" = None) -> List[Finding]:
    """Lint the trainer's traced step jaxpr (pass ``closed`` to reuse a
    :func:`trace_step` result instead of tracing again)."""
    if closed is None:
        closed = trace_step(trainer)
    findings = jaxpr_findings(closed)
    findings.extend(weak_leaf_findings({
        "params": trainer.params, "opt_state": trainer.opt_state,
        "buffers": trainer.buffers}))
    findings.extend(_dp_findings(trainer))
    n_eqns = sum(len(cj.jaxpr.eqns) for cj in iter_closed_jaxprs(closed))
    findings.append(Finding(
        "info", "", f"traced train step: {n_eqns} equations, "
        f"{len(closed.consts)} top-level constants", scope="jaxpr"))
    return findings
