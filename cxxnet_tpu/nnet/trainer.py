"""NetTrainer: the public training API + the jitted SPMD step.

Reference: ``INetTrainer`` (``src/nnet/nnet.h:18-92``) and its implementation
``CXXNetThreadTrainer`` (``nnet_impl-inl.hpp:16-455``).  The reference runs
one worker pthread per GPU, slices each batch across them, and aggregates
gradients through mshadow-ps push/pull with per-layer priorities.  On TPU the
entire Forward+Backprop+Update becomes ONE jitted function over a device
mesh: the batch is sharded on the mesh's "data" axis, jax.grad's psum does
the aggregation over ICI, and XLA's latency-hiding scheduler provides the
comm/compute overlap the reference engineered by hand (priority =
-layer_index, deferred big pulls — async_updater-inl.hpp:128-174).

Capability mapping:
* ``update_period`` grad accumulation     -> in-step accumulator + lax.cond
* ``update_on_server`` optimizer offload  -> optimizer states can be sharded
  over "data" (ZeRO-style) via ``shard_opt_state = 1``
* ``fullc_gather`` activation-gather      -> fullc wmat sharded over "model"
  axis (GSPMD inserts the all-gathers) via ``fullc_gather = 1`` + mesh config
* ``test_on_server`` consistency check    -> :meth:`check_weight_consistency`
* per-device seeds (i + seed*100)         -> one keyed threefry stream,
  folded per step (deterministic regardless of mesh shape)
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import engine
from ..analysis.schema import K
from ..io.data import DataBatch
from ..layers.base import ForwardContext, LabelInfo, as_mat
from ..monitor import TrainingDiverged, log as mlog
from ..monitor.metrics import MetricsRegistry, device_memory_gauges
from ..parallel import mesh as meshlib
from ..updater import UpdaterHyper, create_updater
from ..utils import serializer
from ..utils.metric import MetricSet
from .net import Network
from .netconfig import NetConfig

Pytree = Any

def _metric_check(val: str):
    """Lint-time metric-name validation via the real factory."""
    from ..utils.metric import create_metric
    try:
        create_metric(val)
        return None
    except ValueError as e:
        return str(e)


def _mesh_check(val: str):
    try:
        meshlib.MeshSpec.parse(val)
        return None
    except Exception as e:  # noqa: BLE001 — any parse failure is the finding
        return f"invalid mesh spec: {e}"


#: keys NetTrainer.set_param consumes (engine options declare themselves
#: in engine.py; the metric[...] scoped spellings are pattern keys the
#: lint pass handles structurally).  Harvested by analysis/registry.py —
#: keep in sync with set_param below.
TRAINER_KEYS = (
    K("batch_size", "int", lo=1), K("update_period", "int", lo=1),
    K("seed", "int"), K("dev", "str"),
    K("dtype", "enum", choices=("float32", "bfloat16", "float16")),
    K("mesh", "str", check=_mesh_check, help="axis:size[,axis:size...]"),
    K("fullc_gather", "int", lo=0, hi=1),
    K("pipe_microbatch", "int", lo=0),
    K("pipe_schedule", "enum", choices=("gpipe", "1f1b")),
    K("batch_split", "int", lo=1), K("remat", "int", lo=0),
    K("scale", "float"), K("mean_value", "str"),
    K("shard_opt_state", "int", lo=0, hi=1),
    K("update_on_server", "int", lo=0, hi=1),
    K("silent", "int", lo=0, hi=1),
    K("monitor", "int", lo=0, hi=1),
    K("monitor_interval", "int", lo=1),
    K("monitor_nan", "enum", choices=("warn", "fatal", "off")),
    K("metrics_sink", "str", help="jsonl:<path> or none"),
    K("trace_sample", "int", lo=0, hi=1000000,
      help="host-side span tracing: trace every Nth request/item "
           "through the request path (span records; 0 = off; needs "
           "metrics_sink — doc/monitor.md)"),
    K("eval_train", "int", lo=0, hi=1), K("eval_group", "int", lo=1),
    K("input_s2d", "int", lo=0, hi=1), K("print_step", "int", lo=1),
    K("metric", "str", check=_metric_check,
      help="error/rmse/logloss/rec@n, repeatable"),
    K("metric[*]", "str", check=_metric_check,
      help="scoped metric[field] / metric[field,node]"),
    K("strict_config", "int", lo=0, hi=1,
      help="route silently-ignored config keys through the lint "
           "reporter as warnings"),
)


def _lowered_arg_aliases(mlir_text: str):
    """(donated arg indices, total arg count) from a lowered StableHLO
    module's ``@main`` signature.  jax establishes input/output aliases
    at lowering time (a donated arg whose aval matches an output gets a
    ``tf.aliasing_output`` attribute; an unusable donation gets none),
    so this reads the SAME decision the compiled module's
    ``input_output_alias`` header records — without paying the XLA
    compile."""
    start = mlir_text.find("@main(")
    if start < 0:
        return set(), -1
    i = start + len("@main(")
    depth = 1
    in_str = False
    args: List[str] = []
    buf: List[str] = []
    while i < len(mlir_text) and depth > 0:
        ch = mlir_text[i]
        if ch == '"':
            in_str = not in_str
        elif not in_str:
            if ch in "({[":
                depth += 1
            elif ch in ")}]":
                depth -= 1
                if depth == 0:
                    break
        if ch == "," and depth == 1 and not in_str:
            args.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    if "".join(buf).strip():
        args.append("".join(buf))
    donated = {k for k, a in enumerate(args) if "tf.aliasing_output" in a}
    return donated, len(args)


class NetTrainer:
    """Config-driven trainer (INetTrainer parity: SetParam/InitModel/
    SaveModel/LoadModel/StartRound/Update/Evaluate/Predict/ExtractFeature/
    CopyModelFrom/SetWeight/GetWeight)."""

    def __init__(self) -> None:
        self.cfg: List[Tuple[str, str]] = []
        self.batch_size = 0
        self.update_period = 1
        self.sample_counter = 0
        self.epoch_counter = 0
        self.round = 0
        self.seed = 0
        self.dev = "tpu"
        self.dtype = jnp.float32
        self.mesh_spec: Optional[meshlib.MeshSpec] = None
        self.fullc_gather = 0
        # pipeline parallelism (mesh = pipe:K): microbatches per step;
        # 0 = auto (2 * pipe size, the usual bubble/efficiency trade)
        self.pipe_microbatch = 0
        # gpipe (fill-drain, grads by autodiff) or 1f1b (interleaved
        # schedule, activation footprint flat in microbatch count)
        self.pipe_schedule = "gpipe"
        # batch_split = K: run K independent sub-batch chains inside the
        # step (summed losses) so the scheduler can interleave one
        # chain's compute into another's prefetch stalls
        self.batch_split = 1
        self._pipe_partition = None
        self._pipe_bucket_state = None
        # u8 input path: normalization constants applied ON DEVICE when a
        # batch arrives as uint8 (4x less host work + 2-4x less transfer;
        # the subtract/multiply fuses into conv1)
        self.input_scale = 1.0
        self.input_mean: Optional[np.ndarray] = None
        # input_s2d = 1: transform batches to space-to-depth layout ONCE
        # at staging (outside the jitted step) and run the first conv as
        # the dense stride-1 conv it becomes -- removes the small-cin/
        # large-stride MXU starvation from the step entirely (conv1
        # fwd+wgrad 7.0 ms vs 2.3 ceiling, BASELINE.md round-4 table)
        self.input_s2d = 0
        self._s2d_args = None
        self._s2d_fns = {}
        # remat = K: partition the graph body into K segments (at the same
        # single-activation cut points pipeline parallelism uses) and wrap
        # each in jax.checkpoint — backward recomputes segment activations
        # instead of storing them, trading ~1/3 more FLOPs for ~K-fold
        # less activation memory (bigger batches / longer models fit HBM)
        self.remat = 0
        self._remat_partition = None
        self.shard_opt_state = 0
        self.silent = 0
        self.print_step = 100
        # eval_train=0 skips per-step host materialization of eval nodes for
        # the train metric — the D2H copy is a per-step sync (expensive over
        # a tunneled link; reference copies scores out every Update,
        # nnet_impl-inl.hpp:174-180, because its D2H was on-node PCIe)
        self.eval_train = 1
        # evaluate(): batches scanned per device dispatch (1 = per-batch);
        # one jit call + one D2H per group (VERDICT r3 weak 7)
        self.eval_group = 8
        # telemetry (doc/monitor.md): monitor=1 adds per-layer norm
        # scalars to the traced step (the reference's updater monitor);
        # monitor_nan guards the loss against NaN/inf at monitor_interval
        # cadence; metrics_sink=jsonl:<path> streams structured records
        self.monitor = 0
        self.monitor_interval = 100
        self.monitor_nan = "warn"
        self.metrics = MetricsRegistry()
        self._last_monitor = None
        # metric bindings: (metric_name, label_field, node_name or "")
        self._metric_req: List[Tuple[str, str, str]] = []
        self.metric = MetricSet()
        self.train_metric = MetricSet()
        self.net: Optional[Network] = None
        self._train_step = None
        self._eval_step_cache: Dict[Tuple[int, ...], Any] = {}
        # header "extra" of the last load_model (iterator/sentinel state
        # for the task driver's exact resume); None on a fresh init
        self.loaded_extra: Optional[Dict] = None

    # ------------------------------------------------------------------ cfg
    def set_param(self, name: str, val: str) -> None:
        if name == "batch_size":
            self.batch_size = int(val)
        elif name == "update_period":
            self.update_period = int(val)
        elif name == "seed":
            self.seed = int(val)
        elif name == "dev":
            self.dev = val
        elif name == "dtype":
            self.dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                          "float16": jnp.float16}[val]
        elif name == "mesh":
            self.mesh_spec = meshlib.MeshSpec.parse(val)
        elif name == "fullc_gather":
            self.fullc_gather = int(val)
        elif name == "pipe_microbatch":
            self.pipe_microbatch = int(val)
        elif name == "pipe_schedule":
            assert val in ("gpipe", "1f1b"), \
                f"pipe_schedule = {val}: expected gpipe or 1f1b"
            self.pipe_schedule = val
        elif name == "batch_split":
            self.batch_split = int(val)
        elif name == "remat":
            self.remat = int(val)
        elif name == "scale":
            # device-side normalization for u8 batches (output_u8=1
            # iterators): the same global keys the host iterators consume
            self.input_scale = float(val)
        elif name == "mean_value":
            self.input_mean = np.array(
                [float(v) for v in val.split(",") if v.strip()], np.float32)
        elif name == "shard_opt_state" or name == "update_on_server":
            # update_on_server=1 (server-side optimizer states) maps to
            # ZeRO-style optimizer-state sharding over the data axis
            self.shard_opt_state = int(val)
        elif engine.is_engine_option(name):
            # lowering/gradient-semantics toggles (pool_bwd, fast_wgrad,
            # relu_vjp, ...): first-class config keys, see engine.py
            engine.set_engine_option(name, val)
        elif name == "silent":
            self.silent = int(val)
            mlog.set_silent(self.silent)
        elif name == "monitor":
            self.monitor = int(val)
        elif name == "monitor_interval":
            self.monitor_interval = int(val)
        elif name == "monitor_nan":
            assert val in ("warn", "fatal", "off"), (
                f"monitor_nan = {val}: expected warn, fatal, or off")
            self.monitor_nan = val
        elif name == "metrics_sink":
            self.metrics.configure_sink(val)
        elif name == "trace_sample":
            self.metrics.configure_tracer(int(val))
        elif name == "eval_train":
            self.eval_train = int(val)
        elif name == "eval_group":
            self.eval_group = int(val)
        elif name == "input_s2d":
            self.input_s2d = int(val)
        elif name == "print_step":
            self.print_step = int(val)
        elif name == "strict_config":
            # default off (behavior-preserving): layers report — rather
            # than silently drop — keys no subsystem declares
            from ..layers import base as layer_base
            layer_base.set_strict_config(bool(int(val)))
        elif name.startswith("metric"):
            # metric[label,node] = m | metric[label] = m | metric = m
            import re
            m = re.match(r"^metric\[([^,\]]+),([^\]]+)\]$", name)
            if m:
                self._metric_req.append((val, m.group(1), m.group(2)))
            else:
                m = re.match(r"^metric\[([^\]]+)\]$", name)
                if m:
                    self._metric_req.append((val, m.group(1), ""))
                else:
                    self._metric_req.append((val, "label", ""))
        self.cfg.append((name, val))

    # ----------------------------------------------------------------- init
    def init_model(self) -> None:
        mlog.set_silent(self.silent)  # this trainer owns the log level now
        netcfg = NetConfig()
        netcfg.configure(self.cfg)
        assert self.batch_size > 0, "batch_size must be set"
        self.netcfg = netcfg
        self._setup_mesh()
        self.net = Network(netcfg, self.batch_size, self.dtype)
        key = jax.random.PRNGKey(self.seed * 100 + 11)
        self.params = self.net.init_params(key)
        self.buffers = self.net.init_buffers()
        self._rng_base = jax.random.PRNGKey(self.seed)
        self._post_build()
        mlog.info(self.net.describe())

    def _setup_mesh(self) -> None:
        """Device selection + mesh build, shared by init_model and
        load_model (continue/finetune must come up on the same global mesh
        as a fresh start; the reference restarts its distributed launcher
        in every worker, cxxnet_main.cpp:135-157)."""
        # a CPU device range (dev = cpu:0-3, the mesh examples/tests) needs
        # the host platform to EMULATE that many devices; the flag must
        # land before the first backend touch — including process_count()
        # below — so this runs first (no-op once a backend initialized)
        spec = meshlib.parse_device_spec(self.dev)
        if spec["platform"] == "cpu":
            need = max(
                [self.mesh_spec.size if self.mesh_spec is not None else 1]
                + [i + 1 for i in (spec["ids"] or [])])
            if need > 1:
                meshlib.ensure_host_platform_devices(need)
        if jax.process_count() > 1:
            # multi-host: the mesh must span the global device set; local
            # id selection (dev = tpu:0-3) only makes sense single-host
            self.devices = meshlib.global_devices_for(
                meshlib.parse_device_spec(self.dev)["platform"])
        else:
            self.devices = meshlib.select_devices(self.dev)
        if self.mesh_spec is None and len(self.devices) > 1:
            self.mesh_spec = meshlib.MeshSpec({"data": len(self.devices)})
        self.mesh = meshlib.build_mesh(self.devices, self.mesh_spec)

    def _post_build(self) -> None:
        """Everything derivable from (net, params): updaters, hypers,
        shardings, step functions, metric bindings."""
        net = self.net
        self.updater = create_updater(self.netcfg.updater_type)
        # hyper groups: per (param_key, tag); global cfg then the layer's own
        # section (reference NeuralNet::InitUpdaters ordering)
        self.hypers: Dict[str, Dict[str, UpdaterHyper]] = {}
        key_to_layer_index = {}
        for i, conn in enumerate(net.connections):
            if conn.owns_params:
                key_to_layer_index[conn.param_key] = i
        for pkey, group in self.params.items():
            li = key_to_layer_index.get(pkey)

            def make_hypers(g):
                out = {}
                for tag, p in g.items():
                    if isinstance(p, dict):  # nested group (pairtest sides)
                        out[tag] = make_hypers(p)
                        continue
                    h = UpdaterHyper(tag=tag)
                    for k, v in self.netcfg.defcfg:
                        h.set_param(k, v)
                    if li is not None:
                        for k, v in self.netcfg.layercfg[li]:
                            h.set_param(k, v)
                    out[tag] = h
                return out
            self.hypers[pkey] = make_hypers(group)
        self.opt_state = _map_group(
            self.params, lambda tag, p: self.updater.make_state(p))
        # eval-node requests (metric[label,node]); "" -> final node
        self.eval_node_ids = []
        for (_, _, node) in self._metric_req:
            self.eval_node_ids.append(
                net.node_id(node) if node else net.final_node)
        self.metric = MetricSet()
        self.train_metric = MetricSet()
        for (mname, field, _) in self._metric_req:
            self.metric.add_metric(mname, field)
            self.train_metric.add_metric(mname, field)
        self.loss_scale = 1.0 / (self.batch_size * self.update_period)
        self._label_fields = self.netcfg.label_fields()
        self._make_shardings()
        self._setup_input_s2d()
        self._reorder_relu_pool()
        self._fuse_sibling_convs()
        # audit snapshot of the process-global engine options this trainer
        # compiles against (engine.opts is shared; see engine.py) — taken
        # at FIRST TRACE, not here: jit traces lazily, so options changed
        # between init_model and the first step would make an init-time
        # snapshot misreport exactly the cross-trainer contamination it
        # exists to catch
        self.engine_opts_used = None
        # dp_overlap (parallel/overlap.py): bucket plan built lazily
        # (after the relu->pool reorder sets deferred-bias flags);
        # _overlap_defer selects the two-variant accumulate/apply steps
        # when update_period grad accumulation should reduce once per
        # APPLY instead of per micro-step (dp_reduce_at = apply)
        self._dp_plan_state = None
        self._dp_warned: set = set()
        self._overlap_step_cache: Dict[Tuple[bool, bool], Any] = {}
        defer_wanted = (
            self.update_period > 1 and not self.monitor
            and self.netcfg.extra_data_num == 0
            and engine.opts.dp_reduce_at == "apply"
            and self._dp_overlap_active())
        # the deferred local accumulator carries a leading device axis
        # sharded over "data" with FULL param shapes — pure-DP only;
        # model meshes reduce every micro-step (dp_reduce_at = step
        # semantics, which is also the bitwise-parity mode)
        self._overlap_defer = defer_wanted and not self._dp_model_axis()
        if defer_wanted and not self._overlap_defer \
                and "defer_model" not in self._dp_warned:
            self._dp_warned.add("defer_model")
            mlog.warn("dp_reduce_at = apply is pure-DP; the model mesh "
                      "axis reduces every micro-step instead "
                      "(dp_reduce_at = step semantics)")
        self._train_step = self._build_train_step()
        self._multi_step_cache: Dict[int, Any] = {}
        self._eval_step_cache = {}
        self._eval_many_cache = {}
        self._grad_acc = None
        self.sample_counter = 0
        self.epoch_counter = 0
        # run header for the JSONL sink: one record binding the stream to
        # the config it measures (engine opts at configure time; the
        # trace-time audit stays in engine_opts_used)
        self.metrics.emit(
            "run", updater=self.netcfg.updater_type,
            batch_size=self.batch_size, dtype=str(jnp.dtype(self.dtype)),
            mesh=dict(self.mesh.shape), monitor=self.monitor,
            monitor_interval=self.monitor_interval,
            monitor_nan=self.monitor_nan, engine_opts=engine.snapshot())

    def _make_shardings(self) -> None:
        mesh = self.mesh
        self.batch_shard = meshlib.batch_sharding(mesh)
        self.repl = meshlib.replicated(mesh)
        from ..layers.fullc import FullConnectLayer
        from ..layers.moe import MoELayer, expert_host_axis
        moe_keys = {conn.param_key for conn in self.net.connections
                    if isinstance(conn.layer, MoELayer)}
        # the axis hosting the per-expert dimension ("expert", else
        # "model"): the SAME helper the runtime constraints consult, so
        # rest placement and with_sharding_constraint can never diverge
        expert_axis = expert_host_axis(mesh)

        def param_spec(pkey: str, tag: str, shape) -> NamedSharding:
            # sharding policy lives next to the layer math it shards
            # (fullc.model_shard_spec / moe.shard_spec); the trainer only
            # picks the axis and gates the tensor-parallel mode
            if self.fullc_gather and "model" in mesh.axis_names \
                    and pkey not in moe_keys:
                sp = FullConnectLayer.model_shard_spec(
                    tag, shape, mesh.shape["model"])
                if sp is not None:
                    return NamedSharding(mesh, sp)
            if pkey in moe_keys and expert_axis is not None:
                # expert-parallel AT REST too: each device keeps only its
                # experts' weights (and, via opt_shardings following
                # param leading dims below, their optimizer state) —
                # the memory benefit of EP, not just the compute
                sp = MoELayer.shard_spec(tag, shape, expert_axis,
                                         mesh.shape[expert_axis])
                if sp is not None:
                    return NamedSharding(mesh, sp)
            return self.repl

        self.param_shardings = {
            pkey: _map_group({"": group},
                             lambda tag, p: param_spec(pkey, tag, p.shape))[""]
            for pkey, group in self.params.items()}
        # optimizer state inherits its parameter's sharding (same-shaped
        # leaves: momentum, adam moments, f32 masters) — expert-sharded
        # MoE weights keep their state expert-sharded too
        def opt_group(pgroup, sgroup, shgroup):
            out = {}
            for tag, p in pgroup.items():
                if isinstance(p, dict):
                    out[tag] = opt_group(p, sgroup[tag], shgroup[tag])
                else:
                    out[tag] = {k: shgroup[tag]
                                if getattr(v, "shape", None) == p.shape
                                else self.repl
                                for k, v in sgroup[tag].items()}
            return out
        self.opt_shardings = {
            pkey: opt_group(group, self.opt_state[pkey],
                            self.param_shardings[pkey])
            for pkey, group in self.params.items()}
        # leaves whose gradient the dp-overlap step may REDUCE-SCATTER
        # instead of all-reduce (parallel/overlap.py): exactly the leaves
        # whose optimizer state gets ZeRO-sharded below — the update math
        # then consumes the grad shard it owns, never the full tensor
        self.dp_zero_grads = jax.tree.map(lambda _: False, self.params)
        if self.shard_opt_state and "data" in mesh.axis_names:
            ndata = mesh.shape["data"]

            def opt_spec(p, cur):
                # ZeRO over 'data' for big leaves still replicated after
                # the inherit pass; an already-sharded leaf keeps its axis
                if (cur is self.repl and p.ndim >= 1
                        and p.shape[0] % ndata == 0 and p.size >= 2 ** 14):
                    return NamedSharding(mesh, P("data"))
                return cur
            self.opt_shardings = jax.tree.map(
                opt_spec, self.opt_state, self.opt_shardings)

            def zero_pred(p, sh):
                return bool(sh is self.repl and p.ndim >= 1
                            and p.shape[0] % ndata == 0
                            and p.size >= 2 ** 14)
            self.dp_zero_grads = jax.tree.map(
                zero_pred, self.params, self.param_shardings)
        # leaves sharded over the "model" axis on their LEADING dim: the
        # dp-overlap step all-gathers exactly these at their segment's
        # forward entry and takes their gradients back as shards
        # (parallel/overlap.py model-axis composition)
        self.dp_model_sharded = jax.tree.map(
            lambda p, s: bool(len(s.spec) > 0 and s.spec[0] == "model"),
            self.params, self.param_shardings)
        self.buffer_shardings = jax.tree.map(lambda _: self.repl, self.buffers)
        # place initial state
        self.params = jax.device_put(self.params, self.param_shardings)
        self.opt_state = jax.device_put(self.opt_state, self.opt_shardings)
        self.buffers = jax.device_put(self.buffers, self.buffer_shardings)

    # ----------------------------------------------------------- step build
    def _reorder_relu_pool(self):
        """Peephole: relu feeding a max pool moves AFTER the pool
        (max(relu(x)) == relu(max(x)); gradients agree a.e. — differing
        argmax ties all get zero gradient through the relu mask).  The
        relu backward then runs on the stride^2-smaller pooled tensor
        and the pre-relu activation never needs a second full-size HBM
        pass.  Handles both node forms (``relu`` on a fresh node and the
        zoo builders' ``layer[+0] = relu`` self-loop — the node then
        holds the pre-activation between relu and pool, recorded in
        ``_read_fixups`` for call-time node reads).  Skipped when any
        later connection other than the pool reads the relu's node, the
        node is a train-metric eval node, or the layer instance is
        shared."""
        from ..layers.activation import ReluLayer
        from ..layers.conv import ConvolutionLayer, MaxPoolingLayer
        from ..ops.nn import use_fast_wgrad
        # node id -> ("relu"|"bias", bias_param_key or None): corrections
        # extract_feature must apply when reading a node whose stored value
        # is changed by the reorder (the relu node holds the pre-activation;
        # a defer_bias conv node holds bias-less output)
        self._read_fixups: Dict[int, tuple] = {}
        if engine.opts.pool_relu_reorder != "1":
            return
        conns = self.net.connections
        layer_uses: Dict[int, int] = {}
        for c in conns:
            layer_uses[id(c.layer)] = layer_uses.get(id(c.layer), 0) + 1

        def last_writer(node, before):
            for j in range(before - 1, -1, -1):
                if node in conns[j].nindex_out:
                    return j
            return None

        def readers_after(node, start):
            """Connection indices reading ``node`` after position ``start``
            (execution order matters: self-loop relus overwrite their node,
            so earlier readers see a different value and don't count)."""
            return [j for j in range(start + 1, len(conns))
                    if node in conns[j].nindex_in]

        for i, c in enumerate(conns):
            if type(c.layer) is not MaxPoolingLayer:
                continue
            if layer_uses[id(c.layer)] > 1:
                # shared layer instance (share[tag] / siamese towers):
                # flag mutation would leak past this connection's guards
                continue
            v = c.nindex_in[0]
            j = last_writer(v, i)
            if j is None or type(conns[j].layer) is not ReluLayer:
                continue
            relu = conns[j]
            if layer_uses[id(relu.layer)] > 1:
                continue
            if v in self.eval_node_ids:
                continue
            # the relu's (post-activation) value may feed nothing but this
            # pool — after deferral the node holds the pre-activation
            if readers_after(v, j) != [i]:
                continue
            self_loop = relu.nindex_in == relu.nindex_out
            if self_loop:
                # zoo-style ``layer[+0] = relu``: node v holds the
                # pre-activation between the relu and the pool; the conv
                # beneath is v's previous writer
                k = last_writer(v, j)
            else:
                k = last_writer(relu.nindex_in[0], j)
            relu.layer.defer_to_pool = True
            c.layer.relu_after = True
            self._read_fixups[v] = ("relu", None)
            # the conv bias also commutes with max (per-channel constant:
            # max(z + b) == max(z) + b), so when the relu's producer is a
            # biased conv whose output feeds only the (deferred) relu,
            # the bias add AND its gradient reduce move to the pooled
            # tensor too — on AlexNet b1024 the conv1/conv2 bias-grad
            # reduces read 634/572 MB SAS outputs (0.79 + 0.51 ms) that
            # shrink by stride^2
            if k is None:
                continue
            cprod = conns[k]
            cnode = cprod.nindex_out[0]
            conv_readers = readers_after(cnode, k)
            want = [j, i] if self_loop else [j]
            if (type(cprod.layer) is ConvolutionLayer
                    and not cprod.layer.param.no_bias
                    and layer_uses[id(cprod.layer)] == 1
                    and conv_readers == want
                    and cnode not in self.eval_node_ids
                    and cprod.nindex_in != cprod.nindex_out
                    and (cprod.layer.s2d_input
                         or not use_fast_wgrad(
                             self.net.node_shapes[cprod.nindex_in[0]][1],
                             cprod.layer.param.stride,
                             cprod.layer.param.num_group))):
                cprod.layer.defer_bias = 1
                c.layer.deferred_bias_key = cprod.param_key
                self._read_fixups[cnode] = ("bias", cprod.param_key)
                self._read_fixups[v] = ("relu", cprod.param_key)

    def _fuse_sibling_convs(self):
        """Peephole (``conv_sibling_fuse = 1``): convolutions that read
        the SAME node with the SAME geometry (kernel/stride/pad, ungrouped)
        execute as one fused conv whose weights concatenate along the
        output-channel dim, with per-member channel slices writing the
        original output nodes (net._forward_fused).  Inception modules run
        three 1x1 reduce convs per module on the same input — 27 small
        lane-underfilled MXU calls + 27 weight/optimizer prefetches across
        GoogLeNet become 9 well-tiled ones; dgrad of the shared input is
        one conv instead of a sum of three.  Parameters stay per-layer
        (autodiff slices the fused wgrad), so the updater, sharding,
        checkpoints, and get/set_weight are untouched."""
        self.net.fuse_groups = {}
        self.net.fuse_skip = frozenset()
        if engine.opts.conv_sibling_fuse != "1":
            return
        from ..layers.conv import ConvolutionLayer
        conns = self.net.connections
        layer_uses: Dict[int, int] = {}
        for c in conns:
            layer_uses[id(c.layer)] = layer_uses.get(id(c.layer), 0) + 1

        def eligible(c):
            return (type(c.layer) is ConvolutionLayer
                    and layer_uses[id(c.layer)] == 1
                    and len(c.nindex_in) == 1 and len(c.nindex_out) == 1
                    and c.nindex_in != c.nindex_out
                    and c.layer.param.num_group == 1
                    and not c.layer.space_to_depth
                    and not c.layer.s2d_input
                    and not c.layer.defer_bias)

        def writers_before(node, before):
            return tuple(j for j in range(before)
                         if node in conns[j].nindex_out)

        from ..layers.shape_ops import SplitLayer

        def value_id(v, before):
            """Hashable identity of node ``v``'s VALUE at position
            ``before`` — split outputs alias their input (the layer just
            replicates), so convs reading different split branches of the
            same tensor still group together."""
            w = writers_before(v, before)
            if not w:
                return ("in", v)
            j = w[-1]
            if type(conns[j].layer) is SplitLayer \
                    and len(conns[j].nindex_in) == 1:
                return value_id(conns[j].nindex_in[0], j)
            return ("conn", j)

        groups: Dict[tuple, List[int]] = {}
        for i, c in enumerate(conns):
            if not eligible(c):
                continue
            if writers_before(c.nindex_out[0], i):
                # fused members execute at the group head's position; a
                # member that REBINDS an already-written node would
                # clobber it before intervening readers ran
                continue
            p = c.layer.param
            key = (value_id(c.nindex_in[0], i), p.kernel_height,
                   p.kernel_width, p.stride, p.pad_y, p.pad_x, p.no_bias)
            groups.setdefault(key, []).append(i)
        fuse, skip = {}, set()
        for members in groups.values():
            if len(members) < 2:
                continue
            fuse[members[0]] = members
            skip.update(members[1:])
        self.net.fuse_groups = fuse
        self.net.fuse_skip = frozenset(skip)
        if fuse:
            mlog.info(f"conv_sibling_fuse: {len(fuse)} groups "
                      f"({sum(len(m) for m in fuse.values())} convs)")

    def _setup_input_s2d(self):
        """Wire ``input_s2d = 1``: flag the first conv to consume
        space-to-depth input and record the staging-transform geometry."""
        self._s2d_args = None
        self._s2d_fns = {}
        if not self.input_s2d:
            return
        from ..layers.conv import ConvolutionLayer
        consumers = [c for c in self.net.connections if 0 in c.nindex_in]
        assert len(consumers) == 1, \
            "input_s2d: the data node must feed exactly one layer"
        l = consumers[0].layer
        p = getattr(l, "param", None)
        assert (isinstance(l, ConvolutionLayer) and p.stride > 1
                and p.num_group == 1 and not l.space_to_depth), (
            "input_s2d: the first layer must be an ungrouped strided conv")
        _, c, h, w = self.net.node_shapes[0]
        from ..ops import nn as N_ops
        oh = N_ops.conv_out_size(h, p.kernel_height, p.stride, p.pad_y)
        ow = N_ops.conv_out_size(w, p.kernel_width, p.stride, p.pad_x)
        l.s2d_input = 1
        self._s2d_args = (p.stride, p.kernel_height, p.kernel_width,
                          oh, ow, p.pad_y, p.pad_x)

    def _s2d_transform(self, data, stacked=False):
        """Space-to-depth the staged batch on device, once, outside the
        step.  u8 batches are normalized first (conv padding must pad the
        NORMALIZED zeros, as the in-step path does), so the step sees
        ready-to-convolve f32 data either way.

        When the input pipeline already delivers s2d-shaped batches (the
        host iterators under ``input_s2d = 1``, or bench data generated
        in s2d shape), this is a no-op: the device-side transform is a
        fallback, and a measured-slow one (a (b,3,227,227) bf16
        relayout-transpose runs ~5x off the HBM floor, 4.0 ms/step on
        the b1024 stack — device trace, round 4)."""
        if self._s2d_args is None:
            return data
        cdim = data.shape[2] if stacked else data.shape[1]
        s, _, _, _, _, py, px = self._s2d_args
        _, c_in, _, _ = self.net.node_shapes[0]
        if cdim == c_in * s * s:
            # input pipeline already delivered s2d
            assert not (data.dtype == jnp.uint8 and (py or px)), (
                "input_s2d: pre-s2d u8 delivery is unsupported for a "
                "padded first conv — u8 can only encode padding as raw "
                "0, which normalizes to (0-mean)*scale instead of the "
                "zeros the reference path pads with; deliver plain u8 "
                "batches (the staging transform normalizes before "
                "padding) or pre-normalized f32")
            return data
        key = (stacked, str(data.dtype), data.shape)
        if key not in self._s2d_fns:
            from ..ops import nn as N_ops
            s, kh, kw, oh, ow, py, px = self._s2d_args

            def f(x):
                x = self._normalize_input(x)
                xb, _, _ = N_ops.s2d_input(x, s, kh, kw, oh, ow, py, px)
                return xb
            self._s2d_fns[key] = jax.jit(jax.vmap(f) if stacked else f)
        return self._s2d_fns[key](data)

    def _normalize_input(self, data):
        """Device-side normalization of raw u8 batches (output_u8=1):
        (x - mean_value[c]) * scale, matching the host iterators' SetData
        rule; fuses into the first conv's input read."""
        if data.dtype != jnp.uint8:
            return data
        x = data.astype(jnp.float32)
        if self.input_mean is not None:
            mean = jnp.asarray(self.input_mean)
            if self._s2d_args is not None \
                    and x.shape[-3] == mean.size * self._s2d_args[0] ** 2:
                # u8 batch delivered pre-s2d by the input pipeline: the
                # per-channel mean expands over the (c, sy, sx) order
                mean = jnp.repeat(mean, self._s2d_args[0] ** 2)
            x = x - mean.reshape(1, -1, 1, 1)
        if self.input_scale != 1.0:
            x = x * self.input_scale
        return x

    def _forward(self, params, buffers, data, label_vec, extras, *, train,
                 rng, epoch, mask=None):
        data = self._normalize_input(data)
        fields = {name: label_vec[:, a:b]
                  for name, a, b in self._label_fields} if label_vec is not None else {}
        ctx = ForwardContext(train=train, rng=rng,
                             labels=LabelInfo(fields=fields, mask=mask)
                             if fields else None,
                             epoch=epoch, loss_scale=self.loss_scale,
                             mesh=self.mesh if self.mesh.size > 1 else None)
        inputs = {0: data}
        for i, e in enumerate(extras):
            inputs[1 + i] = e
        nodes, new_buffers = self.net.forward(params, buffers, inputs, ctx)
        return nodes, new_buffers, ctx

    @property
    def _pipelined(self) -> bool:
        return "pipe" in self.mesh.axis_names and self.mesh.shape["pipe"] > 1

    def _pipe_setup(self):
        """Partition the graph once per trainer (static)."""
        if self._pipe_partition is None:
            from . import pipeline_net
            n_stage = self.mesh.shape["pipe"]
            stages, body_end = pipeline_net.partition_network(
                self.net, n_stage)
            if not mlog.is_silent():
                desc = ", ".join(
                    "+".join(self.net.connections[j].layer.type_names[0]
                             for j in range(s0, s1))
                    for s0, s1 in stages)
                mlog.info(f"pipeline: {n_stage} stages [{desc}]")
            self._pipe_partition = (stages, body_end)
        return self._pipe_partition

    def _pipe_microbatches(self, data, label_vec, mask):
        """Shared microbatch prep for the GPipe and 1F1B paths: returns
        ``(x, extra, b)`` — (n_micro, mb, ...) microbatches, the
        per-microbatch label-fields/mask pytree, and the batch size."""
        data = self._normalize_input(data)
        b = data.shape[0]
        n_micro = self.pipe_microbatch or 2 * self.mesh.shape["pipe"]
        assert b % n_micro == 0, (
            f"pipeline: batch {b} not divisible by pipe_microbatch "
            f"{n_micro}")
        x = data.astype(self.dtype).reshape(n_micro, b // n_micro,
                                            *data.shape[1:])
        mb = b // n_micro
        extra = {
            "fields": {name: label_vec[:, a:b_].reshape(n_micro, mb, -1)
                       for name, a, b_ in self._label_fields}
            if label_vec is not None else {},
            "mask": None if mask is None else mask.reshape(n_micro, mb),
        }
        return x, extra, b

    def _pipeline_forward(self, params, data, label_vec, *, train, rng,
                          epoch, mask=None):
        """Forward through the pipelined body + the post-pipeline loss
        tail.  Returns (node env over tail nodes, ctx)."""
        from ..parallel.pipeline import pipeline_apply_hetero
        from . import pipeline_net
        stages, body_end = self._pipe_setup()
        stage_fns = pipeline_net.make_stage_fns(
            self.net, stages, body_end, train=train, epoch=epoch,
            loss_scale=self.loss_scale, rng=rng)
        x, extra, b = self._pipe_microbatches(data, label_vec, mask)
        outs, aux_losses = pipeline_apply_hetero(
            stage_fns, params, x, mesh=self.mesh,
            data_spec=self.batch_shard.spec, extra=extra)
        nodes = {n: o.reshape(b, *o.shape[2:])
                 for n, o in zip(
                     pipeline_net.frontier_nodes(self.net, body_end), outs)}
        # loss tail (self-loop loss layers) outside the pipeline; mid-body
        # loss terms (MoE load balance, aux heads) arrive threaded through
        # the stages
        return self._run_loss_tail(params, nodes, body_end, label_vec,
                                   rng, epoch, mask, train=train,
                                   body_loss=aux_losses.sum())

    @property
    def pipe_bubble_frac(self) -> float:
        """Analytic pipeline-bubble share of the step, ``(S-1)/(M+S-1)``
        (S stages, M micro-batches): the fraction of schedule ticks a
        stage idles during fill/drain.  0.0 on un-pipelined meshes.
        Stamped on step/round records so the goodput ledger can carve
        ``pipe_bubble`` out of dispatch (monitor/ledger.py)."""
        if not self._pipelined:
            return 0.0
        s = self.mesh.shape["pipe"]
        m = self.pipe_microbatch or 2 * s
        return (s - 1) / (m + s - 1)

    def _pipe_bucket_plan(self):
        """Bucket plan for the dp_overlap x pipe composition, or None
        (implicit whole-tree reduction).  Each pipeline stage's param
        keys — plus the loss tail's, riding the last stage — become
        ``dp_bucket_mb``-bounded buckets tagged with the stage whose
        cooldown tick makes them grad-ready.  A key read by several
        stages is assigned the LOWEST stage index (lower stages complete
        later, so every contribution is final when the bucket fires)."""
        if engine.opts.dp_overlap != "1" \
                or self.pipe_schedule != "1f1b" \
                or "data" not in self.mesh.axis_names \
                or self.mesh.shape["data"] < 2:
            return None
        if self._pipe_bucket_state is None:
            from ..parallel import overlap
            stages, body_end = self._pipe_setup()
            n_stage = len(stages)
            owner = {}  # key -> lowest stage index reading it
            for s, (s0, s1) in enumerate(stages):
                for key in overlap._keys_read(self.net, s0, s1,
                                              self.params):
                    owner.setdefault(key, s)
            for key in overlap._keys_read(
                    self.net, body_end, len(self.net.connections),
                    self.params):
                owner.setdefault(key, n_stage - 1)
            bucket_bytes = max(
                float(engine.opts.dp_bucket_mb) * 2 ** 20, 1.0)
            buckets = []
            for s in range(n_stage):
                # reverse layer order within the stage (backward reaches
                # the last connection's grads first — the async_updater
                # fill order), chunked to the wire-size target
                keys = [k for k in reversed(list(owner))
                        if owner[k] == s]
                cur, acc = [], 0.0
                for key in keys:
                    cur.append(key)
                    acc += overlap._group_bytes(self.params[key])
                    if acc >= bucket_bytes:
                        buckets.append((tuple(cur), s))
                        cur, acc = [], 0.0
                if cur:
                    buckets.append((tuple(cur), s))
            self._pipe_bucket_state = (tuple(buckets),)
            if not mlog.is_silent():
                mlog.info(
                    "pipe dp_overlap: %d bucket(s) over %d stages "
                    "(KiB: %s), reduce_dtype=%s — (pipe, data) psums "
                    "issue at cooldown grad-ready ticks" % (
                        len(buckets), n_stage,
                        ",".join(str(sum(overlap._group_bytes(
                            self.params[k]) for k in ks) // 1024)
                            for ks, _ in buckets),
                        engine.opts.dp_reduce_dtype))
        return self._pipe_bucket_state[0]

    def _pipeline_1f1b_loss_and_grads(self, params, buffers, data,
                                      label_vec, epoch, rng, eval_ids,
                                      mask):
        """``pipe_schedule = 1f1b``: loss AND gradients come out of the
        interleaved schedule directly — ``jax.grad`` of the GPipe forward
        stores residuals for every tick, while 1F1B bounds live
        activations at ``2S-1`` microbatches regardless of microbatch
        count (see :func:`parallel.pipeline.pipeline_1f1b_hetero`)."""
        from ..parallel.pipeline import pipeline_1f1b_hetero
        from . import pipeline_net
        from .net import conn_params
        stages, body_end = self._pipe_setup()
        stage_fns = pipeline_net.make_stage_fns(
            self.net, stages, body_end, train=True, epoch=epoch,
            loss_scale=self.loss_scale, rng=rng)
        x, extra, b = self._pipe_microbatches(data, label_vec, mask)
        frontier = pipeline_net.frontier_nodes(self.net, body_end)

        def tail_loss(p, boundary, extra_m, m):
            """Per-microbatch training loss: trailing loss connections on
            the last boundary + the aux terms threaded through the body
            (additive, so their cotangent seeds at 1 automatically)."""
            acts, aux = boundary
            nodes = dict(zip(frontier, acts))
            fields, mb_mask = extra_m["fields"], extra_m["mask"]
            ctx = ForwardContext(
                train=True, rng=rng,
                labels=LabelInfo(fields=fields, mask=mb_mask)
                if fields or mb_mask is not None else None,
                epoch=epoch, loss_scale=self.loss_scale, mesh=None)
            for conn in self.net.connections[body_end:]:
                ins = [nodes[n] for n in conn.nindex_in]
                pp = conn_params(p, conn)
                outs_, _ = conn.layer.forward(pp, {}, ins, ctx)
                for n, v in zip(conn.nindex_out, outs_):
                    nodes[n] = v
            total = aux
            for l in ctx.losses:
                total = total + l
            return total

        from ..parallel.overlap import REDUCE_DTYPES
        buckets = self._pipe_bucket_plan()
        _, grads, outs, auxs = pipeline_1f1b_hetero(
            stage_fns, tail_loss, params, x, mesh=self.mesh,
            data_spec=self.batch_shard.spec, extra=extra,
            buckets=None if buckets is None else list(buckets),
            reduce_dtype=None if buckets is None
            else REDUCE_DTYPES[engine.opts.dp_reduce_dtype])
        # train-metric eval nodes + the REPORTED loss: forward the loss
        # tail once on the collected last-boundary activations (no grad —
        # the 1F1B scan already produced the gradients).  Using this
        # full-batch tail total, rather than the schedule's ascending
        # per-microbatch sum, makes the reported loss the SAME reduction
        # the gpipe path computes — bitwise comparable
        nodes = {n: o.reshape(b, *o.shape[2:])
                 for n, o in zip(frontier, outs)}
        nodes, ctx = self._run_loss_tail(params, nodes, body_end,
                                         label_vec, rng, epoch, mask,
                                         train=True, body_loss=auxs.sum())
        loss = sum(ctx.losses[1:], ctx.losses[0])
        for nid in eval_ids:
            assert nid in nodes, (
                "pipeline: train-metric eval nodes must sit at or "
                "after the last stage boundary")
        outs_eval = {nid: as_mat(nodes[nid]).astype(jnp.float32)
                     for nid in eval_ids}
        grads = jax.tree.map(lambda p, g: g.astype(p.dtype), params, grads)
        return (loss, (buffers, outs_eval, ctx.diagnostics)), grads

    def _run_loss_tail(self, params, nodes, body_end, label_vec, rng,
                       epoch, mask, *, train, body_loss=None):
        """Run the trailing loss connections on the body-boundary node
        env; shared by the remat and pipeline paths.  ``body_loss``
        carries loss terms contributed inside the partitioned body.
        Returns (tail node env, ctx)."""
        fields = {name: label_vec[:, a:b_]
                  for name, a, b_ in self._label_fields} \
            if label_vec is not None else {}
        ctx = ForwardContext(train=train, rng=rng,
                             labels=LabelInfo(fields=fields, mask=mask)
                             if fields else None,
                             epoch=epoch, loss_scale=self.loss_scale,
                             mesh=self.mesh if self.mesh.size > 1 else None)
        nodes = dict(nodes)
        from .net import conn_params
        from ..layers.base import conn_scope_name
        for j, conn in enumerate(self.net.connections[body_end:],
                                 start=body_end):
            with jax.named_scope(conn_scope_name(j, conn)):
                ins = [nodes[n] for n in conn.nindex_in]
                p = conn_params(params, conn)
                outs, _ = conn.layer.forward(p, {}, ins, ctx)
                for n, v in zip(conn.nindex_out, outs):
                    nodes[n] = v
        if body_loss is not None:
            # unconditional: a net whose loss layers are ALL mid-body has
            # an empty tail, and its entire training loss is the threaded
            # term
            ctx.losses.append(body_loss)
        return nodes, ctx

    def _remat_forward(self, params, data, label_vec, *, rng, epoch,
                       mask=None):
        """Forward with jax.checkpoint around each graph segment; the loss
        tail runs outside (losses/diagnostics must not escape a rematted
        region).  Returns (tail node env, ctx)."""
        from . import pipeline_net
        if self._remat_partition is None:
            self._remat_partition = pipeline_net.partition_network(
                self.net, self.remat)
        stages, body_end = self._remat_partition
        stage_fns = pipeline_net.make_stage_fns(
            self.net, stages, body_end, train=True, epoch=epoch,
            loss_scale=self.loss_scale, rng=rng,
            mesh=self.mesh if self.mesh.size > 1 else None)
        extra = {
            "fields": {name: label_vec[:, a:b_]
                       for name, a, b_ in self._label_fields}
            if label_vec is not None else {},
            "mask": mask,
        }
        val = (self._normalize_input(data).astype(self.dtype),
               jnp.float32(0.0), extra)
        for fn in stage_fns:
            val = jax.checkpoint(fn)(params, val, 0)
        acts, body_loss = val[0], val[1]
        nodes = dict(zip(
            pipeline_net.frontier_nodes(self.net, body_end), acts))
        return self._run_loss_tail(params, nodes, body_end, label_vec, rng,
                                   epoch, mask, train=True,
                                   body_loss=body_loss)

    # ----------------------------------------------- dp overlap (explicit)
    def _dp_model_axis(self) -> bool:
        """True when the mesh carries a model axis wider than 1 (the
        overlap schedule then composes weight-shard all-gathers with the
        bucketed data reductions — parallel/overlap.py)."""
        return "model" in self.mesh.axis_names \
            and self.mesh.shape["model"] > 1

    def _dp_warn_once(self, reason: str) -> None:
        if reason not in self._dp_warned:
            self._dp_warned.add(reason)
            mlog.warn(f"dp_overlap = 1 ignored: {reason}; using the "
                      "implicit-psum step")

    def _dp_overlap_plan(self):
        """Lazily-built bucket plan (parallel/overlap.plan_buckets);
        ``None`` when eval nodes sit before the loss-tail frontier."""
        if self._dp_plan_state is None:
            from ..parallel import overlap
            plan = overlap.plan_buckets(
                self.net, self.params, float(engine.opts.dp_bucket_mb),
                tuple(dict.fromkeys(self.eval_node_ids)))
            self._dp_plan_state = (plan,)
            if plan is not None:
                sizes = [sum(overlap._group_bytes(self.params[k])
                             for k in ks) for ks in plan.stage_keys]
                n_gather = sum(bool(l) for l in jax.tree.leaves(
                    self.dp_model_sharded))
                mlog.info(
                    "dp_overlap: %d buckets (KiB per bucket: %s), "
                    "reduce_dtype=%s, reduce_at=%s%s" % (
                        len(plan.stages),
                        ",".join(str(s // 1024) for s in sizes),
                        engine.opts.dp_reduce_dtype,
                        engine.opts.dp_reduce_at,
                        f", model-axis gathers={n_gather} leaves"
                        if self._dp_model_axis() and n_gather else ""))
        return self._dp_plan_state[0]

    def _dp_overlap_active(self) -> bool:
        """True when the explicit bucketed-reduction step should replace
        the implicit jax.grad psum.  Evaluated at trace time (like every
        engine option); each unsupported combination falls back to the
        implicit step with a one-shot warning."""
        if engine.opts.dp_overlap != "1":
            return False
        mesh = self.mesh
        if "data" not in mesh.axis_names or mesh.shape["data"] < 2:
            self._dp_warn_once("mesh has no data axis wider than 1")
            return False
        if self._pipelined:
            # pipe_schedule = 1f1b composes instead of falling back: the
            # pipelined step issues its own bucketed (pipe, data)
            # reductions at each stage's cooldown grad-ready tick
            # (_pipe_bucket_plan); only the gpipe fill-drain — whose
            # backward is autodiff-scheduled — still takes the implicit
            # step
            if self.pipe_schedule != "1f1b":
                self._dp_warn_once(
                    "the gpipe pipeline schedule's backward is autodiff-"
                    "scheduled (pipe_schedule = 1f1b composes)")
            return False
        # a "model" axis composes (weight shards gather at segment entry,
        # parallel/overlap.py); seq/expert collectives are placed by
        # GSPMD/shard_map machinery the sliced-vjp walk can't host
        extra_axes = [a for a in mesh.axis_names
                      if a not in ("data", "model") and mesh.shape[a] > 1]
        if extra_axes:
            self._dp_warn_once(
                f"mesh axes {'/'.join(extra_axes)} need GSPMD-placed "
                "collectives (ring attention / expert all-to-all)")
            return False
        if self._dp_model_axis():
            from ..layers.moe import MoELayer
            if any(isinstance(c.layer, MoELayer)
                   for c in self.net.connections):
                # the model axis HOSTS the experts (moe.expert_host_axis):
                # the implicit step runs expert-parallel dense dispatch
                # with GSPMD all-to-alls, which the sliced-vjp walk can't
                # place — and the explicit step's mesh-less forward would
                # silently resolve moe_dispatch=auto to the sorted path
                # (differently-associated backward, no bitwise parity)
                self._dp_warn_once(
                    "the model axis hosts MoE experts; dispatch/combine "
                    "all-to-alls are GSPMD-placed")
                return False
        if self.remat or self.batch_split > 1:
            self._dp_warn_once("remat/batch_split paths schedule "
                               "their own backward")
            return False
        if self.buffers:
            self._dp_warn_once("stateful layers (running buffers, e.g. "
                               "batch_norm) don't thread through the "
                               "sliced vjp")
            return False
        if self.has_diagnostics:
            self._dp_warn_once("pairtest diagnostics need the implicit "
                               "forward")
            return False
        if engine.opts.conv_sibling_fuse == "1" \
                or engine.opts.concat_virtual == "1":
            self._dp_warn_once("conv_sibling_fuse/concat_virtual rewrite "
                               "the forward graph")
            return False
        if self._dp_overlap_plan() is None:
            self._dp_warn_once("a train-metric eval node sits before the "
                               "loss-tail frontier")
            return False
        return True

    def _build_overlap_steps(self, with_mask: bool):
        """The ``dp_reduce_at = apply`` two-variant steps: micro-steps
        accumulate LOCAL per-device gradient sums (no collectives), the
        apply step folds the accumulator into the last backward and
        reduces each bucket ONCE — 1/update_period the communication of
        the implicit path (the async_updater never pushed partial-period
        gradients either; DDP calls this no_sync)."""
        key = with_mask
        if key in self._overlap_step_cache:
            return self._overlap_step_cache[key]
        from ..parallel import overlap
        eval_ids = tuple(dict.fromkeys(self.eval_node_ids))
        acc_shardings = jax.tree.map(
            lambda _: NamedSharding(self.mesh, P("data")), self.params)
        mask_shard = (self.batch_shard,) if with_mask else ()

        def acc_step(params, buffers, grad_acc, data, label_vec, epoch,
                     rng, *maskarg):
            self.metrics.counter_inc("train_step_traces")
            mask = maskarg[0] if with_mask else None
            loss, outs, new_acc = overlap.accumulate_local(
                self, params, data, label_vec, epoch, rng, eval_ids,
                mask, grad_acc)
            return buffers, new_acc, loss, outs, {}

        acc_fn = jax.jit(
            acc_step,
            in_shardings=(self.param_shardings, self.buffer_shardings,
                          acc_shardings, self.batch_shard,
                          self.batch_shard, self.repl, self.repl)
            + mask_shard,
            out_shardings=(self.buffer_shardings, acc_shardings,
                           self.repl, self.repl, self.repl),
            donate_argnums=(1, 2))

        def apply_step(params, opt_state, buffers, grad_acc, data,
                       label_vec, epoch, rng, *maskarg):
            self.metrics.counter_inc("train_step_traces")
            mask = maskarg[0] if with_mask else None
            loss, outs, grads = overlap.apply_reduce(
                self, params, data, label_vec, epoch, rng, eval_ids,
                mask, grad_acc)
            new_p, new_s = self._apply_update(params, opt_state, grads,
                                              epoch)
            new_acc = jax.tree.map(jnp.zeros_like, grad_acc)
            return new_p, new_s, buffers, new_acc, loss, outs, {}

        apply_fn = jax.jit(
            apply_step,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          self.buffer_shardings, acc_shardings,
                          self.batch_shard, self.batch_shard,
                          self.repl, self.repl) + mask_shard,
            out_shardings=(self.param_shardings, self.opt_shardings,
                           self.buffer_shardings, acc_shardings,
                           self.repl, self.repl, self.repl),
            donate_argnums=(0, 1, 2, 3))
        self._overlap_step_cache[key] = (acc_fn, apply_fn)
        return acc_fn, apply_fn

    def _loss_and_grads(self, params, buffers, data, label_vec, extras,
                        epoch, rng, eval_ids, mask=None):
        if extras and engine.opts.dp_overlap == "1":
            self._dp_warn_once("extra-data inputs are unsupported")
        if not extras and not self.remat and self._dp_overlap_active():
            # explicit bucketed backward-overlapped reduction (tentpole
            # path, parallel/overlap.py).  With update_period > 1 this
            # runs under the cond step, reducing every micro-step
            # (dp_reduce_at = step, or monitored runs); reduce-scatter
            # is reserved for paths whose grads never round-trip through
            # the replicated grad accumulator
            from ..parallel import overlap
            return overlap.loss_and_grads(
                self, params, buffers, data, label_vec, epoch, rng,
                eval_ids, mask=mask,
                scatter_ok=(self.update_period == 1))
        if self.remat:
            # remat = 1 is valid (the whole body as one checkpointed
            # segment: maximum activation saving, maximum recompute)
            assert not self._pipelined, (
                "remat and mesh=pipe are mutually exclusive (the pipeline "
                "schedule already bounds live activations per stage)")
            assert not extras, "remat: extra-data inputs unsupported"

            assert any(c.layer.is_loss for c in self.net.connections), \
                "network has no loss layer; cannot train"

            def loss_fn(p):
                nodes, ctx = self._remat_forward(
                    p, data, label_vec, rng=rng, epoch=epoch, mask=mask)
                total = sum(ctx.losses[1:], ctx.losses[0])
                for nid in eval_ids:
                    assert nid in nodes, (
                        "remat: train-metric eval nodes must sit at or "
                        "after the last segment boundary")
                outs = {nid: as_mat(nodes[nid]).astype(jnp.float32)
                        for nid in eval_ids}
                return total, (buffers, outs, ctx.diagnostics)

            return jax.value_and_grad(loss_fn, has_aux=True)(params)
        if self._pipelined:
            assert not extras, "pipeline: extra-data inputs unsupported"

            assert any(c.layer.is_loss for c in self.net.connections), \
                "network has no loss layer; cannot train"

            if self.pipe_schedule == "1f1b":
                return self._pipeline_1f1b_loss_and_grads(
                    params, buffers, data, label_vec, epoch, rng, eval_ids,
                    mask)

            def loss_fn(p):
                nodes, ctx = self._pipeline_forward(
                    p, data, label_vec, train=True, rng=rng, epoch=epoch,
                    mask=mask)
                total = sum(ctx.losses[1:], ctx.losses[0])
                for nid in eval_ids:
                    assert nid in nodes, (
                        "pipeline: train-metric eval nodes must sit at or "
                        "after the last stage boundary")
                outs = {nid: as_mat(nodes[nid]).astype(jnp.float32)
                        for nid in eval_ids}
                return total, (buffers, outs, ctx.diagnostics)

            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        if self.batch_split > 1:
            assert not extras, "batch_split: extra-data inputs unsupported"
            assert not self.buffers, (
                "batch_split needs stateless layers (batch_norm running "
                "stats would chain per sub-batch)")
            # graph-level software pipelining: run K independent
            # half-batch chains inside one step and sum their losses —
            # XLA's latency-hiding scheduler interleaves chain A's
            # compute into chain B's prefetch stalls (a single serial
            # stem chain gives it nothing to overlap with).  Requires
            # stateless layers (no running buffers); dropout keys fold
            # per chunk, so trajectories differ from unsplit runs the
            # way two microbatches would.
            k = self.batch_split
            assert data.shape[0] % k == 0

            def loss_fn(p):
                total, outs_parts, diags = None, [], None
                for j in range(k):
                    sl = slice(j * data.shape[0] // k,
                               (j + 1) * data.shape[0] // k)
                    nodes, _, ctx = self._forward(
                        p, buffers, data[sl],
                        None if label_vec is None else label_vec[sl],
                        (), train=True,
                        rng=None if rng is None
                        else jax.random.fold_in(rng, j),
                        epoch=epoch,
                        mask=None if mask is None else mask[sl])
                    assert ctx.losses, \
                        "network has no loss layer; cannot train"
                    part = sum(ctx.losses[1:], ctx.losses[0])
                    total = part if total is None else total + part
                    outs_parts.append(
                        {nid: as_mat(nodes[nid]).astype(jnp.float32)
                         for nid in eval_ids})
                    diags = ctx.diagnostics
                outs = {nid: jnp.concatenate(
                    [op[nid] for op in outs_parts], axis=0)
                    for nid in eval_ids}
                return total, (buffers, outs, diags)

            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        def loss_fn(p):
            nodes, new_buffers, ctx = self._forward(
                p, buffers, data, label_vec, extras,
                train=True, rng=rng, epoch=epoch, mask=mask)
            assert ctx.losses, "network has no loss layer; cannot train"
            total = sum(ctx.losses[1:], ctx.losses[0])
            outs = {nid: as_mat(nodes[nid]).astype(jnp.float32)
                    for nid in eval_ids}
            return total, (new_buffers, outs, ctx.diagnostics)
        # NOTE: an lax.optimization_barrier between backprop and the
        # optimizer (to stop the updater's f32 upcast from fusing into the
        # weight-grad convs) was measured slightly SLOWER on v5e (54.7ms vs
        # 53.3ms AlexNet b1024) — XLA's fusion choices here are net wins.
        return jax.value_and_grad(loss_fn, has_aux=True)(params)

    def _apply_update(self, params, opt_state, grads, epoch):
        new_p, new_s = {}, {}
        for pkey, group in params.items():
            def rec(g, gg, ss, hypers):
                np_, ns_ = {}, {}
                for tag, p in g.items():
                    if isinstance(p, dict):  # nested group (pairtest sides)
                        np_[tag], ns_[tag] = rec(
                            p, gg[tag], ss[tag], hypers[tag])
                    else:
                        np_[tag], ns_[tag] = self.updater.apply(
                            p, gg[tag], ss[tag], hypers[tag], epoch)
                return np_, ns_
            new_p[pkey], new_s[pkey] = rec(
                group, grads[pkey], opt_state[pkey], self.hypers[pkey])
        return new_p, new_s

    def _build_train_step(self, with_mask: bool = False):
        """The jitted step.  ``with_mask`` statically selects the loss-mask
        variant: almost every batch is unpadded, and threading an all-ones
        mask through would make every masked code path (BatchNorm masked
        statistics in particular) permanent hot-path work — so the masked
        program is a separate compilation used only for the epoch's padded
        tail batch."""
        accumulate = self.update_period > 1
        eval_ids = tuple(dict.fromkeys(self.eval_node_ids))
        # monitor=1 appends per-leaf norm stacks to the step outputs (the
        # reference's updater monitor, doc/monitor.md).  With monitor=0
        # the builder takes the exact pre-telemetry path: no extra
        # outputs, no ingraph import, identical lowered HLO (asserted in
        # tests/test_monitor.py)
        monitored = bool(self.monitor)

        def monitor_stats(params, grads, new_p):
            from ..monitor import ingraph
            return (ingraph.group_stats(params, grads, new_p),) \
                if monitored else ()

        def loss_and_grads(params, buffers, data, label_vec, extras, epoch,
                           rng, mask):
            return self._loss_and_grads(params, buffers, data, label_vec,
                                        extras, epoch, rng, eval_ids,
                                        mask=mask)

        def apply_update(operand, epoch):
            params, opt_state, grads = operand
            new_p, new_s = self._apply_update(params, opt_state, grads, epoch)
            zeroed = jax.tree.map(jnp.zeros_like, grads)
            return new_p, new_s, zeroed

        mask_shard = (self.batch_shard,) if with_mask else ()
        mon_shard = (self.repl,) if monitored else ()
        if accumulate:
            def step(params, opt_state, buffers, grad_acc, data, label_vec,
                     extras, epoch, rng, do_update, *maskarg):
                # trace-time side effect: runs once per compilation, so
                # the counter exposes silent retraces (shape churn)
                self.metrics.counter_inc("train_step_traces")
                mask = maskarg[0] if with_mask else None
                (loss, (new_buffers, outs, diags)), grads = loss_and_grads(
                    params, buffers, data, label_vec, extras, epoch, rng,
                    mask)
                grads = jax.tree.map(jnp.add, grad_acc, grads)
                new_p, new_s, new_grads = jax.lax.cond(
                    do_update, lambda op: apply_update(op, epoch),
                    lambda op: op, (params, opt_state, grads))
                return (new_p, new_s, new_buffers, new_grads,
                        loss, outs, diags) + monitor_stats(
                            params, grads, new_p)

            shardings_in = (self.param_shardings, self.opt_shardings,
                            self.buffer_shardings, self.param_shardings,
                            self.batch_shard, self.batch_shard,
                            self.batch_shard, self.repl, self.repl,
                            self.repl) + mask_shard
            shardings_out = (self.param_shardings, self.opt_shardings,
                             self.buffer_shardings, self.param_shardings,
                             self.repl, self.repl, self.repl) + mon_shard
            return jax.jit(step, in_shardings=shardings_in,
                           out_shardings=shardings_out,
                           donate_argnums=(0, 1, 2, 3))

        def step(params, opt_state, buffers, data, label_vec,
                 extras, epoch, rng, *maskarg):
            self.metrics.counter_inc("train_step_traces")
            mask = maskarg[0] if with_mask else None
            (loss, (new_buffers, outs, diags)), grads = loss_and_grads(
                params, buffers, data, label_vec, extras, epoch, rng, mask)
            new_p, new_s, _ = apply_update(
                (params, opt_state, grads), epoch)
            return (new_p, new_s, new_buffers, loss, outs,
                    diags) + monitor_stats(params, grads, new_p)

        shardings_in = (self.param_shardings, self.opt_shardings,
                        self.buffer_shardings,
                        self.batch_shard, self.batch_shard,
                        self.batch_shard, self.repl, self.repl) + mask_shard
        shardings_out = (self.param_shardings, self.opt_shardings,
                         self.buffer_shardings,
                         self.repl, self.repl, self.repl) + mon_shard
        return jax.jit(step, in_shardings=shardings_in,
                       out_shardings=shardings_out,
                       donate_argnums=(0, 1, 2))

    def _build_multi_step(self, nsteps: int, with_outs: bool = False):
        """One jitted ``lax.scan`` over ``nsteps`` sequential updates.

        The parameter/optimizer trajectory is identical to ``nsteps`` calls
        of :meth:`update` (period 1), including the per-step PRNG keys
        (``fold_in(rng_base, sample_counter)``, matching update()'s
        increment-then-fold).  A single dispatch amortizes host->device
        launch latency across the scan: the reference hides per-batch launch
        cost with its ThreadBuffer prefetch thread
        (iter_batch_proc-inl.hpp:136-224); on TPU the idiomatic equivalent
        is keeping the loop on device.  With ``with_outs`` the eval-node
        outputs of every step are stacked and returned so the caller can
        accumulate the train metric at full fidelity (one D2H per group
        instead of per step).
        """
        key = (nsteps, with_outs)
        if key in self._multi_step_cache:
            return self._multi_step_cache[key]
        assert self.update_period == 1, \
            "update_many requires update_period=1 (use update() for " \
            "gradient accumulation)"
        eval_ids = tuple(dict.fromkeys(self.eval_node_ids)) if with_outs \
            else ()

        def body(carry, xs):
            params, opt_state, buffers, epoch, rng_base = carry
            data, label_vec = xs
            # epoch here == sample_counter-1 of the equivalent update() call,
            # which folds AFTER incrementing — hence epoch + 1
            rng = jax.random.fold_in(rng_base, epoch + 1)
            (loss, (new_buffers, outs, _)), grads = self._loss_and_grads(
                params, buffers, data, label_vec, (), epoch, rng, eval_ids)
            new_p, new_s = self._apply_update(params, opt_state, grads, epoch)
            return ((new_p, new_s, new_buffers, epoch + 1, rng_base),
                    (loss, outs))

        def run(params, opt_state, buffers, epoch, rng_base, datas, labels):
            self.metrics.counter_inc("train_step_traces")
            carry = (params, opt_state, buffers, epoch, rng_base)
            carry, (losses, outs) = jax.lax.scan(
                body, carry, (datas, labels))
            params, opt_state, buffers, epoch, _ = carry
            return params, opt_state, buffers, losses, outs

        stacked = NamedSharding(self.mesh, P(None, *self.batch_shard.spec))
        fn = jax.jit(
            run,
            in_shardings=(self.param_shardings, self.opt_shardings,
                          self.buffer_shardings, self.repl, self.repl,
                          stacked, stacked),
            out_shardings=(self.param_shardings, self.opt_shardings,
                           self.buffer_shardings, self.repl, self.repl),
            donate_argnums=(0, 1, 2))
        self._multi_step_cache[key] = fn
        return fn

    def _device_stacked(self, arr, dtype=None):
        """(k, batch, ...) host stack -> device array; multi-host processes
        hold their slice of dim 1 (the global batch)."""
        return self._device_put(
            arr, dtype,
            NamedSharding(self.mesh, P(None, *self.batch_shard.spec)),
            lambda a: (a.shape[0], self.batch_size) + a.shape[2:])

    def update_many(self, datas, labels, with_outs: bool = False):
        """Run ``k`` sequential training steps in one device dispatch.

        ``datas``: (k, batch, c, h, w); ``labels``: (k, batch, label_width).
        Returns the (k,) per-step losses (lazy device array); with
        ``with_outs`` returns ``(losses, outs)`` where ``outs`` maps eval
        node id -> (k, batch, width) stacked outputs for train-metric
        accumulation.
        """
        self._note_engine_opts()
        datas = self._s2d_transform(self._device_stacked(datas),
                                    stacked=True)
        labels = self._device_stacked(labels, jnp.float32)
        k = datas.shape[0]
        fn = self._build_multi_step(k, with_outs)
        (self.params, self.opt_state, self.buffers, losses, outs) = fn(
            self.params, self.opt_state, self.buffers,
            jnp.int32(self.epoch_counter), self._rng_base, datas, labels)
        self.sample_counter += k
        self.epoch_counter += k
        self._last_loss = losses[-1]
        self._last_outs = None
        self._last_diags = None
        if with_outs:
            return losses, outs
        return losses

    def _build_eval_many(self, k: int, node_ids: Tuple[int, ...]):
        """One jitted ``lax.scan`` over ``k`` eval batches: one dispatch +
        one D2H per group instead of per batch (VERDICT r3 weak 7 — on a
        tunneled link the per-batch sync made Evaluate disproportionately
        slow next to the scan-batched train path)."""
        self._note_engine_opts()
        key = (k, node_ids)
        if key in self._eval_many_cache:
            return self._eval_many_cache[key]

        def run(params, buffers, datas):
            self.metrics.counter_inc("eval_step_traces")

            def body(carry, data):
                return carry, self.forward_eval(params, buffers, data,
                                                node_ids)
            _, outs = lax.scan(body, 0, datas)
            return outs

        stacked = NamedSharding(self.mesh, P(None, *self.batch_shard.spec))
        fn = jax.jit(run,
                     in_shardings=(self.param_shardings,
                                   self.buffer_shardings, stacked),
                     out_shardings=self.repl)
        self._eval_many_cache[key] = fn
        return fn

    def forward_eval(self, params, buffers, data, node_ids, extras=()):
        """Eval-mode forward to flattened float32 node outputs — the
        shared traced body of the eval steps (:meth:`_get_eval_step`,
        :meth:`_build_eval_many`) and the serving engine's pinned-bucket
        predict (serve/engine.py), so batch eval, ``task = pred``, and
        ``task = serve`` can never drift apart numerically."""
        nodes, _, _ = self._forward(params, buffers, data, None, extras,
                                    train=False, rng=None, epoch=0)
        return {nid: as_mat(nodes[nid]).astype(jnp.float32)
                for nid in node_ids}

    def _get_eval_step(self, node_ids: Tuple[int, ...]):
        self._note_engine_opts()
        if node_ids in self._eval_step_cache:
            return self._eval_step_cache[node_ids]

        def estep(params, buffers, data, extras):
            self.metrics.counter_inc("eval_step_traces")
            return self.forward_eval(params, buffers, data, node_ids,
                                     extras)

        fn = jax.jit(estep,
                     in_shardings=(self.param_shardings,
                                   self.buffer_shardings,
                                   self.batch_shard, self.batch_shard),
                     out_shardings=self.repl)
        self._eval_step_cache[node_ids] = fn
        return fn

    # ------------------------------------------------------------- training
    def start_round(self, r: int) -> None:
        self.round = r
        self.train_metric.clear()

    def _device_batch(self, arr, dtype=None):
        """Host batch -> device array under the batch sharding."""
        return self._device_put(
            arr, dtype, self.batch_shard,
            lambda a: (self.batch_size,) + a.shape[1:])

    def _device_put(self, arr, dtype, sharding, global_shape_fn):
        """Host array -> device array under ``sharding``.

        Single-process: plain transfer (XLA shards it).  Multi-host: each
        process holds only its slice of the global batch (the data iterator
        sharded by dist_worker_rank), so assemble the global array from
        process-local data — the SPMD program then sees one logical
        (global_batch, ...) input, exactly like single-host."""
        if isinstance(arr, jax.Array) and not isinstance(arr, np.ndarray):
            return arr.astype(dtype) if dtype and arr.dtype != dtype else arr
        arr = np.asarray(arr, dtype) if dtype else np.asarray(arr)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, arr, global_shape_fn(arr))
        # committed sharded transfer: the array lands distributed per the
        # step's in_sharding at STAGING time, so the jitted dispatch never
        # pays a reshard/copy (prefetch-to-device needs the whole transfer
        # off the dispatch window, not just the host->device-0 leg)
        return jax.device_put(arr, sharding)

    # -------------------------------------------------------------- staging
    def stage_batch(self, batch) -> "StagedBatch":
        """Host DataBatch -> device-resident :class:`StagedBatch`: dtype
        cast, sharded transfer, the ``input_s2d`` staging transform, and
        the tail loss mask — everything ``update``/``predict`` would
        otherwise do inside the dispatch window.  Blocks until the
        transfer completes, so a queue of staged batches is truly
        device-resident (call off the hot path — the
        :class:`~cxxnet_tpu.io.device_prefetch.DevicePrefetcher` producer
        thread does)."""
        from ..io.device_prefetch import StagedBatch
        t0 = time.perf_counter()
        data = self._s2d_transform(self._device_batch(batch.data))
        label = self._device_batch(batch.label, jnp.float32)
        extras = tuple(self._device_batch(e) for e in batch.extra_data)
        n_padd = int(getattr(batch, "tail_mask_padd", 0))
        mask = None
        if n_padd:
            host_mask = np.ones((batch.batch_size,), np.float32)
            host_mask[batch.batch_size - n_padd:] = 0.0
            mask = self._device_batch(host_mask)
        jax.block_until_ready((data, label, extras)
                              if mask is None else (data, label, extras,
                                                    mask))
        return StagedBatch(
            data=data, label=label, label_host=np.asarray(batch.label),
            index=batch.index, num_batch_padd=batch.num_batch_padd,
            tail_mask_padd=n_padd, extra_data=extras, mask=mask,
            h2d_sec=time.perf_counter() - t0)

    def stage_group(self, group) -> "StagedGroup":
        """Uniform host batches (no tail masks, no extra-data) -> one
        device-resident ``(k, batch, ...)`` stack for
        :meth:`update_many` — the group ``np.stack`` + cast + transfer
        off the dispatch window."""
        from ..io.device_prefetch import StagedGroup, StagedMeta
        t0 = time.perf_counter()
        datas = self._s2d_transform(
            self._device_stacked(np.stack([b.data for b in group])),
            stacked=True)
        labels = self._device_stacked(
            np.stack([b.label for b in group]), jnp.float32)
        jax.block_until_ready((datas, labels))
        return StagedGroup(
            datas=datas, labels=labels,
            meta=[StagedMeta(batch_size=b.batch_size,
                             num_batch_padd=b.num_batch_padd,
                             tail_mask_padd=b.tail_mask_padd,
                             label=np.asarray(b.label), index=b.index)
                  for b in group],
            h2d_sec=time.perf_counter() - t0)

    def stage_eval_group(self, group) -> "StagedEvalGroup":
        """Eval batches -> one device-resident ``(k, batch, ...)`` stack
        for the scanned eval step (labels stay host-side — the metric
        consumes them there)."""
        from ..io.device_prefetch import StagedEvalGroup, StagedMeta
        t0 = time.perf_counter()
        datas = self._s2d_transform(
            self._device_stacked(np.stack([b.data for b in group])),
            stacked=True)
        jax.block_until_ready(datas)
        return StagedEvalGroup(
            datas=datas,
            meta=[StagedMeta(batch_size=b.batch_size,
                             num_batch_padd=b.num_batch_padd,
                             tail_mask_padd=b.tail_mask_padd,
                             label=np.asarray(b.label), index=b.index)
                  for b in group],
            h2d_sec=time.perf_counter() - t0)

    def _grad_acc_init(self):
        if getattr(self, "_overlap_defer", False):
            # per-device LOCAL gradient sums under a leading device axis
            # sharded over "data" — same per-device footprint as one
            # replicated copy, but no cross-chip reduction until apply.
            # Built sharded (jit + out_shardings): materializing the
            # (ndata, ...) zeros on one device first would transiently
            # cost ndata x the parameter bytes on that chip
            shard = NamedSharding(self.mesh, P("data"))
            ndata = self.mesh.shape["data"]
            return jax.jit(
                lambda: jax.tree.map(
                    lambda p: jnp.zeros((ndata,) + p.shape, p.dtype),
                    self.params),
                out_shardings=jax.tree.map(lambda _: shard, self.params))()
        return jax.tree.map(jnp.zeros_like, self.params)

    def _note_engine_opts(self) -> None:
        if getattr(self, "engine_opts_used", None) is None:
            self.engine_opts_used = engine.snapshot()

    def update(self, batch: DataBatch) -> None:
        self._note_engine_opts()
        self.sample_counter += 1
        do_update = (self.sample_counter % self.update_period == 0)
        epoch = self.epoch_counter
        if do_update:
            self.epoch_counter += 1
        rng = jax.random.fold_in(self._rng_base, self.sample_counter)
        data = self._s2d_transform(self._device_batch(batch.data))
        label_vec = self._device_batch(batch.label, jnp.float32)
        extras = tuple(self._device_batch(e) for e in batch.extra_data)
        # tail-batch padding: real instances train, padded replicas are
        # masked out of every loss term (the reference instead re-plumbs
        # node shapes per tail batch, AdjustBatchSize
        # neural_net-inl.hpp:266-277 — shape-polymorphic steps would
        # recompile on TPU, so pad + mask is the equivalent).  round_batch
        # wrap instances (num_batch_padd without tail_mask_padd) are real
        # data and train unmasked, as in the reference.
        n_padd = int(getattr(batch, "tail_mask_padd", 0))
        if n_padd:
            # masked-step variant, compiled lazily (once per trainer): only
            # the epoch's padded tail batch takes this path, so the common
            # step never carries mask operands or masked-statistics code.
            # A StagedBatch arrives with the mask already device-resident
            mask = getattr(batch, "mask", None)
            if mask is None:
                host_mask = np.ones((batch.data.shape[0],), np.float32)
                host_mask[batch.data.shape[0] - n_padd:] = 0.0
                mask = self._device_batch(host_mask)
            maskarg = (mask,)
            if getattr(self, "_train_step_masked", None) is None:
                self._train_step_masked = self._build_train_step(
                    with_mask=True)
            step_fn = self._train_step_masked
        else:
            maskarg = ()
            step_fn = self._train_step
        if self.update_period > 1 and getattr(self, "_overlap_defer", False):
            # dp_reduce_at = apply: separate accumulate/apply programs —
            # micro-steps run no collectives at all, the apply step
            # reduces each bucket once with the accumulator folded into
            # the last backward's grad-ready points
            assert not extras, \
                "dp_overlap deferred reduce: extra-data inputs unsupported"
            if getattr(self, "_grad_acc", None) is None:
                self._grad_acc = self._grad_acc_init()
            acc_fn, apply_fn = self._build_overlap_steps(bool(n_padd))
            if do_update:
                (self.params, self.opt_state, self.buffers,
                 self._grad_acc, loss, outs, diags) = apply_fn(
                    self.params, self.opt_state, self.buffers,
                    self._grad_acc, data, label_vec, jnp.int32(epoch),
                    rng, *maskarg)
            else:
                (self.buffers, self._grad_acc, loss, outs, diags) = acc_fn(
                    self.params, self.buffers, self._grad_acc, data,
                    label_vec, jnp.int32(epoch), rng, *maskarg)
        elif self.update_period > 1:
            if getattr(self, "_grad_acc", None) is None:
                self._grad_acc = self._grad_acc_init()
            out = step_fn(
                self.params, self.opt_state, self.buffers, self._grad_acc,
                data, label_vec, extras,
                jnp.int32(epoch), rng, jnp.bool_(do_update), *maskarg)
            (self.params, self.opt_state, self.buffers, self._grad_acc,
             loss, outs, diags) = out[:7]
        else:
            out = step_fn(
                self.params, self.opt_state, self.buffers,
                data, label_vec, extras, jnp.int32(epoch), rng, *maskarg)
            (self.params, self.opt_state, self.buffers,
             loss, outs, diags) = out[:6]
        self._last_loss = loss
        self._last_outs = outs
        self._last_diags = diags
        self._last_monitor = out[-1] if self.monitor else None
        if self.monitor and self.monitor_interval > 0 \
                and self.sample_counter % self.monitor_interval == 0:
            self._monitor_tick(loss, self._last_monitor)
        if self.eval_train and self.train_metric.evals:
            self.accumulate_train_metric(
                outs, getattr(batch, "label_host", batch.label),
                n_padd=n_padd)

    def _monitor_tick(self, loss, mon) -> None:
        """Materialize one monitored step on the host: the NaN/inf loss
        guard plus per-layer norm records and the reference-style monitor
        line.  This is the step's one deliberate host sync — amortized by
        ``monitor_interval`` (the unmonitored path stays fully async)."""
        from ..monitor import ingraph
        lval = float(np.asarray(loss))
        # per-layer norms FIRST: on a fatal NaN these are exactly the
        # diagnostics worth having (which layer blew up), and the sink
        # flushes per record, so they survive the raise below
        stats = ingraph.unpack_stats(
            {k: np.asarray(v) for k, v in mon.items()})
        for name, s in stats.items():
            self.metrics.emit("monitor", step=self.sample_counter,
                              round=self.round, layer=name, **s)
        if not mlog.is_silent():  # skip the string build when suppressed
            parts = " ".join(
                f"{name}[|w|={s['w_norm']:.4g},|dw|={s['g_norm']:.4g},"
                f"u/w={s['u_ratio']:.3g}]" for name, s in stats.items())
            mlog.info(f"monitor[{self.sample_counter}] "
                      f"loss={lval:.6g} {parts}")
        if not np.isfinite(lval) and self.monitor_nan != "off":
            msg = (f"monitor: non-finite loss {lval} at step "
                   f"{self.sample_counter} (round {self.round}); "
                   f"monitor_nan={self.monitor_nan}")
            self.metrics.counter_inc("nonfinite_loss_steps")
            self.metrics.emit("nan", step=self.sample_counter,
                              round=self.round, loss=lval,
                              action=self.monitor_nan)
            if self.monitor_nan == "fatal":
                raise TrainingDiverged(msg)
            mlog.warn(msg)

    def memory_gauges(self) -> Dict[str, int]:
        """HBM high-water gauges over this trainer's devices (empty on
        backends without memory_stats, e.g. CPU)."""
        return device_memory_gauges(self.devices)

    # -------------------------------------------------- layer attribution
    def layer_scopes(self) -> List[str]:
        """The named-scope strings the net builder stamps each
        connection's forward with — the join keys layer attribution
        (monitor/attribution.py, doc/monitor.md) matches against
        profiler-trace op metadata."""
        from ..layers.base import conn_scope_name
        return [conn_scope_name(i, c)
                for i, c in enumerate(self.net.connections)]

    def step_hlo_text(self) -> Optional[str]:
        """Optimized-HLO text of the compiled train step (AOT-lowered
        from abstract args matching :meth:`update`'s operands), or None
        when this trainer's executed program can't be reproduced that
        way (input_s2d staging shapes, the dp_reduce_at=apply two-step
        path) or lowering fails.  Layer attribution reads each
        instruction's ``op_name`` metadata out of this text to map
        post-fusion trace op names back to layer scopes.

        Cost note: the AOT ``lower().compile()`` pays one extra XLA
        compile (the jit execution cache is keyed separately).  Callers
        gate it behind a closed profiling window with an active metrics
        sink, and the text is cached per trainer, so recurring
        ``prof_every`` windows compile once.  The same compile also
        caches :meth:`step_memory_stats` — text and bytes never cost
        two compiles."""
        return self._step_aot()[0] or None

    def step_memory_stats(self) -> Optional[Dict[str, int]]:
        """Measured memory truth of the compiled train step
        (``compiled.memory_analysis()``): ``args_bytes`` (parameters +
        batch), ``out_bytes`` (fresh outputs), ``temp_bytes`` (the temp
        allocation the memory observatory attributes per layer),
        ``alias_bytes`` (donated buffers the step writes back into),
        and ``code_bytes`` (generated code).  Per device on SPMD
        meshes — the numbers describe the partitioned module.  Shares
        :meth:`step_hlo_text`'s single cached AOT compile; None when
        that path can't reproduce this trainer's program."""
        return self._step_aot()[1]

    def _step_abstract_args(self):
        """Abstract operand tuple matching the jitted train step's
        signature, or None when the executed program can't be reproduced
        by AOT lowering (input_s2d staging shapes, the
        dp_reduce_at=apply two-step path)."""
        if self._s2d_args is not None \
                or getattr(self, "_overlap_defer", False):
            return None
        sds = jax.ShapeDtypeStruct
        absify = lambda t: jax.tree.map(  # noqa: E731
            lambda x: sds(x.shape, x.dtype), t)
        shp = self.net.node_shapes[0]
        label_w = max([b for _, _, b in self._label_fields], default=1)
        data = sds((self.batch_size,) + tuple(shp[1:]), np.float32)
        label = sds((self.batch_size, label_w), np.float32)
        extras = tuple(
            sds((self.batch_size,)
                + tuple(self.net.node_shapes[1 + i][1:]), np.float32)
            for i in range(self.netcfg.extra_data_num))
        p, o, bu = (absify(self.params), absify(self.opt_state),
                    absify(self.buffers))
        epoch = sds((), np.int32)
        rng = jax.random.PRNGKey(0)
        if self.update_period > 1:
            return (p, o, bu, absify(self.params), data, label, extras,
                    epoch, rng, sds((), np.bool_))
        return (p, o, bu, data, label, extras, epoch, rng)

    def _step_lowered(self):
        """Cached ``.lower()`` of the train step — tracing + StableHLO
        emission only, NO XLA compile (the donation audit reads aliasing
        attributes off this; :meth:`_step_aot` compiles it further).
        None when the executed program can't be reproduced or lowering
        fails (failure is cached)."""
        cached = getattr(self, "_step_lowered_cache", None)
        if cached is not None:
            return cached or None
        args = self._step_abstract_args()
        if args is None:
            self._step_lowered_cache = False
            return None
        try:
            import warnings as _warnings
            with _warnings.catch_warnings():
                # an unusable donation is the AUDIT's finding
                # (spmd_undonated), not loose stderr chatter
                _warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                lowered = self._train_step.lower(*args)
        except Exception as e:  # noqa: BLE001 — telemetry only
            mlog.warn(f"step lowering failed ({e}); layer attribution "
                      "and the donation audit are unavailable")
            self._step_lowered_cache = False
            return None
        self._step_lowered_cache = lowered
        return lowered

    def _step_aot(self):
        """(hlo_text, memory_stats) from ONE cached AOT compile of the
        train step; ("", None) caches a permanent failure."""
        cached = getattr(self, "_step_aot_cache", None)
        if cached is not None:
            return cached
        lowered = self._step_lowered()
        if lowered is None:
            self._step_aot_cache = ("", None)
            return self._step_aot_cache
        try:
            compiled = lowered.compile()
            txt = compiled.as_text()
            stats = None
            try:
                ma = compiled.memory_analysis()
                stats = {
                    "args_bytes": int(ma.argument_size_in_bytes),
                    "out_bytes": int(ma.output_size_in_bytes),
                    "temp_bytes": int(ma.temp_size_in_bytes),
                    "alias_bytes": int(ma.alias_size_in_bytes),
                    "code_bytes": int(ma.generated_code_size_in_bytes),
                }
            # disclint: ok(swallow) — stats stay None, callers gate
            except Exception:  # noqa: BLE001 — optional backend API
                pass
        except Exception as e:  # noqa: BLE001 — telemetry only
            mlog.warn(f"step_hlo_text: compile failed ({e}); layer "
                      "attribution will report unattributed time only")
            self._step_aot_cache = ("", None)
            return self._step_aot_cache
        self._step_aot_cache = (txt, stats)
        return self._step_aot_cache

    def step_donation_report(self) -> Optional[Dict[str, Any]]:
        """Per-leaf donation truth of the train step — the alias map the
        SPMD lint's donation audit (analysis/spmdlint.py) checks.

        Rows cover the donated operand trees in jitted-argument order
        (params, opt_state, buffers, and the param-shaped grad
        accumulator under ``update_period > 1``): each row carries the
        leaf's tree, key path, bytes, and whether the step aliases an
        output onto it.  Source selection: when the AOT compile is
        already cached (:meth:`step_hlo_text` / :meth:`step_memory_stats`
        paid for it) the optimized module's ``input_output_alias``
        header is authoritative; otherwise the aliasing attributes of
        the un-optimized lowered module are parsed — same decision
        point (jax establishes aliases at lowering), no XLA compile.
        None when the executed program can't be reproduced by AOT
        lowering or the parsed argument count doesn't match the
        flattened operand trees (nothing to attribute against)."""
        trees = [("params", self.params), ("opt_state", self.opt_state),
                 ("buffers", self.buffers)]
        if self.update_period > 1:
            trees.append(("grad_acc", self.params))
        leaves: List[Dict[str, Any]] = []
        for tname, tree in trees:
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                n = 1
                for d in getattr(leaf, "shape", ()):
                    n *= int(d)
                leaves.append({
                    "tree": tname, "path": jax.tree_util.keystr(path),
                    "bytes": n * jnp.dtype(leaf.dtype).itemsize})
        txt = (getattr(self, "_step_aot_cache", None) or ("", None))[0]
        if txt:
            from ..monitor.memory import entry_param_count, output_aliases
            donated = set(output_aliases(txt).values())
            n_args, source = entry_param_count(txt), "hlo"
        else:
            lowered = self._step_lowered()
            if lowered is None:
                return None
            donated, n_args = _lowered_arg_aliases(lowered.as_text())
            source = "lowered"
        if n_args < len(leaves):
            return None  # arg order can't be attributed to the trees
        for i, row in enumerate(leaves):
            row["donated"] = i in donated
        return {"source": source, "n_args": n_args, "leaves": leaves,
                "alias_bytes": sum(r["bytes"] for r in leaves
                                   if r["donated"])}

    def accumulate_train_metric(self, outs, label, n_padd: int = 0) -> None:
        """Add one batch's eval-node outputs to the train metric (shared by
        the per-batch and grouped multi-step paths).  Padded tail instances
        are excluded, matching the reference's num_batch_padd handling in
        eval (nnet_impl-inl.hpp:237-240)."""
        n_valid = label.shape[0] - n_padd
        preds = [np.asarray(outs[nid])[:n_valid] for nid in self.eval_node_ids]
        labels = {name: label[:n_valid, a:b]
                  for name, a, b in self._label_fields}
        self.train_metric.add_eval(preds, labels)

    @property
    def has_diagnostics(self) -> bool:
        """True when any layer emits step diagnostics (pairtest); such nets
        need the per-batch update path so _last_diags stays populated."""
        from ..layers.pairtest import PairTestLayer
        return any(isinstance(c.layer, PairTestLayer)
                   for c in self.net.connections)

    def _eval_accumulate(self, meta, outs_row) -> None:
        """Add one batch's eval outputs (padding excluded) to the
        metric; ``meta`` is anything with batch_size/num_batch_padd/
        label (host)."""
        n_valid = meta.batch_size - meta.num_batch_padd
        preds = [outs_row[nid][:n_valid] for nid in self.eval_node_ids]
        labels = {fname: np.asarray(meta.label)[:n_valid, a:b_]
                  for fname, a, b_ in self._label_fields}
        self.metric.add_eval(preds, labels)

    def evaluate(self, data_iter, name: str) -> str:
        """Evaluate one pass of ``data_iter`` — raw ``DataBatch``es
        (grouped + staged here, the legacy path) or pre-staged items from
        a :class:`~cxxnet_tpu.io.device_prefetch.DevicePrefetcher`
        (device-resident before dispatch)."""
        from ..io.device_prefetch import (StagedBatch, StagedEvalGroup,
                                          StagedMeta)
        self.metric.clear()
        node_ids = tuple(dict.fromkeys(self.eval_node_ids))
        group: List[DataBatch] = []

        def flush():
            if not group:
                return
            if len(group) == 1:
                estep = self._get_eval_step(node_ids)
                b = group[0]
                outs = estep(self.params, self.buffers,
                             self._s2d_transform(
                                 self._device_batch(b.data)),
                             tuple(self._device_batch(e)
                                   for e in b.extra_data))
                outs = {nid: np.asarray(v)[None] for nid, v in outs.items()}
            else:
                fn = self._build_eval_many(len(group), node_ids)
                datas = self._s2d_transform(
                    self._device_stacked(np.stack([b.data for b in group])),
                    stacked=True)
                outs = jax.tree.map(np.asarray,
                                    fn(self.params, self.buffers, datas))
            for i, b in enumerate(group):
                n_valid = b.batch_size - b.num_batch_padd
                preds = [outs[nid][i][:n_valid]
                         for nid in self.eval_node_ids]
                labels = {fname: b.label[:n_valid, a:b_]
                          for fname, a, b_ in self._label_fields}
                self.metric.add_eval(preds, labels)
            group.clear()

        for batch in data_iter:
            if isinstance(batch, StagedEvalGroup):
                flush()
                fn = self._build_eval_many(len(batch.meta), node_ids)
                outs = jax.tree.map(
                    np.asarray, fn(self.params, self.buffers, batch.datas))
                for i, m in enumerate(batch.meta):
                    self._eval_accumulate(
                        m, {nid: outs[nid][i] for nid in node_ids})
                continue
            if isinstance(batch, StagedBatch):
                flush()
                estep = self._get_eval_step(node_ids)
                outs = estep(self.params, self.buffers, batch.data,
                             batch.extra_data)
                outs = {nid: np.asarray(v) for nid, v in outs.items()}
                self._eval_accumulate(
                    StagedMeta(batch_size=batch.batch_size,
                               num_batch_padd=batch.num_batch_padd,
                               tail_mask_padd=batch.tail_mask_padd,
                               label=batch.label_host, index=batch.index),
                    outs)
                continue
            if batch.extra_data:
                # extra-data side inputs keep the per-batch path
                flush()
                group.append(batch)
                flush()
                continue
            if self.eval_group <= 1:
                group.append(batch)
                flush()
                continue
            # copy: paged iterators may reuse the underlying buffer while
            # the batch waits in the group
            group.append(dataclasses.replace(batch,
                                             data=np.array(batch.data),
                                             label=np.array(batch.label)))
            if len(group) >= self.eval_group:
                flush()
        flush()
        return self.metric.print_line(name)

    def train_eval_line(self, name: str = "train") -> str:
        return self.train_metric.print_line(name)

    # ------------------------------------------------------------ inference
    def predict(self, batch: DataBatch) -> np.ndarray:
        """Class predictions (argmax if multi-class) for one batch
        (reference TransformPred, nnet_impl-inl.hpp:286-299)."""
        raw = self.predict_raw(batch)
        if raw.shape[1] > 1:
            return raw.argmax(axis=1).astype(np.float32)
        return raw[:, 0]

    def predict_raw(self, batch: DataBatch) -> np.ndarray:
        nid = self.net.final_node
        estep = self._get_eval_step((nid,))
        outs = estep(self.params, self.buffers,
                     self._s2d_transform(self._device_batch(batch.data)),
                     tuple(self._device_batch(e) for e in batch.extra_data))
        n_valid = batch.batch_size - batch.num_batch_padd
        return np.asarray(outs[nid])[:n_valid]

    def extract_feature(self, batch: DataBatch, node_name: str) -> np.ndarray:
        nid = self.net.node_id(node_name)
        estep = self._get_eval_step((nid,))
        outs = estep(self.params, self.buffers,
                     self._s2d_transform(self._device_batch(batch.data)),
                     tuple(self._device_batch(e) for e in batch.extra_data))
        n_valid = batch.batch_size - batch.num_batch_padd
        return self._apply_read_fixup(nid, np.asarray(outs[nid])[:n_valid])

    def _apply_read_fixup(self, nid: int, out: np.ndarray) -> np.ndarray:
        """Undo the relu->pool reorder / bias deferral for a node read at
        call time (extract_feature): the relu node stores the
        pre-activation and a defer_bias conv node stores bias-less
        output.  eval_node_ids are excluded from deferral at build time;
        nodes chosen later get the correction applied here instead."""
        fix = getattr(self, "_read_fixups", {}).get(nid)
        if fix is None:
            return out
        kind, bias_key = fix
        flat_shape = out.shape
        # eval steps return as_mat-flattened (batch, C*H*W); restore the
        # node's natural shape so the per-channel bias broadcasts
        out = out.reshape((out.shape[0],) + tuple(self.net.node_shapes[nid][1:]))
        if bias_key is not None:
            bias = np.asarray(self.params[bias_key]["bias"]).astype(out.dtype)
            out = out + bias.reshape((-1,) + (1,) * (out.ndim - 2))
        if kind == "relu":
            out = np.maximum(out, out.dtype.type(0))
        return out.reshape(flat_shape)

    # ----------------------------------------------------------- weights IO
    def _resolve_param_key(self, layer_name: str) -> str:
        for conn in self.net.connections:
            if conn.param_key.split("-", 1)[1] == layer_name:
                return conn.param_key
        raise KeyError(f"unknown layer name {layer_name!r}")

    @staticmethod
    def _walk_tag(group, tag: str, layer_name: str):
        """Resolve a possibly-nested tag ("wmat", or "master:wmat" for a
        pairtest layer's nested {master:{...}, slave:{...}} groups).
        Returns (leaf_dict, leaf_tag)."""
        parts = tag.split(":")
        cur = group
        for p in parts[:-1]:
            if not isinstance(cur.get(p), dict):
                raise KeyError(
                    f"layer {layer_name!r} has no nested group {p!r}; "
                    f"available: {sorted(cur)}")
            cur = cur[p]
        leaf = cur.get(parts[-1])
        if isinstance(leaf, dict):
            raise KeyError(
                f"layer {layer_name!r} tag {tag!r} is a nested group "
                f"(sub-tags {sorted(leaf)}); address a leaf like "
                f"{tag}:{sorted(leaf)[0]}")
        if leaf is None:
            raise KeyError(
                f"layer {layer_name!r} has no tag {tag!r}; "
                f"available: {sorted(cur)}")
        return cur, parts[-1]

    def get_weight(self, layer_name: str, tag: str) -> np.ndarray:
        group = self.params[self._resolve_param_key(layer_name)]
        leaf_dict, leaf_tag = self._walk_tag(group, tag, layer_name)
        return np.asarray(leaf_dict[leaf_tag])

    def set_weight(self, value: np.ndarray, layer_name: str, tag: str) -> None:
        pkey = self._resolve_param_key(layer_name)
        leaf_dict, leaf_tag = self._walk_tag(self.params[pkey], tag,
                                             layer_name)
        old = leaf_dict[leaf_tag]
        assert tuple(old.shape) == tuple(value.shape), \
            f"set_weight: shape mismatch {old.shape} vs {value.shape}"
        shard_dict, _ = self._walk_tag(self.param_shardings[pkey], tag,
                                       layer_name)
        leaf_dict[leaf_tag] = jax.device_put(
            jnp.asarray(value, old.dtype), shard_dict[leaf_tag])
        self._refresh_masters(pkey)

    def _refresh_masters(self, pkey: Optional[str] = None) -> None:
        """Re-derive the optimizer's float32 master copies (``w32``) from
        the current params.  MUST follow any direct param write
        (set_weight / copy_model_from): the update step sources from the
        master, so a stale one would silently revert the written weights
        on the next update."""
        def rec(group, state):
            for tag, p in group.items():
                if isinstance(p, dict):
                    rec(p, state[tag])
                elif isinstance(state.get(tag), dict) and "w32" in state[tag]:
                    # the jitted step reshards this to the opt sharding on
                    # its next invocation (in_shardings are explicit)
                    state[tag]["w32"] = p.astype(jnp.float32)
        for k in ([pkey] if pkey else list(self.params.keys())):
            rec(self.params[k], self.opt_state[k])

    # ---------------------------------------------------------- checkpoints
    def train_state(self) -> Dict[str, Any]:
        """The non-array state exact resume needs: counters plus the LIVE
        rng stream.  The raw PRNG key (not the seed) matters — a
        rollback retry reseeds the stream past the bad window, and the
        resumed run must continue *that* stream, not the seed's."""
        return {"sample_counter": int(self.sample_counter),
                "epoch_counter": int(self.epoch_counter),
                "round": int(self.round), "seed": int(self.seed),
                "rng_key": np.asarray(self._rng_base).tolist(),
                "rng_dtype": str(np.asarray(self._rng_base).dtype)}

    def set_train_state(self, st: Dict[str, Any]) -> None:
        self.sample_counter = int(st["sample_counter"])
        self.epoch_counter = int(st["epoch_counter"])
        self.round = int(st["round"])
        self._rng_base = jnp.asarray(
            np.asarray(st["rng_key"], dtype=st.get("rng_dtype", "uint32")))

    def reseed_rng(self, salt: int) -> None:
        """Fold a salt into the CURRENT rng base — the rollback path's
        "reseed past the bad window": the retried rounds draw different
        dropout/augment randomness, while a later checkpoint of the
        retry carries the folded key so its own resume stays exact."""
        self._rng_base = jax.random.fold_in(self._rng_base,
                                            np.uint32(7919 + salt))

    def _host_tree(self, tree):
        """Device pytree -> independent host copies.  ``np.array`` (not
        ``asarray``): the jitted step donates its operands, and a
        zero-copy view into a donated CPU buffer would be silently
        rewritten while the async writer serializes it."""
        return jax.tree.map(lambda a: np.array(np.asarray(a)), tree)

    def checkpoint_payload(self, *, with_opt: bool = True,
                           extra_state: Optional[Dict] = None
                           ) -> Tuple[Dict[str, Dict[str, np.ndarray]],
                                      Dict[str, Any]]:
        """One snapshot's (shards, manifest-meta): flat host-array shards
        (``params`` / ``buffers`` / ``opt``) plus everything the
        manifest carries for exact resume.  Runs on the train thread (a
        host pull — the donated device buffers can't cross threads);
        the returned arrays are independent copies safe to hand to the
        async writer."""
        dtypes: Dict[str, str] = {}
        # each shard keeps the legacy "group/key" namespace (its own
        # top-level prefix), so the shared dtypes map can never collide
        # across shards
        shards = {"params": serializer.flatten_tree(
            {"params": self._host_tree(self.params)}, dtypes)}
        buf = serializer.flatten_tree(
            {"buffers": self._host_tree(self.buffers)}, dtypes)
        if buf:
            shards["buffers"] = buf
        if with_opt:
            shards["opt"] = serializer.flatten_tree(
                {"opt": self._host_tree(self.opt_state)}, dtypes)
        # a round boundary mid-accumulation (update_period > 1, batches
        # per round not a multiple): the pending local gradient sums are
        # trajectory state too.  The dp_reduce_at=apply accumulator is
        # mesh-shaped (leading device axis) and can't reshard — skipped
        # with a warning (resume is exact only at apply boundaries there)
        pending = self.sample_counter % self.update_period
        if pending and getattr(self, "_grad_acc", None) is not None:
            if getattr(self, "_overlap_defer", False):
                mlog.warn(
                    "checkpoint at a mid-accumulation boundary with "
                    "dp_reduce_at = apply: the device-local accumulator "
                    "is not portable; resume replays the partial window "
                    "inexactly")
            else:
                shards["acc"] = serializer.flatten_tree(
                    {"acc": self._host_tree(self._grad_acc)}, dtypes)
        extra = {"round": int(self.round),
                 "train_state": self.train_state()}
        if extra_state:
            extra.update(extra_state)
        meta = {"net": self.netcfg.to_dict(),
                "epoch": int(self.epoch_counter),
                "has_opt_state": with_opt, "dtypes": dtypes,
                "extra": extra}
        return shards, meta

    def save_model(self, path: str, *, with_opt_state: bool = False,
                   extra_state: Optional[Dict] = None) -> None:
        extra = {"round": self.round, "train_state": self.train_state()}
        if extra_state:
            extra.update(extra_state)
        serializer.save_model(
            path, net_structure=self.netcfg.to_dict(),
            epoch=self.epoch_counter,
            params=jax.tree.map(np.asarray, self.params),
            buffers=jax.tree.map(np.asarray, self.buffers),
            opt_state=jax.tree.map(np.asarray, self.opt_state)
            if with_opt_state else None,
            extra_meta=extra)

    def load_model(self, path: str, validated: bool = False) -> None:
        mlog.set_silent(self.silent)
        import os
        if os.path.isdir(path):
            # atomic snapshot dir (ckpt_async): shards + manifest.
            # ``validated`` = the caller just ran validate_snapshot (the
            # resume/rollback scans do) — skip the second full crc read
            from .. import ckpt
            manifest, shard_arrays = ckpt.load_snapshot(
                path, assume_valid=validated)
            dtypes = manifest.get("dtypes") or {}
            header = {"net": manifest["net"], "epoch": manifest["epoch"],
                      "has_opt_state": manifest.get("has_opt_state"),
                      "extra": manifest.get("extra", {})}
            params = serializer.unflatten_tree(
                shard_arrays.get("params", {}), dtypes).get("params", {})
            buffers = serializer.unflatten_tree(
                shard_arrays.get("buffers", {}), dtypes).get("buffers", {})
            opt = serializer.unflatten_tree(
                shard_arrays.get("opt", {}), dtypes).get("opt") \
                if header["has_opt_state"] else None
            acc = serializer.unflatten_tree(
                shard_arrays.get("acc", {}), dtypes).get("acc") \
                if "acc" in shard_arrays else None
        else:
            header, params, buffers, opt = serializer.load_model(path)
            acc = None
        netcfg = NetConfig.from_dict(header["net"])
        # re-apply the current session's config on top of the checkpoint's:
        # later pairs win inside set_param consumers, so CLI overrides like
        # eta=... or updater=... take effect on continue/finetune (the
        # reference re-broadcasts the live config the same way,
        # cxxnet_main.cpp:205-212)
        netcfg.defcfg = list(netcfg.defcfg) + [
            (k, v) for (k, v) in self.cfg if not k.startswith("layer[")]
        for k, v in self.cfg:
            if k == "updater":
                netcfg.updater_type = v
        self.netcfg = netcfg
        assert self.batch_size > 0, "batch_size must be set before load_model"
        self._setup_mesh()
        self.net = Network(netcfg, self.batch_size, self.dtype)
        self.params = jax.tree.map(jnp.asarray, params)
        self.buffers = jax.tree.map(jnp.asarray, buffers)
        self._rng_base = jax.random.PRNGKey(self.seed)
        self._post_build()
        self.epoch_counter = header["epoch"]
        self.round = header["extra"].get("round", 0)
        if opt is not None:
            self.opt_state = jax.device_put(
                jax.tree.map(jnp.asarray, opt), self.opt_shardings)
        if acc is not None:
            self._grad_acc = jax.device_put(
                jax.tree.map(jnp.asarray, acc), self.param_shardings)
        # exact resume: snapshots written by this codebase carry the
        # live counters + rng stream — restore them so the resumed
        # trajectory continues bitwise (fold_in(rng_base,
        # sample_counter) keys every step).  Older .model files without
        # a train_state approximate sample_counter from the epoch (exact
        # at update_period = 1; the rng base stays seed-derived either
        # way, which matches any run that never rolled back)
        ts = header["extra"].get("train_state")
        if ts is not None:
            self.set_train_state(ts)
        else:
            self.sample_counter = self.epoch_counter * self.update_period
        # iterator / sentinel state for the task driver to re-apply
        # (cleared by _post_build's counters reset above, so set last)
        self.loaded_extra = dict(header["extra"])

    def copy_model_from(self, path: str) -> None:
        """Finetune: copy weights for layers whose name and shapes match
        (reference CopyModelFrom, nnet_impl-inl.hpp:101-134)."""
        header, params, _, _ = serializer.load_model(path)
        by_name = {k.split("-", 1)[1]: v for k, v in params.items()}
        copied = []
        for pkey, group in self.params.items():
            name = pkey.split("-", 1)[1]
            if name in by_name:
                src = by_name[name]
                if all(t in src and tuple(src[t].shape) == tuple(p.shape)
                       for t, p in group.items()):
                    self.params[pkey] = jax.device_put(
                        {t: jnp.asarray(src[t], group[t].dtype)
                         for t in group},
                        self.param_shardings[pkey])
                    self._refresh_masters(pkey)
                    copied.append(name)
        mlog.info(f"copy_model_from: copied layers {copied}")

    # ------------------------------------------------------------- checking
    def check_weight_consistency(self) -> float:
        """Replica-consistency check, the ``test_on_server`` equivalent
        (async_updater-inl.hpp:144-154): max abs difference of any param,
        optimizer-state, or buffer leaf across its replicas (the reference's
        CheckWeight_ covered the thing being updated; here momentum/adam
        state and batch-norm running stats are replicated update targets
        too).  0.0 means all replicas agree.  ZeRO-sharded optimizer leaves
        hold distinct slices per device — the slice-index grouping below
        compares only true replicas."""
        worst = 0.0
        for leaf in jax.tree.leaves((self.params, self.opt_state,
                                     self.buffers)):
            shards = getattr(leaf, "addressable_shards", None)
            if not shards or len(shards) < 2:
                continue
            # group by slice index: only true replicas (same slice of the
            # logical array) must be bit-identical
            by_index = {}
            for s in shards:
                by_index.setdefault(str(s.index), []).append(s)
            for group in by_index.values():
                base = np.asarray(group[0].data)
                for s in group[1:]:
                    d = np.abs(np.asarray(s.data) - base).max()
                    if np.isnan(d):  # NaN-vs-finite IS divergence;
                        return float("inf")  # max() would silently drop it
                    worst = max(worst, float(d))
        return worst


def _map_group(params, fn):
    """Apply fn(tag, leaf) over param groups, recursing through nested
    sub-groups (pairtest layers hold {"master": {...}, "slave": {...}})."""
    def rec(g):
        return {tag: rec(p) if isinstance(p, dict) else fn(tag, p)
                for tag, p in g.items()}
    return {pkey: rec(group) for pkey, group in params.items()}
